"""Region fan-out and feature/label generation driver.

Mirrors the reference orchestration (ref: roko/features.py): contigs are
split into 100 kb regions with 300 bp overlap; each region is processed by
a worker (multiprocessing Pool) producing windows (and labels in training
mode). The fan-out itself is exposed as :func:`open_region_stream` — a
context manager owning the pool lifecycle that yields per-region result
blocks — with two consumers:

- :func:`run_features` buffers results per contig and flushes them to an
  HDF5 file every 10 finished regions (the staged ``features`` CLI);
- ``roko_tpu.pipeline.run_streaming_polish`` feeds the same blocks
  straight into the device predict loop through a bounded queue, no
  HDF5 round-trip (docs/PIPELINE.md).

Workers pick the fastest available extractor backend (C++ via
``roko_tpu.native`` when built, else the Python reference implementation)
— both produce bit-identical windows for a given seed.
"""

from __future__ import annotations

import contextlib
import multiprocessing
import os
from collections import Counter
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from roko_tpu import constants as C
from roko_tpu.config import RegionConfig, RokoConfig
from roko_tpu.data.hdf5 import DataWriter
from roko_tpu.features import labels as L
from roko_tpu.features.backend import (
    extract_region_arrays,
    extract_region_windows,
)
from roko_tpu.features.labels import Region
from roko_tpu.io.bam import BamReader
from roko_tpu.io.fasta import read_fasta
from roko_tpu.utils.rng import derive_region_seed


def generate_regions(
    ref_len: int, name: str, cfg: Optional[RegionConfig] = None
) -> Iterator[Region]:
    """100 kb regions with 300 bp overlap (ref: roko/features.py:16-27)."""
    cfg = cfg or RegionConfig()
    i = 0
    while i < ref_len:
        end = i + cfg.size
        yield Region(name, i, min(end, ref_len))
        if end >= ref_len:
            break
        i = end - cfg.overlap


@dataclass
class _Job:
    bam_x: str
    bam_y: Optional[str]
    region: Region
    seed: int
    config: RokoConfig
    # draft slice covering [region.start, region.end), shipped to
    # workers only when config.window.ref_rows > 0 (the draft-base rows
    # need it). A slice, not the contig: per-job IPC stays O(region)
    # instead of O(contig) x regions.
    ref_seq: Optional[str] = None
    ref_seq_offset: int = 0


def _is_in_region(pos: int, aligns: Sequence[L.TargetAlign]) -> bool:
    return any(a.start <= pos < a.end for a in aligns)


def _empty_arrays(config: RokoConfig):
    w = config.window
    return (
        np.empty((0, w.cols, 2), np.int64),
        np.empty((0, w.rows, w.cols), np.uint8),
    )


def generate_infer(job: _Job):
    """Feature windows for one region, inference mode
    (ref: roko/features.py:97-110). Returns stacked arrays — two
    contiguous buffers cross the worker boundary, not N small ones."""
    region = job.region
    positions, examples = extract_region_arrays(
        job.bam_x,
        region.name,
        region.start,
        region.end,
        job.seed,
        job.config.window,
        job.config.read_filter,
        ref_seq=job.ref_seq,
        ref_seq_offset=job.ref_seq_offset,
    )
    return region.name, positions, examples, None


def generate_train(job: _Job):
    """Feature windows + labels for one region, training mode
    (ref: roko/features.py:37-94)."""
    region = job.region
    with BamReader(job.bam_y) as truth:
        alignments = L.get_aligns(
            truth, ref_name=region.name, start=region.start, end=region.end
        )
    filtered = L.filter_aligns(alignments)
    if not filtered:
        return None

    positions, examples, labels = [], [], []

    for a in filtered:
        pos_labels = {}
        n_pos = set()

        t_pos, t_labels = L.get_pos_and_labels(a, region)
        for p, lab in zip(t_pos, t_labels):
            if lab == C.ENCODED_UNKNOWN:
                n_pos.add(p)
            else:
                pos_labels[p] = lab
        if not pos_labels:
            continue

        pos_sorted = sorted(pos_labels)
        # labeled span, end-exclusive: the last labeled position is
        # excluded, matching the reference's 1-based region string
        # `start+1`-`last` (ref: roko/features.py:62-63)
        span_start, span_end = pos_sorted[0][0], pos_sorted[-1][0]
        if span_end <= span_start:
            continue

        windows = extract_region_windows(
            job.bam_x,
            region.name,
            span_start,
            span_end,
            job.seed,
            job.config.window,
            job.config.read_filter,
            ref_seq=job.ref_seq,
            ref_seq_offset=job.ref_seq_offset,
        )

        for w in windows:
            Y = []
            keep = True
            for p in map(tuple, w.positions):
                if not _is_in_region(p[0], filtered):
                    raise AssertionError(
                        f"window position {p} outside filtered truth alignments"
                    )
                if p in n_pos:
                    keep = False
                    break
                try:
                    y = pos_labels[p]
                except KeyError:
                    if p[1] != 0:
                        # unlabeled insertion slot: the truth has no base
                        # there -> GAP (ref: roko/features.py:81-84)
                        y = C.ENCODED_GAP
                    else:
                        raise KeyError(f"no label mapping for position {p}")
                Y.append(y)

            if keep:
                positions.append(w.positions)
                examples.append(w.matrix)
                labels.append(np.asarray(Y, dtype=np.int64))

    if not positions:
        return region.name, *_empty_arrays(job.config), np.empty((0, job.config.window.cols), np.int64)
    return (
        region.name,
        np.stack(positions),
        np.stack(examples),
        np.stack(labels),
    )


def _use_thread_pool(inference: bool) -> bool:
    """Threads beat processes only when the per-region work releases the
    GIL: the C++ extractor does, but train-mode labeling
    (``generate_train``) is GIL-bound Python around it — a ThreadPool
    there loses most multi-core scaling (ADVICE r1 (d))."""
    from roko_tpu.features.backend import _native_available

    return inference and _native_available()


@dataclass
class RegionStream:
    """A live region fan-out: per-region result blocks plus the metadata
    both consumers need before the first result lands.

    ``results`` yields ``(contig, positions, examples, labels)`` per
    region in job order (``None`` for skipped train-mode regions);
    ``region_counts`` maps contig -> region job count, so a streaming
    consumer can tell when a contig's last region has arrived whatever
    order results come back in."""

    refs: List[Tuple[str, str]]
    jobs: List[_Job]
    results: Iterator
    inference: bool
    region_counts: Dict[str, int] = field(default_factory=dict)

    def __post_init__(self):
        if not self.region_counts:
            self.region_counts = dict(
                Counter(j.region.name for j in self.jobs)
            )


def run_features(
    ref_path: str,
    bam_x: str,
    out_path: str,
    bam_y: Optional[str] = None,
    workers: int = 1,
    seed: int = 0,
    config: Optional[RokoConfig] = None,
    flush_every: int = 10,
    log=print,
    job_retries: int = 1,
    job_timeout: Optional[float] = None,
) -> int:
    """Generate a features HDF5. Returns the number of windows written.

    ``bam_x``/``bam_y`` may also be SAM text files (htslib reads either
    transparently — models.cpp:37-44 — so the CLI contract matches):
    they are converted once to temp coordinate-sorted BAM+BAI so the
    native extractor and region fetches work identically. NB the
    conversion sorts in memory — fine for the modest SAMs this is for;
    genome-scale runs should hand over BAMs, which stream.
    """
    import time

    with open_region_stream(
        ref_path, bam_x, bam_y=bam_y, workers=workers, seed=seed,
        config=config, log=log, job_retries=job_retries,
        job_timeout=job_timeout,
    ) as stream:
        total = 0
        with DataWriter(out_path, stream.inference) as data:
            data.write_contigs(stream.refs)
            t0 = time.perf_counter()
            done = 0
            for result in stream.results:
                done += 1
                # progress heartbeat: a 5-species feature run is hours —
                # report every flush batch (ref printed per region,
                # roko/features.py:139; one line per flush is quieter)
                if done % flush_every == 0:
                    dt = time.perf_counter() - t0
                    rate = done / max(dt, 1e-9)
                    log(
                        f"features: {done}/{len(stream.jobs)} regions, "
                        f"{total} windows "
                        f"({rate:.1f} regions/s, eta {(len(stream.jobs) - done) / max(rate, 1e-9):.0f}s)"
                    )
                    data.write()
                if not result:
                    continue
                contig, p, x, y = result
                data.store(contig, p, x, y)
                total += len(p)
            data.write()
    return total


def _ensure_bam(path: str, stack) -> str:
    """Pass BAMs through; convert SAM text to a temp sorted BAM+BAI.
    A store-scheme URL localizes first (cached, atomic, ``.bai``
    sidecar included) — the native reader needs a real filename."""
    from roko_tpu.datapipe.io import ensure_local

    path = ensure_local(path)
    with open(path, "rb") as fh:
        magic = fh.read(2)
    if magic == b"\x1f\x8b":  # BGZF (BAM) — use as-is
        return path
    import tempfile

    from roko_tpu.io.bam import write_sorted_bam
    from roko_tpu.io.sam import SamReader

    tmpdir = stack.enter_context(tempfile.TemporaryDirectory())
    out = os.path.join(
        tmpdir, os.path.basename(path).rsplit(".", 1)[0] + ".bam"
    )
    with SamReader(path) as r:
        write_sorted_bam(out, r.references, list(r))
    return out


def _recovering_results(results, func, jobs, retries, timeout, log, pool=None):
    """Failure detection/recovery for the region fan-out (SURVEY §5.3).

    Region jobs are pure functions of (bam paths, region, seed), so a
    failed or lost job is safely re-runnable with identical output. Two
    failure classes are handled:

    - a job that RAISES (worker exception propagates through imap/map):
      re-run it in the parent up to ``retries`` times before giving up
      and re-raising — transient faults (OOM-killed sibling, flaky
      filesystem) don't abort an hours-long multi-species run;
    - a job whose worker process DIED (``imap`` would block forever on
      the lost result): when ``timeout`` is set and ``pool`` is a
      process pool, each result wait is bounded; on a timeout the pool
      is terminated and the remainder recomputed in the parent. Opt-in
      because the bound must exceed the slowest honest region, and
      process-pools only (threads cannot die out from under the queue).
    """
    import multiprocessing as mp

    from roko_tpu.resilience import RetryPolicy

    # region jobs are pure and cheap to re-dispatch, so the shared
    # policy runs with zero backoff: the retry IS the recovery, there
    # is no remote rate limit to be polite to
    policy = RetryPolicy(
        max_attempts=max(1, retries), base_delay_s=0.0, jitter=0.0,
        retryable=(Exception,),
    )

    def rerun(job, err):
        def describe(e):
            log(
                f"features: region {job.region.name}:{job.region.start} "
                f"failed ({type(e).__name__}: {e}); "
                f"retry {describe.attempt}/{retries} in the parent"
            )
            describe.attempt += 1

        describe.attempt = 1
        if retries <= 0:
            raise err
        describe(err)  # the pool-side failure that brought us here
        return policy.call(
            lambda: func(job),
            on_retry=lambda failures, e, delay: describe(e),
        )

    it = iter(results)
    can_timeout = (
        timeout is not None and pool is not None and hasattr(it, "next")
    )
    broken = False
    for i, job in enumerate(jobs):
        if broken:
            # pool results are untrustworthy after a lost-result event
            # (any late arrival would mis-align with later jobs) —
            # finish the remainder sequentially in the parent
            try:
                yield func(job)
            except Exception as e:
                yield rerun(job, e)
            continue
        try:
            result = it.next(timeout) if can_timeout else next(it)
        except StopIteration:  # pragma: no cover - defensive
            raise RuntimeError(
                f"result stream ended early at job {i}/{len(jobs)}"
            ) from None
        except mp.TimeoutError:
            log(
                f"features: region {job.region.name}:{job.region.start} "
                f"result not ready after {timeout}s (worker died?); "
                "abandoning the pool — remaining regions run in the parent"
            )
            broken = True
            # kill the orphaned workers NOW: left running they would
            # chew through every queued region in parallel with the
            # parent's recompute, wasting cores and I/O for the whole
            # recovery tail (results would be discarded anyway)
            pool.terminate()
            try:
                yield func(job)
            except Exception as e:
                yield rerun(job, e)
            continue
        except Exception as e:
            result = rerun(job, e)
        yield result


@contextlib.contextmanager
def open_region_stream(
    ref_path: str,
    bam_x: str,
    bam_y: Optional[str] = None,
    *,
    workers: int = 1,
    seed: int = 0,
    config: Optional[RokoConfig] = None,
    log=print,
    job_retries: int = 1,
    job_timeout: Optional[float] = None,
    skip_contigs: Optional[set] = None,
) -> Iterator[RegionStream]:
    """Open the region fan-out and yield a :class:`RegionStream`.

    ``skip_contigs`` names contigs to generate NO region jobs for (the
    crash-resume path: contigs already committed in a polish journal
    must not be re-extracted); they stay in ``refs`` so consumers keep
    the full draft picture.

    Owns the whole extraction lifecycle: SAM->BAM conversion temp files,
    pool creation, the failure-recovery wrapper, and pool teardown on
    exit (terminate for process pools — after a lost-result event the
    stream was deliberately abandoned and a hung worker would block
    ``join`` forever; close/join for thread pools, whose threads cannot
    die out from under the queue)."""
    config = config or RokoConfig()
    with contextlib.ExitStack() as stack:
        bam_x = _ensure_bam(bam_x, stack)
        if bam_y is not None:
            bam_y = _ensure_bam(bam_y, stack)
        inference = bam_y is None
        refs = read_fasta(ref_path)

        jobs: List[_Job] = []
        for name, seq in refs:
            if skip_contigs and name in skip_contigs:
                continue
            for region in generate_regions(len(seq), name, config.region):
                jobs.append(
                    _Job(
                        bam_x=bam_x,
                        bam_y=bam_y,
                        region=region,
                        seed=derive_region_seed(seed, name, region.start),
                        config=config,
                        ref_seq=(
                            seq[region.start : region.end]
                            if config.window.ref_rows > 0
                            else None
                        ),
                        ref_seq_offset=region.start,
                    )
                )

        func = generate_infer if inference else generate_train
        is_thread_pool = False
        if workers <= 1:
            results = map(func, jobs)
            pool = None
        elif _use_thread_pool(inference):
            # the C++ extractor releases the GIL, so threads give
            # full parallelism with zero IPC (results stay in-process
            # — no pickling of the window buffers)
            from multiprocessing.pool import ThreadPool

            pool = ThreadPool(processes=workers)
            results = pool.imap(func, jobs)
            is_thread_pool = True
        else:
            pool = multiprocessing.Pool(processes=workers)
            results = pool.imap(func, jobs)
        # job_timeout applies only to PROCESS pools: a thread cannot die
        # out from under the queue (the failure class the timeout
        # detects), and abandoning a ThreadPool would deadlock the
        # close/join on any genuinely hung thread — say so rather than
        # silently ignoring an explicit flag (r5 review)
        if job_timeout is not None and (is_thread_pool or pool is None):
            log(
                "--job-timeout applies only to process pools; ignored on "
                + ("the thread-pool path" if is_thread_pool else "serial runs")
            )
        results = _recovering_results(
            results, func, jobs, job_retries, job_timeout, log,
            pool=None if is_thread_pool else pool,
        )
        try:
            yield RegionStream(
                refs=refs, jobs=jobs, results=results, inference=inference
            )
        finally:
            if pool is not None:
                if is_thread_pool:
                    pool.close()
                    pool.join()
                else:
                    pool.terminate()
                    pool.join()
