"""Column pileup engine over a coordinate-sorted BAM.

Reimplements the subset of htslib's ``bam_mplp_*`` machinery the feature
extractor needs (ref: models.cpp:73-146, htslib sam.c pileup engine):
for every covered reference position, the set of overlapping filtered
reads with, per read, the query offset, deletion state, and the length of
any indel that follows the position. Reads receive serial ids in file
order — the analogue of htslib's ``bam1_t::id`` (SURVEY.md §2.13) — which
the tensorizer uses to track a read across columns.

This is the readable reference implementation and test oracle; the C++
extractor in ``roko_tpu/native`` mirrors it for the hot path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

from roko_tpu import constants as C
from roko_tpu.config import ReadFilterConfig
from roko_tpu.io.bam import BamReader, BamRecord


@dataclass
class PileupEntry:
    """One read's state at one reference column."""

    read_id: int
    qpos: int  # query offset of the base at this column (M) or of the
    #          # last aligned base before a deletion (D/N columns)
    is_del: bool
    is_refskip: bool
    indel: int  # >0: insertion of this length follows the column;
    #           # <0: deletion of this length follows; 0 otherwise
    record: BamRecord


def passes_filter(rec: BamRecord, cfg: ReadFilterConfig) -> bool:
    """Read filter policy (ref: models.cpp:25-27, include/models.h:22-23)."""
    if rec.flag & cfg.filter_flag:
        return False
    if (
        cfg.require_proper_pair
        and rec.flag & C.FLAG_PAIRED
        and not rec.flag & C.FLAG_PROPER_PAIR
    ):
        return False
    if rec.mapq < cfg.min_mapq:
        return False
    return True


def _column_states(rec: BamRecord) -> List[Tuple[int, bool, bool, int]]:
    """Per reference column covered by ``rec`` (from ``rec.pos``), the
    tuple ``(qpos, is_del, is_refskip, indel)`` with htslib pileup
    semantics: ``indel`` is set on the last column before an I/D op."""
    states: List[Tuple[int, bool, bool, int]] = []
    qpos = 0
    for op, length in rec.cigar:
        if op in (C.CIGAR_M, C.CIGAR_EQ, C.CIGAR_X):
            for i in range(length):
                states.append((qpos + i, False, False, 0))
            qpos += length
        elif op == C.CIGAR_I:
            if states:
                q, d, rs, _ = states[-1]
                states[-1] = (q, d, rs, length)
            qpos += length
        elif op == C.CIGAR_D:
            if states:
                q, d, rs, ind = states[-1]
                states[-1] = (q, d, rs, ind if ind > 0 else -length)
            for _ in range(length):
                # qpos of the base preceding the deletion, as htslib does
                states.append((max(qpos - 1, 0), True, False, 0))
        elif op == C.CIGAR_N:
            for _ in range(length):
                states.append((max(qpos - 1, 0), True, True, 0))
        elif op == C.CIGAR_S:
            qpos += length
        # H, P consume nothing
    return states


def pileup_columns(
    reader: BamReader,
    contig: str,
    start: int,
    end: int,
    filter_cfg: Optional[ReadFilterConfig] = None,
) -> Iterator[Tuple[int, List[PileupEntry]]]:
    """Yield ``(rpos, entries)`` for every position covered by at least one
    filtered read overlapping ``[start, end)``, in ascending position
    order. Like htslib's multi-pileup over a region iterator, columns can
    extend OUTSIDE ``[start, end)`` (reads overlap the region boundary);
    callers clip, exactly as the reference extractor does
    (ref: generate.cpp:47-49). Entry order within a column is read file
    order (htslib adds reads to the pileup in iterator order)."""
    if filter_cfg is None:
        filter_cfg = ReadFilterConfig()

    # Reads overlapping the region, filtered, ids in file order.
    reads: List[Tuple[int, BamRecord, List[Tuple[int, bool, bool, int]]]] = []
    next_id = 0
    for rec in reader.fetch(contig, start, end):
        if not passes_filter(rec, filter_cfg):
            continue
        reads.append((next_id, rec, _column_states(rec)))
        next_id += 1

    if not reads:
        return

    # Sweep columns. Reads are already sorted by start position.
    lo = min(r.pos for _, r, _ in reads)
    hi = max(r.pos + len(states) for _, r, states in reads)
    active: List[int] = []  # indices into `reads`
    nxt = 0
    for rpos in range(lo, hi):
        while nxt < len(reads) and reads[nxt][1].pos <= rpos:
            active.append(nxt)
            nxt += 1
        entries: List[PileupEntry] = []
        still_active: List[int] = []
        for idx in active:
            rid, rec, states = reads[idx]
            col = rpos - rec.pos
            if col >= len(states):
                continue  # read exhausted
            still_active.append(idx)
            if col < 0:
                continue
            qpos, is_del, is_refskip, indel = states[col]
            entries.append(
                PileupEntry(
                    read_id=rid,
                    qpos=qpos,
                    is_del=is_del,
                    is_refskip=is_refskip,
                    indel=indel,
                    record=rec,
                )
            )
        active = still_active
        if entries:
            yield rpos, entries
        if not active and nxt >= len(reads):
            return
