"""Content-addressed window cache for the cascade.

A window's polished predictions are a pure function of (window bytes,
the params that predict them, the quantize mode, and the cascade's own
decision identity). The cache key is the sha256 over exactly those
inputs, so a stale-digest hit is *structurally impossible*: params
drift changes every key. The in-memory tier is a byte-capped LRU; the
optional on-disk sidecar follows the journal-identity discipline
(``meta.json`` pins the run identity; opening it under a different
identity refuses with the same field-level drift diff
BundleMismatch/RegistryMismatch print) and writes each entry atomically
(tmp + rename), so a worker SIGKILLed mid-write never publishes a torn
entry — the property the distpolish fleet relies on to share one cache
directory across workers.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

import numpy as np

#: per-entry bookkeeping overhead charged against the byte cap (key
#: string + OrderedDict node); keeps the cap honest for tiny entries
ENTRY_OVERHEAD = 128


class CascadeMismatch(RuntimeError):
    """A cascade artifact (cache sidecar, calibration, tier model) does
    not match the running process's params digest / quantize mode /
    registry version. Serving it would scatter predictions from a
    DIFFERENT model into the output — wrong bases, not wrong speed —
    so the cascade refuses, in the BundleMismatch drift-diff shape."""

    def __init__(self, what: str, where: str, diff: Dict[str, Tuple[Any, Any]]):
        lines = [
            f"{key}: artifact={theirs!r} run={ours!r}"
            for key, (theirs, ours) in sorted(diff.items())
        ]
        super().__init__(
            f"cascade {what} at {where!r} belongs to a different run; "
            "refusing to use it (a mismatched cascade artifact would "
            "produce wrong bases, not just wrong speed). Differing "
            "fields:\n  " + "\n  ".join(lines or ["<identity mismatch>"])
            + "\nDelete the artifact or rerun with the matching "
            "params/quantize/registry version."
        )
        self.diff = diff


def params_digest(params: Any) -> str:
    """sha256 over the params tree's leaf bytes (shape/dtype-framed) —
    the cache-key identity of "which weights predict". Quantized params
    hash differently from their float source by construction."""
    import jax

    h = hashlib.sha256()
    leaves, treedef = jax.tree_util.tree_flatten(params)
    h.update(repr(treedef).encode())
    for leaf in leaves:
        arr = np.asarray(leaf)
        h.update(f"{arr.dtype.str}{arr.shape}".encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def cache_identity(
    *,
    params_digest: str,
    quantize: Optional[str],
    tier: str,
    threshold: float,
    method: str,
    temperature: float,
    tier_version: Optional[str] = None,
) -> Dict[str, Any]:
    """Everything a cached prediction depends on. The params digest +
    quantize mode cover the reference tier; tier/threshold/method/
    temperature cover the cascade DECISION (a window kept by tier 1 at
    threshold 0.02 may be escalated at 0.5, so the decision identity
    must ride in the key or thresholds would cross-contaminate)."""
    return {
        "params_digest": str(params_digest),
        "quantize": quantize or "none",
        "tier": str(tier),
        "tier_version": tier_version or "none",
        "threshold": float(threshold),
        "method": str(method),
        "temperature": float(temperature),
    }


def window_key(window_bytes: bytes, identity: Dict[str, Any]) -> str:
    """sha256 hex over the window's raw bytes + the cache identity."""
    h = hashlib.sha256()
    h.update(json.dumps(identity, sort_keys=True).encode())
    h.update(b"\x00")
    h.update(window_bytes)
    return h.hexdigest()


class WindowCache:
    """Thread-safe byte-capped LRU: key (hex digest) -> int32 preds."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._data: "OrderedDict[str, np.ndarray]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @staticmethod
    def _cost(key: str, preds: np.ndarray) -> int:
        return len(key) + int(preds.nbytes) + ENTRY_OVERHEAD

    def get(self, key: str) -> Optional[np.ndarray]:
        with self._lock:
            preds = self._data.get(key)
            if preds is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return preds

    def put(self, key: str, preds: np.ndarray) -> None:
        preds = np.ascontiguousarray(preds, dtype=np.int32)
        cost = self._cost(key, preds)
        if cost > self.max_bytes:
            return  # an entry larger than the whole cap never fits
        with self._lock:
            old = self._data.pop(key, None)
            if old is not None:
                self._bytes -= self._cost(key, old)
            self._data[key] = preds
            self._bytes += cost
            while self._bytes > self.max_bytes and self._data:
                k, v = self._data.popitem(last=False)
                self._bytes -= self._cost(k, v)
                self.evictions += 1

    def __len__(self) -> int:
        return len(self._data)

    @property
    def bytes(self) -> int:
        return self._bytes

    def stats(self) -> Dict[str, int]:
        return {
            "entries": len(self._data),
            "bytes": self._bytes,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
        }


class DiskWindowCache:
    """Shared on-disk sidecar: one file per key under two-level hex
    fanout, written atomically. ``meta.json`` pins the cache identity
    (journal discipline); an identity drift on open refuses loudly.

    Concurrency model: many processes may read and write the same
    directory. Writes go to a pid-suffixed tmp file then ``os.replace``
    — a reader either sees a complete entry or no entry, never a torn
    one (the SIGKILL-survival property the stub-fleet test pins).
    Entries under a different identity cannot be *served* even if the
    directory is reused wrongly, because the identity is inside every
    key — meta.json exists to fail FAST and loudly, not as the only
    line of defense."""

    META = "meta.json"

    def __init__(self, root: str, identity: Dict[str, Any]):
        self.root = root
        self.identity = json.loads(json.dumps(identity, sort_keys=True))
        self.hits = 0
        self.misses = 0
        os.makedirs(root, exist_ok=True)
        meta_path = os.path.join(root, self.META)
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    have = json.load(f)
            except (OSError, ValueError):
                raise CascadeMismatch(
                    "cache sidecar", root, {"meta.json": ("<unreadable>", "valid")}
                ) from None
            if have != self.identity:
                diff = {
                    k: (have.get(k, "<absent>"), self.identity.get(k, "<absent>"))
                    for k in sorted(set(have) | set(self.identity))
                    if have.get(k, "<absent>") != self.identity.get(k, "<absent>")
                }
                raise CascadeMismatch("cache sidecar", root, diff)
        else:
            tmp = f"{meta_path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.identity, f, indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, meta_path)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key[:2], key + ".npy")

    def get(self, key: str) -> Optional[np.ndarray]:
        try:
            with open(self._path(key), "rb") as f:
                preds = np.load(f, allow_pickle=False)
        except (OSError, ValueError):
            self.misses += 1  # absent OR torn-looking: both are misses
            return None
        self.hits += 1
        return np.ascontiguousarray(preds, dtype=np.int32)

    def put(self, key: str, preds: np.ndarray) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                np.save(f, np.ascontiguousarray(preds, dtype=np.int32),
                        allow_pickle=False)
                f.flush()
            os.replace(tmp, path)
        except OSError:
            # best-effort sidecar: a full disk degrades to a smaller
            # cache, never to a failed polish
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def stats(self) -> Dict[str, int]:
        entries = 0
        total = 0
        for sub in os.listdir(self.root):
            d = os.path.join(self.root, sub)
            if len(sub) != 2 or not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if name.endswith(".npy"):
                    entries += 1
                    try:
                        total += os.path.getsize(os.path.join(d, name))
                    except OSError:
                        pass
        return {
            "entries": entries,
            "bytes": total,
            "hits": self.hits,
            "misses": self.misses,
        }
