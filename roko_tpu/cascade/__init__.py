"""Adaptive compute: confidence cascade + content-addressed window cache.

Most pileup windows in a high-coverage genome are easy — the draft
already matches consensus — yet the plain session pays the full
reference-GRU price for every one. This package routes each window
through a cheap tier first (the pileup majority vote, or a named
registry model), keeps the windows whose *calibrated* confidence clears
a threshold, and escalates only the uncertain rest to the reference
model as a second batcher submit. A content-addressed cache (key =
window bytes + params digest + quantize mode) sits in front of tier 1
so a whole-genome distpolish job pays for each distinct window once
across the fleet.

Identity discipline mirrors the bundle/registry/journal refusals: a
cache or calibration artifact fitted against different params digests,
quantize modes, or registry versions refuses loudly
(:class:`CascadeMismatch`) instead of silently serving drift.

docs/SERVING.md "Adaptive compute" is the operator-facing contract.
"""

from roko_tpu.cascade.cache import (
    CascadeMismatch,
    DiskWindowCache,
    WindowCache,
    cache_identity,
    params_digest,
    window_key,
)
from roko_tpu.cascade.calibration import (
    Calibration,
    calibration_path_for,
    confidence_scores,
    escalate_mask,
    fit_calibration,
    fit_temperature,
)
from roko_tpu.cascade.router import (
    MAJORITY_TEMPERATURE,
    CascadeFuture,
    CascadeRouter,
    build_router,
)

__all__ = [
    "Calibration",
    "MAJORITY_TEMPERATURE",
    "CascadeFuture",
    "CascadeMismatch",
    "CascadeRouter",
    "DiskWindowCache",
    "WindowCache",
    "build_router",
    "cache_identity",
    "calibration_path_for",
    "confidence_scores",
    "escalate_mask",
    "fit_calibration",
    "fit_temperature",
    "params_digest",
    "window_key",
]
