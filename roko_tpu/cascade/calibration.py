"""Confidence calibration for the cascade's cheap tier.

Raw softmax confidences are systematically over- or under-confident;
routing on them makes the escalation threshold meaningless across model
kinds and coverage levels. The standard fix is temperature scaling
(Guo et al.): divide the logits by one scalar ``T`` fitted to minimize
NLL on held-out data, which preserves the argmax (tier-1 predictions
never change) while making "0.95 confident" mean roughly 95% accurate.

Two confidence functions are supported:

- ``max_softmax`` — max of the temperature-scaled softmax;
- ``margin`` — the two-class softmax of the top-2 logits, i.e.
  ``sigmoid((top1 - top2) / T)``; less sensitive to the tail classes.

A *window's* confidence is the MIN over its columns: one uncertain
base escalates the whole window, because the escalated tier re-predicts
whole windows (the batcher's unit of work) and a window is only as
correct as its weakest column.

The threshold rule is pinned at both ends (the byte-identity gate
depends on it): escalate iff ``confidence <= 1 - threshold``.
``threshold=0`` escalates EVERYTHING — even a saturated confidence of
exactly 1.0 (hence the non-strict comparison) — so the cascade output
is byte-identical to the plain session path; ``threshold=1`` escalates
nothing (softmax confidence is strictly positive).

The fitted artifact persists as JSON beside the checkpoint manifest
and records the params digest it was fitted against; loading it next
to different params refuses (:class:`~roko_tpu.cascade.cache.CascadeMismatch`).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

import numpy as np

#: supported confidence functions (CascadeConfig.method values)
METHODS = ("max_softmax", "margin")

#: artifact filename, placed beside the checkpoint/bundle manifest
CALIBRATION_FILE = "cascade_calibration.json"


def calibration_path_for(checkpoint_path: str) -> str:
    """The calibration artifact's canonical home: beside the checkpoint
    (or bundle manifest) it was fitted for. A file path gets its
    directory taken; a directory is used as-is."""
    base = checkpoint_path
    if os.path.splitext(base)[1] or os.path.isfile(base):
        base = os.path.dirname(base) or "."
    return os.path.join(base, CALIBRATION_FILE)


def _scaled_log_softmax(logits: np.ndarray, temperature: float) -> np.ndarray:
    z = np.asarray(logits, dtype=np.float64) / float(temperature)
    z = z - z.max(axis=-1, keepdims=True)
    return z - np.log(np.exp(z).sum(axis=-1, keepdims=True))


def nll(logits: np.ndarray, labels: np.ndarray, temperature: float) -> float:
    """Mean negative log-likelihood of ``labels`` under temperature-scaled
    softmax — the objective temperature fitting minimizes."""
    logits = np.asarray(logits, dtype=np.float64).reshape(-1, np.shape(logits)[-1])
    labels = np.asarray(labels).reshape(-1)
    if logits.shape[0] == 0:
        raise ValueError("cannot evaluate NLL on zero examples")
    logp = _scaled_log_softmax(logits, temperature)
    return float(-logp[np.arange(len(labels)), labels].mean())


def fit_temperature(
    logits: np.ndarray,
    labels: np.ndarray,
    *,
    lo: float = 0.05,
    hi: float = 20.0,
    iters: int = 80,
) -> float:
    """Fit the temperature minimizing held-out NLL by golden-section
    search over ``log T`` (the NLL is unimodal in T for fixed logits).
    Deterministic, numpy-only; ~80 iterations pins T to ~1e-9 relative."""
    a, b = np.log(lo), np.log(hi)
    gr = (np.sqrt(5.0) - 1.0) / 2.0
    c = b - gr * (b - a)
    d = a + gr * (b - a)
    fc = nll(logits, labels, float(np.exp(c)))
    fd = nll(logits, labels, float(np.exp(d)))
    for _ in range(iters):
        if fc < fd:
            b, d, fd = d, c, fc
            c = b - gr * (b - a)
            fc = nll(logits, labels, float(np.exp(c)))
        else:
            a, c, fc = c, d, fd
            d = a + gr * (b - a)
            fd = nll(logits, labels, float(np.exp(d)))
    return float(np.exp((a + b) / 2.0))


def confidence_scores(
    logits: np.ndarray, method: str = "max_softmax", temperature: float = 1.0
) -> np.ndarray:
    """Per-position confidence in (0, 1] from raw logits (any leading
    shape; the last axis is classes). ``method`` is one of
    :data:`METHODS`."""
    if method not in METHODS:
        raise ValueError(f"unknown confidence method {method!r}; want one of {METHODS}")
    if float(temperature) <= 0:
        raise ValueError(f"temperature must be positive, got {temperature}")
    logits = np.asarray(logits, dtype=np.float64)
    if method == "max_softmax":
        logp = _scaled_log_softmax(logits, temperature)
        return np.exp(logp.max(axis=-1))
    # margin: two-class softmax of the top-2 logits = sigmoid(gap / T)
    part = np.partition(logits, -2, axis=-1)
    gap = (part[..., -1] - part[..., -2]) / float(temperature)
    return 1.0 / (1.0 + np.exp(-gap))


def window_confidence(
    logits: np.ndarray, method: str = "max_softmax", temperature: float = 1.0
) -> np.ndarray:
    """Reduce ``logits[n, cols, classes]`` to one confidence per window:
    the MIN over columns (the weakest base gates the window)."""
    conf = confidence_scores(logits, method, temperature)
    if conf.ndim == 1:  # already per-window
        return conf
    return conf.min(axis=tuple(range(1, conf.ndim)))


def escalate_mask(confidence: np.ndarray, threshold: float) -> np.ndarray:
    """True where the window must escalate to the reference tier.

    Pinned endpoints: ``threshold=0`` -> all True (non-strict compare,
    so even confidence exactly 1.0 escalates — the byte-identity gate);
    ``threshold=1`` -> all False (softmax confidence is > 0)."""
    t = float(threshold)
    if not 0.0 <= t <= 1.0:
        raise ValueError(f"threshold must lie in [0, 1], got {threshold}")
    return np.asarray(confidence, dtype=np.float64) <= (1.0 - t)


@dataclass(frozen=True)
class Calibration:
    """The persisted calibration artifact: one temperature, the method
    it was fitted for, and the identity of the params it calibrates."""

    temperature: float = 1.0
    method: str = "max_softmax"
    #: digest of the params the calibration was fitted against; loading
    #: beside different params refuses (identity discipline)
    params_digest: Optional[str] = None
    #: held-out examples the fit saw (documentation, not identity)
    fitted_on: int = 0
    #: NLL before/after — the artifact carries its own receipts
    nll_before: Optional[float] = None
    nll_after: Optional[float] = None

    def confidence(self, logits: np.ndarray) -> np.ndarray:
        return window_confidence(logits, self.method, self.temperature)

    def to_json(self) -> dict:
        return {
            "temperature": self.temperature,
            "method": self.method,
            "params_digest": self.params_digest,
            "fitted_on": self.fitted_on,
            "nll_before": self.nll_before,
            "nll_after": self.nll_after,
        }

    def save(self, path: str) -> str:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=2, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)  # atomic: readers never see a torn file
        return path

    @classmethod
    def load(
        cls, path: str, *, expect_params_digest: Optional[str] = None
    ) -> "Calibration":
        with open(path) as f:
            raw = json.load(f)
        cal = cls(
            temperature=float(raw.get("temperature", 1.0)),
            method=str(raw.get("method", "max_softmax")),
            params_digest=raw.get("params_digest"),
            fitted_on=int(raw.get("fitted_on", 0)),
            nll_before=raw.get("nll_before"),
            nll_after=raw.get("nll_after"),
        )
        if cal.method not in METHODS:
            raise ValueError(
                f"calibration {path}: unknown method {cal.method!r}"
            )
        if cal.temperature <= 0:
            raise ValueError(
                f"calibration {path}: non-positive temperature {cal.temperature}"
            )
        if (
            expect_params_digest is not None
            and cal.params_digest is not None
            and cal.params_digest != expect_params_digest
        ):
            from roko_tpu.cascade.cache import CascadeMismatch

            raise CascadeMismatch(
                "calibration/params drift", path,
                {"params_digest": (cal.params_digest, expect_params_digest)},
            )
        return cal


def fit_calibration(
    logits: np.ndarray,
    labels: np.ndarray,
    *,
    method: str = "max_softmax",
    params_digest: Optional[str] = None,
) -> Calibration:
    """Fit a :class:`Calibration` on held-out (logits, labels)."""
    if method not in METHODS:
        raise ValueError(f"unknown confidence method {method!r}; want one of {METHODS}")
    t = fit_temperature(logits, labels)
    flat = np.asarray(labels).reshape(-1)
    return Calibration(
        temperature=t,
        method=method,
        params_digest=params_digest,
        fitted_on=int(flat.size),
        nll_before=nll(logits, labels, 1.0),
        nll_after=nll(logits, labels, t),
    )
