"""The cascade tier router.

Tier 1 is CHEAP and host-side; tier 2 is the reference model behind
whatever predict machinery the caller already runs (a
``ContinuousBatcher.submit`` in serving/streaming, the padded-rung
jitted step in ``run_inference``). The router:

1. looks every window up in the content-addressed cache;
2. runs the remaining windows through tier 1 (``majority``: the pileup
   majority vote the stitcher already computes, as count-logits;
   ``model``: a named registry version predicted host-side with
   logits), reduces calibrated confidence per window, and keeps the
   confident ones;
3. escalates the rest as ONE second submit to the reference tier and
   scatters the results back by index.

Identity discipline: the router is built against one params digest +
quantize mode; its cache keys embed them, its calibration artifact
must match them, and a ``model``-tier registry entry is re-verified on
resolve (PR 12's digest checks) — any drift refuses with
:class:`~roko_tpu.cascade.cache.CascadeMismatch` before a single
window is served.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

from roko_tpu import constants as C
from roko_tpu.cascade.cache import (
    CascadeMismatch,
    DiskWindowCache,
    WindowCache,
    cache_identity,
    window_key,
)
from roko_tpu.cascade.calibration import Calibration, escalate_mask

#: tier-1 kinds CascadeConfig.tier may name
TIERS = ("majority", "model")

#: default temperature for the majority tier when no fitted calibration
#: artifact is supplied. Raw vote COUNTS are wildly overconfident at
#: T=1 — softmax of a 12-vs-8 split is e^4/(e^4+1) ~ 0.98 even though
#: a 60/40 vote is nowhere near 98% right — so an unscaled majority
#: tier keeps systematically-wrong homopolymer columns and fails the
#: Q-parity gate. Dividing by ~the per-class count scale spreads the
#: scores back over (0, 1); 8.0 holds held-out Q AT the reference on
#: the sim gate at the default threshold (escalating ~16%). A fitted
#: ``cascade_calibration.json`` overrides this.
MAJORITY_TEMPERATURE = 8.0


def majority_logits(x: np.ndarray) -> np.ndarray:
    """Count-logits of the pileup majority vote: fold the strand offset
    (feature code % STRAND_OFFSET), count votes per base class down the
    read axis, and return ``float32[n, cols, NUM_CLASSES]`` counts.
    ``ENCODED_UNKNOWN`` rows contribute nothing. Softmaxing counts
    (temperature-scaled) gives a natural confidence: a 30/0 column is
    near-certain, a 16/14 split is not."""
    x = np.asarray(x)
    folded = (x % C.STRAND_OFFSET).astype(np.int64)
    # one bincount per class beats a (n*rows*cols) scatter for the small
    # fixed class count
    counts = np.empty(x.shape[:1] + x.shape[2:] + (C.NUM_CLASSES,), np.float32)
    for cls in range(C.NUM_CLASSES):
        counts[..., cls] = (folded == cls).sum(axis=1)
    return counts


class CascadeFuture:
    """Future over one routed batch, interface-compatible with
    :class:`roko_tpu.serve.batcher.PredictFuture` (``done()`` /
    ``result(timeout)``), so the streaming polish drain loop treats a
    cascaded submit exactly like a plain one."""

    def __init__(
        self,
        preds: np.ndarray,
        esc_idx: np.ndarray,
        inner,
        on_escalated: Optional[Callable[[np.ndarray], None]] = None,
    ):
        self._preds = preds
        self._esc_idx = esc_idx
        self._inner = inner
        self._on_escalated = on_escalated
        self._resolved = inner is None

    def done(self) -> bool:
        return self._resolved or self._inner.done()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._resolved:
            sub = self._inner.result(timeout)  # raises TimeoutError as-is
            self._preds[self._esc_idx] = np.asarray(sub, dtype=np.int32)
            if self._on_escalated is not None:
                self._on_escalated(self._preds)
            self._resolved = True
        return self._preds


class CascadeRouter:
    """Routes window batches through cache -> tier 1 -> escalation."""

    def __init__(
        self,
        *,
        tier: str = "majority",
        threshold: float = 0.05,
        calibration: Optional[Calibration] = None,
        params_digest: str,
        quantize: Optional[str] = None,
        tier_version: Optional[str] = None,
        tier_logits_fn: Optional[Callable[[np.ndarray], np.ndarray]] = None,
        cache_bytes: int = 0,
        cache_dir: Optional[str] = None,
        metrics=None,
    ):
        if tier not in TIERS:
            raise ValueError(f"unknown cascade tier {tier!r}; want one of {TIERS}")
        if not 0.0 <= float(threshold) <= 1.0:
            raise ValueError(f"cascade threshold must lie in [0, 1], got {threshold}")
        self.tier = tier
        self.threshold = float(threshold)
        self.calibration = calibration or Calibration()
        self.params_digest = str(params_digest)
        self.quantize = quantize
        self.tier_version = tier_version
        self._tier_logits = (
            tier_logits_fn if tier_logits_fn is not None else majority_logits
        )
        self.metrics = metrics
        self._lock = threading.Lock()
        self.identity = cache_identity(
            params_digest=self.params_digest,
            quantize=self.quantize,
            tier=self.tier,
            threshold=self.threshold,
            method=self.calibration.method,
            temperature=self.calibration.temperature,
            tier_version=self.tier_version,
        )
        self.cache = WindowCache(cache_bytes) if cache_bytes > 0 else None
        self.disk = (
            DiskWindowCache(cache_dir, self.identity) if cache_dir else None
        )
        # counters (stats() and /metrics read these)
        self.windows = 0
        self.escalated = 0
        self.cache_hits = 0
        self.tier1_seconds = 0.0
        self.tier2_seconds = 0.0

    # -- identity ------------------------------------------------------------

    def check_identity(
        self, *, params_digest: Optional[str] = None, quantize: Optional[str] = None
    ) -> None:
        """Refuse escalation across drifted identity: the tier-2 params
        this router scatters into must be the ones it was built for."""
        diff: Dict[str, Any] = {}
        if params_digest is not None and params_digest != self.params_digest:
            diff["params_digest"] = (self.params_digest, params_digest)
        if quantize is not None and (quantize or "none") != (self.quantize or "none"):
            diff["quantize"] = (self.quantize or "none", quantize or "none")
        if diff:
            raise CascadeMismatch("tier router", "<escalation>", diff)

    def with_threshold(self, threshold: float) -> "CascadeRouter":
        """A same-identity router at a different threshold (the /polish
        per-request override). Tier fn, calibration, and metrics are
        shared; the cache is NOT — a different threshold is a different
        decision identity, so its keyspace is disjoint by construction —
        and the disk sidecar stays with the server default (an override
        must not open an identity-pinned sidecar it mismatches). Clones
        are memoized per threshold so repeated overrides stay cheap."""
        t = float(threshold)
        with self._lock:
            clones = self.__dict__.setdefault("_clones", {})
            got = clones.get(t)
            if got is None:
                got = CascadeRouter(
                    tier=self.tier,
                    threshold=t,
                    calibration=self.calibration,
                    params_digest=self.params_digest,
                    quantize=self.quantize,
                    tier_version=self.tier_version,
                    tier_logits_fn=self._tier_logits,
                    cache_bytes=self.cache.max_bytes if self.cache else 0,
                    cache_dir=None,
                    metrics=self.metrics,
                )
                clones[t] = got
        return got

    # -- the decision --------------------------------------------------------

    def _decide(self, x: np.ndarray):
        """Cache + tier-1 pass over one batch. Returns
        ``(preds[n, cols] int32, esc_idx int64[], keys_to_store)`` —
        ``preds`` rows at ``esc_idx`` are tier-1 placeholders awaiting
        the escalated results."""
        x = np.ascontiguousarray(x, dtype=np.uint8)
        n = len(x)
        cols = x.shape[2] if x.ndim == 3 else 0
        preds = np.empty((n, cols), np.int32)
        need = []  # indices not answered by the cache
        keys = [None] * n
        cache_hits = 0
        if self.cache is not None or self.disk is not None:
            for i in range(n):
                key = window_key(x[i].tobytes(), self.identity)
                keys[i] = key
                got = self.cache.get(key) if self.cache is not None else None
                if got is None and self.disk is not None:
                    got = self.disk.get(key)
                    if got is not None and got.shape == (cols,) and self.cache is not None:
                        self.cache.put(key, got)
                if got is not None and got.shape == (cols,):
                    preds[i] = got
                    cache_hits += 1
                else:
                    need.append(i)
        else:
            need = list(range(n))

        esc_local = np.empty(0, np.int64)
        t0 = time.perf_counter()
        if need:
            idx = np.asarray(need, dtype=np.int64)
            logits = self._tier_logits(x[idx])
            preds[idx] = np.argmax(logits, axis=-1).astype(np.int32)
            conf = self.calibration.confidence(logits)
            esc_local = idx[escalate_mask(conf, self.threshold)]
        dt = time.perf_counter() - t0

        with self._lock:
            self.windows += n
            self.escalated += int(len(esc_local))
            self.cache_hits += cache_hits
            self.tier1_seconds += dt
        if self.metrics is not None:
            self.metrics.observe_cascade(
                windows=n, escalated=int(len(esc_local)),
                cache_hits=cache_hits, tier1_seconds=dt,
            )
        # kept tier-1 windows are cacheable now; escalated ones after
        # their reference preds land (the future's callback)
        esc_set = set(esc_local.tolist())
        store_now = [
            (keys[i], preds[i]) for i in need
            if keys[i] is not None and i not in esc_set
        ]
        esc_keys = [keys[i] for i in esc_local.tolist()]
        self._store(store_now)
        return preds, esc_local, esc_keys

    def _store(self, pairs) -> None:
        for key, row in pairs:
            if key is None:
                continue
            if self.cache is not None:
                self.cache.put(key, row)
            if self.disk is not None:
                self.disk.put(key, row)

    def _escalated_callback(self, esc_idx, esc_keys, t_submit):
        def _cb(preds: np.ndarray) -> None:
            dt = time.perf_counter() - t_submit
            with self._lock:
                self.tier2_seconds += dt
            if self.metrics is not None:
                self.metrics.observe_cascade(tier2_seconds=dt)
            self._store(
                [(k, preds[i]) for k, i in zip(esc_keys, esc_idx.tolist())]
            )
        return _cb

    # -- entry points --------------------------------------------------------

    def submit(self, x: np.ndarray, submit_fn, trace=None) -> CascadeFuture:
        """Route one batch; ``submit_fn(x_subset, trace=...) -> future``
        is the reference tier (e.g. ``batcher.submit``). Returns a
        future resolving to the full batch's preds."""
        t0 = time.perf_counter()
        preds, esc_idx, esc_keys = self._decide(x)
        if trace is not None:
            trace.add("tier1", time.perf_counter() - t0)
        if len(esc_idx) == 0:
            return CascadeFuture(preds, esc_idx, None)
        inner = submit_fn(np.ascontiguousarray(x)[esc_idx], trace=trace)
        return CascadeFuture(
            preds, esc_idx, inner,
            self._escalated_callback(esc_idx, esc_keys, time.perf_counter()),
        )

    def predict(
        self, x: np.ndarray, submit_fn, timeout: Optional[float] = None, trace=None
    ) -> np.ndarray:
        """submit + result in one call (the HTTP handler's path)."""
        return self.submit(x, submit_fn, trace=trace).result(timeout)

    def route(
        self, x: np.ndarray, predict_fn: Callable[[np.ndarray], np.ndarray],
    ) -> np.ndarray:
        """Synchronous routing for the batch path (``run_inference``):
        ``predict_fn(x_subset) -> preds`` is the reference tier."""
        preds, esc_idx, esc_keys = self._decide(x)
        if len(esc_idx):
            t0 = time.perf_counter()
            sub = np.asarray(
                predict_fn(np.ascontiguousarray(x)[esc_idx]), dtype=np.int32
            )
            preds[esc_idx] = sub
            self._escalated_callback(esc_idx, esc_keys, t0)(preds)
        return preds

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "tier": self.tier,
                "threshold": self.threshold,
                "windows": self.windows,
                "escalated": self.escalated,
                "escalation_fraction": (
                    self.escalated / self.windows if self.windows else 0.0
                ),
                "cache_hits": self.cache_hits,
                "cache_hit_rate": (
                    self.cache_hits / self.windows if self.windows else 0.0
                ),
                "tier1_seconds": self.tier1_seconds,
                "tier2_seconds": self.tier2_seconds,
            }
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        if self.disk is not None:
            out["disk"] = self.disk.stats()
        return out


def _model_tier_logits(cascade_cfg, model_cfg, registry_dir=None):
    """Build the ``model`` tier: resolve the named registry version
    (digest-verified — PR 12), load + quantize its params, and return a
    host-side logits fn. The registered model must agree with the
    cascade's pinned expectations or resolution refuses."""
    import jax

    from roko_tpu.models.model import RokoModel
    from roko_tpu.models.quant import maybe_quantize
    from roko_tpu.serve.registry import resolve_model, resolve_registry_dir
    from roko_tpu.training.checkpoint import load_params

    name = cascade_cfg.tier_version
    if not name:
        raise ValueError(
            "cascade tier 'model' needs tier_version (a registry name)"
        )
    entry = resolve_model(resolve_registry_dir(registry_dir), name, verify=True)
    if not entry.get("params_path"):
        raise CascadeMismatch(
            "tier model", name, {"params_path": ("<absent>", "<required>")}
        )
    mcfg = entry.get("model") or {}
    import dataclasses

    tier_cfg = dataclasses.replace(
        model_cfg,
        kind=mcfg.get("kind", model_cfg.kind),
        compute_dtype=mcfg.get("compute_dtype", model_cfg.compute_dtype),
        quantize=mcfg.get("quantize"),
    )
    params = maybe_quantize(load_params(entry["params_path"]), tier_cfg)
    model = RokoModel(tier_cfg)

    @jax.jit
    def _logits(xb):
        return model.apply(params, xb, deterministic=True)

    def fn(x: np.ndarray) -> np.ndarray:
        return np.asarray(_logits(x), dtype=np.float32)

    return fn


def build_router(
    cfg,
    *,
    params,
    metrics=None,
    registry_dir: Optional[str] = None,
    threshold: Optional[float] = None,
    cache_dir: Optional[str] = None,
) -> "CascadeRouter":
    """Construct the router from ``cfg.cascade`` against the reference
    ``params`` (post-quantize — the exact tree tier 2 predicts with).
    ``threshold``/``cache_dir`` override the config (per-request /
    distpolish-coordinator knobs)."""
    from roko_tpu.cascade.cache import params_digest as _digest

    ccfg = cfg.cascade
    digest = _digest(params)
    calibration = None
    if ccfg.calibration_path:
        calibration = Calibration.load(
            ccfg.calibration_path, expect_params_digest=digest
        )
    if calibration is None:
        calibration = Calibration(
            method=ccfg.method,
            temperature=MAJORITY_TEMPERATURE if ccfg.tier == "majority" else 1.0,
        )
    elif calibration.method != ccfg.method and ccfg.method:
        # explicit config method wins over the artifact's
        calibration = Calibration(
            temperature=calibration.temperature,
            method=ccfg.method,
            params_digest=calibration.params_digest,
            fitted_on=calibration.fitted_on,
        )
    tier_fn = None
    if ccfg.tier == "model":
        tier_fn = _model_tier_logits(ccfg, cfg.model, registry_dir)
    return CascadeRouter(
        tier=ccfg.tier,
        threshold=ccfg.threshold if threshold is None else float(threshold),
        calibration=calibration,
        params_digest=digest,
        quantize=cfg.model.quantize,
        tier_version=ccfg.tier_version,
        tier_logits_fn=tier_fn,
        cache_bytes=ccfg.cache_bytes,
        cache_dir=cache_dir if cache_dir is not None else ccfg.cache_dir,
        metrics=metrics,
    )
