"""Device-mesh parallelism: mesh construction, sharding specs, and the
collective patterns (data/tensor/sequence parallel) used by training and
inference.

The reference has no distributed backend at all (SURVEY.md §2 parallelism
inventory); this package is the TPU-native runtime that replaces nothing
and adds dp/tp/sp over a `jax.sharding.Mesh` with XLA collectives riding
ICI.
"""

from roko_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_SP,
    AXIS_TP,
    data_sharding,
    make_mesh,
    mesh_shape,
    replicated_sharding,
    shard_batch,
)

__all__ = [
    "AXIS_DP",
    "AXIS_TP",
    "AXIS_SP",
    "make_mesh",
    "mesh_shape",
    "data_sharding",
    "replicated_sharding",
    "shard_batch",
]
