"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

The reference has no sequence parallelism of any kind (SURVEY.md §2
parallelism inventory; §5.7 explains why roko's 90-column windows don't
need it). The framework still ships it as a first-class capability for
the transformer variant at long context: each device holds a sequence
shard of Q/K/V, computes blockwise attention against the K/V block it
currently owns, and rotates K/V around the ring with ``lax.ppermute``
over ICI while accumulating an online (streaming) softmax — the
Liu et al. blockwise/ring-attention construction. Communication volume
per device is O(T/sp · D) per step, overlapping with the local matmul.

Exactness: the online-softmax accumulation makes the result identical
(up to float reassociation) to dense attention over the full sequence —
asserted by tests/test_ring.py on the virtual CPU mesh.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp

try:  # jax >= 0.6 exports shard_map at the top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax keeps it in experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from roko_tpu.parallel.mesh import AXIS_DP, AXIS_SP


def _ring_attention_local(
    q: jax.Array,  # [B, Tq, D] local query shard
    k: jax.Array,  # [B, Tk, D] local key shard
    v: jax.Array,  # [B, Tk, D] local value shard
    num_heads: int,
    axis_name: str,
    n_shards: int,
):
    """Runs inside shard_map: blockwise attention with K/V ring rotation.

    The ring loop is unrolled over the (static) sp extent so the last
    iteration can skip its rotation — no wasted ICI transfer — and so
    XLA can overlap each rotation with the next block's matmuls.
    """
    B, Tq, D = q.shape
    H = num_heads
    hd = D // H
    scale = 1.0 / math.sqrt(hd)

    def heads(x):  # [B,T,D] -> [B,H,T,hd]
        return x.reshape(B, -1, H, hd).transpose(0, 2, 1, 3)

    qh = heads(q) * scale
    perm = [(i, (i + 1) % n_shards) for i in range(n_shards)]

    # online softmax state
    o = jnp.zeros((B, H, Tq, hd), jnp.float32)
    m = jnp.full((B, H, Tq), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, H, Tq), jnp.float32)

    k_blk, v_blk = k, v
    for i in range(n_shards):
        kh = heads(k_blk)
        vh = heads(v_blk)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qh, kh, preferred_element_type=jnp.float32
        )  # [B,H,Tq,Tk]
        new_m = jnp.maximum(m, s.max(axis=-1))
        # rescale previous accumulators, add this block's contribution
        alpha = jnp.exp(m - new_m)  # [B,H,Tq]
        p = jnp.exp(s - new_m[..., None])
        o = o * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vh.astype(jnp.float32)
        )
        l = l * alpha + p.sum(axis=-1)
        m = new_m
        if i + 1 < n_shards:
            # rotate K/V to the next device on the ring (ICI neighbour)
            k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
            v_blk = jax.lax.ppermute(v_blk, axis_name, perm)

    out = o / l[..., None]
    return out.transpose(0, 2, 1, 3).reshape(B, Tq, D).astype(q.dtype)


def make_ring_attention(mesh: Mesh, num_heads: int):
    """Returns an ``attn_fn(q, k, v, num_heads)`` drop-in for
    roko_tpu.models.transformer.attention that shards the sequence axis
    over the mesh's ``sp`` axis and runs the ring construction. Batch
    stays sharded over ``dp`` (every axis a caller shards must appear in
    the specs, or shard_map would all-gather and replicate the work)."""
    spec = P(AXIS_DP, AXIS_SP, None)

    local = partial(
        _ring_attention_local,
        num_heads=num_heads,
        axis_name=AXIS_SP,
        n_shards=mesh.shape[AXIS_SP],
    )
    # the replication-check kwarg was renamed check_rep -> check_vma
    # across jax versions; pass whichever this jax accepts
    import inspect

    check_kw = (
        "check_vma"
        if "check_vma" in inspect.signature(shard_map).parameters
        else "check_rep"
    )
    sharded = shard_map(
        lambda q, k, v: local(q, k, v),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        **{check_kw: False},
    )

    def attn_fn(q, k, v, heads):
        assert heads == num_heads, "ring attention head count fixed at build"
        return sharded(q, k, v)

    return attn_fn
