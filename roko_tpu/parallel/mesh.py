"""Mesh construction and sharding helpers.

Axis convention (MeshConfig, roko_tpu/config.py):

- ``dp``  — data parallel: shards the window/batch axis. The workhorse:
  roko's genome-scale decomposition is window-level (SURVEY.md §5.7), so
  dp over windows *is* its sequence scaling.
- ``tp``  — tensor parallel: shards hidden dims of the transformer
  variant's matmuls.
- ``sp``  — sequence parallel: shards the pileup-column (time) axis for
  the transformer variant's ring attention.

All specs are `PartitionSpec`s over these names; `jit` with
`NamedSharding(in/out_shardings)` makes XLA insert the psum/all-gather
collectives over ICI — there is no hand-written communication outside
`roko_tpu/parallel/ring.py`.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from roko_tpu.config import MeshConfig

AXIS_DP = "dp"
AXIS_TP = "tp"
AXIS_SP = "sp"

# Random bits must not depend on how the consuming array is sharded: the
# non-partitionable threefry lowering (this jax's default) generates
# different dropout masks on a dp-only vs dp x tp mesh — the tp train
# step's loss diverged 6e-3 from the replicated run on identical inputs,
# breaking cross-mesh parity and the bit-identical resume contract.
# Partitionable threefry (the default on later jax) is sharding-invariant;
# force it here, where every mesh is built.
jax.config.update("jax_threefry_partitionable", True)


def mesh_shape(
    cfg: MeshConfig, n_devices: Optional[int] = None
) -> tuple[int, int, int]:
    """Resolve (dp, tp, sp) sizes; a -1 axis absorbs remaining devices."""
    n = n_devices if n_devices is not None else len(jax.devices())
    sizes = [cfg.dp, cfg.tp, cfg.sp]
    n_free = sizes.count(-1)
    if n_free > 1:
        raise ValueError("at most one mesh axis may be -1")
    fixed = math.prod(s for s in sizes if s != -1)
    if n_free:
        if n % fixed:
            raise ValueError(
                f"{n} devices not divisible by fixed axes product {fixed}"
            )
        sizes = [n // fixed if s == -1 else s for s in sizes]
    elif fixed != n:
        raise ValueError(f"mesh {sizes} wants {fixed} devices, have {n}")
    return tuple(sizes)  # type: ignore[return-value]


def make_mesh(
    cfg: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    cfg = cfg or MeshConfig()
    devs = list(devices) if devices is not None else jax.devices()
    dp, tp, sp = mesh_shape(cfg, len(devs))
    arr = np.array(devs).reshape(dp, tp, sp)
    return Mesh(arr, (AXIS_DP, AXIS_TP, AXIS_SP))


def data_sharding(mesh: Mesh) -> NamedSharding:
    """Batch axis sharded over dp, everything else replicated."""
    return NamedSharding(mesh, P(AXIS_DP))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch):
    """Place a host batch (pytree of arrays, leading axis = batch) onto the
    mesh sharded over dp. Batch size must divide by the dp extent."""
    sharding = data_sharding(mesh)
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def fleet_worker_slice(
    worker_index: int, num_workers: int, devices_per_worker: int
) -> "list[int]":
    """Contiguous device-id slice a fleet worker owns: worker ``i`` of
    ``n`` gets ids ``[i*k, (i+1)*k)`` for ``k = devices_per_worker`` —
    the same contiguous-slice convention ``make_mesh`` uses to reshape
    ``jax.devices()`` into axes, so neighbouring workers sit on
    ICI-adjacent chips."""
    if worker_index < 0 or worker_index >= num_workers:
        raise ValueError(
            f"worker_index {worker_index} outside fleet of {num_workers}"
        )
    if devices_per_worker < 1:
        raise ValueError("devices_per_worker must be >= 1 to pin a slice")
    first = worker_index * devices_per_worker
    return list(range(first, first + devices_per_worker))


def _default_backend() -> Optional[str]:
    """Backend name from ``JAX_PLATFORMS`` — the supervisor-side sniff
    shared by :func:`fleet_worker_env`, :func:`visible_device_count`,
    and :func:`resolve_fleet_topology`, which must all agree WITHOUT
    initialising a jax backend."""
    import os

    return (
        (os.environ.get("JAX_PLATFORMS") or "").split(",")[0].strip() or None
    )


def fleet_worker_env(
    worker_index: int,
    num_workers: int,
    devices_per_worker: int = 0,
    backend: Optional[str] = None,
) -> "dict[str, str]":
    """Environment overlay pinning one fleet worker process to its
    device slice. Pure computation — deliberately touches no jax device
    API, because the SUPERVISOR calls it and must never initialise a
    backend itself (on TPU, initialising would claim the very chips the
    workers need). The overlay must be applied before the worker
    process imports jax; the worker's default ``dp=-1`` mesh then
    absorbs exactly its visible slice.

    ``devices_per_worker == 0`` returns an empty overlay: every worker
    sees all devices (only sane on CPU, where host "devices" are
    process-local virtual constructs, not shared hardware).

    ``backend`` defaults from ``JAX_PLATFORMS``; when it cannot be
    determined, both TPU and GPU visibility vars are set — harmless on
    whichever stack is absent."""
    import os
    import re

    if devices_per_worker <= 0:
        return {}
    if backend is None:
        backend = _default_backend()
    env: "dict[str, str]" = {}
    if backend == "cpu":
        # virtual host devices are per-process: each worker simply
        # creates its own count (there is no shared id space to slice)
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        env["XLA_FLAGS"] = (
            flags
            + f" --xla_force_host_platform_device_count={devices_per_worker}"
        ).strip()
        return env
    ids = ",".join(
        str(i)
        for i in fleet_worker_slice(
            worker_index, num_workers, devices_per_worker
        )
    )
    if backend in (None, "tpu"):
        # per-chip process split: each worker's libtpu claims only its
        # chips (the multi-process-per-host convention TPU serving
        # stacks use; megacore chips count as one id here)
        env["TPU_VISIBLE_DEVICES"] = ids
    if backend in (None, "gpu", "cuda", "rocm"):
        env["CUDA_VISIBLE_DEVICES"] = ids
    return env


def visible_device_count(backend: Optional[str] = None) -> Optional[int]:
    """How many accelerator devices THIS process (or a child inheriting
    its environment) would see — computed WITHOUT touching any jax
    device API, because the fleet supervisor calls it and must never
    initialise a backend (on TPU that would claim the workers' chips).

    Sources, per backend (``backend`` defaults from ``JAX_PLATFORMS``):

    - ``cpu``: the ``--xla_force_host_platform_device_count`` XLA flag
      (jax's virtual host devices); absent = 1, jax's CPU default;
    - ``tpu``: ``TPU_VISIBLE_DEVICES`` when set, else the ``/dev/accel*``
      device nodes a TPU VM exposes (megacore chips count once, matching
      ``fleet_worker_env``'s id space);
    - ``gpu``: ``CUDA_VISIBLE_DEVICES`` when set, else ``/dev/nvidia[0-9]*``.

    Returns None when the count cannot be determined (e.g. a TPU backend
    with no local evidence) — callers must then refuse auto topology and
    ask for an explicit count rather than guess."""
    import glob
    import os
    import re

    if backend is None:
        backend = _default_backend()
    if backend == "cpu":
        m = re.search(
            r"--xla_force_host_platform_device_count=(\d+)",
            os.environ.get("XLA_FLAGS", ""),
        )
        return int(m.group(1)) if m else 1
    if backend in (None, "tpu"):
        ids = os.environ.get("TPU_VISIBLE_DEVICES")
        if ids:
            return len([t for t in ids.split(",") if t.strip()])
        accels = glob.glob("/dev/accel[0-9]*")
        if accels:
            return len(accels)
        if backend == "tpu":
            return None
    if backend in (None, "gpu", "cuda", "rocm"):
        # ROCm hosts expose HIP_VISIBLE_DEVICES, not the CUDA evidence
        for var in ("CUDA_VISIBLE_DEVICES", "HIP_VISIBLE_DEVICES"):
            ids = os.environ.get(var)
            if ids is not None:
                return len([t for t in ids.split(",") if t.strip()])
        nvidia = glob.glob("/dev/nvidia[0-9]*")
        if nvidia:
            return len(nvidia)
    return None


def resolve_fleet_topology(fleet_cfg, backend: Optional[str] = None):
    """Resolve ``--workers auto`` and refuse oversubscription; returns a
    (possibly updated) FleetConfig. Pure env/config computation (no jax)
    so the supervisor can call it before spawning anything.

    - ``workers == -1`` (auto): ``visible devices // devices_per_worker``
      (1 per worker when pinning is unset) — and pinning is turned ON
      for the resolved slice so a host is never silently oversubscribed.
      An undeterminable device count refuses with an actionable error.
    - explicit ``workers`` with ``devices_per_worker > 0``: on
      accelerator backends a worker count x mesh size exceeding the
      visible chips refuses loudly instead of letting N workers fight
      over the same silicon. On CPU the refusal does NOT apply: host
      "devices" are per-process virtual constructs — each worker child
      re-pins its own ``--xla_force_host_platform_device_count`` slice
      (``fleet_worker_env``), so there is no shared id space to
      oversubscribe."""
    import dataclasses

    fc = fleet_cfg
    if backend is None:
        backend = _default_backend()
    n = visible_device_count(backend)
    if fc.workers == -1:
        per = fc.devices_per_worker if fc.devices_per_worker > 0 else 1
        if n is None:
            raise ValueError(
                "--workers auto: cannot determine the visible device "
                "count on this host (no TPU_VISIBLE_DEVICES / "
                "/dev/accel* / CUDA_VISIBLE_DEVICES evidence); pass an "
                "explicit --workers N --devices-per-worker K"
            )
        workers = n // per
        if workers < 1:
            raise ValueError(
                f"--workers auto: {n} visible device(s) cannot host even "
                f"one worker of {per} device(s) (--devices-per-worker); "
                "reduce the per-worker mesh or pass --workers explicitly"
            )
        fc = dataclasses.replace(
            fc, workers=workers, devices_per_worker=per
        )
    if (
        backend != "cpu"
        and fc.workers > 0
        and fc.devices_per_worker > 0
        and n is not None
    ):
        need = fc.workers * fc.devices_per_worker
        if need > n:
            raise ValueError(
                f"fleet topology oversubscribes the host: {fc.workers} "
                f"worker(s) x {fc.devices_per_worker} device(s) each = "
                f"{need} > {n} visible device(s). Use --workers auto, or "
                f"at most {n // fc.devices_per_worker} worker(s) at this "
                "mesh size."
            )
    return fc


def put_replicated(tree, mesh: Mesh):
    """Replicate a host pytree over the whole mesh, multi-host safe.

    ``device_put`` onto a sharding that spans non-addressable devices
    raises on pods; ``make_array_from_process_local_data`` assembles the
    global replicated array from each process's full local copy instead
    (every process must hold identical values — true for PRNG-derived
    init and for checkpoint restores)."""
    repl = replicated_sharding(mesh)
    if jax.process_count() == 1:
        return jax.device_put(tree, repl)
    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(
            repl, np.asarray(a), np.shape(a)
        ),
        tree,
    )
