"""Tensor-parallel parameter sharding rules.

The GRU consensus model (1.1 M params) needs no tensor parallelism —
params replicate and the batch shards over ``dp`` (SURVEY.md §2
"Tensor parallel" row). The transformer variant's matmuls do shard: the
classic Megatron split — column-parallel into the attention/MLP hidden,
row-parallel back out — expressed purely as `PartitionSpec`s; XLA
inserts the all-reduces over ICI when the jitted step consumes them.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from roko_tpu.config import ModelConfig
from roko_tpu.parallel.mesh import AXIS_TP


def _repl(tree):
    return jax.tree.map(lambda _: P(), tree)


def _layer_specs() -> Dict[str, Any]:
    qkv_spec = {"kernel": P(None, AXIS_TP), "bias": P(AXIS_TP)}
    return {
        "ln1": {"scale": P(), "bias": P()},
        # column-parallel: each of q/k/v shards its output (head) axis
        "q": dict(qkv_spec),
        "k": dict(qkv_spec),
        "v": dict(qkv_spec),
        # row-parallel back to d_model; XLA all-reduces the partial sums
        "proj": {"kernel": P(AXIS_TP, None), "bias": P()},
        "ln2": {"scale": P(), "bias": P()},
        "mlp_in": {"kernel": P(None, AXIS_TP), "bias": P(AXIS_TP)},
        "mlp_out": {"kernel": P(AXIS_TP, None), "bias": P()},
    }


def param_specs(cfg: ModelConfig, params: Dict[str, Any]) -> Dict[str, Any]:
    """PartitionSpec pytree matching ``params`` from ``RokoModel.init``."""
    specs = {
        "embedding": P(),
        "fc1": {"kernel": P(), "bias": P()},
        "fc2": {"kernel": P(), "bias": P()},
        "head": {"kernel": P(), "bias": P()},
    }
    if cfg.kind in ("gru", "lingru"):
        # the recurrent families replicate over tp (dp shards the batch)
        specs[cfg.kind] = _repl(params[cfg.kind])
    else:
        n_layers = len(params["encoder"]["layers"])
        specs["encoder"] = {
            "in_proj": {"kernel": P(), "bias": P()},
            "pos_embed": P(),
            "layers": tuple(_layer_specs() for _ in range(n_layers)),
            "ln_out": {"scale": P(), "bias": P()},
        }
    return specs


def param_sharding(
    cfg: ModelConfig, params: Dict[str, Any], mesh: Mesh
) -> Dict[str, Any]:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        param_specs(cfg, params),
        is_leaf=lambda x: isinstance(x, P),
    )
