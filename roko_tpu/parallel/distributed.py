"""Multi-host initialisation for TPU pods.

The reference has no distributed backend to replace (SURVEY.md §5.8);
this is the TPU-native runtime entry: on a TPU-VM pod slice each host
calls :func:`initialize` once before any jax computation, after which
``jax.devices()`` spans the whole slice and the dp/tp/sp mesh from
``roko_tpu.parallel.mesh`` lays shardings over ICI (and DCN across
slices if a multi-slice topology is ever used). Collectives themselves
are XLA's — nothing here exchanges data.
"""

from __future__ import annotations

import os
from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialise ``jax.distributed`` when running multi-host.

    With no arguments, TPU-VM metadata autodetects the topology
    (``jax.distributed.initialize()``'s default path). Off-TPU (e.g. the
    2-process CPU test) the topology comes from ``ROKO_COORDINATOR``,
    ``ROKO_NUM_PROCESSES`` and ``ROKO_PROCESS_ID``. Returns True if
    distributed mode was initialised, False for single-host runs (no
    coordinator reachable / single process) — callers can proceed
    either way.
    """
    # Decide single-host purely from env/args BEFORE importing anything
    # that could touch jax state: even jax.process_count() initialises
    # the local backend, after which distributed init is impossible.
    explicit = coordinator_address or os.environ.get("ROKO_COORDINATOR")
    if num_processes is None and os.environ.get("ROKO_NUM_PROCESSES"):
        num_processes = int(os.environ["ROKO_NUM_PROCESSES"])
    if process_id is None and os.environ.get("ROKO_PROCESS_ID"):
        process_id = int(os.environ["ROKO_PROCESS_ID"])
    # TPU_WORKER_HOSTNAMES is set even on single-worker VMs; only a
    # comma-separated list indicates an actual pod slice
    workers = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    single_host = (
        explicit is None
        and num_processes is None
        and "," not in workers
        and os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") is None
    )
    if single_host:
        return False

    import jax

    # idempotent: train() and run_inference() both call this, and
    # re-initialising after the backend is live raises
    try:
        from jax._src.distributed import global_state as _gs

        if getattr(_gs, "client", None) is not None:
            return jax.process_count() > 1
    except ImportError:  # pragma: no cover - jax internals moved
        pass

    try:
        jax.distributed.initialize(
            coordinator_address=explicit,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as e:
        if "already" in str(e).lower():
            pass  # initialise called twice: keep the existing topology
        else:
            # e.g. called after a jax computation initialised the backend —
            # a real ordering bug at the call site; don't mask it
            raise
    return jax.process_count() > 1


def is_primary() -> bool:
    """True on the host that should write checkpoints / logs."""
    import jax

    return jax.process_index() == 0
