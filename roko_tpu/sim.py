"""Synthetic-data simulation: genomes, noisy reads with exact
alignments, and draft derivation with known truth CIGARs.

The reference has no counterpart (it ships no tests and assumes
external assemblers/aligners, SURVEY.md §4); here simulation is a
public component because everything downstream depends on it: the test
suite's fixtures (tests/helpers re-exports this module), the
feature-extraction benchmark, the end-to-end example
(examples/synthetic_e2e.py), and the verify recipe all drive the real
pipeline over data built here — no samtools/pysam/aligner needed in
the image.

Key property: reads are simulated WITH their exact alignments (errors
are introduced together with matching CIGAR ops), and a draft derived
from a truth genome carries the exact truth-to-draft CIGAR — so
self-consistent BAMs exist without running an aligner, and labels are
exact by construction (`compose_read_to_draft` re-maps truth-space
reads onto the draft the way pomoxis mini_align would align them).
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Sequence, Tuple

from roko_tpu import constants as C
from roko_tpu.io.bam import BamRecord

BASES = "ACGT"

# Effective per-position indel rates are capped here no matter how long
# the homopolymer run: beyond ~0.4 a simulated read decays into gap
# soup that no longer resembles a sequencing error profile.
_HP_RATE_CAP = 0.4


def random_seq(rng: random.Random, n: int) -> str:
    return "".join(rng.choice(BASES) for _ in range(n))


def random_genome(rng: random.Random, n: int, hp_extend: float = 0.0) -> str:
    """Random genome with geometric homopolymer run lengths: each base
    repeats with probability ``hp_extend`` per extra copy (0 = i.i.d.
    bases, which almost never produces the >=5-base runs real genomes
    carry). ``hp_extend=0.45`` gives mean run ~1.8 with runs of 8+
    appearing at genome scale — the substrate the homopolymer error
    model (``hp_indel_bias``) needs to be adversarial."""
    if hp_extend <= 0.0:
        return random_seq(rng, n)
    out: List[str] = []
    while len(out) < n:
        b = rng.choice(BASES)
        if out and out[-1] == b:  # runs are shaped by hp_extend alone
            continue
        out.append(b)
        while len(out) < n and rng.random() < hp_extend:
            out.append(b)
    return "".join(out)


def _run_lengths(seq: str) -> List[int]:
    """run[i] = length of the homopolymer run containing position i."""
    n = len(seq)
    out = [1] * n
    i = 0
    while i < n:
        j = i
        while j < n and seq[j] == seq[i]:
            j += 1
        for k in range(i, j):
            out[k] = j - i
        i = j
    return out


def _hp_factor(run_len: int, bias: float) -> float:
    return 1.0 + bias * (run_len - 1)


def mutate(
    rng: random.Random,
    seq: str,
    sub_rate: float = 0.0,
    ins_rate: float = 0.0,
    del_rate: float = 0.0,
    max_indel: int = 3,
) -> str:
    """Apply random substitutions/insertions/deletions — used to derive a
    'draft' from a 'truth' genome or noisy reads from a template."""
    out = []
    i = 0
    while i < len(seq):
        r = rng.random()
        if r < del_rate:
            i += rng.randint(1, max_indel)
            continue
        b = seq[i]
        if r < del_rate + sub_rate:
            b = rng.choice([x for x in BASES if x != seq[i]])
        out.append(b)
        if rng.random() < ins_rate:
            out.append(random_seq(rng, rng.randint(1, max_indel)))
        i += 1
    return "".join(out)


def align_to_ref(query: str, ref: str, ref_start: int) -> Tuple[int, Tuple[Tuple[int, int], ...]]:
    """Trivial gapless alignment helper: full-length M at ref_start."""
    return ref_start, ((C.CIGAR_M, len(query)),)


def make_record(
    name: str,
    tid: int,
    pos: int,
    seq: str,
    cigar: Sequence[Tuple[int, int]],
    flag: int = 0,
    mapq: int = 60,
) -> BamRecord:
    return BamRecord(
        name=name,
        flag=flag,
        tid=tid,
        pos=pos,
        mapq=mapq,
        cigar=tuple(cigar),
        seq=seq,
        qual=b"I" * len(seq),
    )


def cigar_from_string(s: str) -> Tuple[Tuple[int, int], ...]:
    """Parse '5M2I3M' into ((M,5),(I,2),(M,3))."""
    out: List[Tuple[int, int]] = []
    num = ""
    for ch in s:
        if ch.isdigit():
            num += ch
        else:
            out.append((C.CIGAR_OPS.index(ch), int(num)))
            num = ""
    return tuple(out)


def query_len_for_cigar(cigar: Sequence[Tuple[int, int]]) -> int:
    return sum(l for op, l in cigar if C.CIGAR_CONSUMES_QUERY[op])


def simulate_reads(
    rng: random.Random,
    ref: str,
    tid: int,
    coverage: int = 30,
    read_len: int = 200,
    sub_rate: float = 0.02,
    ins_rate: float = 0.01,
    del_rate: float = 0.01,
    hp_indel_bias: float = 0.0,
) -> List[BamRecord]:
    """Simulate noisy reads from `ref` with known (exact) alignments: errors
    are introduced with matching CIGAR ops, so the BAM is self-consistent
    without needing an aligner.

    ``hp_indel_bias`` turns on the homopolymer error mode (nanopore's
    dominant error class, which the uniform model underrepresents —
    VERDICT r3 missing #1): at a position inside a run of length L the
    indel rates scale by ``1 + bias*(L-1)`` (capped), and biased
    insertions EXTEND the run (same base) instead of drawing a random
    one — reproducing the run-length ambiguity that makes consensus
    polishing hard."""
    n_reads = max(1, coverage * len(ref) // read_len)
    runs = _run_lengths(ref) if hp_indel_bias > 0 else None
    records = []
    for ridx in range(n_reads):
        start = rng.randrange(0, max(1, len(ref) - read_len))
        end = min(len(ref), start + read_len)
        seq_parts: List[str] = []
        cigar: List[Tuple[int, int]] = []

        def push(op: int, length: int):
            if length <= 0:
                return
            if cigar and cigar[-1][0] == op:
                cigar[-1] = (op, cigar[-1][1] + length)
            else:
                cigar.append((op, length))

        i = start
        while i < end:
            if runs is not None:
                f = _hp_factor(runs[i], hp_indel_bias)
                del_i = min(_HP_RATE_CAP, del_rate * f)
                ins_i = min(_HP_RATE_CAP, ins_rate * f)
            else:
                del_i, ins_i = del_rate, ins_rate
            r = rng.random()
            if r < del_i and i > start:
                d = rng.randint(1, 2)
                d = min(d, end - i)
                push(C.CIGAR_D, d)
                i += d
                continue
            b = ref[i]
            if r < del_i + sub_rate:
                b = rng.choice([x for x in BASES if x != ref[i]])
            seq_parts.append(b)
            push(C.CIGAR_M, 1)
            if rng.random() < ins_i:
                if runs is not None and runs[i] > 1:
                    ins = ref[i] * rng.randint(1, 2)  # run extension
                else:
                    ins = random_seq(rng, rng.randint(1, 2))
                seq_parts.append(ins)
                push(C.CIGAR_I, len(ins))
            i += 1
        seq = "".join(seq_parts)
        if not seq:
            continue
        flag = C.FLAG_REVERSE if rng.random() < 0.5 else 0
        records.append(
            make_record(f"read{ridx}", tid, start, seq, cigar, flag=flag, mapq=60)
        )
    return records


def mutate_with_cigar(
    rng: random.Random,
    truth: str,
    sub_rate: float = 0.0,
    ins_rate: float = 0.0,
    del_rate: float = 0.0,
    max_indel: int = 2,
    hp_indel_bias: float = 0.0,
) -> Tuple[str, Tuple[Tuple[int, int], ...]]:
    """Derive a 'draft' from ``truth`` and return the exact truth-to-draft
    alignment CIGAR (query = truth, reference = draft).

    Op mapping from the edit script: a substitution stays M; dropping a
    truth base from the draft means truth has a base the draft lacks -> I
    (query-only); extra bases inserted into the draft -> D (ref-only).
    ``hp_indel_bias`` applies the homopolymer error mode (see
    :func:`simulate_reads`) — assembler drafts inherit the read error
    profile, so draft errors concentrate in runs too.
    """
    out: List[str] = []
    cigar: List[Tuple[int, int]] = []
    runs = _run_lengths(truth) if hp_indel_bias > 0 else None

    def push(op: int, length: int = 1):
        if length <= 0:
            return
        if cigar and cigar[-1][0] == op:
            cigar[-1] = (op, cigar[-1][1] + length)
        else:
            cigar.append((op, length))

    for i, ch in enumerate(truth):
        if runs is not None:
            f = _hp_factor(runs[i], hp_indel_bias)
            del_i = min(_HP_RATE_CAP, del_rate * f)
            ins_i = min(_HP_RATE_CAP, ins_rate * f)
        else:
            del_i, ins_i = del_rate, ins_rate
        r = rng.random()
        if r < del_i:  # draft lacks this truth base
            push(C.CIGAR_I)
            continue
        b = ch
        if r < del_i + sub_rate:
            b = rng.choice([x for x in BASES if x != ch])
        out.append(b)
        push(C.CIGAR_M)
        if rng.random() < ins_i:  # draft gains extra bases
            if runs is not None and runs[i] > 1:
                ins = ch * rng.randint(1, max_indel)  # run extension
            else:
                ins = random_seq(rng, rng.randint(1, max_indel))
            out.append(ins)
            push(C.CIGAR_D, len(ins))
    return "".join(out), tuple(cigar)


def truth_to_draft_map(cigar: Sequence[Tuple[int, int]]) -> List[int]:
    """Per truth position, the draft position it aligns to, or -1 for
    truth-only bases (I ops). CIGAR orientation as mutate_with_cigar."""
    t2d: List[int] = []
    d = 0
    for op, length in cigar:
        if op == C.CIGAR_M:
            for _ in range(length):
                t2d.append(d)
                d += 1
        elif op == C.CIGAR_I:  # truth-only
            t2d.extend([-1] * length)
        elif op == C.CIGAR_D:  # draft-only
            d += length
    return t2d


def compose_read_to_draft(
    read_pos_t: int,
    read_cigar: Sequence[Tuple[int, int]],
    t2d: Sequence[int],
) -> Optional[Tuple[int, Tuple[Tuple[int, int], ...]]]:
    """Re-map a read aligned to truth (at ``read_pos_t`` with
    ``read_cigar``) onto the draft via the truth->draft map.

    Returns (draft_pos, cigar) or None when the read never touches a
    mapped draft base. Leading/trailing query bases that end up unmapped
    become soft clips; draft-only bases inside the span become D.
    """
    events: List[Tuple[int, int]] = []  # (op, length) pre-merge

    def push(op: int, length: int = 1):
        if length <= 0:
            return
        if events and events[-1][0] == op:
            events[-1] = (op, events[-1][1] + length)
        else:
            events.append((op, length))

    t = read_pos_t
    start_d = None
    last_d = None

    def advance_draft(to_d: int):
        nonlocal last_d
        if last_d is not None and to_d > last_d + 1:
            push(C.CIGAR_D, to_d - last_d - 1)  # draft-only bases between
        last_d = to_d

    for op, length in read_cigar:
        if op in (C.CIGAR_M, C.CIGAR_EQ, C.CIGAR_X):
            for _ in range(length):
                d = t2d[t] if t < len(t2d) else -1
                if d < 0:
                    push(C.CIGAR_I)  # aligned to a truth-only base
                else:
                    if start_d is None:
                        start_d = d
                    advance_draft(d)
                    push(C.CIGAR_M)
                t += 1
        elif op == C.CIGAR_I:
            push(C.CIGAR_I, length)
        elif op == C.CIGAR_D:
            for _ in range(length):
                d = t2d[t] if t < len(t2d) else -1
                if d >= 0:
                    if start_d is None:
                        # deletion before any aligned base: skip, the
                        # alignment will start at the next M
                        pass
                    else:
                        advance_draft(d)
                        push(C.CIGAR_D)
                t += 1
        elif op == C.CIGAR_S:
            push(C.CIGAR_S, length)

    if start_d is None:
        return None
    # leading I (query bases before the first draft-aligned base) -> S
    out: List[Tuple[int, int]] = []
    for i, (op, length) in enumerate(events):
        if op == C.CIGAR_M:
            out.extend(events[i:])
            break
        if op in (C.CIGAR_I, C.CIGAR_S):
            out.append((C.CIGAR_S, length))
        # leading D: drop
    # trailing I/D/S run -> one S (I and S carry query bases with nowhere
    # left to align; D's are dropped — keeping one would strand a
    # deletion next to the clip, e.g. '...M D S', which SAM forbids and
    # the pileup would misread as a spurious deletion column)
    trailing_s = 0
    while out and out[-1][0] in (C.CIGAR_I, C.CIGAR_D, C.CIGAR_S):
        op, length = out.pop()
        if op != C.CIGAR_D:
            trailing_s += length
    if trailing_s:
        out.append((C.CIGAR_S, trailing_s))
    # merge any S+S introduced above
    merged: List[Tuple[int, int]] = []
    for op, length in out:
        if merged and merged[-1][0] == op:
            merged[-1] = (op, merged[-1][1] + length)
        else:
            merged.append((op, length))
    return start_d, tuple(merged)


def build_synthetic_project(
    out_dir: str,
    *,
    seed: int = 7,
    genome_len: int = 10_000,
    contig: str = "ctg",
    coverage: int = 30,
    read_len: int = 400,
    draft_sub: float = 0.005,
    draft_ins: float = 0.003,
    draft_del: float = 0.003,
    read_sub: float = 0.02,
    read_ins: float = 0.01,
    read_del: float = 0.01,
    hp_indel_bias: float = 0.0,
    hp_extend: float = 0.0,
) -> Dict[str, str]:
    """Write a complete synthetic polishing project into ``out_dir``:

    - ``truth.fasta``   — the ground-truth genome
    - ``draft.fasta``   — an error-bearing draft derived from it
    - ``reads.bam(.bai)`` — noisy truth-space reads re-mapped onto the
      draft via exact CIGAR composition (what an aligner would produce)
    - ``truth.bam(.bai)`` — the truth-to-draft alignment for training
      labels

    Returns a dict of the file paths plus the contig name. This is the
    data layer behind the end-to-end tests, the verify recipe, and
    examples/synthetic_e2e.py.

    ``hp_indel_bias`` + ``hp_extend`` switch the project to the
    homopolymer error regime: a run-rich truth genome
    (:func:`random_genome`) with indels concentrated in runs in both
    the draft and the reads — the adversarial proxy for real nanopore
    data (VERDICT r3 task 5).
    """
    import os

    from roko_tpu.io.bam import write_sorted_bam
    from roko_tpu.io.fasta import write_fasta

    rng = random.Random(seed)
    truth = random_genome(rng, genome_len, hp_extend)
    draft, cig = mutate_with_cigar(
        rng, truth, sub_rate=draft_sub, ins_rate=draft_ins, del_rate=draft_del,
        hp_indel_bias=hp_indel_bias,
    )
    t2d = truth_to_draft_map(cig)
    reads_t = simulate_reads(
        rng, truth, 0, coverage=coverage, read_len=read_len,
        sub_rate=read_sub, ins_rate=read_ins, del_rate=read_del,
        hp_indel_bias=hp_indel_bias,
    )
    reads_d = []
    for r in reads_t:
        res = compose_read_to_draft(r.pos, r.cigar, t2d)
        if res is None:
            continue
        pos_d, cigar_d = res
        reads_d.append(
            make_record(r.name, 0, pos_d, r.seq, cigar_d, flag=r.flag, mapq=60)
        )

    os.makedirs(out_dir, exist_ok=True)
    paths = {
        "truth_fasta": os.path.join(out_dir, "truth.fasta"),
        "draft_fasta": os.path.join(out_dir, "draft.fasta"),
        "reads_bam": os.path.join(out_dir, "reads.bam"),
        "truth_bam": os.path.join(out_dir, "truth.bam"),
        "contig": contig,
    }
    write_fasta(paths["truth_fasta"], [(contig, truth)])
    write_fasta(paths["draft_fasta"], [(contig, draft)])
    refs = [(contig, len(draft))]
    write_sorted_bam(paths["reads_bam"], refs, reads_d)
    write_sorted_bam(
        paths["truth_bam"], refs, [make_record("truth", 0, 0, truth, cig)]
    )
    return paths
