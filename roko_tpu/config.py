"""Typed configuration for the whole framework.

One config object replaces the reference's scattered constants and argparse
defaults (ref: include/generate.h:19-23, roko/features.py:16,
roko/rnn_model.py:10-12, roko/train.py:12-15, include/models.h:22-23).
All configs are frozen dataclasses serialisable to/from plain dicts so they
can ride along in checkpoints and HDF5 attrs.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple

from roko_tpu import constants as C


def _asdict(cfg: Any) -> Dict[str, Any]:
    return dataclasses.asdict(cfg)


@dataclass(frozen=True)
class WindowConfig:
    """Pileup window geometry (ref: include/generate.h:19-23)."""

    rows: int = C.WINDOW_ROWS
    cols: int = C.WINDOW_COLS
    stride: int = C.WINDOW_STRIDE
    max_ins: int = C.MAX_INS
    #: first ref_rows rows carry the draft base per column (GAP at
    #: insertion slots, forward-strand) — generate.cpp:109-119; the
    #: reference compiles REF_ROWS=0 and so do we
    ref_rows: int = C.REF_ROWS


@dataclass(frozen=True)
class ReadFilterConfig:
    """Pileup read filter policy (ref: include/models.h:22-23, models.cpp:25-27)."""

    min_mapq: int = C.MIN_MAPQ
    filter_flag: int = C.FILTER_FLAG
    #: paired reads must additionally be proper pairs
    require_proper_pair: bool = True


@dataclass(frozen=True)
class RegionConfig:
    """Contig -> region fan-out (ref: roko/features.py:16-27)."""

    size: int = C.REGION_SIZE
    overlap: int = C.REGION_OVERLAP


#: valid ``ModelConfig.kind`` values: "gru" is the torch-exact reference
#: recurrence, "lingru" the associative-scan linear recurrence (log-depth
#: inference, models/lingru.py), "transformer" the attention variant
MODEL_KINDS = ("gru", "lingru", "transformer")

#: valid ``ModelConfig.compute_dtype`` values. "auto" resolves per
#: backend at model construction (``default_compute_dtype``): bfloat16
#: on TPU — the matmuls ride the MXU at half the HBM operand width —
#: and float32 everywhere else (bf16 is EMULATED on CPU, slower than
#: f32). Params are always STORED float32; the dtype is the matmul
#: compute width.
COMPUTE_DTYPES = ("auto", "float32", "bfloat16")

#: valid ``ModelConfig.quantize`` values (besides None = off): "int8"
#: is conversion-time weight-only quantization of the dense/GRU/lingru
#: matmul kernels to int8 with per-output-channel float32 scales
#: (models/quant.py). Activations, biases, the embedding, logits, and
#: recurrence state stay float — int8 cuts the bytes each weight moves
#: from HBM per window by 4x, the memory-bound serving lever.
QUANTIZE_MODES = ("int8",)


def default_compute_dtype(backend: Optional[str] = None) -> str:
    """The concrete compute dtype ``compute_dtype="auto"`` resolves to
    on ``backend`` (default: the live jax backend): bfloat16 on TPU,
    float32 everywhere else. The ONE place the TPU-defaults policy
    lives — the CLI, every bench suite, and model construction all
    resolve through here."""
    if backend is None:
        import jax

        backend = jax.default_backend()
    return "bfloat16" if backend == "tpu" else "float32"


@dataclass(frozen=True)
class ModelConfig:
    """Model family + dimensions (ref: roko/rnn_model.py:10-12,24-44)."""

    kind: str = "gru"  # one of MODEL_KINDS
    embed_vocab: int = C.FEATURE_VOCAB
    #: window geometry the model consumes — kept in ModelConfig (not just
    #: WindowConfig) because it sizes fc1 and the positional table; the
    #: CLI syncs it from WindowConfig for non-default geometries
    window_rows: int = C.WINDOW_ROWS
    window_cols: int = C.WINDOW_COLS
    embed_dim: int = 50
    read_mlp: Tuple[int, ...] = (100, 10)
    hidden_size: int = 128
    num_layers: int = 3
    dropout: float = 0.2
    num_classes: int = C.NUM_CLASSES
    # transformer variant only
    d_model: int = 256
    num_heads: int = 8
    mlp_ratio: int = 4
    # compute dtype for matmuls, one of COMPUTE_DTYPES ("bfloat16" rides
    # the MXU; params stay f32). "auto" (the default) resolves per
    # backend at model construction — bf16 on TPU, f32 elsewhere
    # (default_compute_dtype); AOT bundle digests carry the RESOLVED
    # dtype, so a bf16 bundle refuses to load into an f32 session
    compute_dtype: str = "auto"
    # weight-only quantization mode, one of QUANTIZE_MODES or None.
    # CONVERSION-TIME only: training always runs full precision; the
    # params are quantized when loaded for inference/serve (or when
    # `roko-tpu compile --quantize int8` builds an AOT bundle, whose
    # digest then covers this field — models/quant.py)
    quantize: Optional[str] = None
    # use the fused Pallas kernels when running on TPU: the GRU
    # recurrence (models/pallas_gru.py) for kind="gru", the fused
    # log-depth scan (models/pallas_lingru.py) for kind="lingru".
    # Participates in the AOT bundle identity like every other model
    # field, so a pallas bundle refuses to load into a scan session.
    # Off-TPU the scan path runs instead (ROKO_PALLAS_INTERPRET=1
    # forces the interpret-mode kernels for CPU parity tests).
    use_pallas: bool = False
    # rematerialise the embed->fc2 front-end in the training backward
    # (jax.checkpoint): trades ~3 ms of recompute for ~1.8 GB of stored
    # activations + dropout masks per batch-512 step — the measured
    # train-step bottleneck is HBM residual traffic, not FLOPs
    # (BASELINE.md "training backward anomaly"). Off by default until
    # the driver-measured bench row (train_gru_remat) proves it on chip.
    remat_frontend: bool = False
    # rematerialise the GRU scan cell in the training backward
    # (jax.checkpoint on the per-step function): the scan backward
    # otherwise streams every step's gate activations (r/z/n/hp,
    # ~6 arrays per step x 90 steps) through HBM — the scan-path
    # analogue of the Pallas backward kernel's recompute-from-h
    # strategy. Off by default until the driver-measured bench row
    # (train_gru_remat_scan) proves it on chip.
    remat_scan: bool = False

    def __post_init__(self) -> None:
        # validate at construction (config layering, JSON load, CLI) so a
        # typo'd kind fails where it was written, not at first init/apply
        if self.kind not in MODEL_KINDS:
            raise ValueError(
                f"unknown model kind {self.kind!r}; expected one of "
                + "|".join(MODEL_KINDS)
            )
        if self.compute_dtype not in COMPUTE_DTYPES:
            raise ValueError(
                f"unknown compute_dtype {self.compute_dtype!r}; expected "
                "one of " + "|".join(COMPUTE_DTYPES)
            )
        if self.quantize is not None and self.quantize not in QUANTIZE_MODES:
            raise ValueError(
                f"unknown quantize mode {self.quantize!r}; expected one "
                "of " + "|".join(QUANTIZE_MODES) + " (or null/absent)"
            )
        if self.quantize is not None and self.kind == "transformer":
            raise ValueError(
                "quantize covers the gru/lingru consensus models (their "
                "dense/recurrence matmul kernels); the transformer "
                "variant has no int8 weight path"
            )

    def resolve(self, backend: Optional[str] = None) -> "ModelConfig":
        """This config with ``compute_dtype="auto"`` replaced by the
        backend's concrete default (no-op when already concrete). The
        AOT bundle identity and the model itself both resolve through
        here, so an "auto" session and an explicit-f32 session on the
        same backend share one digest."""
        if self.compute_dtype != "auto":
            return self
        return dataclasses.replace(
            self, compute_dtype=default_compute_dtype(backend)
        )

    @property
    def gru_in_size(self) -> int:
        return self.embed_dim * self.read_mlp[-1]


@dataclass(frozen=True)
class TrainConfig:
    """Optimisation hyperparameters (ref: roko/train.py:12-15)."""

    batch_size: int = 128
    epochs: int = 100
    lr: float = 1e-4
    patience: int = 7
    seed: int = 0
    #: keep the whole dataset resident in host RAM (ref: --memory flag)
    in_memory: bool = True
    #: with no --val set, hold out this fraction of the training windows
    #: for validation (seeded split) so early stopping still works;
    #: 0.0 = no split, early stopping disabled without a val set
    val_fraction: float = 0.0
    #: checkpoint directory keeps this many best checkpoints
    keep_checkpoints: int = 3
    #: number of host prefetch batches queued ahead of the device
    prefetch: int = 2
    #: in-epoch heartbeat: log rate/ETA every N steps (0 disables)
    log_every_steps: int = 200
    #: PRNG implementation for the dropout-mask stream: "threefry"
    #: (jax default, counter-based, costly mask generation on TPU) or
    #: "rbg" (hardware RNG path, much cheaper per mask). One of the
    #: levers on the train-backward anomaly (BASELINE.md): three
    #: dropout masks per step are generated inside the fwd+bwd
    #: pipeline. Training-reproducibility note: the mask stream
    #: differs between impls; resume mixes streams only if the flag is
    #: changed mid-run.
    dropout_rng_impl: str = "threefry"


@dataclass(frozen=True)
class DataConfig:
    """Deterministic sharded input data plane (roko_tpu/datapipe,
    docs/TRAINING.md "Sharded input pipeline"): a seqio-style file-set
    index with per-host shard streams that partition the global
    shuffled stream exactly, sample-granular checkpointable iterators,
    and streaming span reads with bounded host prefetch."""

    #: number of data shards the corpus splits into; 0 = auto
    #: (``jax.process_count()`` — one shard per pod host)
    shards: int = 0
    #: this process's shard; -1 = auto (``jax.process_index()``)
    shard_id: int = -1
    #: stream seed for the epoch shuffle/shard permutations; -1 = use
    #: ``TrainConfig.seed`` (the historical behavior)
    seed: int = -1
    #: span-block granularity in rows: the unit the global shuffle
    #: permutes, each host reads, and fast-forward skips
    block_size: int = 256
    #: cross-block mix-group width: each shard pools this many
    #: consecutive permuted blocks and shuffles rows across the pool,
    #: so a batch mixes up to this many random corpus regions (HDF5
    #: corpora are locality-ordered); resident rows scale with
    #: block_size * mix_blocks
    mix_blocks: int = 8
    #: bounded host readahead depth in MIX GROUPS — the producer thread
    #: keeps up to this many decoded groups (each up to
    #: ``mix_blocks * block_size`` rows) queued ahead of batching;
    #: device staging depth stays ``TrainConfig.prefetch``
    input_prefetch: int = 2
    #: pinned manifest path. None = the default sidecar next to the
    #: corpus (stale sidecars rebuild loudly; a PINNED manifest that
    #: mismatches the files refuses with the per-file diff)
    manifest: Optional[str] = None


@dataclass(frozen=True)
class MeshConfig:
    """Device mesh axes. dp shards the batch; tp shards the model
    (transformer variant); sp shards the sequence axis (ring attention)."""

    dp: int = -1  # -1 = all remaining devices
    tp: int = 1
    sp: int = 1


#: tenant id every request without an explicit ``X-Roko-Tenant`` header
#: (or client ``tenant=`` kwarg) is accounted under — unconfigured
#: single-tenant deployments keep exactly the old behavior because one
#: tenant's deficit round-robin degenerates to arrival order
DEFAULT_TENANT = "default"


@dataclass(frozen=True)
class TenantConfig:
    """One row of the tenant fair-share table (docs/SERVING.md
    "Multi-tenant & elastic fleet"): admission weight plus optional
    per-tenant caps. Unlisted tenants get ``weight=1`` and no caps, so
    the table only needs rows for tenants that differ."""

    name: str
    #: deficit-round-robin weight: each scheduler round grants a tenant
    #: ``weight``x the base share of device-slot windows (2.0 = twice
    #: the bulk tenant's share per round)
    weight: float = 1.0
    #: queued windows this tenant may hold in the batcher pool; beyond
    #: it submissions are rejected 429 + Retry-After (0 = no cap,
    #: bounded only by the global ``max_queue``)
    max_queue: int = 0
    #: concurrent in-flight REQUESTS for this tenant; beyond it 429
    #: (0 = no cap)
    max_inflight: int = 0

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("tenant name must be non-empty")
        if self.weight <= 0:
            # zero/negative weight would never accumulate deficit —
            # the DRR loop's termination proof needs weight > 0
            raise ValueError(
                f"tenant {self.name!r} weight must be > 0; got {self.weight}"
            )
        if self.max_queue < 0 or self.max_inflight < 0:
            raise ValueError(
                f"tenant {self.name!r} caps must be >= 0; got "
                f"max_queue={self.max_queue} max_inflight={self.max_inflight}"
            )


#: valid ``ServeConfig.batching`` policies: "continuous" packs windows
#: from many requests densely into ladder-rung device steps and refills
#: freed capacity the moment earlier requests complete (batch shape
#: decoupled from request boundaries — serve/scheduler.py); "deadline"
#: is the classic whole-request coalescer (serve/batcher.py), still the
#: right call for single-tenant bulk polish (docs/SERVING.md);
#: "ragged" drives the continuous packing plane but dispatches every
#: step at the TOP rung with an explicit valid-row count the device
#: masks — one executable, no padded-rung ladder, no rung-upgrade
#: heuristics (docs/SERVING.md "Ragged dispatch")
BATCHING_MODES = ("continuous", "deadline", "ragged")


@dataclass(frozen=True)
class ServeConfig:
    """Persistent polishing service (roko_tpu/serve, docs/SERVING.md)."""

    host: str = "127.0.0.1"
    port: int = 8000
    #: padded GLOBAL batch-size ladder the session pre-compiles; every
    #: dispatch pads to a rung so no request shape ever triggers a
    #: recompile. Explicit rungs are global batch sizes sharded over the
    #: mesh dp axis (each must be a positive multiple of dp). The
    #: default () = AUTO: ``ladder_base`` names the PER-DEVICE shard
    #: sizes and the session compiles global rungs of ``base * dp`` —
    #: one config drives any mesh, and the batching plane's slot count
    #: re-denominates to rung x n_devices automatically (docs/SERVING.md
    #: "Mesh-sharded sessions")
    ladder: Tuple[int, ...] = ()
    #: per-device rung shards the auto ladder scales by the mesh dp
    #: extent (ignored when ``ladder`` pins explicit global rungs)
    ladder_base: Tuple[int, ...] = (32, 128, 512)
    #: bounded request queue — submissions beyond this are rejected with
    #: a retry-after instead of growing host memory (backpressure)
    max_queue: int = 64
    #: micro-batching deadline: a partially filled batch dispatches at
    #: most this long after its first request arrived
    max_delay_ms: float = 25.0
    #: batching policy, one of BATCHING_MODES (docs/SERVING.md
    #: "Continuous batching"): "continuous" (default) decouples device
    #: batch shape from request boundaries — a 4-window request never
    #: waits behind a 512-window one; "deadline" restores the
    #: whole-request coalescer
    batching: str = "continuous"
    #: continuous mode: the oldest queued window waits at most this long
    #: before a partial batch dispatches padded (the continuous analogue
    #: of — and deliberately equal to — ``max_delay_ms``, so a lone
    #: request's latency floor never regresses vs deadline mode; until
    #: then the scheduler prefers waiting for arrivals or dispatching
    #: completely FULL smaller rungs)
    max_queue_age_ms: float = 25.0
    #: continuous mode rung-upgrade hysteresis: pending windows pad up
    #: to the next-larger ladder rung only when they would fill at least
    #: this fraction of it; below that a completely full smaller rung
    #: dispatches instead (padding efficiency over batch size)
    rung_upgrade_fill: float = 0.75
    #: seconds a rejected client is told to wait before retrying
    retry_after_s: float = 1.0
    #: per-stage latency reservoir size backing the /metrics p50/p99 rows
    latency_samples: int = 1024
    #: confine the POST /polish ref+bam convenience form (which opens
    #: server-local files named by the client) to paths under this
    #: directory; None = any readable path — acceptable on the default
    #: loopback bind, set this when binding beyond localhost
    data_root: Optional[str] = None
    #: structured event-log JSONL sink (docs/OBSERVABILITY.md): every
    #: ROKO_* event also appends one JSON record here, size-capped
    #: rotation at ``event_log_max_mb``; None = stderr lines only.
    #: Fleet workers suffix ``.w<id>`` so processes never share a file.
    event_log: Optional[str] = None
    event_log_max_mb: float = 64.0
    #: GET /tracez retention: the last N completed request traces plus
    #: a slowest-N leaderboard (bounded by construction)
    trace_ring: int = 256
    trace_slowest: int = 32
    #: tenant fair-share table (``--tenants name:weight:max_queue:
    #: max_inflight,...``); empty = single default tenant, admission
    #: behavior byte-identical to the pre-tenant scheduler
    tenants: Tuple[TenantConfig, ...] = ()

    def __post_init__(self) -> None:
        # validate at construction (config layering, JSON load, CLI) so
        # a typo'd policy fails where it was written, not at serve start
        if self.batching not in BATCHING_MODES:
            raise ValueError(
                f"unknown batching policy {self.batching!r}; expected one "
                "of " + "|".join(BATCHING_MODES)
            )
        if not 0.0 < self.rung_upgrade_fill <= 1.0:
            raise ValueError(
                "rung_upgrade_fill must lie in (0, 1]; got "
                f"{self.rung_upgrade_fill}"
            )
        if self.max_queue_age_ms < 0:
            # a negative age would make every scheduler cycle flush
            # immediately — tiny padded batches, the exact waste
            # continuous batching exists to remove
            raise ValueError(
                f"max_queue_age_ms must be >= 0; got {self.max_queue_age_ms}"
            )
        if not self.ladder_base or any(r <= 0 for r in self.ladder_base):
            raise ValueError(
                "ladder_base must name at least one positive per-device "
                f"rung size; got {self.ladder_base}"
            )
        if self.trace_ring < 1 or self.trace_slowest < 1:
            raise ValueError(
                "trace_ring/trace_slowest must be >= 1; got "
                f"{self.trace_ring}/{self.trace_slowest}"
            )
        names = [t.name for t in self.tenants]
        if len(names) != len(set(names)):
            dupes = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate tenant names: {dupes}")

    def tenant_table(self) -> Dict[str, "TenantConfig"]:
        """``{name: TenantConfig}`` lookup — unlisted tenants fall back
        to ``TenantConfig(name, weight=1.0)`` at the call site."""
        return {t.name: t for t in self.tenants}


def resolve_ladder(serve: "ServeConfig", dp: int) -> Tuple[int, ...]:
    """The GLOBAL rung ladder a session on a ``dp``-wide mesh compiles:
    explicit ``serve.ladder`` rungs pass through (sorted, deduped —
    validity against dp is the session's/exporter's job, where the mesh
    is known), and the auto default scales each per-device
    ``serve.ladder_base`` rung by dp. The ONE place ladder-vs-mesh
    denomination lives — PolishSession, the AOT bundle exporter, and the
    batch/streaming tail-rung paths all resolve through here."""
    if dp < 1:
        raise ValueError(f"mesh dp axis must be >= 1; got {dp}")
    if serve.ladder:
        return tuple(sorted(set(serve.ladder)))
    return tuple(sorted({r * dp for r in serve.ladder_base}))


def validate_ladder(rungs, dp: int, *, flag: str = "--ladder") -> None:
    """Refuse global rungs that cannot shard over the dp mesh axis,
    naming the axis and suggesting the nearest valid rungs (a bare
    "bad list" error sent operators to the source). Shared by
    PolishSession and the AOT bundle exporter so the CLI surfaces one
    message everywhere."""
    bad = [r for r in rungs if r <= 0 or r % dp]
    if not bad:
        return
    def nearest(r: int) -> str:
        lo = (r // dp) * dp
        hi = lo + dp
        # non-positive rungs have no neighbour below: suggest dp itself
        opts = [v for v in (lo, hi) if v > 0] or [dp]
        return f"{r} -> " + " or ".join(str(v) for v in dict.fromkeys(opts))
    raise ValueError(
        f"ladder rungs {bad} are not positive multiples of the mesh dp "
        f"axis (dp={dp}): a global rung shards rung/dp windows onto "
        f"each of the dp devices. Nearest valid: "
        + "; ".join(nearest(r) for r in sorted(bad))
        + f". Pick multiples of dp, or leave {flag} unset to auto-scale "
        "the per-device base ladder by dp."
    )


@dataclass(frozen=True)
class FleetConfig:
    """Multi-worker serving tier (roko_tpu/serve/fleet.py +
    supervisor.py; docs/SERVING.md "Multi-worker topology & failure
    handling"): a supervising front end forks ``workers`` serve
    processes, each pinned to a device slice, and routes around
    crashed/hung/breaker-tripped workers."""

    #: worker process count; 0 = classic single-process `roko-tpu serve`
    #: (no supervisor, no fleet); -1 = AUTO (`--workers auto`): visible
    #: devices / devices-per-worker (1 when unset), resolved by the
    #: supervisor via ``parallel.mesh.visible_device_count`` WITHOUT
    #: initialising a jax backend — a host is never silently
    #: oversubscribed (docs/SERVING.md "Mesh-sharded sessions")
    workers: int = 0
    #: devices each worker may see (visible-device pinning via
    #: ``parallel.mesh.fleet_worker_env``); 0 = no pinning — every
    #: worker sees all devices (only sane on CPU, where "devices" are
    #: process-local virtual ones)
    devices_per_worker: int = 0
    #: supervisor heartbeat cadence: seconds between /healthz probes of
    #: each worker (liveness AND readiness ride the same probe)
    heartbeat_interval_s: float = 2.0
    #: per-probe HTTP timeout; an unanswered probe is a missed heartbeat
    heartbeat_timeout_s: float = 5.0
    #: consecutive missed heartbeats after which a worker is declared
    #: hung and killed (SIGTERM, then SIGKILL after ``term_grace_s``)
    heartbeat_misses: int = 3
    #: seconds a fresh worker gets to bind its socket and announce its
    #: port (warmup has its own budget: a warming worker answers
    #: /healthz 503 "warming", which counts as a heartbeat)
    spawn_deadline_s: float = 120.0
    #: SIGTERM -> SIGKILL escalation grace for hung/drained workers
    term_grace_s: float = 10.0
    #: restart backoff: delay before restart k is
    #: ``restart_base_delay_s * 2**(k-1)`` capped at
    #: ``restart_max_delay_s`` (shared RetryPolicy shape + jitter)
    restart_base_delay_s: float = 0.5
    restart_max_delay_s: float = 30.0
    #: restart-storm circuit breaker: this many restarts without an
    #: intervening stable period mark the worker FAILED (the fleet
    #: degrades instead of flapping); after ``storm_reset_s`` one
    #: half-open probe restart is admitted
    storm_threshold: int = 5
    storm_reset_s: float = 60.0
    #: seconds a restarted worker must stay in rotation before its
    #: restart-storm breaker records success and the backoff resets
    stable_after_s: float = 30.0
    #: distinct workers one request may be routed to before the front
    #: end gives up with 503 (failover: a worker dying mid-request is
    #: retried transparently — polish is idempotent)
    failover_attempts: int = 3
    #: front-end admission control: concurrent in-flight requests
    #: beyond this are shed with 503 + Retry-After; 0 = workers x
    #: serve.max_queue
    max_inflight: int = 0
    #: worker logs + port-announce files live here; None = a
    #: ``roko-fleet-<pid>`` directory under the system tmpdir (where CI
    #: failure dumps look for surviving-worker stderr). The rollout
    #: journal lives here too — pin this for rollout crash recovery to
    #: survive a supervisor restart (docs/SERVING.md "Model lifecycle")
    runtime_dir: Optional[str] = None
    #: model registry directory for `roko-tpu rollout` (named version ->
    #: AOT bundle digest + params manifest, serve/registry.py); None =
    #: ~/.cache/roko-tpu/registry, env ROKO_REGISTRY overrides both
    registry_dir: Optional[str] = None
    #: rollout canary bake: seconds a freshly rolled worker must hold a
    #: CONTIGUOUS healthy (in-rotation) stretch before the next worker
    #: is touched; the canary gate is judged over this window
    bake_s: float = 15.0
    #: rollback trigger: canary error percentage over the bake window
    #: beyond this (and beyond the incumbent baseline) rolls the fleet
    #: back to the incumbent version
    rollback_error_pct: float = 2.0
    #: rollback trigger: canary p99 beyond this multiple of the
    #: incumbent's pre-rollout p99 rolls back
    rollback_p99_x: float = 3.0
    #: seconds a rolled worker gets to re-enter rotation (spawn + AOT
    #: re-warm) before the rollout gives up and rolls back; generous —
    #: a cold compile on a bundleless config legitimately takes minutes
    rollout_ready_timeout_s: float = 900.0
    #: backlog-driven autoscaling bounds (docs/SERVING.md "Multi-tenant
    #: & elastic fleet"): worker count floats in [min_workers,
    #: max_workers]. Both 0 = autoscaler off (static ``workers`` fleet).
    #: min_workers 0 with max set defaults the floor to ``workers``.
    min_workers: int = 0
    max_workers: int = 0
    #: autoscaler control-loop cadence in seconds
    autoscale_interval_s: float = 1.0
    #: scale UP one worker when the smoothed backlog-per-worker exceeds
    #: this many windows (and cooldown has passed)
    autoscale_up_backlog: float = 32.0
    #: scale DOWN is armed only while smoothed backlog-per-worker stays
    #: at or below this — deliberately far under the up threshold
    #: (hysteresis band) so oscillating load cannot flap the fleet
    autoscale_down_backlog: float = 4.0
    #: continuous seconds the backlog must stay under the down
    #: threshold before ONE worker retires (the sustained-idle rule;
    #: the stretch re-arms after every step down)
    autoscale_idle_s: float = 10.0
    #: minimum seconds between scale-up steps (a spike adds workers
    #: one spawn-latency at a time, not all at once)
    autoscale_cooldown_s: float = 3.0
    #: EMA decay for the backlog-per-worker signal (weight on the
    #: PREVIOUS smoothed value; smaller = twitchier)
    autoscale_ema_beta: float = 0.5
    #: A/B candidate lane (``--ab-lane NAME:FRACTION``): registry
    #: version name a fraction of UNPINNED traffic routes to, with
    #: per-model latency histograms side by side in /metrics
    ab_version: Optional[str] = None
    ab_fraction: float = 0.0
    #: federation (docs/SERVING.md "Multi-host federation"): this
    #: host's stable identity at the front end's registry. Empty =
    #: derived from the agent pid (fine for loopback tests, set it for
    #: real deployments so re-registration after a crash bumps the
    #: SAME host's epoch instead of minting a new host)
    host_id: Optional[str] = None
    #: federation front end to join as ``HOST:PORT`` (set by
    #: ``--join``); non-empty turns ``roko-tpu serve`` into a host
    #: agent
    join: Optional[str] = None
    #: registration lease TTL in seconds: the agent renews every
    #: ttl/3; a lease that expires (partitioned or dead agent) leaves
    #: rotation until the agent re-registers — which bumps the epoch
    #: and fences the old one
    lease_ttl_s: float = 10.0
    #: per-host circuit breaker: consecutive connection failures that
    #: open it, and seconds until a half-open probe
    fed_breaker_failures: int = 3
    fed_breaker_reset_s: float = 10.0

    def __post_init__(self) -> None:
        if self.min_workers < 0 or self.max_workers < 0:
            raise ValueError(
                "min_workers/max_workers must be >= 0; got "
                f"{self.min_workers}/{self.max_workers}"
            )
        if self.max_workers and self.min_workers > self.max_workers:
            raise ValueError(
                f"min_workers ({self.min_workers}) exceeds max_workers "
                f"({self.max_workers})"
            )
        if not 0.0 <= self.ab_fraction <= 1.0:
            raise ValueError(
                f"ab_fraction must lie in [0, 1]; got {self.ab_fraction}"
            )
        if self.ab_fraction > 0 and not self.ab_version:
            raise ValueError(
                "ab_fraction > 0 needs ab_version (a registry name)"
            )
        if self.autoscale_down_backlog > self.autoscale_up_backlog:
            raise ValueError(
                "autoscale_down_backlog must not exceed "
                "autoscale_up_backlog (the hysteresis band); got "
                f"{self.autoscale_down_backlog} > {self.autoscale_up_backlog}"
            )
        if not 0.0 <= self.autoscale_ema_beta < 1.0:
            raise ValueError(
                f"autoscale_ema_beta must lie in [0, 1); got "
                f"{self.autoscale_ema_beta}"
            )
        if self.lease_ttl_s <= 0:
            raise ValueError(
                f"lease_ttl_s must be > 0; got {self.lease_ttl_s}"
            )
        if self.fed_breaker_failures < 1:
            raise ValueError(
                "fed_breaker_failures must be >= 1; got "
                f"{self.fed_breaker_failures}"
            )


@dataclass(frozen=True)
class PipelineConfig:
    """Streaming polish engine (roko_tpu/pipeline, docs/PIPELINE.md):
    feature extraction, host batching, and device inference run as one
    overlapped pipeline instead of serial stages sharing an HDF5."""

    #: bounded region-result queue depth (in region blocks, each ~a few
    #: thousand windows). Full queue blocks the extraction workers —
    #: explicit backpressure instead of unbounded host memory growth.
    queue_regions: int = 8
    #: host batcher deadline: a partially filled device batch dispatches
    #: at most this long after its first window arrived while the region
    #: queue is empty, so a slow extractor cannot park windows forever.
    #: Partial batches pad to the serve ladder, never a novel shape.
    max_batch_delay_ms: float = 250.0
    #: device prefetch depth: batches staged ahead of the predict step
    #: (the former overload of the features --t flag; now its own knob)
    prefetch: int = 2


@dataclass(frozen=True)
class DistPolishConfig:
    """Distributed polish over the worker fleet (``roko-tpu polish
    --distributed``; roko_tpu/pipeline/distpolish.py, docs/PIPELINE.md
    "Distributed polish"): a whole-genome job splits into per-contig
    work units (giant contigs into region-aligned block spans),
    dispatched across fleet workers with per-unit commit/retry through
    the crash-resume journal — a killed worker costs one unit's re-run
    and the output stays byte-identical to single-process polish."""

    #: contigs longer than this split into multiple span units at the
    #: deterministic extraction-region boundaries (the same span table
    #: the single-process fan-out walks, so the union of the units'
    #: windows is exactly the single-process window set); span units
    #: return raw predictions and the coordinator votes + stitches.
    #: 0 = whole-contig units only
    unit_bases: int = 1_000_000
    #: distinct dispatch attempts one unit gets (each on a worker not
    #: yet excluded for it) before it is QUARANTINED and the job fails
    #: loudly naming the contig — never a silent gap in the FASTA
    unit_attempts: int = 3
    #: hard cap on units in flight across the fleet; 0 = auto
    #: (``inflight_per_worker`` x worker count)
    max_inflight_units: int = 0
    #: units in flight per READY worker — the live limit degrades with
    #: the fleet (a 2-of-4-ready fleet carries half the units) instead
    #: of failing the job
    inflight_per_worker: int = 2
    #: hard deadline on one unit's dispatch round-trip (extraction +
    #: predict + stitch on the worker). The watchdog shape: on expiry
    #: the attempt fails LOUDLY and re-dispatches — never a silent
    #: park behind a hung worker (the fleet's heartbeat supervision
    #: kills the hang independently)
    unit_timeout_s: float = 600.0
    #: scheduler poll cadence while parked (fleet draining, no ready
    #: workers, or every pending unit in backoff)
    park_poll_s: float = 0.25
    #: seconds to wait for the first worker to warm before the job
    #: refuses to start (and for a fully-unready fleet mid-job before
    #: the coordinator gives up)
    ready_timeout_s: float = 600.0

    def __post_init__(self) -> None:
        if self.unit_attempts < 1:
            raise ValueError(
                f"unit_attempts must be >= 1; got {self.unit_attempts}"
            )
        if self.inflight_per_worker < 1:
            raise ValueError(
                "inflight_per_worker must be >= 1; got "
                f"{self.inflight_per_worker}"
            )


@dataclass(frozen=True)
class CompileConfig:
    """Cold-start elimination (roko_tpu/compile; docs/SERVING.md
    "Cold start & compile cache"): persistent XLA compilation cache,
    AOT executable bundles, and parallel ladder warmup."""

    #: persistent compilation cache on/off (the documented opt-out is
    #: this flag, ``--no-compile-cache``, or ``ROKO_COMPILE_CACHE=off``;
    #: the env var overrides everything here)
    enabled: bool = True
    #: cache directory; None = ``~/.cache/roko-tpu/xla-cache``
    cache_dir: Optional[str] = None
    #: LRU size budget for the cache dir in MiB (jax evicts least-
    #: recently-used entries past it); <= 0 = unbounded
    cache_max_mb: int = 1024
    #: only cache compiles slower than this (0 = cache everything — a
    #: serve ladder is many small programs and cold start pays them all)
    min_compile_time_s: float = 0.0
    #: AOT bundle directory (written by ``roko-tpu compile``) to load
    #: executables from instead of compiling; a digest mismatch refuses
    #: loudly. None = compile (through the persistent cache).
    bundle_dir: Optional[str] = None
    #: compile ladder rungs concurrently during warmup (XLA compilation
    #: releases the GIL); False = the old serial loop
    parallel_warmup: bool = True
    #: warmup thread cap; 0 = min(len(ladder), host cores)
    warmup_workers: int = 0


@dataclass(frozen=True)
class ResilienceConfig:
    """Failure-handling knobs shared by pipeline, serve, and bench
    (roko_tpu/resilience; docs/PIPELINE.md + docs/SERVING.md
    "Failure handling")."""

    #: hard deadline on one device compile/predict call — on expiry the
    #: watchdog dumps every thread stack and raises instead of hanging
    #: forever (the r5 wedge signature: devices answer, the first XLA
    #: compile never returns). 0 disables the watchdog entirely.
    predict_deadline_s: float = 600.0
    #: separate (much larger) deadline for the FIRST dispatch of each
    #: padded batch shape — warmup and cold-cache compiles are
    #: legitimately slow, and under the single predict budget a cold
    #: XLA compile could masquerade as a device hang. 0 disables the
    #: watchdog for first dispatches.
    compile_deadline_s: float = 1800.0
    #: what a blown predict deadline does next: "none" propagates the
    #: HangError (the CLI exits nonzero), "cpu" recompiles the predict
    #: step on the host CPU and finishes the run there — degraded
    #: throughput, completed output
    hang_fallback: str = "none"
    #: serve: consecutive device failures that trip the circuit breaker
    #: (healthz goes unhealthy, /polish sheds load with 503+Retry-After)
    breaker_failures: int = 5
    #: serve: seconds an open breaker waits before half-open probing
    breaker_reset_s: float = 30.0
    #: serve: SIGTERM drain deadline — seconds in-flight requests get
    #: to finish before the process exits anyway
    drain_deadline_s: float = 20.0


@dataclass(frozen=True)
class GuardConfig:
    """Bulletproof-training sentinel (roko_tpu/training/guard.py,
    docs/TRAINING.md "Failure handling"): NaN/Inf and loss-spike
    detection with update-skip and checkpoint rollback, plus the
    step-granular checkpoint cadence."""

    #: sentinel switch — False restores the fused train step (no
    #: per-step host sync, no skip/rollback). ``save_every_steps`` is
    #: independent of it: step-granular checkpoints work either way.
    enabled: bool = True
    #: a loss further than this many EMA standard deviations ABOVE the
    #: loss EMA is a spike: the update is skipped (one-sided — fast
    #: improvement is never penalised)
    spike_sigma: float = 6.0
    #: decay of the loss EMA and its variance EMA
    ema_beta: float = 0.98
    #: good steps of EMA history required before spike detection arms
    #: (non-finite detection is armed from step 0)
    warmup_steps: int = 20
    #: consecutive bad (skipped) steps that trigger a rollback to the
    #: last good checkpoint with a re-jittered dropout RNG stream
    max_bad_steps: int = 3
    #: rollbacks after which the run gives up loudly (a deterministic
    #: fault replays identically; re-jittering only helps transients)
    max_rollbacks: int = 3
    #: ALSO checkpoint (latest-only, not best-k) every N optimiser
    #: steps inside an epoch, carrying the data-pipeline position so
    #: --resume replays from exactly that batch; 0 = epoch-boundary
    #: checkpoints only
    save_every_steps: int = 0
    #: structured event-log JSONL sink for TRAINING runs
    #: (docs/OBSERVABILITY.md): every ROKO_GUARD skip/rollback/
    #: ckpt-integrity event also appends one JSON record here,
    #: size-capped rotation at ``event_log_max_mb``; None = stderr only
    event_log: Optional[str] = None
    event_log_max_mb: float = 64.0


@dataclass(frozen=True)
class CascadeConfig:
    """Adaptive compute (roko_tpu/cascade; docs/SERVING.md "Adaptive
    compute"): route every window through a cheap tier first, escalate
    only the uncertain rest to the reference model, and answer repeated
    windows from a content-addressed cache."""

    #: master switch — False keeps the plain single-tier path everywhere
    enabled: bool = False
    #: tier-1 kind: "majority" (the pileup majority vote, host-side,
    #: zero device cost) or "model" (a named registry version)
    tier: str = "majority"
    #: registry version name for ``tier="model"`` (PR 12 registry;
    #: resolution re-verifies bundle + params digests)
    tier_version: Optional[str] = None
    #: escalation knob, pinned at both ends: windows with calibrated
    #: confidence <= 1 - threshold escalate. 0 escalates EVERYTHING
    #: (output byte-identical to the plain path — the identity gate);
    #: 1 escalates nothing. The useful range is SMALL values: the
    #: keep-floor is 1 - threshold, so 0.05 keeps only windows whose
    #: weakest column is >= 0.95 confident (max_softmax is bounded
    #: below by 1/NUM_CLASSES and margin by 0.5, so thresholds past
    #: those bounds can never escalate — 0.05 holds held-out Q at the
    #: reference on the sim gate while escalating ~16%).
    threshold: float = 0.05
    #: confidence function: "max_softmax" or "margin" (top-2 logit gap)
    method: str = "max_softmax"
    #: temperature-scaling artifact (JSON beside the checkpoint
    #: manifest); None = the tier default (MAJORITY_TEMPERATURE for
    #: raw count-logits, 1.0 for the model tier)
    calibration_path: Optional[str] = None
    #: in-memory LRU byte cap for the window cache; 0 disables it
    cache_bytes: int = 64 * 2**20
    #: on-disk sidecar directory a distpolish fleet shares (identity-
    #: pinned via meta.json); None = in-memory only
    cache_dir: Optional[str] = None

    def __post_init__(self):
        if self.tier not in ("majority", "model"):
            raise ValueError(
                f"cascade.tier must be 'majority' or 'model', got {self.tier!r}"
            )
        if self.method not in ("max_softmax", "margin"):
            raise ValueError(
                f"cascade.method must be 'max_softmax' or 'margin', "
                f"got {self.method!r}"
            )
        if not 0.0 <= self.threshold <= 1.0:
            raise ValueError(
                f"cascade.threshold must lie in [0, 1], got {self.threshold}"
            )
        if self.cache_bytes < 0:
            raise ValueError(
                f"cascade.cache_bytes must be >= 0, got {self.cache_bytes}"
            )
        if self.tier == "model" and not self.tier_version:
            raise ValueError(
                "cascade.tier='model' needs cascade.tier_version "
                "(a model-registry name)"
            )


@dataclass(frozen=True)
class StoreConfig:
    """Hardened object-store data plane (roko_tpu/datapipe/store.py,
    docs/STORAGE.md): ranged reads through a checksummed block cache,
    retry/hedge/breaker around every request, read-verify-commit
    uploads. ``gs://``/``s3://`` URLs resolve through ``endpoint`` (or
    ``ROKO_STORE_ENDPOINT``); fault injection is env-only
    (``ROKO_STORE_FAULTS``)."""

    #: on-disk block/object cache directory (``--store-cache``); None =
    #: no persistent cache (remote reads are still correct, just colder)
    cache_dir: Optional[str] = None
    #: block-cache eviction cap in bytes (LRU past it)
    cache_bytes: int = 256 * 2**20
    #: ranged-read granularity — the unit cached and checksummed
    block_bytes: int = 4 * 2**20
    #: per-request socket timeout
    timeout_s: float = 30.0
    #: total attempts per request (shared RetryPolicy; 1 = no retries)
    max_attempts: int = 4
    #: seconds before a straggling ranged read gets a hedged second
    #: request racing it; 0 disables hedging
    hedge_s: float = 0.0
    #: consecutive endpoint failures that trip its circuit breaker
    breaker_failures: int = 5
    #: seconds an open breaker waits before half-open probing
    breaker_reset_s: float = 30.0
    #: HTTP(S) gateway prefix for gs://-/s3://-scheme URLs
    endpoint: Optional[str] = None

    def __post_init__(self):
        if self.cache_bytes < 0:
            raise ValueError(
                f"store.cache_bytes must be >= 0, got {self.cache_bytes}"
            )
        if self.block_bytes < 1:
            raise ValueError(
                f"store.block_bytes must be >= 1, got {self.block_bytes}"
            )
        if self.max_attempts < 1:
            raise ValueError(
                f"store.max_attempts must be >= 1, got {self.max_attempts}"
            )
        if self.hedge_s < 0:
            raise ValueError(
                f"store.hedge_s must be >= 0, got {self.hedge_s}"
            )
        if self.breaker_failures < 1:
            raise ValueError(
                "store.breaker_failures must be >= 1, got "
                f"{self.breaker_failures}"
            )


@dataclass(frozen=True)
class RokoConfig:
    window: WindowConfig = field(default_factory=WindowConfig)
    read_filter: ReadFilterConfig = field(default_factory=ReadFilterConfig)
    region: RegionConfig = field(default_factory=RegionConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    data: DataConfig = field(default_factory=DataConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    pipeline: PipelineConfig = field(default_factory=PipelineConfig)
    distpolish: DistPolishConfig = field(default_factory=DistPolishConfig)
    resilience: ResilienceConfig = field(default_factory=ResilienceConfig)
    compile: CompileConfig = field(default_factory=CompileConfig)
    guard: GuardConfig = field(default_factory=GuardConfig)
    cascade: CascadeConfig = field(default_factory=CascadeConfig)
    store: StoreConfig = field(default_factory=StoreConfig)

    def to_json(self) -> str:
        return json.dumps(_asdict(self), indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "RokoConfig":
        raw = json.loads(text)
        return RokoConfig(
            window=WindowConfig(**raw.get("window", {})),
            read_filter=ReadFilterConfig(**raw.get("read_filter", {})),
            region=RegionConfig(**raw.get("region", {})),
            model=ModelConfig(**{k: tuple(v) if k == "read_mlp" else v
                                 for k, v in raw.get("model", {}).items()}),
            train=TrainConfig(**raw.get("train", {})),
            data=DataConfig(**raw.get("data", {})),
            mesh=MeshConfig(**raw.get("mesh", {})),
            serve=ServeConfig(**{
                k: (tuple(v) if k in ("ladder", "ladder_base")
                    else tuple(TenantConfig(**t) for t in v)
                    if k == "tenants" else v)
                for k, v in raw.get("serve", {}).items()
            }),
            fleet=FleetConfig(**raw.get("fleet", {})),
            pipeline=PipelineConfig(**raw.get("pipeline", {})),
            distpolish=DistPolishConfig(**raw.get("distpolish", {})),
            resilience=ResilienceConfig(**raw.get("resilience", {})),
            compile=CompileConfig(**raw.get("compile", {})),
            guard=GuardConfig(**raw.get("guard", {})),
            cascade=CascadeConfig(**raw.get("cascade", {})),
            store=StoreConfig(**raw.get("store", {})),
        )


def default_config(model_kind: str = "gru", **model_overrides: Any) -> RokoConfig:
    return RokoConfig(model=ModelConfig(kind=model_kind, **model_overrides))
