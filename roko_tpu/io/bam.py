"""BAM reader/writer with BAI linear-index region queries.

Implements the BAM binary format (SAM spec §4) directly over
:mod:`roko_tpu.io.bgzf` — no htslib. Provides what the framework needs:

- :class:`BamReader` — header parse, sequential iteration, and
  ``fetch(contig, start, end)`` region queries using the BAI linear index
  (replaces htslib's ``sam_itr_querys`` used at ref: models.cpp:77 and the
  pysam ``fetch`` used at ref: roko/labels.py:38);
- :class:`BamRecord` — flags/cigar/seq accessors plus
  :meth:`BamRecord.get_aligned_pairs` with pysam-compatible semantics
  (insertions AND soft-clips yield ``(qpos, None)``; deletions and ref
  skips yield ``(None, rpos)``) as consumed by ref: roko/labels.py:135;
- :class:`BamWriter` — writes coordinate-sorted BAM plus a ``.bai`` index
  (used by the test fixtures and the read simulator).
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from roko_tpu import constants as C
from roko_tpu.io.bgzf import BgzfReader, BgzfWriter

_BAM_MAGIC = b"BAM\x01"
_BAI_MAGIC = b"BAI\x01"

#: BAM 4-bit seq codes: "=ACMGRSVTWYHKDBN"
_SEQ_CODES = "=ACMGRSVTWYHKDBN"
_CHAR_TO_NIBBLE = {c: i for i, c in enumerate(_SEQ_CODES)}
for _c in "acgtn":
    _CHAR_TO_NIBBLE[_c] = _CHAR_TO_NIBBLE[_c.upper()]

#: linear-index interval width (16 kb, SAM spec §5.1.3)
_LINEAR_SHIFT = 14


def reg2bin(beg: int, end: int) -> int:
    """Compute the BAI distributed bin for a [beg, end) interval
    (SAM spec §5.3)."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def reg2bins(beg: int, end: int) -> List[int]:
    """All bins that may hold records overlapping [beg, end)
    (SAM spec §5.3 list-of-bins recurrence)."""
    end -= 1
    bins = [0]
    for base, shift in (
        (1, 26), (9, 23), (73, 20), (585, 17), (4681, 14)
    ):
        bins.extend(range(base + (beg >> shift), base + (end >> shift) + 1))
    return bins


@dataclass
class BamRecord:
    name: str
    flag: int
    tid: int
    pos: int  # 0-based leftmost coordinate
    mapq: int
    cigar: Tuple[Tuple[int, int], ...]  # (op, length) with op in 0..8
    seq: str
    qual: bytes
    next_tid: int = -1
    next_pos: int = -1
    tlen: int = 0
    tags: bytes = b""

    # -- derived ------------------------------------------------------------
    @property
    def is_unmapped(self) -> bool:
        return bool(self.flag & C.FLAG_UNMAP)

    @property
    def is_secondary(self) -> bool:
        return bool(self.flag & C.FLAG_SECONDARY)

    @property
    def is_reverse(self) -> bool:
        return bool(self.flag & C.FLAG_REVERSE)

    @property
    def reference_start(self) -> int:
        return self.pos

    @property
    def reference_end(self) -> int:
        """One past the last aligned reference position (htslib
        ``bam_endpos``: pos+1 when the cigar consumes no reference)."""
        n = sum(l for op, l in self.cigar if C.CIGAR_CONSUMES_REF[op])
        return self.pos + n if n > 0 else self.pos + 1

    @property
    def reference_length(self) -> int:
        return self.reference_end - self.reference_start

    @property
    def query_sequence(self) -> Optional[str]:
        return self.seq if self.seq else None

    def get_aligned_pairs(self) -> List[Tuple[Optional[int], Optional[int]]]:
        """pysam-compatible aligned pairs: M/=/X -> (qpos, rpos);
        I and S -> (qpos, None); D and N -> (None, rpos); H/P -> nothing."""
        pairs: List[Tuple[Optional[int], Optional[int]]] = []
        qpos, rpos = 0, self.pos
        for op, length in self.cigar:
            if op in (C.CIGAR_M, C.CIGAR_EQ, C.CIGAR_X):
                for i in range(length):
                    pairs.append((qpos + i, rpos + i))
                qpos += length
                rpos += length
            elif op in (C.CIGAR_I, C.CIGAR_S):
                for i in range(length):
                    pairs.append((qpos + i, None))
                qpos += length
            elif op in (C.CIGAR_D, C.CIGAR_N):
                for i in range(length):
                    pairs.append((None, rpos + i))
                rpos += length
            # H, P: consume nothing visible
        return pairs

    def overlaps(self, start: int, end: int) -> bool:
        return self.pos < end and self.reference_end > start


def _encode_record(rec: BamRecord) -> bytes:
    name_b = rec.name.encode() + b"\x00"
    n_cigar = len(rec.cigar)
    l_seq = len(rec.seq)
    bin_ = reg2bin(rec.pos, rec.reference_end)
    fixed = struct.pack(
        "<iiBBHHHiiii",
        rec.tid,
        rec.pos,
        len(name_b),
        rec.mapq,
        bin_,
        n_cigar,
        rec.flag,
        l_seq,
        rec.next_tid,
        rec.next_pos,
        rec.tlen,
    )
    cigar_b = b"".join(
        struct.pack("<I", (length << 4) | op) for op, length in rec.cigar
    )
    seq_b = bytearray()
    for i in range(0, l_seq, 2):
        hi = _CHAR_TO_NIBBLE.get(rec.seq[i], 15)
        lo = _CHAR_TO_NIBBLE.get(rec.seq[i + 1], 15) if i + 1 < l_seq else 0
        seq_b.append((hi << 4) | lo)
    qual_b = rec.qual if len(rec.qual) == l_seq else b"\xff" * l_seq
    body = fixed + name_b + cigar_b + bytes(seq_b) + qual_b + rec.tags
    return struct.pack("<i", len(body)) + body


def _find_cg_tag(tags: bytes) -> Optional[List[int]]:
    """Scan the tag region for a ``CG:B,I`` array — the real CIGAR of a
    read whose op count overflows the 16-bit n_cigar field (SAM spec
    §4.2.2). Returns raw (len<<4|op) words or None."""
    off = 0
    n = len(tags)
    while off + 3 <= n:
        t0, t1, typ = tags[off : off + 3]
        off += 3
        ch = chr(typ)
        if ch in "AcC":
            off += 1
        elif ch in "sS":
            off += 2
        elif ch in "iIf":
            off += 4
        elif ch in "ZH":
            end = tags.find(b"\x00", off)
            if end < 0:  # truncated string tag: give up gracefully
                return None
            off = end + 1
        elif ch == "B":
            if off + 5 > n:
                return None
            elem = chr(tags[off])
            count = struct.unpack_from("<I", tags, off + 1)[0]
            esize = {"c": 1, "C": 1, "s": 2, "S": 2}.get(elem, 4)
            if t0 == ord("C") and t1 == ord("G") and elem == "I":
                if off + 5 + 4 * count > n:
                    return None
                return list(struct.unpack_from(f"<{count}I", tags, off + 5))
            off += 5 + esize * count
        else:
            return None
    return None


def _decode_record(body: bytes) -> BamRecord:
    (
        tid,
        pos,
        l_read_name,
        mapq,
        _bin,
        n_cigar,
        flag,
        l_seq,
        next_tid,
        next_pos,
        tlen,
    ) = struct.unpack_from("<iiBBHHHiiii", body, 0)
    off = 32
    name = body[off : off + l_read_name - 1].decode()
    off += l_read_name
    cigar = []
    for _ in range(n_cigar):
        v = struct.unpack_from("<I", body, off)[0]
        cigar.append((v & 0xF, v >> 4))
        off += 4
    seq_chars = []
    for i in range(l_seq):
        byte = body[off + (i >> 1)]
        nib = (byte >> 4) if i % 2 == 0 else (byte & 0xF)
        seq_chars.append(_SEQ_CODES[nib])
    off += (l_seq + 1) // 2
    qual = body[off : off + l_seq]
    off += l_seq
    tags = body[off:]
    # ultralong-read CIGAR overflow: placeholder "<l_seq>S<ref_len>N" with
    # the real CIGAR in a CG:B,I tag
    if (
        len(cigar) == 2
        and cigar[0] == (C.CIGAR_S, l_seq)
        and cigar[1][0] == C.CIGAR_N
    ):
        cg = _find_cg_tag(tags)
        if cg is not None:
            cigar = [(v & 0xF, v >> 4) for v in cg]
    return BamRecord(
        name=name,
        flag=flag,
        tid=tid,
        pos=pos,
        mapq=mapq,
        cigar=tuple(cigar),
        seq="".join(seq_chars),
        qual=qual,
        next_tid=next_tid,
        next_pos=next_pos,
        tlen=tlen,
        tags=tags,
    )


class BamReader:
    def __init__(self, path: str):
        self.path = path
        self._bgzf = BgzfReader(path)
        magic = self._bgzf.read(4)
        if magic != _BAM_MAGIC:
            raise ValueError(f"{path}: not a BAM file")
        l_text = struct.unpack("<i", self._bgzf.read(4))[0]
        self.header_text = self._bgzf.read(l_text).decode(errors="replace")
        n_ref = struct.unpack("<i", self._bgzf.read(4))[0]
        self.references: List[Tuple[str, int]] = []
        for _ in range(n_ref):
            l_name = struct.unpack("<i", self._bgzf.read(4))[0]
            name = self._bgzf.read(l_name)[:-1].decode()
            l_ref = struct.unpack("<i", self._bgzf.read(4))[0]
            self.references.append((name, l_ref))
        self.tid_by_name: Dict[str, int] = {
            n: i for i, (n, _) in enumerate(self.references)
        }
        self._first_record_voffset = self._bgzf.tell_virtual()
        self._index = None
        self._warned_no_index = False

    # -- raw iteration ------------------------------------------------------
    def _read_record(self) -> Optional[BamRecord]:
        size_b = self._bgzf.read(4)
        if len(size_b) < 4:
            return None
        block_size = struct.unpack("<i", size_b)[0]
        body = self._bgzf.read(block_size)
        if len(body) < block_size:
            raise ValueError(f"{self.path}: truncated record")
        return _decode_record(body)

    def __iter__(self) -> Iterator[BamRecord]:
        self._bgzf.seek_virtual(self._first_record_voffset)
        while True:
            rec = self._read_record()
            if rec is None:
                return
            yield rec

    # -- indexed fetch ------------------------------------------------------
    def _load_index(self):
        """Parse the full ``.bai``: per ref, (bins: {bin -> [(chunk_beg,
        chunk_end)]}, linear ioffsets). Returns None (with a one-time
        warning) when no index exists — fetch then falls back to a full
        scan from the first record, O(file) per region."""
        if self._index is not None:
            return self._index
        bai_path = self.path + ".bai"
        if not os.path.exists(bai_path):
            if not self._warned_no_index:
                self._warned_no_index = True
                import warnings

                warnings.warn(
                    f"{self.path}: no .bai index — every fetch() scans "
                    "from the first record (O(file size) per region). "
                    "Write the BAM through BamWriter to get an index.",
                    stacklevel=3,
                )
            return None
        with open(bai_path, "rb") as fh:
            data = fh.read()
        if data[:4] != _BAI_MAGIC:
            raise ValueError(f"{bai_path}: not a BAI index")
        off = 4
        n_ref = struct.unpack_from("<i", data, off)[0]
        off += 4
        index: List[Tuple[Dict[int, List[Tuple[int, int]]], List[int]]] = []
        for _ in range(n_ref):
            n_bin = struct.unpack_from("<i", data, off)[0]
            off += 4
            bins: Dict[int, List[Tuple[int, int]]] = {}
            for _ in range(n_bin):
                bin_id, n_chunk = struct.unpack_from("<Ii", data, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, cend = struct.unpack_from("<QQ", data, off)
                    off += 16
                    chunks.append((beg, cend))
                if bin_id == 37450:
                    # samtools' metadata pseudo-bin (SAM spec §5.2): its
                    # two "chunks" are (file range, mapped/unmapped
                    # counts), NOT virtual offsets. reg2bins can never
                    # return 37450 (real bins top out at 37448), but
                    # storing it would still poison any future whole-bin
                    # consumer — drop it explicitly.
                    continue
                bins[bin_id] = chunks
            n_intv = struct.unpack_from("<i", data, off)[0]
            off += 4
            ioffsets = list(struct.unpack_from(f"<{n_intv}Q", data, off))
            off += 8 * n_intv
            index.append((bins, ioffsets))
        self._index = index
        return index

    def _linear_min_voffset(self, ioffsets: List[int], start: int) -> int:
        """Smallest useful virtual offset from the linear index: records
        overlapping ``start`` cannot begin before it."""
        if not ioffsets:
            return 0
        i = min(start >> _LINEAR_SHIFT, len(ioffsets) - 1)
        while i >= 0 and ioffsets[i] == 0:
            i -= 1
        return ioffsets[i] if i >= 0 else 0

    def _region_chunks(
        self, tid: int, start: int, end: int
    ) -> Optional[List[Tuple[int, int]]]:
        """htslib-style region query: candidate bins' chunks, pruned by
        the linear index, merged when overlapping/adjacent. None when no
        index (or an old linear-only index) is available."""
        index = self._load_index()
        if index is None or tid >= len(index):
            return None
        bins, ioffsets = index[tid]
        if not bins:
            return None  # linear-only .bai (our own pre-bin writer)
        min_voff = self._linear_min_voffset(ioffsets, start)
        chunks = []
        for b in reg2bins(start, end):
            for beg, cend in bins.get(b, ()):
                if cend > min_voff:
                    chunks.append((max(beg, min_voff), cend))
        chunks.sort()
        merged: List[Tuple[int, int]] = []
        for beg, cend in chunks:
            if merged and beg <= merged[-1][1]:
                merged[-1] = (merged[-1][0], max(merged[-1][1], cend))
            else:
                merged.append((beg, cend))
        return merged

    def fetch(
        self, contig: str, start: int = 0, end: Optional[int] = None
    ) -> Iterator[BamRecord]:
        """Yield mapped records overlapping ``[start, end)`` on ``contig``
        in file (coordinate) order. With a binned ``.bai`` the read is
        restricted to the region's chunk list (htslib semantics, ref:
        Dependencies/htslib-1.9/htslib/sam.h bin+chunk query); a
        linear-only index gives a tight start offset; no index falls
        back to a full scan (with a warning)."""
        if contig not in self.tid_by_name:
            raise KeyError(f"unknown contig {contig!r}")
        tid = self.tid_by_name[contig]
        if end is None:
            end = self.references[tid][1]

        chunks = self._region_chunks(tid, start, end)
        if chunks is not None:
            yield from self._fetch_chunks(chunks, tid, start, end)
            return

        voffset = self._first_record_voffset
        index = self._load_index()
        if index is not None and tid < len(index):
            lin = self._linear_min_voffset(index[tid][1], start)
            if lin:
                voffset = lin
        self._bgzf.seek_virtual(voffset)

        while True:
            rec = self._read_record()
            if rec is None:
                return
            if rec.tid != tid:
                # coordinate-sorted: a later tid means we're past our contig
                if rec.tid > tid or rec.tid < 0:
                    return
                continue
            if rec.pos >= end:
                return
            if rec.is_unmapped:
                continue
            if rec.reference_end > start:
                yield rec

    def _fetch_chunks(
        self, chunks: List[Tuple[int, int]], tid: int, start: int, end: int
    ) -> Iterator[BamRecord]:
        for beg, cend in chunks:
            self._bgzf.seek_virtual(beg)
            while self._bgzf.tell_virtual() < cend:
                rec = self._read_record()
                if rec is None:
                    return
                if rec.tid != tid:
                    if rec.tid > tid or rec.tid < 0:
                        return  # coordinate-sorted: past our contig
                    continue
                if rec.pos >= end:
                    return  # coordinate-sorted: past the region
                if rec.is_unmapped:
                    continue
                if rec.reference_end > start:
                    yield rec

    def close(self) -> None:
        self._bgzf.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BamWriter:
    """Writes a coordinate-sorted BAM and its ``.bai`` with the full
    bin+chunk structure plus the linear index (SAM spec §5.1.3/§5.3 —
    the same layout htslib emits), so :class:`BamReader` and the native
    extractor can restrict region fetches to the relevant chunks."""

    def __init__(self, path: str, references: Sequence[Tuple[str, int]]):
        self.path = path
        self.references = list(references)
        self._bgzf = BgzfWriter(path)
        header_lines = ["@HD\tVN:1.6\tSO:coordinate"] + [
            f"@SQ\tSN:{n}\tLN:{l}" for n, l in self.references
        ]
        text = ("\n".join(header_lines) + "\n").encode()
        self._bgzf.write(_BAM_MAGIC)
        self._bgzf.write(struct.pack("<i", len(text)) + text)
        self._bgzf.write(struct.pack("<i", len(self.references)))
        for name, length in self.references:
            nb = name.encode() + b"\x00"
            self._bgzf.write(struct.pack("<i", len(nb)) + nb + struct.pack("<i", length))
        # index accumulators: per ref, interval -> min voffset (linear)
        # and bin -> [(chunk_beg, chunk_end)] (distributed bins)
        self._ioffsets: List[Dict[int, int]] = [dict() for _ in self.references]
        self._bins: List[Dict[int, List[List[int]]]] = [
            dict() for _ in self.references
        ]
        self._last_key: Optional[Tuple[int, int]] = None

    def write(self, rec: BamRecord) -> None:
        if rec.tid >= 0:
            key = (rec.tid, rec.pos)
            if self._last_key is not None and key < self._last_key:
                raise ValueError("records must be written in coordinate order")
            self._last_key = key
        voffset = self._bgzf.tell_virtual()
        self._bgzf.write(_encode_record(rec))
        if rec.tid >= 0 and not rec.is_unmapped:
            rec_end = max(rec.reference_end, rec.pos + 1)
            for iv in range(rec.pos >> _LINEAR_SHIFT, (rec_end - 1 >> _LINEAR_SHIFT) + 1):
                self._ioffsets[rec.tid].setdefault(iv, voffset)
            # extend the bin's open chunk when records are contiguous in
            # the file (htslib merges exactly this way), else open one
            vend = self._bgzf.tell_virtual()
            chunks = self._bins[rec.tid].setdefault(
                reg2bin(rec.pos, rec_end), []
            )
            if chunks and chunks[-1][1] == voffset:
                chunks[-1][1] = vend
            else:
                chunks.append([voffset, vend])

    def close(self) -> None:
        self._bgzf.close()
        with open(self.path + ".bai", "wb") as fh:
            fh.write(_BAI_MAGIC)
            fh.write(struct.pack("<i", len(self.references)))
            for tid in range(len(self.references)):
                bins = self._bins[tid]
                fh.write(struct.pack("<i", len(bins)))
                for bin_id in sorted(bins):
                    chunks = bins[bin_id]
                    fh.write(struct.pack("<Ii", bin_id, len(chunks)))
                    for beg, cend in chunks:
                        fh.write(struct.pack("<QQ", beg, cend))
                ivs = self._ioffsets[tid]
                n_intv = (max(ivs) + 1) if ivs else 0
                fh.write(struct.pack("<i", n_intv))
                for i in range(n_intv):
                    fh.write(struct.pack("<Q", ivs.get(i, 0)))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_sorted_bam(
    path: str,
    references: Sequence[Tuple[str, int]],
    records: Sequence[BamRecord],
) -> None:
    """Sort ``records`` by (tid, pos) and write BAM + BAI."""
    recs = sorted(records, key=lambda r: (r.tid if r.tid >= 0 else 1 << 30, r.pos))
    with BamWriter(path, references) as w:
        for r in recs:
            w.write(r)
