"""BGZF (blocked gzip) reader/writer.

BGZF is the container format under BAM/BAI: a series of gzip members, each
at most 64 KiB uncompressed, carrying a BSIZE extra field so readers can
seek to a block boundary without inflating. Virtual file offsets are
``(compressed_offset << 16) | within_block_offset`` (SAM spec §4.1).

Self-contained on top of :mod:`zlib`; no htslib.
"""

from __future__ import annotations

import struct
import zlib
from typing import BinaryIO, Optional

#: gzip magic + deflate + FEXTRA flag
_HEADER_PREFIX = b"\x1f\x8b\x08\x04"
#: fixed 28-byte empty terminator block (SAM spec §4.1.2)
EOF_MARKER = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)

#: maximum uncompressed payload per block
MAX_BLOCK_DATA = 65280


class BgzfError(ValueError):
    pass


def _compress_block(data: bytes, level: int = 6) -> bytes:
    comp = zlib.compressobj(level, zlib.DEFLATED, -15)
    payload = comp.compress(data) + comp.flush()
    # header = 12 fixed bytes + 6 extra-field bytes; trailer = crc32 + isize.
    bsize = 18 + len(payload) + 8
    if bsize > 65536:
        raise BgzfError("BGZF block too large after compression")
    header = (
        _HEADER_PREFIX
        + b"\x00\x00\x00\x00"  # mtime
        + b"\x00\xff"  # XFL, OS
        + struct.pack("<H", 6)  # XLEN
        + b"BC"
        + struct.pack("<H", 2)  # SLEN
        + struct.pack("<H", bsize - 1)
    )
    trailer = struct.pack("<II", zlib.crc32(data) & 0xFFFFFFFF, len(data) & 0xFFFFFFFF)
    return header + payload + trailer


class BgzfWriter:
    def __init__(self, fileobj_or_path, level: int = 6):
        if isinstance(fileobj_or_path, (str, bytes)):
            self._fh: BinaryIO = open(fileobj_or_path, "wb")
            self._owns = True
        else:
            self._fh = fileobj_or_path
            self._owns = False
        self._level = level
        self._buf = bytearray()

    # -- virtual offset of the next byte to be written ----------------------
    def tell_virtual(self) -> int:
        return (self._fh.tell() << 16) | len(self._buf)

    def write(self, data: bytes) -> None:
        self._buf.extend(data)
        while len(self._buf) >= MAX_BLOCK_DATA:
            self._flush_block(MAX_BLOCK_DATA)

    def flush(self) -> None:
        while self._buf:
            self._flush_block(min(len(self._buf), MAX_BLOCK_DATA))

    def _flush_block(self, n: int) -> None:
        chunk = bytes(self._buf[:n])
        del self._buf[:n]
        self._fh.write(_compress_block(chunk, self._level))

    def close(self) -> None:
        self.flush()
        self._fh.write(EOF_MARKER)
        if self._owns:
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class BgzfReader:
    """Sequential reader with virtual-offset seek (for BAI chunk starts)."""

    def __init__(self, fileobj_or_path):
        if isinstance(fileobj_or_path, (str, bytes)):
            self._fh: BinaryIO = open(fileobj_or_path, "rb")
            self._owns = True
        else:
            self._fh = fileobj_or_path
            self._owns = False
        self._block: bytes = b""
        self._block_coffset = 0  # compressed offset of current block
        self._within = 0  # cursor within the current (uncompressed) block
        self._eof = False

    def _load_block_at(self, coffset: int) -> bool:
        """Read the block starting at compressed offset ``coffset``.
        Returns False at physical EOF."""
        self._fh.seek(coffset)
        header = self._fh.read(18)
        if len(header) == 0:
            return False
        if len(header) < 18 or header[:4] != _HEADER_PREFIX:
            raise BgzfError(f"bad BGZF header at offset {coffset}")
        xlen = struct.unpack_from("<H", header, 10)[0]
        # scan extra subfields for BC/BSIZE
        if xlen >= 6:
            extra = header[12:18] + self._fh.read(xlen - 6)
        else:
            extra = header[12 : 12 + xlen]
        bsize = None
        off = 0
        while off + 4 <= len(extra):
            si1, si2, slen = extra[off], extra[off + 1], struct.unpack_from("<H", extra, off + 2)[0]
            if si1 == 0x42 and si2 == 0x43 and slen == 2:
                bsize = struct.unpack_from("<H", extra, off + 4)[0] + 1
                break
            off += 4 + slen
        if bsize is None:
            raise BgzfError(f"no BSIZE field in BGZF block at {coffset}")
        payload_len = bsize - (12 + xlen) - 8
        payload = self._fh.read(payload_len)
        trailer = self._fh.read(8)
        if len(payload) != payload_len or len(trailer) != 8:
            raise BgzfError("truncated BGZF block")
        crc, isize = struct.unpack("<II", trailer)
        data = zlib.decompress(payload, -15)
        if len(data) != isize or (zlib.crc32(data) & 0xFFFFFFFF) != crc:
            raise BgzfError(f"BGZF block checksum mismatch at {coffset}")
        self._block = data
        self._block_coffset = coffset
        self._within = 0
        return True

    def read(self, n: int) -> bytes:
        out = bytearray()
        while n > 0:
            if self._within >= len(self._block):
                coffset = self._fh.tell()
                if not self._load_block_at(coffset):
                    break
                if not self._block:  # empty EOF block — keep reading (may be mid-file)
                    continue
            take = min(n, len(self._block) - self._within)
            out.extend(self._block[self._within : self._within + take])
            self._within += take
            n -= take
        return bytes(out)

    def seek_virtual(self, voffset: int) -> None:
        coffset, within = voffset >> 16, voffset & 0xFFFF
        if not self._load_block_at(coffset):
            raise BgzfError(f"virtual offset {voffset:#x} beyond EOF")
        if within > len(self._block):
            raise BgzfError(f"virtual offset {voffset:#x} beyond block end")
        self._within = within

    def tell_virtual(self) -> int:
        if self._within >= len(self._block):
            # cursor is logically at the start of the next block
            return self._fh.tell() << 16
        return (self._block_coffset << 16) | self._within

    def close(self) -> None:
        if self._owns:
            self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
