"""Host-side I/O: FASTA, BGZF, BAM(+BAI), HDF5 interchange.

Self-contained — no htslib/pysam/biopython dependency. The C++ extractor in
``roko_tpu/native`` implements the same BAM/BGZF formats for the hot path;
this package is the readable reference implementation and the test oracle.
"""

from roko_tpu.io.fasta import read_fasta, write_fasta  # noqa: F401
from roko_tpu.io.bam import BamReader, BamRecord, BamWriter  # noqa: F401
from roko_tpu.io.sam import SamError, SamReader  # noqa: F401
