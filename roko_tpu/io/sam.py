"""SAM text reader — the plain-text sibling of :mod:`roko_tpu.io.bam`.

The reference consumes alignments through htslib, which transparently
reads SAM text as well as BAM (Dependencies/htslib-1.9/sam.c
``sam_read1``); callers never know which container they were handed.
This module gives the framework the same property: :class:`SamReader`
yields the same :class:`~roko_tpu.io.bam.BamRecord` objects as
:class:`~roko_tpu.io.bam.BamReader`, so every downstream stage (pileup,
extractor, labeler) works off either container unchanged.

Field semantics follow the SAM spec v1 (mandatory 11 columns + typed
aux tags) with htslib's conventions: 1-based POS converted to 0-based,
``*`` sentinels mapped to the BAM in-memory encodings (empty cigar/seq,
0xff qual), ``=``/``*`` RNEXT resolved against the @SQ-declared
references, and aux tags re-encoded into BAM binary tag bytes (ints take
the smallest width that fits, as htslib's ``sam_parse1`` does).
"""

from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Tuple

from roko_tpu import constants as C
from roko_tpu.io.bam import BamRecord

_CIGAR_OP_BY_CHAR = {c: i for i, c in enumerate(C.CIGAR_OPS)}


class SamError(ValueError):
    pass


def _parse_cigar(text: str) -> Tuple[Tuple[int, int], ...]:
    if text == "*":
        return ()
    ops: List[Tuple[int, int]] = []
    n = 0
    seen_digit = False
    for ch in text:
        if ch.isdigit():
            n = n * 10 + ord(ch) - 48
            seen_digit = True
            continue
        op = _CIGAR_OP_BY_CHAR.get(ch)
        if op is None or not seen_digit:
            raise SamError(f"bad CIGAR {text!r}")
        ops.append((op, n))
        n = 0
        seen_digit = False
    if seen_digit:
        raise SamError(f"bad CIGAR {text!r} (trailing length)")
    return tuple(ops)


# B-array subtypes: struct code + value range check is delegated to
# struct.pack itself (it raises for out-of-range, which we wrap)
_B_SUBTYPES = {"c": "b", "C": "B", "s": "h", "S": "H", "i": "i", "I": "I", "f": "f"}


def _encode_int_tag(tag: bytes, value: int) -> bytes:
    """Smallest-width BAM int encoding, mirroring htslib sam_parse1."""
    if value >= 0:
        if value <= 0xFF:
            return tag + b"C" + struct.pack("<B", value)
        if value <= 0x7FFF:
            return tag + b"s" + struct.pack("<h", value)
        if value <= 0xFFFF:
            return tag + b"S" + struct.pack("<H", value)
        if value <= 0x7FFFFFFF:
            return tag + b"i" + struct.pack("<i", value)
        if value <= 0xFFFFFFFF:
            return tag + b"I" + struct.pack("<I", value)
        raise SamError(f"integer tag value {value} exceeds 32 bits")
    if value >= -0x80:
        return tag + b"c" + struct.pack("<b", value)
    if value >= -0x8000:
        return tag + b"s" + struct.pack("<h", value)
    if value >= -0x80000000:
        return tag + b"i" + struct.pack("<i", value)
    raise SamError(f"integer tag value {value} exceeds 32 bits")


def _encode_tag(field: str) -> bytes:
    try:
        name, typ, val = field.split(":", 2)
    except ValueError:
        raise SamError(f"bad aux field {field!r}") from None
    if len(name) != 2:
        raise SamError(f"bad aux tag name in {field!r}")
    tag = name.encode()
    try:
        if typ == "A":
            if len(val) != 1:
                raise SamError(f"bad A tag {field!r}")
            return tag + b"A" + val.encode()
        if typ == "i":
            return _encode_int_tag(tag, int(val))
        if typ == "f":
            return tag + b"f" + struct.pack("<f", float(val))
        if typ == "Z":
            return tag + b"Z" + val.encode() + b"\x00"
        if typ == "H":
            bytes.fromhex(val)  # validate hex digits (pairs)
            return tag + b"H" + val.encode() + b"\x00"
        if typ == "B":
            parts = val.split(",")
            sub = parts[0]
            code = _B_SUBTYPES.get(sub)
            if code is None:
                raise SamError(f"bad B subtype in {field!r}")
            conv = float if sub == "f" else int
            vals = [conv(p) for p in parts[1:]]
            return (
                tag
                + b"B"
                + sub.encode()
                + struct.pack("<i", len(vals))
                + struct.pack(f"<{len(vals)}{code}", *vals)
            )
    except (ValueError, struct.error) as e:
        raise SamError(f"bad aux field {field!r}: {e}") from None
    raise SamError(f"unknown aux type {typ!r} in {field!r}")


class SamReader:
    """Iterate a SAM text file as :class:`BamRecord` objects.

    Exposes the same surface the pipeline uses on :class:`BamReader`:
    ``references`` (from @SQ lines, in order), ``tid_by_name``, and
    ``header_text``. No random access — SAM text has no index; region
    queries should go through a coordinate-sorted BAM
    (:func:`roko_tpu.io.bam.write_sorted_bam`).
    """

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path, "rt", encoding="utf-8", errors="replace")
        self.references: List[Tuple[str, int]] = []
        header_lines: List[str] = []
        self._first_line: str | None = None
        try:
            for line in self._fh:
                if line.startswith("@"):
                    header_lines.append(line)
                    if line.startswith("@SQ"):
                        fields = dict(
                            f.split(":", 1)
                            for f in line.rstrip("\n").split("\t")[1:]
                            if ":" in f
                        )
                        try:
                            self.references.append(
                                (fields["SN"], int(fields["LN"]))
                            )
                        except (KeyError, ValueError):
                            raise SamError(
                                f"{path}: bad @SQ line {line!r}"
                            ) from None
                    continue
                if line.strip() == "":
                    continue  # same permissive blank-line skip as __iter__
                self._first_line = line
                break
        except BaseException:
            self._fh.close()
            raise
        self.header_text = "".join(header_lines)
        self.tid_by_name: Dict[str, int] = {
            n: i for i, (n, _) in enumerate(self.references)
        }

    def _parse_line(self, line: str, lineno: int) -> BamRecord:
        # trailing tabs produce empty fields (seen in htslib fixtures) —
        # drop them rather than mis-parse as an aux tag
        fields = [f for f in line.rstrip("\r\n").split("\t") if f != ""]
        if len(fields) < 11:
            raise SamError(
                f"{self.path}:{lineno}: {len(fields)} fields (need 11)"
            )
        (qname, flag_s, rname, pos_s, mapq_s, cigar_s,
         rnext, pnext_s, tlen_s, seq, qual) = fields[:11]
        try:
            flag = int(flag_s)
            pos = int(pos_s) - 1
            mapq = int(mapq_s)
            pnext = int(pnext_s) - 1
            tlen = int(tlen_s)
        except ValueError:
            raise SamError(
                f"{self.path}:{lineno}: non-numeric mandatory field"
            ) from None
        if rname == "*":
            tid = -1
        else:
            tid = self.tid_by_name.get(rname, -2)
            if tid == -2:
                raise SamError(
                    f"{self.path}:{lineno}: RNAME {rname!r} not in @SQ"
                )
        if rnext == "*":
            next_tid = -1
        elif rnext == "=":
            next_tid = tid
        else:
            next_tid = self.tid_by_name.get(rnext, -2)
            if next_tid == -2:
                raise SamError(
                    f"{self.path}:{lineno}: RNEXT {rnext!r} not in @SQ"
                )
        seq_str = "" if seq == "*" else seq
        if qual == "*":
            qual_b = b"\xff" * len(seq_str)
        else:
            qual_b = bytes((ord(c) - 33) & 0xFF for c in qual)
            if seq_str and len(qual_b) != len(seq_str):
                raise SamError(
                    f"{self.path}:{lineno}: SEQ/QUAL length mismatch"
                )
        tags = b"".join(_encode_tag(f) for f in fields[11:])
        return BamRecord(
            name=qname,
            flag=flag,
            tid=tid,
            pos=pos,
            mapq=mapq,
            cigar=_parse_cigar(cigar_s),
            seq=seq_str,
            qual=qual_b,
            next_tid=next_tid,
            next_pos=pnext,
            tlen=tlen,
            tags=tags,
        )

    def __iter__(self) -> Iterator[BamRecord]:
        lineno = self.header_text.count("\n")
        if self._first_line is not None:
            lineno += 1
            yield self._parse_line(self._first_line, lineno)
            self._first_line = None
        for line in self._fh:
            lineno += 1
            if line.strip() == "":
                continue  # permissive: blank trailing lines
            yield self._parse_line(line, lineno)

    def close(self) -> None:
        self._fh.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
