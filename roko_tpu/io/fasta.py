"""Minimal FASTA reader/writer (replaces Bio.SeqIO usage, ref:
roko/features.py:125-126, roko/inference.py:150-154)."""

from __future__ import annotations

import gzip
import io
from typing import Iterator, List, Sequence, Tuple, Union


def _open_text(path: str):
    from roko_tpu.datapipe.io import open_input, path_scheme, strip_file_scheme

    if path_scheme(path) in ("", "file"):
        # local fast path, unchanged
        local = strip_file_scheme(path)
        if local.endswith(".gz"):
            return gzip.open(local, "rt")
        return open(local, "r")
    # remote: ranged/cached binary reads through the opener seam
    fh = open_input(path)
    if path.endswith(".gz"):
        return gzip.open(fh, "rt")
    return io.TextIOWrapper(fh)


def iter_fasta(path: str) -> Iterator[Tuple[str, str]]:
    """Yield ``(name, sequence)`` per record. The name is the first
    whitespace-delimited token of the header line."""
    name = None
    chunks: List[str] = []
    with _open_text(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            if line.startswith(">"):
                if name is not None:
                    yield name, "".join(chunks)
                name = line[1:].split()[0]
                chunks = []
            else:
                if name is None:
                    raise ValueError(f"{path}: sequence data before first header")
                chunks.append(line)
        if name is not None:
            yield name, "".join(chunks)


def read_fasta(path: str) -> List[Tuple[str, str]]:
    return list(iter_fasta(path))


def write_fasta_record(fh, name: str, seq: str, line_width: int = 80) -> None:
    """One record in this module's canonical layout. The single source
    of the on-disk format: the streaming engine's incremental writer
    (roko_tpu/pipeline) promises byte-identity with :func:`write_fasta`
    and keeps it by calling this."""
    fh.write(f">{name}\n")
    for i in range(0, len(seq), line_width):
        fh.write(seq[i : i + line_width])
        fh.write("\n")


def write_fasta(
    path: str, records: Sequence[Tuple[str, str]], line_width: int = 80
) -> None:
    from roko_tpu.datapipe.io import abort_output, open_output

    fh = open_output(path, "w")
    try:
        for name, seq in records:
            write_fasta_record(fh, name, seq, line_width)
    except BaseException:
        # a remote handle must not upload a half-written FASTA on the
        # way out; a local file keeps the historical leave-partial
        # behavior (abort_output just closes it)
        abort_output(fh)
        raise
    else:
        fh.close()
