"""Banded unit-cost global alignment with exact edit-op counts.

Two implementations with identical semantics (including tie-breaking:
diagonal preferred over a gap at equal cost, deletion preferred over
insertion), so the pure-Python one is the test oracle for the C++ hot
path in native/src/align.cc:

- :func:`banded_align_py` — reference implementation, plain Python DP.
- :func:`banded_align` — dispatches to the native library when it is
  available (built on demand, same auto-build as the feature
  extractor) and falls back to the Python DP otherwise.

Tie-breaking matters here: equal-cost alignments can decompose the
same edit distance differently (two substitutions vs an
insertion+deletion pair never tie, but gap placement around repeats
does), and the assess report promises native-vs-Python bit-equality
the way the extractor does (tests/test_assess.py).
"""

from __future__ import annotations

from dataclasses import dataclass

# Default DP working-set cap: one traceback byte per cell.
MAX_CELLS = 256_000_000


@dataclass
class AlignResult:
    match: int
    sub: int
    ins: int  # bases present only in ``b``
    dele: int  # bases of ``a`` missing from ``b``
    hit_band_edge: bool
    #: error events in a-coordinates, only when requested (collect_ops)
    ops: "list[tuple[str, int]] | None" = None

    @property
    def errors(self) -> int:
        return self.sub + self.ins + self.dele


def banded_align_py(
    a: bytes,
    b: bytes,
    pad: int,
    max_cells: int = MAX_CELLS,
    *,
    collect_ops: bool = False,
) -> AlignResult:
    """Python reference DP; see module docstring. Raises MemoryError
    when ``(len(a)+1) * band_width`` exceeds ``max_cells``.

    With ``collect_ops`` the result's ``ops`` lists the error events in
    ``a``-coordinates, ordered by position: ``("sub", i)`` — ``a[i]``
    substituted; ``("del", i)`` — ``a[i]`` missing from ``b``;
    ``("ins", i)`` — extra ``b`` base(s) aligned before ``a[i]`` (i may
    equal ``len(a)`` for a trailing insertion). Matches are omitted.
    The assess tool uses this only on the (few) segments whose native
    counts show errors, so the hot path stays in C++."""
    la, lb = len(a), len(b)
    if la == 0 or lb == 0:
        res = AlignResult(0, 0, lb, la, False)
        if collect_ops:
            res.ops = (
                [("ins", 0)] * lb if lb else []
            ) + ([("del", i) for i in range(la)])
        return res
    dlo = min(0, lb - la) - pad
    dhi = max(0, lb - la) + pad
    width = dhi - dlo + 1
    if (la + 1) * width > max_cells:
        raise MemoryError("alignment working set exceeds max_cells")

    INF = 1 << 60
    DIAG, UP, LEFT, NONE = 1, 2, 3, 0
    prev = [INF] * width
    moves = [bytearray(width) for _ in range(la + 1)]
    for w in range(width):
        j = dlo + w
        if 0 <= j <= lb:
            prev[w] = j
            moves[0][w] = LEFT if j else NONE
    for i in range(1, la + 1):
        cur = [INF] * width
        row = moves[i]
        ai = a[i - 1]
        for w in range(width):
            j = i + dlo + w
            if j < 0 or j > lb:
                continue
            best = prev[w + 1] + 1 if w + 1 < width and prev[w + 1] < INF else INF
            mv = UP
            if w >= 1 and cur[w - 1] < INF and cur[w - 1] + 1 < best:
                best = cur[w - 1] + 1
                mv = LEFT
            if j >= 1 and prev[w] < INF:
                c = prev[w] + (0 if ai == b[j - 1] else 1)
                if c <= best:
                    best = c
                    mv = DIAG
            if j == 0:
                best = i
                mv = UP
            cur[w] = best
            row[w] = mv if best < INF else NONE
        prev = cur

    end_w = lb - la - dlo
    if not (0 <= end_w < width) or prev[end_w] >= INF:
        raise RuntimeError("band does not contain the end cell")

    res = AlignResult(0, 0, 0, 0, False)
    ops: list = [] if collect_ops else None  # type: ignore[assignment]
    i, w = la, end_w
    while i > 0 or i + dlo + w > 0:
        j = i + dlo + w
        if (w == 0 or w == width - 1) and i > 0 and j > 0:
            res.hit_band_edge = True
        mv = moves[i][w]
        if mv == DIAG:
            if a[i - 1] == b[j - 1]:
                res.match += 1
            else:
                res.sub += 1
                if collect_ops:
                    ops.append(("sub", i - 1))
            i -= 1
        elif mv == UP:
            res.dele += 1
            if collect_ops:
                ops.append(("del", i - 1))
            i -= 1
            w += 1
        elif mv == LEFT:
            res.ins += 1
            if collect_ops:
                ops.append(("ins", i))
            w -= 1
        else:
            raise RuntimeError("corrupt traceback")
    if collect_ops:
        ops.reverse()
        res.ops = ops
    return res


def banded_align(
    a: bytes, b: bytes, pad: int, max_cells: int = MAX_CELLS
) -> AlignResult:
    """Native-if-available banded alignment (semantics of
    :func:`banded_align_py`)."""
    from roko_tpu.native import binding

    if binding.is_available():
        m, s, i, d, edge = binding.align_counts(a, b, pad, max_cells)
        return AlignResult(m, s, i, d, edge)
    return banded_align_py(a, b, pad, max_cells)


def align_with_band_growth(
    a: bytes,
    b: bytes,
    *,
    pad: int = 16,
    max_pad: int = 4096,
    max_cells: int = MAX_CELLS,
) -> AlignResult:
    """Run :func:`banded_align`, doubling the band padding until the
    result is provably optimal by the Ukkonen bound: once the in-band
    cost satisfies ``errors <= pad``, every alignment of that cost or
    cheaper fits entirely inside the band (an alignment with ``e``
    errors deviates at most ``e`` diagonals from the ``[0, lb-la]``
    hull), so the in-band optimum IS the global optimum. Band-edge
    contact alone is neither necessary nor sufficient — fuzzing found
    no-contact results 1-2 above the true distance (ADVICE r3) — so it
    is no longer the stop condition. Returns with ``hit_band_edge``
    True only when ``max_pad`` or the cell budget capped growth before
    the bound held, i.e. the counts are an upper bound, so callers can
    count capped segments honestly."""
    pad = max(1, pad)  # pad=0 would double to 0 forever
    while True:
        try:
            res = banded_align(a, b, pad, max_cells)
        except MemoryError:
            # shrink until the working set fits; the result is then
            # explicitly marked band-capped
            while pad > 16:
                pad //= 2
                try:
                    res = banded_align(a, b, pad, max_cells)
                except MemoryError:
                    continue
                res.hit_band_edge = True
                return res
            raise  # even the narrowest band does not fit
        if res.errors <= pad:
            res.hit_band_edge = False  # provably exact, even on contact
            return res
        if pad >= max_pad:
            res.hit_band_edge = True  # upper bound, not provably exact
            return res
        pad *= 2
