"""Polished-vs-truth assembly assessment.

Produces the metrics of the reference's published comparison table —
total error, mismatch, deletion, insertion rates and Qscore
(/root/reference/README.md:103-112) — which the reference obtains from
the external ``pomoxis assess_assembly`` tool (README.md:97-101; not
available in this image). Having the evaluator in-framework makes the
north-star accuracy metric (BASELINE.md) self-measurable.

Method (dnadiff-style anchor decomposition, not a translation of any
tool): contigs are paired by name or by shared unique-k-mer content
(either orientation), then each pair is decomposed into collinear
unique-16-mer anchors (numpy rolling hash -> unique-in-both ->
longest-increasing-subsequence chain) and the short inter-anchor
segments are globally aligned with the banded unit-cost DP
(eval/align.py; C++ hot path). Anchored bases count as matches; edit
ops come from exact tracebacks, so rates are alignment-derived like
pomoxis', not k-mer estimates.

Conventions: rates are per truth base (``errors / truth_len``);
``Qscore = -10 log10(total_error_rate)``, infinite for a perfect
match. Deletion = truth base missing from the polished sequence;
insertion = polished base absent from truth. ``N`` bases in the truth
break anchors and compare as mismatches in aligned segments; their
count is surfaced per contig (``truth_n``) and in the report so
unknown-truth artefacts are distinguishable from polishing errors.
"""

from __future__ import annotations

import json
import math
from bisect import bisect_left
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from roko_tpu.eval.align import AlignResult, align_with_band_growth

K = 16  # anchor k-mer size (fits 2 bits/base in int32; unique-in-both)
MIN_ANCHOR_SPACING = 50  # thin anchors to one per this many truth bases
PAIRING_SAMPLE_STRIDE = 8  # k-mer subsample stride for contig pairing

_COMP = bytes.maketrans(b"ACGTacgt", b"TGCAtgca")


def revcomp(seq: bytes) -> bytes:
    return seq.translate(_COMP)[::-1]


def _kmer_codes(seq: bytes, k: int = K) -> Tuple[np.ndarray, np.ndarray]:
    """(codes, positions) of all ACGT-only k-mers, 2-bit rolling encode.
    Positions with any non-ACGT base are dropped (N's break anchors)."""
    if not 1 <= k <= 32:
        raise ValueError(f"k must be in [1, 32] (2 bits/base in int64), got {k}")
    arr = np.frombuffer(seq.upper(), dtype=np.uint8)
    n = arr.size
    if n < k:
        return np.empty(0, np.int64), np.empty(0, np.int64)
    code2 = np.full(n, 255, np.uint8)
    for v, base in enumerate(b"ACGT"):
        code2[arr == base] = v
    valid = code2 != 255
    codes = np.zeros(n - k + 1, np.int64)
    ok = np.ones(n - k + 1, bool)
    for t in range(k):
        codes = (codes << 2) | code2[t : n - k + 1 + t]
        ok &= valid[t : n - k + 1 + t]
    pos = np.nonzero(ok)[0]
    return codes[pos], pos


def _unique_kmers(seq: bytes, k: int = K) -> Tuple[np.ndarray, np.ndarray]:
    """(codes, positions) of k-mers occurring exactly once in ``seq``."""
    codes, pos = _kmer_codes(seq, k)
    if codes.size == 0:
        return codes, pos
    uniq, first, counts = np.unique(codes, return_index=True, return_counts=True)
    keep = counts == 1
    return uniq[keep], pos[first[keep]]


def _lis_chain(tpos: np.ndarray, ppos: np.ndarray) -> List[Tuple[int, int]]:
    """Longest strictly-increasing chain of (truth_pos, polished_pos)
    anchor pairs: input sorted by tpos (unique), LIS on ppos."""
    tails: List[int] = []  # ppos of chain tails
    tails_idx: List[int] = []
    parent = np.full(len(ppos), -1, np.int64)
    for i, p in enumerate(ppos):
        j = bisect_left(tails, p)
        if j == len(tails):
            tails.append(p)
            tails_idx.append(i)
        else:
            tails[j] = p
            tails_idx[j] = i
        parent[i] = tails_idx[j - 1] if j > 0 else -1
    chain: List[Tuple[int, int]] = []
    i = tails_idx[-1] if tails_idx else -1
    while i >= 0:
        chain.append((int(tpos[i]), int(ppos[i])))
        i = parent[i]
    chain.reverse()
    return chain


def _anchors(truth: bytes, polished: bytes, k: int = K) -> List[Tuple[int, int]]:
    """Collinear non-overlapping (truth_pos, polished_pos) anchors."""
    tc, tp = _unique_kmers(truth, k)
    pc, pp = _unique_kmers(polished, k)
    if tc.size == 0 or pc.size == 0:
        return []
    # _unique_kmers outputs are unique by construction; skip the re-dedup
    shared, ti, pi = np.intersect1d(
        tc, pc, assume_unique=True, return_indices=True
    )
    if shared.size == 0:
        return []
    tpos, ppos = tp[ti], pp[pi]
    order = np.argsort(tpos, kind="stable")
    tpos, ppos = tpos[order], ppos[order]
    # thin: ~one anchor per MIN_ANCHOR_SPACING truth bases keeps the LIS
    # cheap on megabase contigs without losing chain resolution. Bucket
    # firsts instead of a greedy running-distance walk: vectorised O(n)
    # (the Python loop was the profile's hottest line on multi-Mb
    # contigs), and the later >=k non-overlap filter bounds closeness
    # across bucket edges. Anchors are exact matches by construction, so
    # thinning strategy affects segmentation, never counts.
    if tpos.size > 2:
        buckets = tpos // MIN_ANCHOR_SPACING  # tpos sorted -> buckets sorted
        keep = np.concatenate([[True], np.diff(buckets) != 0])
        tpos, ppos = tpos[keep], ppos[keep]
    chain = _lis_chain(tpos, ppos)
    # enforce non-overlap in BOTH sequences so anchor k-mers can be
    # counted as k matches each without double counting
    out: List[Tuple[int, int]] = []
    last_t, last_p = -(10**18), -(10**18)
    for t, p in chain:
        if t >= last_t + k and p >= last_p + k:
            out.append((t, p))
            last_t, last_p = t, p
    return out


@dataclass
class ContigAssessment:
    truth_name: str
    polished_name: Optional[str]  # None: truth contig had no partner
    truth_len: int
    polished_len: int = 0
    reverse_complemented: bool = False
    match: int = 0
    sub: int = 0
    ins: int = 0
    dele: int = 0
    anchors: int = 0
    band_capped_segments: int = 0
    #: 'N' bases in the truth contig: they break anchors and compare as
    #: mismatches in aligned segments (the polished sequence is ACGT
    #: only), so up to this many reported errors may be unknown-truth
    #: artefacts rather than polishing mistakes
    truth_n: int = 0
    #: merged truth-space error rows (start, end, kind, count), only
    #: when assessed with collect_errors (the --bed CLI path)
    error_intervals: Optional[List[Tuple[int, int, str, int]]] = None

    @property
    def errors(self) -> int:
        return self.sub + self.ins + self.dele

    @property
    def error_rate(self) -> float:
        return self.errors / self.truth_len if self.truth_len else 0.0

    def rate(self, n: int) -> float:
        return n / self.truth_len if self.truth_len else 0.0

    @property
    def qscore(self) -> float:
        if self.truth_len == 0:
            return 0.0
        if self.errors == 0:
            return math.inf
        return -10.0 * math.log10(self.error_rate)


@dataclass
class AssessResult:
    contigs: List[ContigAssessment] = field(default_factory=list)

    @property
    def truth_len(self) -> int:
        return sum(c.truth_len for c in self.contigs)

    def _total(self, attr: str) -> int:
        return sum(getattr(c, attr) for c in self.contigs)

    @property
    def error_rate(self) -> float:
        t = self.truth_len
        return self._total("errors") / t if t else 0.0

    @property
    def qscore(self) -> float:
        if self.error_rate == 0.0:
            return math.inf
        return -10.0 * math.log10(self.error_rate)

    def summary(self) -> Dict[str, object]:
        t = self.truth_len or 1
        q = self.qscore
        return {
            "contigs": len(self.contigs),
            "truth_len": self.truth_len,
            "total_error_pct": round(100.0 * self.error_rate, 4),
            "mismatch_pct": round(100.0 * self._total("sub") / t, 4),
            "deletion_pct": round(100.0 * self._total("dele") / t, 4),
            "insertion_pct": round(100.0 * self._total("ins") / t, 4),
            "qscore": None if math.isinf(q) else round(q, 2),
            "band_capped_segments": self._total("band_capped_segments"),
            "truth_n_bases": self._total("truth_n"),
            "unpaired_truth_contigs": [
                c.truth_name for c in self.contigs if c.polished_name is None
            ],
        }


def assess_pair(
    truth: bytes,
    polished: bytes,
    *,
    k: int = K,
    truth_name: str = "truth",
    polished_name: str = "polished",
    try_revcomp: bool = True,
    collect_errors: bool = False,
) -> ContigAssessment:
    """Assess one polished contig against one truth contig.

    ``collect_errors`` additionally fills ``error_intervals`` with
    merged truth-space (start, end, kind, count) rows — only segments
    whose (native-counted) result shows errors are re-walked through
    the Python traceback, so the hot path stays in C++."""
    # normalise case: soft-masked (lowercase) regions are sequence, not
    # differences — anchoring already uppercases, the DP must agree
    truth = truth.upper()
    polished = polished.upper()
    fwd_anchors = _anchors(truth, polished, k)
    anchors, seq, rc = fwd_anchors, polished, False
    # only pay for the reverse-complement pass when forward anchoring is
    # weak; a correctly-oriented contig anchors near the thinning density
    dense = len(fwd_anchors) >= max(4, len(truth) // (4 * MIN_ANCHOR_SPACING))
    if try_revcomp and not dense:
        rc_seq = revcomp(polished)
        rc_anchors = _anchors(truth, rc_seq, k)
        if len(rc_anchors) > len(fwd_anchors):
            anchors, seq, rc = rc_anchors, rc_seq, True
    out = ContigAssessment(
        truth_name=truth_name,
        polished_name=polished_name,
        truth_len=len(truth),
        polished_len=len(polished),
        reverse_complemented=rc,
        anchors=len(anchors),
        truth_n=truth.count(b"N"),
    )
    rows: Optional[List[Tuple[int, int, str, int]]] = (
        [] if collect_errors else None
    )
    if not anchors:
        # no common unique k-mers: align whole-vs-whole (tiny contigs)
        # or give up and count the truth as fully missing (honest
        # worst case; a band over megabases would be meaningless).
        # _segment degrades to the worst case on MemoryError, so a
        # pathological pair can't abort the whole report.
        if len(truth) * 2 < 1 << 20 and len(seq) * 2 < 1 << 20:
            _add(out, _segment(truth, seq, 0, rows))
        else:
            out.dele += len(truth)
            out.ins += len(seq)
            if collect_errors:
                rows.append((0, len(truth), "del", len(truth)))
                if seq:
                    rows.append((0, min(1, len(truth)), "ins", len(seq)))
        out.error_intervals = rows
        return out
    # prefix + inter-anchor segments + suffix; anchor k-mers are exact
    # matches by construction
    t_prev, p_prev = 0, 0
    for ti, pi in anchors:
        _add(out, _segment(truth[t_prev:ti], seq[p_prev:pi], t_prev, rows))
        out.match += k
        t_prev, p_prev = ti + k, pi + k
    _add(out, _segment(truth[t_prev:], seq[p_prev:], t_prev, rows))
    out.error_intervals = rows
    return out


# cells budget for the pure-Python position re-walk: far below the C++
# MAX_CELLS because each cell is an interpreted loop iteration (~50M
# cells ~ tens of seconds); bigger error-bearing segments fall back to
# coarse per-kind span rows instead of exact positions
_OPS_MAX_CELLS = 50_000_000


def _segment(
    a: bytes,
    b: bytes,
    t_offset: int = 0,
    rows: Optional[List[Tuple[int, int, str, int]]] = None,
) -> AlignResult:
    if not a and not b:
        return AlignResult(0, 0, 0, 0, False)
    pad = max(16, abs(len(a) - len(b)) + 16)
    try:
        res = align_with_band_growth(a, b, pad=pad)
    except MemoryError:
        # an anchor-free stretch too long for even the narrowest band
        # (multi-Mb structural divergence): degrade to the honest worst
        # case instead of aborting the whole report, and flag it capped
        res = AlignResult(0, 0, len(b), len(a), True)
        if rows is not None:
            _coarse_rows(rows, res, t_offset, len(a))
        return res
    if rows is not None and res.errors:
        # re-walk only error-bearing segments through the Python oracle
        # (identical tie-breaking -> identical path) to get exact
        # positions; oversized segments degrade to coarse span rows
        ops = _segment_ops(a, b, pad)
        if ops is None:
            _coarse_rows(rows, res, t_offset, len(a))
        else:
            rows.extend(
                (s + t_offset, e + t_offset, kind, n)
                for s, e, kind, n in merge_error_events(ops)
            )
    return res


def _coarse_rows(
    rows: List[Tuple[int, int, str, int]],
    res: AlignResult,
    t_offset: int,
    la: int,
) -> None:
    """Per-kind whole-segment rows when exact positions are unavailable:
    counts stay reconcilable with the report even without loci."""
    span_end = t_offset + max(1, la)
    if res.sub:
        rows.append((t_offset, span_end, "sub", res.sub))
    if res.dele:
        rows.append((t_offset, span_end, "del", res.dele))
    if res.ins:
        rows.append((t_offset, min(t_offset + 1, span_end), "ins", res.ins))


def _segment_ops(a: bytes, b: bytes, pad: int) -> Optional[List[Tuple[str, int]]]:
    """Exact error events for a segment, or None when the interpreted DP
    would exceed the cells budget (caller degrades to coarse rows)."""
    from roko_tpu.eval.align import banded_align_py

    pad = max(1, pad)
    while True:
        width = abs(len(b) - len(a)) + 2 * pad + 1
        if (len(a) + 1) * width > _OPS_MAX_CELLS:
            return None
        try:
            r = banded_align_py(a, b, pad, collect_ops=True)
        except MemoryError:
            return None
        # same Ukkonen stop rule as align_with_band_growth: errors <= pad
        # proves in-band optimality; edge contact alone does not
        if r.errors <= pad or pad >= 4096:
            return r.ops or []
        pad *= 2


def merge_error_events(
    events: Optional[List[Tuple[str, int]]],
) -> List[Tuple[int, int, str, int]]:
    """Per-base (kind, truth_pos) events -> merged, sorted
    (start, end, kind, count) rows. sub/del runs merge into half-open
    intervals; insertions at the same point stack their count into one
    zero-advance row reported as [pos, pos+1) (the truth base the extra
    sequence precedes)."""
    if not events:
        return []
    events = sorted(events, key=lambda e: (e[1], e[0]))
    rows: List[Tuple[int, int, str, int]] = []
    for kind, pos in events:
        if rows:
            s, e, pkind, n = rows[-1]
            if pkind == kind and (
                (kind in ("sub", "del") and pos == e)
                or (kind == "ins" and pos == s)
            ):
                rows[-1] = (s, e if kind == "ins" else pos + 1, kind, n + 1)
                continue
        rows.append((pos, pos + 1, kind, 1))
    return rows


def _add(out: ContigAssessment, r: AlignResult) -> None:
    out.match += r.match
    out.sub += r.sub
    out.ins += r.ins
    out.dele += r.dele
    if r.hit_band_edge:
        out.band_capped_segments += 1


def _pair_contigs(
    truth: Dict[str, bytes], polished: Dict[str, bytes], k: int = K
) -> List[Tuple[str, Optional[str]]]:
    """(truth_name, polished_name) pairs: by identical names when they
    all line up, else greedy best shared-unique-k-mer matching (both
    orientations, subsampled for speed)."""
    if set(truth) == set(polished):
        return [(n, n) for n in truth]
    t_sets = {
        n: set(_unique_kmers(s, k)[0][::PAIRING_SAMPLE_STRIDE].tolist())
        for n, s in truth.items()
    }
    scores: List[Tuple[int, str, str]] = []
    for pn, ps in polished.items():
        cand = set(_unique_kmers(ps, k)[0][::PAIRING_SAMPLE_STRIDE].tolist())
        cand |= set(
            _unique_kmers(revcomp(ps), k)[0][::PAIRING_SAMPLE_STRIDE].tolist()
        )
        for tn, ts in t_sets.items():
            shared = len(ts & cand)
            if shared:
                scores.append((shared, tn, pn))
    scores.sort(reverse=True)
    pairs: List[Tuple[str, Optional[str]]] = []
    used_t, used_p = set(), set()
    for _, tn, pn in scores:
        if tn in used_t or pn in used_p:
            continue
        pairs.append((tn, pn))
        used_t.add(tn)
        used_p.add(pn)
    for tn in truth:
        if tn not in used_t:
            pairs.append((tn, None))
    return pairs


def assess_fastas(
    truth: Dict[str, bytes],
    polished: Dict[str, bytes],
    *,
    k: int = K,
    collect_errors: bool = False,
) -> AssessResult:
    """Assess every truth contig against its best polished partner.

    Truth contigs with no partner are reported as fully deleted
    (polished assembly simply lacks them); extra polished contigs are
    ignored, matching the per-truth-base rate convention."""
    # no .upper() here: assess_pair normalises case itself, and
    # _kmer_codes (pairing) uppercases internally — doubling the copies
    # of multi-megabase contigs buys nothing
    res = AssessResult()
    for tn, pn in _pair_contigs(truth, polished, k):
        if pn is None:
            res.contigs.append(
                ContigAssessment(
                    truth_name=tn,
                    polished_name=None,
                    truth_len=len(truth[tn]),
                    dele=len(truth[tn]),
                    truth_n=truth[tn].upper().count(b"N"),
                    error_intervals=(
                        [(0, len(truth[tn]), "del", len(truth[tn]))]
                        if collect_errors
                        else None
                    ),
                )
            )
        else:
            res.contigs.append(
                assess_pair(
                    truth[tn],
                    polished[pn],
                    k=k,
                    truth_name=tn,
                    polished_name=pn,
                    collect_errors=collect_errors,
                )
            )
    return res


def format_report(res: AssessResult) -> str:
    """Human-readable table in the shape of the reference's README
    comparison (total / mismatch / deletion / insertion / Qscore)."""
    lines = []
    hdr = (
        f"{'contig':<20} {'len':>10} {'err%':>8} {'mis%':>8} "
        f"{'del%':>8} {'ins%':>8} {'Q':>7}"
    )
    lines.append(hdr)
    lines.append("-" * len(hdr))

    def q(c) -> str:
        v = c.qscore
        return "inf" if math.isinf(v) else f"{v:.2f}"

    for c in res.contigs:
        name = c.truth_name + ("(rc)" if c.reverse_complemented else "")
        lines.append(
            f"{name:<20} {c.truth_len:>10} {100 * c.error_rate:>8.4f} "
            f"{100 * c.rate(c.sub):>8.4f} {100 * c.rate(c.dele):>8.4f} "
            f"{100 * c.rate(c.ins):>8.4f} {q(c):>7}"
        )
    s = res.summary()
    lines.append("-" * len(hdr))
    lines.append(
        f"{'TOTAL':<20} {s['truth_len']:>10} {s['total_error_pct']:>8.4f} "
        f"{s['mismatch_pct']:>8.4f} {s['deletion_pct']:>8.4f} "
        f"{s['insertion_pct']:>8.4f} "
        f"{'inf' if s['qscore'] is None else s['qscore']:>7}"
    )
    if s["band_capped_segments"]:
        lines.append(
            f"note: {s['band_capped_segments']} segment(s) hit the band cap; "
            "rates there are upper bounds"
        )
    if s["truth_n_bases"]:
        lines.append(
            f"note: truth contains {s['truth_n_bases']} N base(s); each "
            "aligned N counts as a mismatch (unknown truth, not "
            "necessarily a polishing error)"
        )
    return "\n".join(lines)


def write_bed(res: AssessResult, path: str) -> None:
    """Truth-space error loci as BED: ``contig  start  end  kind  count``
    (0-based half-open). ``sub``/``del`` rows span the affected truth
    bases; an ``ins`` row marks the truth base the extra polished
    sequence precedes ([pos, pos+1), count = inserted bases). Requires
    an AssessResult produced with ``collect_errors=True``."""
    with open(path, "w") as f:
        for c in res.contigs:
            if c.error_intervals is None:
                raise ValueError(
                    f"{c.truth_name}: no error intervals collected — "
                    "assess with collect_errors=True"
                )
            for start, end, kind, count in c.error_intervals:
                if kind == "ins" and end > c.truth_len:
                    # trailing insertion: anchor the row to the last base
                    start, end = max(0, c.truth_len - 1), c.truth_len
                f.write(f"{c.truth_name}\t{start}\t{end}\t{kind}\t{count}\n")


def write_json(res: AssessResult, path: str) -> None:
    doc = {
        "summary": res.summary(),
        "contigs": [
            {
                "truth": c.truth_name,
                "polished": c.polished_name,
                "truth_len": c.truth_len,
                "polished_len": c.polished_len,
                "reverse_complemented": c.reverse_complemented,
                "match": c.match,
                "mismatch": c.sub,
                "deletion": c.dele,
                "insertion": c.ins,
                "anchors": c.anchors,
                "band_capped_segments": c.band_capped_segments,
                "truth_n": c.truth_n,
                "error_rate": c.error_rate,
                "qscore": None if math.isinf(c.qscore) else c.qscore,
            }
            for c in res.contigs
        ],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=2)
        f.write("\n")
