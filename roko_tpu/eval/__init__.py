"""Assembly assessment: built-in equivalent of the external pomoxis
``assess_assembly`` step the reference's workflow depends on for its
published accuracy table (/root/reference/README.md:97-112)."""

from roko_tpu.eval.align import banded_align, AlignResult
from roko_tpu.eval.assess import (
    AssessResult,
    ContigAssessment,
    assess_fastas,
    assess_pair,
    format_report,
    write_bed,
    write_json,
)

__all__ = [
    "AlignResult",
    "AssessResult",
    "ContigAssessment",
    "assess_fastas",
    "assess_pair",
    "banded_align",
    "format_report",
    "write_bed",
    "write_json",
]
