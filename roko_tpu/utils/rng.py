"""SplitMix64 — the deterministic sampling RNG shared by the Python and C++
feature extractors.

The reference seeds libc ``rand`` from wall-clock per extractor call
(ref: gen.cpp:11), making feature matrices nondeterministic run-to-run.
Here every region derives a stable seed from (user seed, contig, region
start) and both extractor implementations use this exact generator, so
golden tests can assert bit-identical windows across languages.
"""

from __future__ import annotations

import zlib

_MASK = (1 << 64) - 1


class SplitMix64:
    """Sebastiano Vigna's SplitMix64 (public domain reference algorithm)."""

    def __init__(self, seed: int):
        self.state = seed & _MASK

    def next_u64(self) -> int:
        self.state = (self.state + 0x9E3779B97F4A7C15) & _MASK
        z = self.state
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & _MASK
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & _MASK
        return (z ^ (z >> 31)) & _MASK

    def next_below(self, n: int) -> int:
        """Uniform-ish draw in [0, n) by modulo (bias < 2**-50 for the
        n <= a-few-thousand draws used here; determinism matters more)."""
        return self.next_u64() % n


def derive_region_seed(base_seed: int, contig: str, start: int) -> int:
    """Stable per-region seed so results are independent of worker
    scheduling. crc32 keeps the contig hash trivially portable to the
    C++ side; every input is then run through the SplitMix64 finalizer
    so near-identical (seed, contig, start) triples land in unrelated
    parts of the seed space (VERDICT r2 weak #7: the previous
    crc32 | start concatenation mixed weaker than the generator it
    feeds, and truncated starts beyond 2**32)."""
    h = SplitMix64(base_seed)
    h.state = (h.state ^ zlib.crc32(contig.encode())) & _MASK
    h.next_u64()
    h.state = (h.state ^ start) & _MASK
    return h.next_u64()
