"""Tracing / profiling utilities.

The reference has no observability beyond prints and a tqdm bar
(SURVEY.md §5.1). This module provides the two tools the pipeline
stages use:

- :class:`StageTimer` — lightweight named wall-clock spans with a
  summary table, for host-side stage attribution (feature extraction,
  H2D, device compute, vote merge, stitch);
- :func:`device_trace` — context manager around ``jax.profiler`` that
  writes a TensorBoard-loadable XPlane trace when a directory is given
  and is a no-op otherwise, so callers can leave it in place
  unconditionally.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict
from typing import Callable, Dict, Iterator, Optional


class StageTimer:
    """Accumulates wall-clock time per named stage.

    >>> timer = StageTimer()
    >>> with timer("extract"):
    ...     do_work()
    >>> timer.report(print)
    """

    def __init__(self) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)

    @contextlib.contextmanager
    def __call__(self, stage: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[stage] += time.perf_counter() - t0
            self.counts[stage] += 1

    def report(self, log: Callable[[str], None] = print) -> None:
        if not self.totals:
            return
        width = max(len(s) for s in self.totals)
        total = sum(self.totals.values())
        for stage, t in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            log(
                f"  {stage:<{width}}  {t:8.2f}s  {100 * t / max(total, 1e-9):5.1f}%"
                f"  ({self.counts[stage]} spans)"
            )


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` when ``trace_dir`` is set; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up in device traces (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
