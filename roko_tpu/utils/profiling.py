"""Tracing / profiling utilities.

The reference has no observability beyond prints and a tqdm bar
(SURVEY.md §5.1). This module provides the two tools the pipeline
stages use:

- :class:`StageTimer` — lightweight named wall-clock spans with a
  summary table, for host-side stage attribution (feature extraction,
  H2D, device compute, vote merge, stitch);
- :func:`device_trace` — context manager around ``jax.profiler`` that
  writes a TensorBoard-loadable XPlane trace when a directory is given
  and is a no-op otherwise, so callers can leave it in place
  unconditionally.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict, deque
from typing import Callable, Dict, Iterator, Optional


class StageTimer:
    """Accumulates wall-clock time per named stage.

    With ``max_samples > 0`` the last N span durations per stage are
    additionally retained (bounded deque, so a long-lived service can't
    grow without bound) and :meth:`percentile` answers latency-quantile
    queries — the serving layer's ``/metrics`` p50/p99 rows are built on
    this (docs/SERVING.md).

    >>> timer = StageTimer()
    >>> with timer("extract"):
    ...     do_work()
    >>> timer.report(print)
    """

    def __init__(self, max_samples: int = 0) -> None:
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.max_samples = max_samples
        self.samples: Dict[str, deque] = {}
        # the serving path records from every HTTP handler thread
        # concurrently; the += read-modify-writes would lose updates
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def __call__(self, stage: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(stage, time.perf_counter() - t0)

    def record(self, stage: str, seconds: float) -> None:
        """Account one span measured by the caller (threads that time a
        request across a queue hand-off can't hold a context manager
        open on both sides)."""
        with self._lock:
            self.totals[stage] += seconds
            self.counts[stage] += 1
            if self.max_samples:
                window = self.samples.get(stage)
                if window is None:
                    window = self.samples[stage] = deque(
                        maxlen=self.max_samples
                    )
                window.append(seconds)

    def percentile(self, stage: str, q: float) -> Optional[float]:
        """q-th percentile (0..100) over the retained window of ``stage``
        spans; None when no samples were retained."""
        with self._lock:
            window = self.samples.get(stage)
            if not window:
                return None
            ordered = sorted(window)
        # nearest-rank on the retained window: exact for the sizes a
        # metrics endpoint serves, no numpy dependency in the hot path
        rank = max(0, min(len(ordered) - 1, round(q / 100 * (len(ordered) - 1))))
        return ordered[int(rank)]

    def report(self, log: Callable[[str], None] = print) -> None:
        if not self.totals:
            return
        width = max(len(s) for s in self.totals)
        total = sum(self.totals.values())
        for stage, t in sorted(self.totals.items(), key=lambda kv: -kv[1]):
            log(
                f"  {stage:<{width}}  {t:8.2f}s  {100 * t / max(total, 1e-9):5.1f}%"
                f"  ({self.counts[stage]} spans)"
            )


@contextlib.contextmanager
def device_trace(trace_dir: Optional[str]) -> Iterator[None]:
    """``jax.profiler.trace`` when ``trace_dir`` is set; no-op otherwise."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield


def capture_device_trace(
    trace_dir: str,
    seconds: float,
    sleep: Callable[[float], None] = time.sleep,
) -> str:
    """Hold a ``jax.profiler`` XPlane capture open for ``seconds`` of
    wall time and return ``trace_dir`` — the live-service half of
    :func:`device_trace`: ``POST /profilez?seconds=N`` wraps the next N
    seconds of device steps without restarting anything
    (docs/OBSERVABILITY.md). The capture covers whatever the process
    dispatches in the window; the result loads in TensorBoard."""
    import jax

    jax.profiler.start_trace(trace_dir)
    try:
        sleep(max(0.0, seconds))
    finally:
        jax.profiler.stop_trace()
    return trace_dir


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named region that shows up in device traces (TraceAnnotation)."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
