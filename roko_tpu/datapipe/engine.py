"""Shuffle/shard/batch engine: global shuffle without a global read.

The epoch stream over a span table is defined by three pure functions
of (rng, num_shards, shard_id):

1. **block order** — one seeded permutation over span blocks;
2. **row order** — a per-block permutation derived from a per-block
   seed (the seeds are drawn in canonical block order, so every shard
   — and a host simulating another shard — consumes the rng
   identically and can reproduce any block's rows without reading it);
3. **shard assignment** — block ``b`` belongs to shard
   ``b % num_shards`` (canonical id, not permuted position): per-shard
   row counts are fixed across epochs, and the shard streams cover the
   corpus disjointly — their union is exactly the 1-shard stream as a
   multiset;
4. **mix groups** — each shard pools ``mix_blocks`` consecutive blocks
   of its permuted sequence and applies one seeded permutation across
   the pool, so a batch mixes rows from up to ``mix_blocks`` random
   corpus regions instead of 1-2 disk-adjacent ones (HDF5 corpora are
   written contig-by-contig; without this, every batch would be
   locality-correlated — the within-batch diversity the legacy
   shuffle-buffer reader provided).

A host therefore reads only its own blocks (sequential HDF5 span
reads), holds at most a mix group of rows at any moment (asserted via
:class:`ReadStats`), and fast-forwards to any sample position in
O(spans skipped) — wholly-skipped mix groups are never read.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

import numpy as np


class ReadStats:
    """Read-accounting hook for the index reader: how many rows were
    actually read from disk, and the high-water mark of rows resident
    on the host at any moment — the assertion that global shuffle
    never materialises the corpus.

    Residency is measured as ``rows_read - rows_emitted``: every row
    read but not yet handed out in a batch, INCLUDING rows sitting in
    the prefetch queue between the producer and consumer threads (an
    earlier consumer-buffer-only count under-reported by the queue
    depth). The two counters are bumped from different threads; int
    increments are GIL-atomic and a high-water mark tolerates the
    benign race."""

    def __init__(self) -> None:
        self.rows_read = 0
        self.rows_emitted = 0
        self.blocks_read = 0
        self.batches = 0
        self.max_resident_rows = 0

    def note_read(self, rows: int) -> None:
        self.rows_read += int(rows)
        self.blocks_read += 1
        self._note_resident()

    def note_emitted(self, rows: int) -> None:
        self.rows_emitted += int(rows)

    def _note_resident(self) -> None:
        resident = self.rows_read - self.rows_emitted
        if resident > self.max_resident_rows:
            self.max_resident_rows = int(resident)

    def note_batch(self) -> None:
        self.batches += 1
        self._note_resident()


@dataclasses.dataclass(frozen=True)
class Schedule:
    """One shard's epoch schedule: its blocks in global-stream order
    plus the per-block row-permutation seeds (for ALL blocks — any
    shard's rows are reproducible from the schedule alone)."""

    mine: Tuple[int, ...]  # this shard's block ids, in permuted order
    seeds: Optional[np.ndarray]  # per-block row-perm seeds; None = no shuffle
    counts: Tuple[int, ...]  # effective rows per block (post-holdout)

    def row_order(self, block: int, kept: Optional[np.ndarray] = None) -> np.ndarray:
        """Row emission order WITHIN ``block`` (indices into the span's
        rows). ``kept`` restricts to a holdout-filtered subset."""
        base = (
            np.asarray(kept)
            if kept is not None
            else np.arange(self.counts[block])
        )
        if self.seeds is None:
            return base
        perm = np.random.default_rng(int(self.seeds[block])).permutation(len(base))
        return base[perm]

    def shard_rows(self) -> int:
        return sum(self.counts[b] for b in self.mine)


def epoch_schedule(
    counts: Sequence[int],
    rng: Optional[np.random.Generator],
    *,
    num_shards: int = 1,
    shard_id: int = 0,
) -> Schedule:
    """Build one epoch's schedule. The rng is consumed identically for
    every (num_shards, shard_id) — one block permutation plus one seed
    per block, both over ALL blocks in canonical order — so shard
    streams partition the 1-shard stream exactly."""
    if not 0 <= shard_id < num_shards:
        raise ValueError(f"shard_id {shard_id} outside [0, {num_shards})")
    n = len(counts)
    if rng is None:
        order = np.arange(n)
        seeds = None
    else:
        order = rng.permutation(n)
        # canonical-order draw: O(blocks) state, independent of which
        # shard is asking; the per-block perms materialise lazily only
        # for blocks actually read
        seeds = rng.integers(0, np.iinfo(np.int64).max, size=n, dtype=np.int64)
    mine = tuple(int(b) for b in order if b % num_shards == shard_id)
    return Schedule(mine=mine, seeds=seeds, counts=tuple(int(c) for c in counts))


def shard_row_counts(counts: Sequence[int], num_shards: int) -> List[int]:
    """Fixed per-shard row totals (canonical modulo assignment)."""
    totals = [0] * num_shards
    for b, c in enumerate(counts):
        totals[b % num_shards] += int(c)
    return totals


def batches_per_epoch(
    counts: Sequence[int],
    batch_size: int,
    num_shards: int = 1,
    *,
    drop_remainder: bool = False,
) -> int:
    """The equalised step count every shard must emit — the max over
    shards of its own batch count, so collective-issuing training
    loops stay in lockstep (shards short on rows pad with zero-weight
    batches)."""
    per = []
    for rows in shard_row_counts(counts, num_shards):
        per.append(rows // batch_size if drop_remainder else -(-rows // batch_size))
    return max(per) if per else 0


def _zero_batch(
    batch_size: int, row_template: Tuple[tuple, str, tuple, str]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    x_shape, x_dtype, y_shape, y_dtype = row_template
    x = np.zeros((batch_size,) + tuple(x_shape), np.dtype(x_dtype))
    y = np.zeros((batch_size,) + tuple(y_shape), np.dtype(y_dtype or np.int32))
    return x, y, np.zeros(batch_size, np.float32)


#: default cross-block mix-group width: a batch draws from up to this
#: many randomly-permuted blocks (8 x 256-row default blocks = a
#: 2048-row pool, the scale of the legacy reader's shuffle buffer)
DEFAULT_MIX_BLOCKS = 8


def iter_span_batches(
    counts: Sequence[int],
    read_rows: Callable[[int, np.ndarray], Tuple[np.ndarray, np.ndarray]],
    batch_size: int,
    *,
    rng: Optional[np.random.Generator] = None,
    num_shards: int = 1,
    shard_id: int = 0,
    kept: Optional[Sequence[Optional[np.ndarray]]] = None,
    drop_remainder: bool = False,
    pad_to: Optional[int] = None,
    skip_batches: int = 0,
    start_samples: Optional[int] = None,
    min_batches: Optional[int] = None,
    prefetch: int = 0,
    mix_blocks: int = DEFAULT_MIX_BLOCKS,
    stats: Optional[ReadStats] = None,
    row_template: Optional[Tuple[tuple, str, tuple, str]] = None,
    cleanup: Optional[Callable[[], None]] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
    """Yield (x, y, weight) batches of this shard's slice of the epoch
    stream. Same (x, y, w) contract as the legacy datasets' ``batches``.

    ``read_rows(block, order)`` returns the block's rows in emission
    order — the ONLY place data bytes move; everything else is index
    arithmetic, which is what makes ``skip_batches``/``start_samples``
    fast-forward O(spans skipped): whole skipped blocks are counted,
    never read.

    ``min_batches`` (with ``pad_to``) equalises the emitted batch count
    across shards: a shard that runs out of rows emits all-padding
    zero-weight batches so lockstep collectives on a pod never starve.

    ``cleanup`` (close file handles, release buffers) runs when the
    BLOCK generator finishes or is closed — i.e. in the same thread
    that called ``read_rows``. With ``prefetch`` the reads happen on
    the producer thread, so a consumer-side ``finally`` would race a
    close against an in-flight read; this hook cannot.
    """
    eff_counts = (
        [len(k) if k is not None else int(c) for c, k in zip(counts, kept)]
        if kept is not None
        else [int(c) for c in counts]
    )
    sched = epoch_schedule(
        eff_counts, rng, num_shards=num_shards, shard_id=shard_id
    )
    start = (
        int(start_samples)
        if start_samples is not None
        else skip_batches * batch_size
    )

    # this shard's permuted block sequence, pooled into mix groups of
    # up to mix_blocks blocks; each group is an atomic stream unit
    width = max(1, mix_blocks)
    groups = [
        sched.mine[i : i + width] for i in range(0, len(sched.mine), width)
    ]

    def _group_rows(group) -> Tuple[np.ndarray, np.ndarray]:
        """Read one mix group and permute rows ACROSS its blocks (one
        seeded draw — deterministic, shard-local, index-only)."""
        xs, ys = [], []
        for b in group:
            if sched.counts[b] == 0:
                continue
            order = sched.row_order(b, kept[b] if kept is not None else None)
            x, y = read_rows(b, order)
            if stats is not None:
                stats.note_read(len(order))
            xs.append(x)
            ys.append(y)
        x = xs[0] if len(xs) == 1 else np.concatenate(xs)
        y = ys[0] if len(ys) == 1 else np.concatenate(ys)
        if sched.seeds is not None and len(xs) > 1:
            perm = np.random.default_rng(
                np.random.SeedSequence([int(sched.seeds[group[0]]), 1])
            ).permutation(len(x))
            x, y = x[perm], y[perm]
        return x, y

    def _blocks() -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        try:
            pos = 0
            for group in groups:
                size = sum(sched.counts[b] for b in group)
                if size == 0:
                    continue
                if pos + size <= start:
                    pos += size  # fast-forward: whole group skipped, never read
                    continue
                x, y = _group_rows(group)
                if pos < start:
                    # the sliced-off prefix was read but will never be
                    # emitted — credit it, or every later residency
                    # sample would carry the discarded rows forever
                    if stats is not None:
                        stats.note_emitted(start - pos)
                    x, y = x[start - pos :], y[start - pos :]
                pos += size
                yield x, y
        finally:
            if cleanup is not None:
                cleanup()

    stream: Iterator = _blocks()
    if prefetch > 0:
        # bounded host readahead: the block reads run in a producer
        # thread while the consumer batches/places — the same helper
        # that stages device batches (training/data.py)
        from roko_tpu.training.data import prefetch_to_device

        stream = prefetch_to_device(stream, prefetch, lambda item: item)

    emitted = 0
    buf_x: List[np.ndarray] = []
    buf_y: List[np.ndarray] = []
    held = 0

    def _cut(n: int) -> Tuple[np.ndarray, np.ndarray]:
        nonlocal buf_x, buf_y, held
        x = buf_x[0] if len(buf_x) == 1 else np.concatenate(buf_x)
        y = buf_y[0] if len(buf_y) == 1 else np.concatenate(buf_y)
        out = x[:n], y[:n]
        buf_x = [x[n:]] if len(x) > n else []
        buf_y = [y[n:]] if len(y) > n else []
        held = max(0, len(x) - n)
        return out

    def _emit(x, y, w, real_rows):
        nonlocal emitted
        emitted += 1
        if stats is not None:
            stats.note_emitted(real_rows)
            stats.note_batch()
        return x, y, w

    for x, y in stream:
        buf_x.append(x)
        buf_y.append(y)
        held += len(x)
        while held >= batch_size:
            xb, yb = _cut(batch_size)
            yield _emit(
                xb, yb, np.ones(batch_size, np.float32), batch_size
            )
    if held:
        xb, yb = _cut(held)
        real = len(xb)
        if drop_remainder:
            pass
        elif pad_to is not None:
            pad = pad_to - len(xb)
            w = np.concatenate(
                [np.ones(len(xb), np.float32), np.zeros(pad, np.float32)]
            )
            if pad > 0:
                xb = np.concatenate([xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
                yb = np.concatenate([yb, np.zeros((pad,) + yb.shape[1:], yb.dtype)])
            yield _emit(xb, yb, w, real)
        else:
            yield _emit(xb, yb, np.ones(len(xb), np.float32), real)
    if min_batches is not None and emitted < min_batches:
        if pad_to is None or row_template is None:
            raise ValueError(
                "min_batches needs pad_to and row_template to synthesise "
                "padding batches"
            )
        while emitted < min_batches:
            yield _emit(*_zero_batch(pad_to, row_template), 0)
