"""Index layer: a persistent manifest over an HDF5 training file set.

The manifest records, per file, the lexicographically-sorted basename,
byte size, content digests, and per-group row counts, plus the fixed
(file, group, row-range) span table cut at ``block_size`` rows — the
unit the shuffle/shard engine permutes. Everything downstream (shard
assignment, epoch order, fast-forward) is a pure function of
(manifest, num_shards, shard_id, seed), which is what makes sharded
kill-and-resume bit-identical and lets every host agree on the stream
without talking to each other.

Two digests per file:

- ``sha256`` — the full content hash, computed once at build time (the
  manifest is persistent precisely so this cost is paid once);
- ``sample_sha256`` — size + first/middle/last MiB, cheap enough to
  re-check at every open. Verification uses the sample digest; a
  mutation inside an untouched-size file larger than ~3 MiB can evade
  it between full verifies, but every re-extraction, truncation,
  append, or file swap is caught at open time.

A stale *default* sidecar manifest (the corpus was legitimately
regenerated in place) is rebuilt with a loud log line; an *explicitly
pinned* manifest (``--data-manifest`` / ``manifest_path=``) that no
longer matches the files refuses with the per-file diff — pinning is
how a resumed or multi-host run asserts "the corpus I trained on".
"""

from __future__ import annotations

import dataclasses
import glob as _glob
import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

MANIFEST_BASENAME = "roko_datapipe_manifest.json"
MANIFEST_VERSION = 1
#: bytes hashed per stripe by the cheap open-time sample digest
SAMPLE_BYTES = 1 << 20
#: default span-block granularity (rows); matches the legacy streaming
#: chunk size — big enough for streaming HDF5 reads, small enough that
#: block-granular shuffle approaches a global permutation
DEFAULT_BLOCK_SIZE = 256


class ManifestError(RuntimeError):
    """Manifest build/load failure (no inputs, inconsistent geometry...)."""


class ManifestMismatch(ManifestError):
    """The file set on disk does not match the manifest (missing/extra/
    changed files); message carries the per-path diff."""


def _is_remote(path: str) -> bool:
    from roko_tpu.datapipe.io import path_scheme

    return path_scheme(path) not in ("", "file")


def resolve_file_set(spec: Union[str, Sequence[str]]) -> List[str]:
    """Resolve a file, directory, or list of paths/globs into the
    canonical file set: lexicographic by basename (stable across hosts
    and filesystems — directory enumeration order is not), symlinked
    duplicates removed by ``data.hdf5.file_identity``.

    A store-scheme URL (``gs://``/``s3://``/``http(s)://``) names ONE
    corpus file and passes through verbatim — object stores have no
    portable listing/glob, so a remote corpus is spelled as an explicit
    URL list; the URL itself is the dedup identity."""
    from roko_tpu.data.hdf5 import file_identity, hdf5_files

    specs = [spec] if isinstance(spec, str) else list(spec)
    if not specs:
        raise ManifestError("empty input file-set spec")
    found: List[str] = []
    for s in specs:
        if _is_remote(s):
            found.append(s)
        elif os.path.isdir(s) or os.path.isfile(s):
            found.extend(hdf5_files(s))
        else:
            matches = sorted(_glob.glob(s))
            if not matches:
                raise ManifestError(f"no HDF5 inputs match {s!r}")
            for m in matches:
                found.extend(hdf5_files(m))
    out: List[str] = []
    seen: set = set()
    for p in sorted(found, key=lambda p: (os.path.basename(p), p)):
        ident = p if _is_remote(p) else file_identity(p)
        if ident in seen:
            continue  # symlinked/duplicate path to the same file
        seen.add(ident)
        out.append(p)
    if not out:
        raise ManifestError(f"no HDF5 inputs under {spec!r}")
    return out


def _file_size(path: str) -> int:
    """Byte size through the input seam: local files stat; remote ones
    seek-to-end on a ranged-read handle (no whole-object download)."""
    if not _is_remote(path):
        return os.path.getsize(path)
    from roko_tpu.datapipe.io import open_input

    with open_input(path) as f:
        return f.seek(0, os.SEEK_END)


def _sample_digest(path: str) -> str:
    """sha256 over (size, first/middle/last SAMPLE_BYTES stripes)."""
    from roko_tpu.datapipe.io import open_input

    size = _file_size(path)
    h = hashlib.sha256(str(size).encode())
    with open_input(path) as f:
        offsets = {0, max(0, size // 2 - SAMPLE_BYTES // 2), max(0, size - SAMPLE_BYTES)}
        for off in sorted(offsets):
            f.seek(off)
            h.update(f.read(SAMPLE_BYTES))
    return h.hexdigest()


def _full_digest(path: str) -> str:
    from roko_tpu.datapipe.io import open_input

    h = hashlib.sha256()
    with open_input(path) as f:
        for chunk in iter(lambda: f.read(1 << 22), b""):
            h.update(chunk)
    return h.hexdigest()


@dataclasses.dataclass(frozen=True)
class FileEntry:
    name: str  # basename — the cross-host identity (roots differ)
    size: int
    sha256: str  # full content (build-time)
    sample_sha256: str  # cheap open-time check
    groups: Tuple[Tuple[str, int], ...]  # (group name, rows)


@dataclasses.dataclass(frozen=True)
class Span:
    """One fixed-size block of consecutive rows inside (file, group) —
    the unit the shuffle/shard engine permutes and the reader reads."""

    file_idx: int
    group: str
    start: int
    count: int


@dataclasses.dataclass(frozen=True)
class Manifest:
    files: Tuple[FileEntry, ...]
    block_size: int
    labeled: bool
    x_shape: Tuple[int, ...]  # per-row example shape
    x_dtype: str
    y_shape: Tuple[int, ...]  # per-row label shape (() when unlabeled)
    y_dtype: str

    @property
    def total_rows(self) -> int:
        return sum(r for fe in self.files for _, r in fe.groups)

    @property
    def fingerprint(self) -> str:
        """Corpus identity: digest over the per-file entries (content
        digests included). Independent of block_size — recutting spans
        does not change what corpus this is."""
        blob = json.dumps(
            [
                [fe.name, fe.size, fe.sha256, list(map(list, fe.groups))]
                for fe in self.files
            ],
            sort_keys=True,
        )
        return hashlib.sha256(blob.encode()).hexdigest()

    def fingerprint32_pair(self) -> Tuple[int, int]:
        """The fingerprint's first 64 bits as two signed int32s — the
        form that survives a jax/orbax checkpoint round-trip with x64
        disabled (``data_state.pipe`` in training/loop.py)."""
        v = int(self.fingerprint[:16], 16)
        hi, lo = (v >> 32) & 0xFFFFFFFF, v & 0xFFFFFFFF
        return (hi - (1 << 32) if hi >= 1 << 31 else hi,
                lo - (1 << 32) if lo >= 1 << 31 else lo)

    def spans(self, block_size: Optional[int] = None) -> List[Span]:
        """The (file, group, row-range) span table at ``block_size``
        granularity (default: the manifest's own)."""
        bs = block_size or self.block_size
        out: List[Span] = []
        for fi, fe in enumerate(self.files):
            for g, rows in fe.groups:
                for start in range(0, rows, bs):
                    out.append(Span(fi, g, start, min(bs, rows - start)))
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": MANIFEST_VERSION,
            "block_size": self.block_size,
            "labeled": self.labeled,
            "x_shape": list(self.x_shape),
            "x_dtype": self.x_dtype,
            "y_shape": list(self.y_shape),
            "y_dtype": self.y_dtype,
            "fingerprint": self.fingerprint,
            "files": [
                {
                    "name": fe.name,
                    "size": fe.size,
                    "sha256": fe.sha256,
                    "sample_sha256": fe.sample_sha256,
                    "groups": [[g, r] for g, r in fe.groups],
                }
                for fe in self.files
            ],
        }

    @staticmethod
    def from_dict(raw: Dict[str, Any]) -> "Manifest":
        files = tuple(
            FileEntry(
                name=f["name"],
                size=int(f["size"]),
                sha256=f["sha256"],
                sample_sha256=f["sample_sha256"],
                groups=tuple((g, int(r)) for g, r in f["groups"]),
            )
            for f in raw["files"]
        )
        return Manifest(
            files=files,
            block_size=int(raw["block_size"]),
            labeled=bool(raw["labeled"]),
            x_shape=tuple(raw["x_shape"]),
            x_dtype=raw["x_dtype"],
            y_shape=tuple(raw["y_shape"]),
            y_dtype=raw["y_dtype"],
        )

    def save(self, path: str) -> None:
        """Atomic write (tmp + fsync + rename), same discipline as the
        checkpoint integrity manifests. A remote sidecar goes through
        ``open_output`` (the store's verified atomic upload)."""
        if _is_remote(path):
            from roko_tpu.datapipe.io import abort_output, open_output

            fh = open_output(path, "w")
            try:
                json.dump(self.to_dict(), fh, indent=1, sort_keys=True)
            except BaseException:
                abort_output(fh)
                raise
            fh.close()
            return
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "Manifest":
        from roko_tpu.datapipe.io import open_input

        try:
            with open_input(path) as f:  # binary for local AND remote
                raw = json.loads(f.read().decode("utf-8"))
        except (OSError, ValueError, RuntimeError) as e:
            # RuntimeError: a store-scheme sidecar that 404s/truncates
            # (datapipe.store.StoreError and subclasses)
            if isinstance(e, RuntimeError) and not _is_remote(path):
                raise
            raise ManifestError(f"unreadable manifest {path}: {e}") from None
        if raw.get("version") != MANIFEST_VERSION:
            raise ManifestError(
                f"manifest {path} has version {raw.get('version')!r}; "
                f"this build reads version {MANIFEST_VERSION}"
            )
        return Manifest.from_dict(raw)

    def verify_files(self, paths: Sequence[str]) -> None:
        """Check the resolved on-disk file set against the manifest.

        Raises :class:`ManifestMismatch` with the full per-path diff —
        missing (manifest names the file, disk doesn't have it), extra
        (on disk but not in the manifest), and changed (size or sampled
        content digest differs). This is the loud refusal that keeps a
        host with a diverged view of the corpus — or a mutated file —
        from silently shifting every shard's stream.
        """
        by_name = {os.path.basename(p): p for p in paths}
        missing = [fe.name for fe in self.files if fe.name not in by_name]
        known = {fe.name for fe in self.files}
        extra = sorted(n for n in by_name if n not in known)
        changed: List[str] = []
        for fe in self.files:
            p = by_name.get(fe.name)
            if p is None:
                continue
            size = _file_size(p)
            if size != fe.size:
                changed.append(f"{fe.name} (size {fe.size} -> {size})")
            elif _sample_digest(p) != fe.sample_sha256:
                changed.append(f"{fe.name} (content digest changed)")
        if missing or extra or changed:
            parts = []
            if missing:
                parts.append("missing: " + ", ".join(missing))
            if extra:
                parts.append("extra: " + ", ".join(extra))
            if changed:
                parts.append("changed: " + ", ".join(changed))
            raise ManifestMismatch(
                "file set does not match manifest "
                f"(fingerprint {self.fingerprint[:12]}): " + "; ".join(parts)
            )


def _scan_file(path: str, require_labels: bool) -> Tuple[FileEntry, Dict[str, Any]]:
    from roko_tpu.data.hdf5 import data_group_names
    from roko_tpu.datapipe.io import open_h5

    groups: List[Tuple[str, int]] = []
    geom: Dict[str, Any] = {}
    with open_h5(path) as fd:
        for g in data_group_names(fd):
            ex = fd[g]["examples"]
            if require_labels and "labels" not in fd[g]:
                raise ManifestError(f"{path}:{g} has no labels")
            groups.append((g, int(ex.shape[0])))
            row_geom = {
                "x_shape": tuple(ex.shape[1:]),
                "x_dtype": str(ex.dtype),
            }
            if "labels" in fd[g]:
                lb = fd[g]["labels"]
                row_geom["y_shape"] = tuple(lb.shape[1:])
                row_geom["y_dtype"] = str(lb.dtype)
            if not geom:
                geom = row_geom
            elif geom != row_geom:
                raise ManifestError(
                    f"inconsistent row geometry across the file set: "
                    f"{path}:{g} has {row_geom}, earlier groups {geom}"
                )
    entry = FileEntry(
        name=os.path.basename(path),
        size=_file_size(path),
        sha256=_full_digest(path),
        sample_sha256=_sample_digest(path),
        groups=tuple(groups),
    )
    return entry, geom


def build_manifest(
    spec: Union[str, Sequence[str]],
    *,
    block_size: int = DEFAULT_BLOCK_SIZE,
    require_labels: bool = True,
    log=None,
) -> Tuple[Manifest, List[str]]:
    """Scan the resolved file set into a fresh manifest. One full-file
    hash per file — paid once, the manifest persists."""
    paths = resolve_file_set(spec)
    names = [os.path.basename(p) for p in paths]
    if len(set(names)) != len(names):
        dup = sorted({n for n in names if names.count(n) > 1})
        raise ManifestError(
            "duplicate basenames in the file set (the manifest's "
            f"cross-host identity is the basename): {', '.join(dup)}"
        )
    # every resolved file gets an entry — even one with no data groups
    # (zero spans): manifest.files[i] must stay aligned with the
    # resolved path list, and verify_files must not call a known-empty
    # file "extra" on every later load
    entries: List[FileEntry] = []
    geom: Dict[str, Any] = {}
    for p in paths:
        entry, g = _scan_file(p, require_labels)
        entries.append(entry)
        if not g:
            continue
        if not geom:
            geom = g
        elif geom != g:
            raise ManifestError(
                f"inconsistent row geometry across the file set at {p}: "
                f"{g} vs {geom}"
            )
    if not geom or not any(fe.groups for fe in entries):
        raise ManifestError(f"no training groups found under {spec!r}")
    manifest = Manifest(
        files=tuple(entries),
        block_size=block_size,
        labeled="y_dtype" in geom,
        x_shape=geom["x_shape"],
        x_dtype=geom["x_dtype"],
        y_shape=geom.get("y_shape", ()),
        y_dtype=geom.get("y_dtype", ""),
    )
    if log is not None:
        log(
            f"datapipe: indexed {len(manifest.files)} file(s), "
            f"{manifest.total_rows} rows, {len(manifest.spans())} spans "
            f"(block {manifest.block_size}), "
            f"fingerprint {manifest.fingerprint[:12]}"
        )
    return manifest, paths


def default_manifest_path(spec: Union[str, Sequence[str]]) -> Optional[str]:
    """Where the sidecar manifest lives for a simple spec: inside a
    directory input, next to a single-file input (remote single-URL
    specs included — the sidecar uploads next to the corpus object),
    nowhere (in-memory only) for list/glob specs unless the caller
    pins a path."""
    if isinstance(spec, str):
        if _is_remote(spec):
            return spec + ".manifest.json"
        if os.path.isdir(spec):
            return os.path.join(spec, MANIFEST_BASENAME)
        if os.path.isfile(spec):
            return spec + ".manifest.json"
    return None


def _manifest_exists(mpath: str) -> bool:
    """``os.path.exists`` generalized through the store: a remote
    sidecar exists when a ``stat`` succeeds (any store failure —
    missing object, endpoint down — reads as "no sidecar"; the build
    path then decides loudly what to do)."""
    if not _is_remote(mpath):
        return os.path.exists(mpath)
    from roko_tpu.datapipe import store as _store

    try:
        _store.install().stat(mpath)
        return True
    except (OSError, RuntimeError, ValueError):
        return False


def load_or_build_manifest(
    spec: Union[str, Sequence[str]],
    *,
    manifest_path: Optional[str] = None,
    block_size: int = DEFAULT_BLOCK_SIZE,
    require_labels: bool = True,
    log=None,
) -> Tuple[Manifest, List[str]]:
    """Load a persisted manifest if one matches the files, else build
    (and persist, best-effort) a fresh one.

    An explicitly pinned ``manifest_path`` that mismatches the on-disk
    files REFUSES with the path diff (the caller asserted a corpus
    identity); the default sidecar merely logs loudly and rebuilds (a
    regenerated corpus is a legitimate state, not an error).
    """
    pinned = manifest_path is not None
    mpath = manifest_path or default_manifest_path(spec)
    paths = resolve_file_set(spec)
    if mpath and _manifest_exists(mpath):
        try:
            # ManifestError covers unreadable/corrupt/version-mismatch
            # sidecars as well as a file-set mismatch — for the DEFAULT
            # sidecar all of them mean "rebuild the index loudly", not
            # "refuse a file the user never created"; only a PINNED
            # manifest is an identity assertion worth refusing over
            manifest = Manifest.load(mpath)
            manifest.verify_files(paths)
        except ManifestError as e:
            if pinned:
                raise
            if log is not None:
                log(
                    f"datapipe: manifest {mpath} is stale or unreadable "
                    f"for the file set on disk ({e}); rebuilding the index"
                )
        else:
            if manifest.block_size != block_size:
                manifest = dataclasses.replace(manifest, block_size=block_size)
            return manifest, paths
    manifest, paths = build_manifest(
        paths, block_size=block_size, require_labels=require_labels, log=log
    )
    if mpath:
        try:
            manifest.save(mpath)
        except OSError as e:  # read-only corpus dir: index stays in RAM
            if log is not None:
                log(f"datapipe: could not persist manifest at {mpath}: {e}")
        except RuntimeError as e:
            # store upload failure (read-only bucket, endpoint down):
            # same posture — the index stays in RAM for this run
            if not _is_remote(mpath):
                raise
            if log is not None:
                log(f"datapipe: could not persist manifest at {mpath}: {e}")
    return manifest, paths


def crosscheck_fingerprint(manifest: Manifest, log=None) -> None:
    """Multi-host agreement check: every process must have computed the
    same corpus fingerprint, or shard assignment is undefined. Gathers
    the 64-bit fingerprint prefix over jax's coordination service and
    refuses loudly (with this host's file list in the message) on any
    divergence. No-op single-process."""
    import jax

    if jax.process_count() <= 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils

    hi, lo = manifest.fingerprint32_pair()
    mine = np.asarray([hi, lo, len(manifest.files), manifest.total_rows], np.int64)
    allv = np.asarray(multihost_utils.process_allgather(mine))
    bad = [i for i in range(allv.shape[0]) if not np.array_equal(allv[i], mine)]
    if bad:
        names = ", ".join(fe.name for fe in manifest.files)
        raise ManifestMismatch(
            f"hosts disagree on the training file set: process "
            f"{jax.process_index()} fingerprint {manifest.fingerprint[:12]} "
            f"({len(manifest.files)} files, {manifest.total_rows} rows: "
            f"{names}) differs from process(es) {bad}. Every host must "
            "see the identical corpus — sync the files or pin a shared "
            "manifest with --data-manifest, then compare each host's "
            "refusal line to see the per-host diff."
        )
