"""Deterministic sharded input data plane (ROADMAP item 5).

A seqio/t5x-style input layer between the HDF5 feature files on disk and
the device train step (docs/TRAINING.md "Sharded input pipeline"):

- ``manifest.py`` — the index layer: scans an HDF5 file set (file, dir,
  or list of paths/globs) into a persistent manifest of (file, group,
  row-range) spans with sizes and a content fingerprint, so shard
  assignment is a pure function of (manifest, num_shards, shard_id,
  seed) and a mutated/diverged corpus is refused loudly instead of
  silently changing the stream.
- ``engine.py`` — the shuffle/shard/batch engine: global shuffle
  without a global read (seeded block permutation + per-block row
  permutations derived from per-block seeds), strided shard assignment
  whose union over shards is exactly the 1-shard stream, O(spans
  skipped) fast-forward, bounded host prefetch, and a read-accounting
  hook proving the corpus is never materialised.
- ``dataset.py`` — :class:`ShardedDataset`: the manifest-backed dataset
  the training loop consumes (single-host and dp-mesh pods), with a
  sample-granular checkpointable iterator (``state()``/``restore``)
  wired into the checkpoint ``data_state``.
- ``io.py`` — the pluggable input opener behind every span read
  (ROADMAP item 5a): fsspec-style ``opener(path, mode)`` signature,
  local-path (+ ``file://``) default, ``register_opener`` for remote
  schemes — object-storage input is one registered adapter away.

The two legacy datasets (``training/data.py`` InMemoryDataset,
``training/lazy_data.py`` StreamingDataset) keep their public paths but
delegate ``batches(..., skip_batches=)`` to this engine.
"""

from roko_tpu.datapipe.dataset import CheckpointableIterator, ShardedDataset
from roko_tpu.datapipe.engine import ReadStats, epoch_schedule, iter_span_batches
from roko_tpu.datapipe.io import (
    ensure_local,
    open_input,
    open_output,
    register_opener,
    register_writer,
    registered_schemes,
)
from roko_tpu.datapipe.manifest import (
    MANIFEST_BASENAME,
    Manifest,
    ManifestError,
    ManifestMismatch,
    build_manifest,
    load_or_build_manifest,
    resolve_file_set,
)

__all__ = [
    "CheckpointableIterator",
    "ShardedDataset",
    "ReadStats",
    "epoch_schedule",
    "iter_span_batches",
    "MANIFEST_BASENAME",
    "Manifest",
    "ManifestError",
    "ManifestMismatch",
    "build_manifest",
    "load_or_build_manifest",
    "ensure_local",
    "open_input",
    "open_output",
    "register_opener",
    "register_writer",
    "registered_schemes",
    "resolve_file_set",
]
