"""ShardedDataset: the manifest-backed dataset the training loop
consumes, plus the sample-granular checkpointable iterator.

One dataset object = one (corpus, shard) view. The stream is a pure
function of (manifest fingerprint, num_shards, shard_id, seed, epoch):
every host derives the same global order and reads only its own span
blocks, so a pod needs no data coordination beyond agreeing on the
manifest — and a killed-and-resumed run replays bit-identically from
any sample position (docs/TRAINING.md "Sharded input pipeline").
"""

from __future__ import annotations

import copy
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from roko_tpu.datapipe import engine as _engine
from roko_tpu.datapipe.manifest import (
    DEFAULT_BLOCK_SIZE,
    Manifest,
    load_or_build_manifest,
)


class ShardedDataset:
    """Deterministic sharded view over an HDF5 file set.

    ``batches`` keeps the legacy ``(x, y, w)`` iterator contract of
    InMemoryDataset/StreamingDataset (the train loop and ``evaluate``
    treat all three interchangeably); ``iterator`` wraps it in a
    :class:`CheckpointableIterator` with ``state()``/``restore``.
    """

    def __init__(
        self,
        path: Union[str, Sequence[str]],
        *,
        num_shards: int = 1,
        shard_id: int = 0,
        seed: int = 0,
        block_size: Optional[int] = None,
        prefetch_blocks: int = 2,
        mix_blocks: int = _engine.DEFAULT_MIX_BLOCKS,
        preload: bool = False,
        manifest_path: Optional[str] = None,
        require_labels: bool = True,
        log=None,
        manifest: Optional[Manifest] = None,
        paths: Optional[List[str]] = None,
        opener=None,
    ) -> None:
        if not 0 <= shard_id < num_shards:
            raise ValueError(
                f"shard_id {shard_id} outside [0, num_shards={num_shards})"
            )
        if manifest is None:
            manifest, paths = load_or_build_manifest(
                path,
                manifest_path=manifest_path,
                block_size=block_size or DEFAULT_BLOCK_SIZE,
                require_labels=require_labels,
                log=log,
            )
            # (load_or_build_manifest already verified the files on the
            # load path and scanned exactly these on the build path —
            # no second verification pass.) span.file_idx indexes
            # manifest.files; re-key the resolved paths into that order.
            by_name = {os.path.basename(p): p for p in paths}
            paths = [by_name[fe.name] for fe in manifest.files]
        self.manifest = manifest
        self.paths: List[str] = list(paths or [])
        #: fsspec-style ``opener(path, mode) -> file-like`` behind every
        #: span read (the ROADMAP 5a remote-input seam, datapipe/io.py);
        #: None = local paths / the process-wide scheme registry
        self._opener = opener
        self.num_shards = num_shards
        self.shard_id = shard_id
        self.seed = seed
        self.prefetch_blocks = prefetch_blocks
        self.mix_blocks = mix_blocks
        self._spans = manifest.spans(block_size)
        #: per-span kept-row indices (holdout views); None = all rows
        self._kept: Optional[List[Optional[np.ndarray]]] = None
        self._arrays: Optional[Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]]] = None
        if preload:
            self._preload()

    # -- backends ----------------------------------------------------

    def _preload(self) -> None:
        """Load every (file, group) into host RAM once (the --memory
        path). The stream stays byte-identical to the disk-backed one:
        both read through the same span plan."""
        from roko_tpu.datapipe.io import open_h5

        arrays: Dict[Tuple[int, str], Tuple[np.ndarray, np.ndarray]] = {}
        for fi, p in enumerate(self.paths):
            with open_h5(p, opener=self._opener) as fd:
                for g, _rows in self.manifest.files[fi].groups:
                    x = np.ascontiguousarray(fd[g]["examples"][()])
                    y = np.ascontiguousarray(fd[g]["labels"][()], np.int32)
                    arrays[(fi, g)] = (x, y)
        self._arrays = arrays

    def _counts(self) -> List[int]:
        return [s.count for s in self._spans]

    # -- sizes -------------------------------------------------------

    def __len__(self) -> int:
        """GLOBAL kept rows across all shards (what the loop logs)."""
        if self._kept is None:
            return sum(s.count for s in self._spans)
        return sum(
            len(k) if k is not None else s.count
            for s, k in zip(self._spans, self._kept)
        )

    @property
    def num_blocks(self) -> int:
        return len(self._spans)

    def local_rows(self) -> int:
        """Rows this shard owns (fixed across epochs — canonical
        modulo block assignment)."""
        counts = self._effective_counts()
        return _engine.shard_row_counts(counts, self.num_shards)[self.shard_id]

    def _effective_counts(self) -> List[int]:
        if self._kept is None:
            return self._counts()
        return [
            len(k) if k is not None else s.count
            for s, k in zip(self._spans, self._kept)
        ]

    def steps_per_epoch(
        self, batch_size: int, *, drop_remainder: bool = False
    ) -> int:
        """Equalised per-shard batch count (max over shards): every
        shard emits exactly this many batches per epoch, padding with
        zero-weight batches if its rows run out first, so lockstep
        collectives on a pod cannot starve."""
        return _engine.batches_per_epoch(
            self._effective_counts(),
            batch_size,
            self.num_shards,
            drop_remainder=drop_remainder,
        )

    # -- reading -----------------------------------------------------

    def _row_template(self) -> Tuple[tuple, str, tuple, str]:
        # labels always surface as int32 (see read_rows/_preload)
        m = self.manifest
        return (m.x_shape, m.x_dtype, m.y_shape, "int32")

    def batches(
        self,
        batch_size: int,
        *,
        rng: Optional[np.random.Generator] = None,
        drop_remainder: bool = False,
        pad_to: Optional[int] = None,
        skip_batches: int = 0,
        start_samples: Optional[int] = None,
        stats: Optional[_engine.ReadStats] = None,
        equalize: Optional[bool] = None,
    ) -> Iterator[Tuple[np.ndarray, np.ndarray, np.ndarray]]:
        """Legacy-contract batch iterator over THIS shard's stream.

        ``equalize`` (default: on for multi-shard runs with ``pad_to``)
        pads the emitted batch count up to :meth:`steps_per_epoch`.
        Fast-forward via ``skip_batches``/``start_samples`` is O(spans
        skipped): skipped blocks are never read.
        """
        if equalize is None:
            equalize = self.num_shards > 1 and pad_to is not None
        min_batches = None
        if equalize:
            start = (
                int(start_samples)
                if start_samples is not None
                else skip_batches * batch_size
            )
            min_batches = self.steps_per_epoch(
                batch_size, drop_remainder=drop_remainder
            ) - start // batch_size
        from roko_tpu.datapipe.io import open_h5

        fds: dict = {}

        def read_rows(b: int, order: np.ndarray):
            span = self._spans[b]
            if self._arrays is not None:
                x, y = self._arrays[(span.file_idx, span.group)]
                sel = span.start + order
                return x[sel], y[sel]
            fd = fds.get(span.file_idx)
            if fd is None:
                # the one opener seam behind every span read
                # (datapipe/io.py): local paths keep the direct h5py
                # fast path; remote schemes are one registered adapter
                # away (ROADMAP 5a)
                fd = fds[span.file_idx] = open_h5(
                    self.paths[span.file_idx], opener=self._opener
                )
            g = fd[span.group]
            lo, hi = span.start, span.start + span.count
            # one contiguous block read, then in-RAM permute: streaming
            # I/O for HDF5, shuffle quality from the index layer. Label
            # dtype pins to int32 (the device dtype) so streamed, pre-
            # loaded, and synthesised padding batches all agree.
            x = np.asarray(g["examples"][lo:hi])
            y = np.asarray(g["labels"][lo:hi], np.int32)
            return x[order], y[order]

        def close_fds():
            for fd in fds.values():
                fd.close()
            fds.clear()

        # cleanup rides the engine's block generator so it runs in the
        # thread doing the reads (the prefetch producer) — a consumer-
        # side finally here would race fd.close against in-flight reads
        yield from _engine.iter_span_batches(
            self._counts(),
            read_rows,
            batch_size,
            rng=rng,
            num_shards=self.num_shards,
            shard_id=self.shard_id,
            kept=self._kept,
            drop_remainder=drop_remainder,
            pad_to=pad_to,
            skip_batches=skip_batches,
            start_samples=start_samples,
            min_batches=min_batches,
            prefetch=0 if self._arrays is not None else self.prefetch_blocks,
            mix_blocks=self.mix_blocks,
            stats=stats,
            row_template=self._row_template(),
            cleanup=close_fds,
        )

    def epoch_rng(self, epoch: int) -> np.random.Generator:
        """The per-epoch stream rng — same ``(seed, epoch)`` derivation
        the training loop has always used, so epoch E shuffles
        identically whether or not the run was interrupted inside it."""
        return np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))

    def iterator(
        self,
        epoch: int,
        batch_size: int,
        *,
        shuffle: bool = True,
        pad_to: Optional[int] = None,
        drop_remainder: bool = False,
        start_batch: int = 0,
        start_samples: Optional[int] = None,
        stats: Optional[_engine.ReadStats] = None,
    ) -> "CheckpointableIterator":
        return CheckpointableIterator(
            self,
            epoch,
            batch_size,
            shuffle=shuffle,
            pad_to=pad_to,
            drop_remainder=drop_remainder,
            start_batch=start_batch,
            start_samples=start_samples,
            stats=stats,
        )

    def unsharded(self) -> "ShardedDataset":
        """A 1-shard view of the same corpus (same backend, same kept
        rows): what evaluation uses so every host sees the identical
        stream regardless of the train shard spec."""
        if self.num_shards == 1:
            return self
        view = copy.copy(self)
        view.num_shards, view.shard_id = 1, 0
        return view

    # -- holdout -----------------------------------------------------

    def split_holdout(
        self, fraction: float, seed: int
    ) -> Tuple["ShardedDataset", "ShardedDataset"]:
        """Deterministic row-level (train, val) split, identical on
        every host: a seeded global permutation holds out
        ``max(1, round(fraction * N))`` rows. The val view is always
        unsharded (every host evaluates the identical full holdout);
        the train view keeps this dataset's shard spec."""
        if not 0.0 < fraction < 1.0:
            raise ValueError(f"val fraction must be in (0, 1), got {fraction}")
        n = len(self)
        if self._kept is not None:
            raise ValueError("cannot split an already-split dataset view")
        n_val = max(1, round(fraction * n))
        if n_val >= n:
            raise ValueError(
                f"val fraction {fraction} leaves no training windows (N={n})"
            )
        perm = np.random.default_rng(seed).permutation(n)
        val_mask = np.zeros(n, bool)
        val_mask[perm[:n_val]] = True
        kept_train: List[Optional[np.ndarray]] = []
        kept_val: List[Optional[np.ndarray]] = []
        off = 0
        for s in self._spans:
            m = val_mask[off : off + s.count]
            kept_val.append(np.nonzero(m)[0].astype(np.int64))
            kept_train.append(np.nonzero(~m)[0].astype(np.int64))
            off += s.count

        train = copy.copy(self)
        train._kept = kept_train
        val = copy.copy(self)
        val._kept = kept_val
        val.num_shards, val.shard_id = 1, 0
        return train, val


class CheckpointableIterator:
    """Sample-granular checkpointable epoch iterator.

    ``state()`` returns ``{"epoch", "batch", "samples"}`` — the exact
    position in the shard's epoch stream — and ``restore`` rebuilds an
    iterator that continues bit-identically from it, in O(spans
    skipped) (no prefix re-read). The training loop persists the same
    coordinates in the checkpoint's ``data_state``.
    """

    def __init__(
        self,
        dataset: ShardedDataset,
        epoch: int,
        batch_size: int,
        *,
        shuffle: bool = True,
        pad_to: Optional[int] = None,
        drop_remainder: bool = False,
        start_batch: int = 0,
        start_samples: Optional[int] = None,
        stats=None,
    ) -> None:
        self.dataset = dataset
        self.epoch = int(epoch)
        self.batch_size = int(batch_size)
        self._samples = (
            int(start_samples)
            if start_samples is not None
            else start_batch * batch_size
        )
        self._batch = self._samples // batch_size
        self._gen = dataset.batches(
            batch_size,
            rng=dataset.epoch_rng(epoch) if shuffle else None,
            pad_to=pad_to,
            drop_remainder=drop_remainder,
            start_samples=self._samples,
            stats=stats,
        )

    def __iter__(self) -> "CheckpointableIterator":
        return self

    def __next__(self):
        batch = next(self._gen)
        self._batch += 1
        self._samples += self.batch_size
        return batch

    def state(self) -> Dict[str, int]:
        return {
            "epoch": self.epoch,
            "batch": self._batch,
            "samples": self._samples,
        }

    @staticmethod
    def restore(
        dataset: ShardedDataset, state: Dict[str, int], batch_size: int, **kw
    ) -> "CheckpointableIterator":
        return CheckpointableIterator(
            dataset,
            int(state["epoch"]),
            batch_size,
            start_samples=int(state["samples"]),
            **kw,
        )
