"""Pluggable input/output seams behind the data plane's reads and
writes — the ROADMAP item 5(a)/3 seams.

The manifest's span reader historically opened LOCAL paths only
(``h5py.File(path)``); streaming a corpus from object storage — the
t5x/seqio posture (PAPERS.md) — needs exactly one indirection: an
fsspec-style ``opener(path, mode) -> file-like``. This module is that
indirection, deliberately tiny:

- :func:`open_input` resolves a path to a binary file-like object:
  plain paths and ``file://`` URLs open locally by default; other
  schemes resolve through the opener registry;
- :func:`open_output` is the matching WRITE seam: local paths open
  with ``open``; registered remote schemes get an upload-on-close
  handle (with an ``abort()`` escape hatch so a failed producer never
  publishes a torn artifact);
- :func:`register_opener` / :func:`register_writer` install scheme
  handlers process-wide;
- :class:`ShardedDataset` accepts a per-dataset ``opener=`` override
  (tests inject a counting ``file://`` shim through it).

No new dependencies: the default opener is ``open``, and the
``gs://`` / ``s3://`` / ``http(s)://`` schemes auto-install the
stdlib hardened object-store client (``datapipe/store.py``,
docs/STORAGE.md) on first use. Any other scheme refuses loudly, with
the currently registered schemes in the message.
"""

from __future__ import annotations

from typing import BinaryIO, Callable, Dict, Optional

#: fsspec-style opener signature: ``opener(path, mode) -> file-like``
Opener = Callable[[str, str], BinaryIO]

#: process-wide scheme registries (``register_opener`` /
#: ``register_writer``); ``file`` and scheme-less paths never consult
#: them
_OPENERS: Dict[str, Opener] = {}
_WRITERS: Dict[str, Opener] = {}

#: schemes the hardened object-store client (datapipe/store.py) serves;
#: an unregistered one auto-installs the default client on first use
_STORE_SCHEMES = ("gs", "s3", "http", "https")


def path_scheme(path: str) -> str:
    """The URL scheme of ``path`` (empty for plain local paths).
    Windows drive letters would false-positive on ``:`` alone, so the
    marker is the full ``://``."""
    head, sep, _ = path.partition("://")
    return head.lower() if sep else ""


def strip_file_scheme(path: str) -> str:
    """``file:///x`` / ``file://x`` -> a plain local path."""
    if path_scheme(path) != "file":
        return path
    rest = path.split("://", 1)[1]
    # file:///abs/path carries an empty authority; keep the leading /
    return rest if not rest.startswith("/") else "/" + rest.lstrip("/")


def local_open(path: str, mode: str = "rb") -> BinaryIO:
    """The default opener: the local filesystem (``file://`` accepted)."""
    return open(strip_file_scheme(path), mode)


def _check_registrable(scheme: str) -> str:
    scheme = scheme.lower()
    if scheme in ("", "file"):
        raise ValueError(
            "local paths always open through the default opener; "
            f"cannot register scheme {scheme!r}"
        )
    return scheme


def register_opener(scheme: str, opener: Optional[Opener]) -> None:
    """Install (or with ``None`` remove) the process-wide opener for
    ``scheme`` — e.g. ``register_opener("gs", ...)`` to stream corpora
    from object storage. ``file`` / scheme-less paths are not
    overridable: local reads must stay local."""
    scheme = _check_registrable(scheme)
    if opener is None:
        _OPENERS.pop(scheme, None)
    else:
        _OPENERS[scheme] = opener


def register_writer(scheme: str, writer: Optional[Opener]) -> None:
    """The :func:`open_output` counterpart of :func:`register_opener`."""
    scheme = _check_registrable(scheme)
    if writer is None:
        _WRITERS.pop(scheme, None)
    else:
        _WRITERS[scheme] = writer


def registered_schemes() -> Dict[str, tuple]:
    """``{"input": (...), "output": (...)}`` — the currently registered
    remote schemes (what the unknown-scheme refusal prints)."""
    return {
        "input": tuple(sorted(_OPENERS)),
        "output": tuple(sorted(_WRITERS)),
    }


def _autoinstall(scheme: str) -> bool:
    """Lazily install the default hardened store client for its
    schemes, so a ``gs://``/``http://`` path works with zero setup."""
    if scheme not in _STORE_SCHEMES:
        return False
    from roko_tpu.datapipe import store as _store

    _store.install()
    return True


def _refuse(kind: str, registry: Dict[str, Opener], scheme: str,
            path: str, register_fn: str) -> ValueError:
    have = ", ".join(sorted(registry)) or "<none>"
    return ValueError(
        f"no {kind} registered for scheme {scheme!r} ({path!r}); "
        f"currently registered schemes: {have}. Call "
        f"roko_tpu.datapipe.{register_fn}({scheme!r}, fn) with an "
        "fsspec-style fn(path, mode) -> file-like"
    )


def open_input(
    path: str, mode: str = "rb", *, opener: Optional[Opener] = None
) -> BinaryIO:
    """Open ``path`` for reading through the seam: an explicit
    ``opener`` wins, then the scheme registry (store schemes
    auto-install), then the local default. An unregistered scheme
    refuses with the registered-scheme list in the message instead of
    a bare ``FileNotFoundError`` on a URL-shaped path."""
    if opener is not None:
        return opener(path, mode)
    scheme = path_scheme(path)
    if scheme in ("", "file"):
        return local_open(path, mode)
    handler = _OPENERS.get(scheme)
    if handler is None and _autoinstall(scheme):
        handler = _OPENERS.get(scheme)
    if handler is None:
        raise _refuse("input opener", _OPENERS, scheme, path,
                      "register_opener")
    return handler(path, mode)


def open_output(
    path: str, mode: str = "wb", *, writer: Optional[Opener] = None
) -> BinaryIO:
    """Open ``path`` for writing through the seam. Local paths open
    plainly; registered remote schemes return an upload-on-close
    handle whose ``abort()`` (when present) discards the spooled bytes
    — error paths must call it instead of publishing a torn object."""
    if writer is not None:
        return writer(path, mode)
    scheme = path_scheme(path)
    if scheme in ("", "file"):
        return open(strip_file_scheme(path), mode)
    handler = _WRITERS.get(scheme)
    if handler is None and _autoinstall(scheme):
        handler = _WRITERS.get(scheme)
    if handler is None:
        raise _refuse("output writer", _WRITERS, scheme, path,
                      "register_writer")
    return handler(path, mode)


def abort_output(fh) -> None:
    """Discard a partially written :func:`open_output` handle: remote
    handles ``abort()`` (nothing is uploaded); local files just close —
    the CALLER owns unlinking a torn local file, exactly as before."""
    abort = getattr(fh, "abort", None)
    if abort is not None:
        abort()
    else:
        fh.close()


def ensure_local(path: str):
    """A local filesystem path for ``path``: plain/``file://`` paths
    pass through; store-scheme URLs download (cached, atomic) via
    ``ObjectStore.localize`` — for consumers that need a REAL filename
    (the native BAM reader, h5py's mmap fast path)."""
    scheme = path_scheme(path)
    if scheme in ("", "file"):
        return strip_file_scheme(path)
    from roko_tpu.datapipe import store as _store

    s = _store.install()
    if path.endswith(".bam"):
        return s.localize_bam(path)
    return s.localize(path)


def open_h5(path: str, *, opener: Optional[Opener] = None):
    """Open one corpus HDF5 through the seam. Plain local paths with no
    explicit opener keep the direct ``h5py.File(path)`` fast path
    (mmap-friendly); everything else goes through :func:`open_input`
    and h5py's file-like driver."""
    import h5py

    if opener is None and path_scheme(path) == "":
        return h5py.File(path, "r")
    return h5py.File(open_input(path, opener=opener), "r")
