"""Pluggable input opener behind the datapipe's span reads — the
ROADMAP item 5(a) seam.

The manifest's span reader historically opened LOCAL paths only
(``h5py.File(path)``); streaming a corpus from object storage — the
t5x/seqio posture (PAPERS.md) — needs exactly one indirection: an
fsspec-style ``opener(path, mode) -> file-like``. This module is that
indirection, deliberately tiny:

- :func:`open_input` resolves a path to a binary file-like object:
  plain paths and ``file://`` URLs open locally by default; other
  schemes resolve through the opener registry;
- :func:`register_opener` installs a scheme handler process-wide
  (``register_opener("gs", fsspec_open)`` is the whole remote-input
  adapter once an fsspec-like client exists in the image — nothing
  else in the data plane changes);
- :class:`ShardedDataset` accepts a per-dataset ``opener=`` override
  (tests inject a counting ``file://`` shim through it).

No new dependencies: the default opener is ``open``. The container
image has no fsspec; remote schemes refuse loudly until an adapter is
registered.
"""

from __future__ import annotations

from typing import BinaryIO, Callable, Dict, Optional

#: fsspec-style opener signature: ``opener(path, mode) -> file-like``
Opener = Callable[[str, str], BinaryIO]

#: process-wide scheme registry (``register_opener``); ``file`` and
#: scheme-less paths never consult it
_OPENERS: Dict[str, Opener] = {}


def path_scheme(path: str) -> str:
    """The URL scheme of ``path`` (empty for plain local paths).
    Windows drive letters would false-positive on ``:`` alone, so the
    marker is the full ``://``."""
    head, sep, _ = path.partition("://")
    return head.lower() if sep else ""


def strip_file_scheme(path: str) -> str:
    """``file:///x`` / ``file://x`` -> a plain local path."""
    if path_scheme(path) != "file":
        return path
    rest = path.split("://", 1)[1]
    # file:///abs/path carries an empty authority; keep the leading /
    return rest if not rest.startswith("/") else "/" + rest.lstrip("/")


def local_open(path: str, mode: str = "rb") -> BinaryIO:
    """The default opener: the local filesystem (``file://`` accepted)."""
    return open(strip_file_scheme(path), mode)


def register_opener(scheme: str, opener: Optional[Opener]) -> None:
    """Install (or with ``None`` remove) the process-wide opener for
    ``scheme`` — e.g. ``register_opener("gs", ...)`` to stream corpora
    from object storage. ``file`` / scheme-less paths are not
    overridable: local reads must stay local."""
    scheme = scheme.lower()
    if scheme in ("", "file"):
        raise ValueError(
            "local paths always open through the default opener; "
            f"cannot register scheme {scheme!r}"
        )
    if opener is None:
        _OPENERS.pop(scheme, None)
    else:
        _OPENERS[scheme] = opener


def open_input(
    path: str, mode: str = "rb", *, opener: Optional[Opener] = None
) -> BinaryIO:
    """Open ``path`` for reading through the seam: an explicit
    ``opener`` wins, then the scheme registry, then the local default.
    An unregistered remote scheme refuses with the fix in the message
    instead of a bare ``FileNotFoundError`` on a URL-shaped path."""
    if opener is not None:
        return opener(path, mode)
    scheme = path_scheme(path)
    if scheme in ("", "file"):
        return local_open(path, mode)
    handler = _OPENERS.get(scheme)
    if handler is None:
        raise ValueError(
            f"no input opener registered for scheme {scheme!r} "
            f"({path!r}); call roko_tpu.datapipe.register_opener"
            f"({scheme!r}, opener) with an fsspec-style "
            "opener(path, mode) -> file-like"
        )
    return handler(path, mode)


def open_h5(path: str, *, opener: Optional[Opener] = None):
    """Open one corpus HDF5 through the seam. Plain local paths with no
    explicit opener keep the direct ``h5py.File(path)`` fast path
    (mmap-friendly); everything else goes through :func:`open_input`
    and h5py's file-like driver."""
    import h5py

    if opener is None and path_scheme(path) == "":
        return h5py.File(path, "r")
    return h5py.File(open_input(path, opener=opener), "r")
