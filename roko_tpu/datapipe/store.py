"""Hardened object-storage data plane (ROADMAP item 3, docs/STORAGE.md).

One generic ranged-read object-store client behind the ``open_input`` /
``open_output`` seams (``datapipe/io.py``), stdlib ``http.client`` only:

- **ranged GETs + block cache** — :class:`StoreFile` reads in fixed
  ``block_bytes`` blocks through a bounded, sha256-checksummed local
  :class:`BlockCache` (atomic tmp+rename entries; a corrupt or torn
  entry is deleted and refetched, never served). The cache directory
  carries an identity pin (``meta.json``); opening it under a different
  format refuses in the :class:`CascadeMismatch <StoreMismatch>`
  field-diff shape.
- **retry/hedge/breaker** — every request runs under the shared
  :class:`RetryPolicy` (``Retry-After`` is a delay *floor*), behind a
  per-endpoint :class:`CircuitBreaker`; an optional hedged second read
  races a straggling range. Uploads are read-verify-commit: PUT with a
  sha256 header, HEAD-verify size/digest, re-PUT on mismatch — a torn
  remote object is never left standing as the final state.
- **fault injection** — :class:`FaultyStore` wraps the transport and
  injects timeouts / 5xx / truncated bodies / torn writes at
  env-selectable rates (``ROKO_STORE_FAULTS=timeout:0.1,http500:0.05``),
  and :class:`StubObjectStore` is an in-process stdlib object-store
  server (Range GET / HEAD / checksum-verified atomic PUT) for tests
  and the CI ``storage-gate`` lane.
- **observability** — structured ``emit()`` events (``store_retry``,
  ``store_hedge``, ``store_breaker_open``, ``cache_hit``) plus
  process-wide counters rendered into ``GET /metrics`` via
  :func:`store_metrics_lines`.

``gs://`` and ``s3://`` URLs resolve through ``ROKO_STORE_ENDPOINT``
(an HTTP(S) gateway prefix; the bucket/key ride as the path) — the
client speaks plain authenticated-elsewhere HTTP, which is exactly what
the stub server and any S3/GCS-compatible proxy expose. ``http(s)://``
URLs are used as-is.
"""

from __future__ import annotations

import hashlib
import http.client
import io
import json
import os
import queue
import random
import socket
import tempfile
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

from roko_tpu.datapipe.io import path_scheme
from roko_tpu.obs import events as obs_events
from roko_tpu.resilience.breaker import CircuitBreaker
from roko_tpu.resilience.retry import RetryPolicy

#: URL schemes this client serves through the opener/writer registries
STORE_SCHEMES = ("gs", "s3", "http", "https")

#: ranged-read block size: the unit the block cache keys on. 4 MiB
#: amortises per-request latency over object-store RTTs while keeping
#: the cache useful for the manifest's span-table reads (a 256-row span
#: of typical window geometry is well under one block).
DEFAULT_BLOCK_BYTES = 4 * 2**20
DEFAULT_CACHE_BYTES = 256 * 2**20

#: the checksum header the client sends on PUT and verifies on
#: read-back; the stub server enforces it server-side (422 on mismatch)
CHECKSUM_HEADER = "x-roko-content-sha256"

_FAULT_KINDS = ("timeout", "http500", "truncate", "torn_write")


# -- errors ------------------------------------------------------------------

class StoreError(RuntimeError):
    """Object-store client failure (after retries, where applicable)."""


class StoreHTTPError(StoreError):
    """A non-2xx response. 5xx/429 are retryable; other 4xx are a
    caller bug or a missing object and propagate immediately."""

    def __init__(self, url: str, status: int, reason: str = "",
                 retry_after: Optional[float] = None):
        super().__init__(f"HTTP {status} for {url!r}" +
                         (f": {reason}" if reason else ""))
        self.url = url
        self.status = status
        self.retry_after = retry_after


class TruncatedRead(StoreError):
    """Body shorter than the response promised — a cut connection or a
    misbehaving proxy. Retryable: the bytes are wrong, not the object."""


class ChecksumMismatch(StoreError):
    """Downloaded/uploaded bytes hash differently from the expected
    sha256 — corruption in flight or a torn remote object. Retryable."""


class BreakerOpen(StoreError):
    """The endpoint's circuit breaker is open: recent requests failed
    consecutively and the client is shedding load instead of hammering
    a sick endpoint. Carries the breaker's remaining cool-down."""

    def __init__(self, endpoint: str, retry_after: float):
        super().__init__(
            f"circuit breaker open for store endpoint {endpoint!r} "
            f"(retry in {retry_after:.1f}s)"
        )
        self.endpoint = endpoint
        self.retry_after = retry_after


class StoreMismatch(StoreError):
    """A store artifact (block-cache directory) carries a different
    identity than this client writes — same field-diff refusal shape as
    ``cascade.CascadeMismatch``: one line per differing field."""

    def __init__(self, what: str, where: str,
                 diff: Dict[str, Tuple[Any, Any]]):
        lines = [
            f"{key}: artifact={theirs!r} run={ours!r}"
            for key, (theirs, ours) in sorted(diff.items())
        ]
        super().__init__(
            f"store {what} at {where!r} belongs to a different "
            "format/run; refusing to use it. Differing fields:\n  "
            + "\n  ".join(lines or ["<identity mismatch>"])
            + "\nDelete the directory or point the store at a fresh one."
        )
        self.diff = diff


# -- counters (process-wide, /metrics) ---------------------------------------

_COUNTER_NAMES = (
    "requests", "request_failures", "retries", "hedges", "hedge_wins",
    "breaker_open", "cache_hits", "cache_misses", "cache_corrupt",
    "put_retries", "faults_injected",
)
_counters = {name: 0 for name in _COUNTER_NAMES}
_counters_lock = threading.Lock()


def _bump(name: str, n: int = 1) -> None:
    with _counters_lock:
        _counters[name] += n


def store_counters() -> Dict[str, int]:
    """A snapshot of the process-wide store counters."""
    with _counters_lock:
        return dict(_counters)


def reset_store_counters() -> None:
    """Zero the counters (tests only — /metrics counters are lifetime)."""
    with _counters_lock:
        for name in _COUNTER_NAMES:
            _counters[name] = 0


def store_metrics_lines() -> list:
    """Prometheus text lines for ``GET /metrics`` (serve/metrics.py)."""
    lines = []
    for name, value in sorted(store_counters().items()):
        full = f"roko_store_{name}_total"
        lines.append(f"# TYPE {full} counter")
        lines.append(f"{full} {value}")
    return lines


# -- fault injection ---------------------------------------------------------

def parse_fault_spec(spec: str) -> Dict[str, float]:
    """``"timeout:0.1,http500:0.05"`` -> ``{"timeout": 0.1, ...}``.
    Unknown kinds and out-of-range rates refuse with the valid set in
    the message (this parses an env var — a typo must not silently
    disable the fault it meant to enable)."""
    rates: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, sep, rate_s = part.partition(":")
        kind = kind.strip()
        if not sep or kind not in _FAULT_KINDS:
            raise ValueError(
                f"bad fault spec entry {part!r}; expected kind:rate with "
                f"kind one of {', '.join(_FAULT_KINDS)}"
            )
        try:
            rate = float(rate_s)
        except ValueError:
            raise ValueError(
                f"bad fault rate in {part!r}: not a number"
            ) from None
        if not 0.0 <= rate <= 1.0:
            raise ValueError(f"fault rate in {part!r} outside [0, 1]")
        rates[kind] = rate
    return rates


class FaultyStore:
    """Transport wrapper injecting transient store faults at fixed
    per-request rates — the adversary the retry/verify machinery is
    tested against. Faults are *transient by construction* (a fresh
    coin flip per attempt), so a client with retries converges on the
    correct bytes; a client without them fails loudly.

    - ``timeout``: raise ``socket.timeout`` without touching the wire;
    - ``http500``: synthesize a 500 without touching the wire;
    - ``truncate``: forward the request, then drop the second half of a
      GET body (headers intact — the client's length check trips);
    - ``torn_write``: forward a PUT with the second half of the body
      missing (checksum header intact — the server/verify step trips).
    """

    def __init__(self, inner: Callable, rates: Dict[str, float],
                 seed: int = 0):
        bad = set(rates) - set(_FAULT_KINDS)
        if bad:
            raise ValueError(f"unknown fault kinds: {sorted(bad)}")
        self.inner = inner
        self.rates = dict(rates)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected: Dict[str, int] = {k: 0 for k in _FAULT_KINDS}

    def _roll(self, kind: str) -> bool:
        rate = self.rates.get(kind, 0.0)
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < rate
            if hit:
                self.injected[kind] += 1
        if hit:
            _bump("faults_injected")
        return hit

    def __call__(self, method: str, url: str, headers: Dict[str, str],
                 body: Optional[bytes], timeout: float):
        if self._roll("timeout"):
            raise socket.timeout(f"injected timeout for {method} {url}")
        if self._roll("http500"):
            return 500, {}, b"injected http500 fault"
        if method == "PUT" and body and self._roll("torn_write"):
            # half the body arrives, framed as if complete (the checksum
            # header still describes the full payload) — the tear must
            # be caught by CHECKSUM verification, not by the server
            # waiting out a short read
            body = body[: len(body) // 2]
            headers = dict(headers, **{"Content-Length": str(len(body))})
        status, hdrs, data = self.inner(method, url, headers, body, timeout)
        if (
            method == "GET" and status in (200, 206) and len(data) > 1
            and self._roll("truncate")
        ):
            data = data[: len(data) // 2]
        return status, hdrs, data


# -- the checksummed block cache ---------------------------------------------

_CACHE_META = {"kind": "roko-store-block-cache", "version": 1}


class BlockCache:
    """Bounded on-disk cache of sha256-checksummed byte blocks.

    Entry layout: ``<dir>/<key[:2]>/<key>.blk`` where ``key`` is the
    sha256 over (url, object identity, offset, length); each entry file
    is ``<64-hex payload digest>\\n<payload>``. Reads verify the digest
    — a torn or bit-rotted entry is deleted and treated as a miss,
    never returned. Writes are atomic (pid-suffixed tmp + ``os.replace``)
    so concurrent distpolish workers can share one directory. Eviction
    is LRU-by-mtime down to ``max_bytes``.
    """

    def __init__(self, cache_dir: str, max_bytes: int = DEFAULT_CACHE_BYTES):
        self.dir = cache_dir
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        os.makedirs(self.dir, exist_ok=True)
        self._pin_identity()

    def _pin_identity(self) -> None:
        meta_path = os.path.join(self.dir, "meta.json")
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as fh:
                    have = json.load(fh)
            except (OSError, ValueError):
                have = {}
            diff = {
                k: (have.get(k), v)
                for k, v in _CACHE_META.items()
                if have.get(k) != v
            }
            if diff:
                raise StoreMismatch("block cache", self.dir, diff)
            return
        tmp = f"{meta_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            json.dump(_CACHE_META, fh, sort_keys=True)
        os.replace(tmp, meta_path)

    @staticmethod
    def key(url: str, ident: str, offset: int, length: int) -> str:
        h = hashlib.sha256()
        h.update(f"{url}\x00{ident}\x00{offset}\x00{length}".encode())
        return h.hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, key[:2], key + ".blk")

    def get(self, key: str) -> Optional[bytes]:
        path = self._path(key)
        try:
            with open(path, "rb") as fh:
                digest = fh.read(65)[:64].decode("ascii", "replace")
                payload = fh.read()
        except OSError:
            return None
        if hashlib.sha256(payload).hexdigest() != digest:
            # torn/corrupt entry: delete so the refetch can repopulate
            _bump("cache_corrupt")
            with self._lock:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            return None
        return payload

    def put(self, key: str, payload: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        digest = hashlib.sha256(payload).hexdigest()
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:
                fh.write(digest.encode("ascii") + b"\n")
                fh.write(payload)
            os.replace(tmp, path)
        except OSError:
            # a full/readonly cache disk degrades to uncached reads —
            # the data plane must not fail because the *cache* did
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        self._evict()

    def _entries(self):
        for sub in os.listdir(self.dir):
            d = os.path.join(self.dir, sub)
            if len(sub) != 2 or not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if name.endswith(".blk"):
                    yield os.path.join(d, name)

    def stats(self) -> Tuple[int, int]:
        """(entry count, total bytes) — what ``cache_probe`` prints."""
        entries = total = 0
        for path in self._entries():
            try:
                total += os.path.getsize(path)
                entries += 1
            except OSError:
                pass
        return entries, total

    def _evict(self) -> None:
        with self._lock:
            sized = []
            total = 0
            for path in self._entries():
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                sized.append((st.st_mtime, st.st_size, path))
                total += st.st_size
            if total <= self.max_bytes:
                return
            for _, size, path in sorted(sized):
                try:
                    os.unlink(path)
                except OSError:
                    continue
                total -= size
                if total <= self.max_bytes:
                    break


# -- transport ---------------------------------------------------------------

def http_transport(method: str, url: str, headers: Dict[str, str],
                   body: Optional[bytes], timeout: float):
    """One stdlib HTTP round-trip: ``(status, lowercase headers, body)``.
    A fresh connection per call — thread-safe and proxy-simple; the
    block cache, not keep-alive, is this client's latency lever."""
    u = urllib.parse.urlsplit(url)
    conn_cls = (
        http.client.HTTPSConnection if u.scheme == "https"
        else http.client.HTTPConnection
    )
    conn = conn_cls(u.hostname, u.port, timeout=timeout)
    try:
        path = u.path or "/"
        if u.query:
            path += "?" + u.query
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        try:
            data = resp.read()
        except http.client.IncompleteRead as e:
            # surface what DID arrive; the caller's length check refuses
            data = e.partial
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        return resp.status, hdrs, data
    finally:
        conn.close()


# -- the client --------------------------------------------------------------

def _parse_retry_after(value: Optional[str]) -> Optional[float]:
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None  # HTTP-date form: ignore, backoff still applies


def _parse_content_length(value: Optional[str]) -> Optional[int]:
    """A malformed Content-Length is treated as absent, never as a bare
    ValueError escaping the typed-error contract."""
    if value is None:
        return None
    try:
        return int(value)
    except ValueError:
        return None


class ObjectStore:
    """The hardened ranged-read client. Thread-safe; one instance
    serves a whole process (`default_store()`)."""

    def __init__(
        self,
        *,
        cache_dir: Optional[str] = None,
        cache_bytes: int = DEFAULT_CACHE_BYTES,
        block_bytes: int = DEFAULT_BLOCK_BYTES,
        timeout_s: float = 30.0,
        retry: Optional[RetryPolicy] = None,
        hedge_s: float = 0.0,
        breaker_failures: int = 5,
        breaker_reset_s: float = 30.0,
        endpoint: Optional[str] = None,
        transport: Optional[Callable] = None,
        log: Optional[Callable[[str], None]] = None,
    ):
        if block_bytes < 1:
            raise ValueError(f"block_bytes must be >= 1, got {block_bytes}")
        self.block_bytes = int(block_bytes)
        self.timeout_s = float(timeout_s)
        self.hedge_s = float(hedge_s)
        self.endpoint = endpoint
        self.log = log
        self.retry = retry or RetryPolicy(
            max_attempts=4, base_delay_s=0.2, max_delay_s=10.0,
            retryable=(StoreError, OSError),
        )
        self.transport = transport or http_transport
        self.cache = (
            BlockCache(cache_dir, cache_bytes) if cache_dir else None
        )
        self._breaker_failures = int(breaker_failures)
        self._breaker_reset_s = float(breaker_reset_s)
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._breakers_lock = threading.Lock()
        self._scratch: Optional[str] = None
        self._scratch_lock = threading.Lock()

    # -- URL resolution ------------------------------------------------------

    def resolve_url(self, url: str) -> str:
        """``gs://bucket/key`` / ``s3://bucket/key`` -> the configured
        HTTP(S) gateway; ``http(s)://`` passes through."""
        scheme = path_scheme(url)
        if scheme in ("http", "https"):
            return url
        if scheme in ("gs", "s3"):
            ep = self.endpoint or os.environ.get("ROKO_STORE_ENDPOINT")
            if not ep:
                raise StoreError(
                    f"cannot resolve {url!r}: {scheme}:// URLs need an "
                    "HTTP(S) gateway endpoint — set ROKO_STORE_ENDPOINT "
                    "(or StoreConfig.endpoint) to e.g. "
                    "http://storage-gateway:9000"
                )
            return ep.rstrip("/") + "/" + url.split("://", 1)[1]
        raise StoreError(
            f"unsupported store URL scheme {scheme!r} in {url!r} "
            f"(supported: {', '.join(STORE_SCHEMES)})"
        )

    def _breaker(self, url: str) -> Tuple[str, CircuitBreaker]:
        key = urllib.parse.urlsplit(self.resolve_url(url)).netloc
        with self._breakers_lock:
            br = self._breakers.get(key)
            if br is None:
                br = self._breakers[key] = CircuitBreaker(
                    failure_threshold=self._breaker_failures,
                    reset_s=self._breaker_reset_s,
                )
            return key, br

    # -- one attempt ---------------------------------------------------------

    def _request(
        self,
        method: str,
        url: str,
        headers: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
    ):
        """ONE breaker-guarded attempt; retry/hedge layer above."""
        endpoint, br = self._breaker(url)
        if not br.allow():
            _bump("breaker_open")
            obs_events.emit(
                "store", "store_breaker_open", log=self.log,
                endpoint=endpoint, retry_after_s=br.retry_after_s(),
            )
            raise BreakerOpen(endpoint, br.retry_after_s())
        resolved = self.resolve_url(url)
        _bump("requests")
        try:
            status, hdrs, data = self.transport(
                method, resolved, dict(headers or {}), body, self.timeout_s
            )
        except (OSError, http.client.HTTPException) as e:
            br.record_failure()
            _bump("request_failures")
            raise StoreError(f"{method} {url!r} failed: {e}") from e
        if status >= 500 or status == 429:
            br.record_failure()
            _bump("request_failures")
            raise StoreHTTPError(
                url, status, reason=data[:200].decode("utf-8", "replace"),
                retry_after=_parse_retry_after(hdrs.get("retry-after")),
            )
        # a body shorter than Content-Length is a transport fault, not
        # an object property — it counts against the endpoint's breaker.
        # An unparsable header is treated as absent (the typed-error
        # contract: callers only ever see StoreError/OSError).
        want = _parse_content_length(hdrs.get("content-length"))
        if (
            method != "HEAD" and want is not None
            and len(data) != want
        ):
            br.record_failure()
            _bump("request_failures")
            raise TruncatedRead(
                f"{method} {url!r}: body {len(data)}B != "
                f"Content-Length {want}B"
            )
        br.record_success()
        if status >= 400:
            raise StoreHTTPError(url, status,
                                 reason=data[:200].decode("utf-8", "replace"))
        return status, hdrs, data

    def _retrying(self, what: str, url: str, fn: Callable):
        """Wrap one-attempt ``fn`` in the shared RetryPolicy with
        ``Retry-After``/breaker-cooldown floors and the retry event."""

        def on_retry(failures: int, exc: BaseException, delay: float):
            _bump("retries")
            obs_events.emit(
                "store", "store_retry", log=self.log,
                op=what, url=url, attempt=failures, delay_s=delay,
                error=f"{type(exc).__name__}: {exc}"[:200],
            )

        def giveup(exc: BaseException) -> bool:
            # 4xx (other than 429, already excluded by _request raising
            # it as retryable-5xx class) is a caller bug or a missing
            # object: retrying cannot help
            return (
                isinstance(exc, StoreHTTPError)
                and 400 <= exc.status < 500 and exc.status != 429
            )

        return self.retry.call(
            fn,
            on_retry=on_retry,
            retry_after=lambda e: getattr(e, "retry_after", None),
            giveup=giveup,
        )

    # -- public ops ----------------------------------------------------------

    def stat(self, url: str) -> Tuple[int, str]:
        """``(size, identity)`` via HEAD. Identity is the server's
        checksum header or ETag (falls back to the size) — what block
        cache keys and localized copies pin against, so a replaced
        remote object invalidates every cached byte of the old one."""

        def attempt():
            _, hdrs, _ = self._request("HEAD", url)
            want = _parse_content_length(hdrs.get("content-length"))
            size = -1 if want is None else want
            ident = (
                hdrs.get(CHECKSUM_HEADER)
                or hdrs.get("etag", "").strip('"')
                or f"size={size}"
            )
            return size, ident

        return self._retrying("stat", url, attempt)

    def _ranged_get(self, url: str, offset: int, length: int) -> bytes:
        def attempt():
            end = offset + length - 1
            status, hdrs, data = self._request(
                "GET", url, headers={"Range": f"bytes={offset}-{end}"}
            )
            if status == 200:
                # server ignored Range: slice the full body
                data = data[offset:offset + length]
            if len(data) != length:
                raise TruncatedRead(
                    f"range [{offset}, {offset + length}) of {url!r}: "
                    f"got {len(data)}B, wanted {length}B"
                )
            return data

        if self.hedge_s <= 0:
            return self._retrying("read", url, attempt)
        return self._hedged(url, lambda: self._retrying("read", url, attempt))

    def _hedged(self, url: str, fn: Callable) -> bytes:
        """Race a second identical read against a straggling first one;
        first success wins, the loser's bytes are discarded (reads are
        idempotent, so duplication is safe)."""
        results: "queue.Queue" = queue.Queue()

        def run(tag: str):
            try:
                results.put((tag, fn(), None))
            except BaseException as e:  # noqa: BLE001 — reported below
                results.put((tag, None, e))

        legs = 1
        threading.Thread(
            target=run, args=("primary",), daemon=True
        ).start()
        try:
            tag, value, err = results.get(timeout=self.hedge_s)
        except queue.Empty:
            _bump("hedges")
            obs_events.emit(
                "store", "store_hedge", log=self.log,
                url=url, after_s=self.hedge_s,
            )
            legs = 2
            threading.Thread(
                target=run, args=("hedge",), daemon=True
            ).start()
            tag, value, err = results.get()
        if err is not None and legs == 2:
            # one of two legs failed: wait for the other before giving up
            tag, value, err2 = results.get()
            if err2 is not None:
                raise err
            err = None
        if err is not None:
            # sole leg failed (primary failed before the hedge fired):
            # there is no second result to wait for
            raise err
        if tag == "hedge":
            _bump("hedge_wins")
        return value

    def read_block(self, url: str, index: int, size: int,
                   ident: str) -> bytes:
        """One cache-backed block: block ``index`` of ``url`` whose
        total object size is ``size`` (the last block is short)."""
        offset = index * self.block_bytes
        length = min(self.block_bytes, size - offset)
        if length <= 0:
            return b""
        if self.cache is not None:
            key = BlockCache.key(url, ident, offset, length)
            data = self.cache.get(key)
            if data is not None:
                _bump("cache_hits")
                obs_events.emit(
                    "store", "cache_hit", quiet=True,
                    url=url, block=index, bytes=length,
                )
                return data
            _bump("cache_misses")
        data = self._ranged_get(url, offset, length)
        if self.cache is not None:
            self.cache.put(key, data)
        return data

    def get_object(self, url: str) -> bytes:
        """The whole object, length- and (when advertised) checksum-
        verified."""

        def attempt():
            _, hdrs, data = self._request("GET", url)
            want = hdrs.get(CHECKSUM_HEADER)
            if want and hashlib.sha256(data).hexdigest() != want:
                raise ChecksumMismatch(
                    f"GET {url!r}: body sha256 != advertised {want[:12]}…"
                )
            return data

        if self.hedge_s <= 0:
            return self._retrying("read", url, attempt)
        return self._hedged(url, lambda: self._retrying("read", url, attempt))

    def put_object(self, url: str, data: bytes) -> None:
        """Atomic read-verify-commit upload: PUT with the sha256
        header, then HEAD-verify size/identity; a mismatch (torn write)
        re-PUTs under the retry budget. The stub server (and any
        checksum-aware gateway) additionally verifies server-side and
        commits tmp+rename, so a torn body can never become the
        object."""
        sha = hashlib.sha256(data).hexdigest()
        first = [True]

        def attempt():
            if not first[0]:
                _bump("put_retries")
            first[0] = False
            self._request(
                "PUT", url, body=data,
                headers={
                    CHECKSUM_HEADER: sha,
                    "Content-Length": str(len(data)),
                },
            )
            size, ident = self.stat(url)
            diff = []
            if size != len(data):
                diff.append(f"size {size} != {len(data)}")
            if ident != sha and len(ident) == 64 and "-" not in ident:
                # only a plain sha256 identity is comparable — a
                # multipart/md5-style ETag says nothing either way
                diff.append(f"checksum {ident[:12]}… != {sha[:12]}…")
            if diff:
                raise ChecksumMismatch(
                    f"PUT {url!r} verification failed "
                    f"({'; '.join(diff)}) — torn write, re-uploading"
                )

        self._retrying("write", url, attempt)

    # -- file-like seams -----------------------------------------------------

    def open_read(self, url: str) -> io.BufferedReader:
        """Seekable read handle over ranged, block-cached GETs —
        what the ``open_input`` registry hands to h5py/fasta/json."""
        return io.BufferedReader(
            _StoreRawFile(self, url), buffer_size=self.block_bytes
        )

    def open_write(self, url: str, mode: str = "wb"):
        """Upload-on-close handle for ``open_output``: bytes spool in
        memory and commit atomically via :meth:`put_object` on
        ``close()``; ``abort()`` discards them (the error path of a
        partially produced output — never publish a torn artifact)."""
        buf = _StoreUploadBuffer(self, url)
        if "b" in mode:
            return buf
        return _TextUploadWrapper(buf)

    def opener(self, path: str, mode: str = "rb"):
        """The fsspec-style ``register_opener`` adapter."""
        if "r" not in mode or "+" in mode:
            raise ValueError(
                f"store opener is read-only; got mode {mode!r} for "
                f"{path!r} (writes go through open_output)"
            )
        return self.open_read(path)

    def writer(self, path: str, mode: str = "wb"):
        """The ``register_writer`` adapter."""
        if "w" not in mode or "+" in mode or "a" in mode:
            raise ValueError(
                f"store writer supports plain 'w'/'wb'; got {mode!r} "
                f"for {path!r}"
            )
        return self.open_write(path, mode)

    # -- whole-object localization -------------------------------------------

    def _scratch_dir(self) -> str:
        if self.cache is not None:
            d = os.path.join(self.cache.dir, "objects")
            os.makedirs(d, exist_ok=True)
            return d
        with self._scratch_lock:
            if self._scratch is None:
                self._scratch = tempfile.mkdtemp(prefix="roko-store-")
            return self._scratch

    def localize(self, url: str) -> str:
        """Download ``url`` to a local cached file and return its path
        — for consumers that need a real filename (the native BAM
        reader). Re-validated against the remote identity on every
        call: a replaced remote object re-downloads; an unchanged one
        is served from disk. Atomic (tmp + rename), so concurrent
        workers localizing the same URL never see a torn file."""
        size, ident = self.stat(url)
        if size < 0:
            # same refusal as _StoreRawFile: without a size we would
            # "download" zero blocks and commit an empty file as verified
            raise StoreError(
                f"localize {url!r}: server did not report an object size "
                "(Content-Length missing on HEAD)"
            )
        d = os.path.join(
            self._scratch_dir(),
            hashlib.sha256(url.encode()).hexdigest()[:16],
        )
        os.makedirs(d, exist_ok=True)
        dest = os.path.join(d, os.path.basename(
            urllib.parse.urlsplit(self.resolve_url(url)).path
        ) or "object")
        ident_path = dest + ".ident"
        try:
            with open(ident_path) as fh:
                have = json.load(fh)
            if (
                have.get("ident") == ident
                and os.path.getsize(dest) == size
            ):
                _bump("cache_hits")
                obs_events.emit(
                    "store", "cache_hit", quiet=True, url=url, bytes=size,
                )
                return dest
        except (OSError, ValueError):
            pass
        _bump("cache_misses")
        tmp = f"{dest}.tmp.{os.getpid()}"
        h = hashlib.sha256()
        with open(tmp, "wb") as out:
            n_blocks = max(1, -(-size // self.block_bytes))
            for i in range(n_blocks):
                block = self.read_block(url, i, size, ident)
                h.update(block)
                out.write(block)
        if ident == h.hexdigest() or ident.startswith("size="):
            pass  # identity verified (or server offered none beyond size)
        elif "-" not in ident and len(ident) == 64:
            os.unlink(tmp)
            raise ChecksumMismatch(
                f"localize {url!r}: assembled sha256 "
                f"{h.hexdigest()[:12]}… != remote {ident[:12]}…"
            )
        os.replace(tmp, dest)
        with open(f"{ident_path}.tmp.{os.getpid()}", "w") as fh:
            json.dump({"ident": ident, "size": size}, fh)
        os.replace(f"{ident_path}.tmp.{os.getpid()}", ident_path)
        return dest

    def localize_bam(self, url: str) -> str:
        """Localize a BAM plus its ``.bai`` sidecar (best-effort: an
        unindexed remote BAM still localizes; fetch() then scans)."""
        bam = self.localize(url)
        try:
            bai = self.localize(url + ".bai")
        except StoreError:
            return bam
        want = bam + ".bai"
        if os.path.realpath(bai) != os.path.realpath(want):
            tmp = f"{want}.tmp.{os.getpid()}"
            with open(bai, "rb") as src, open(tmp, "wb") as dst:
                dst.write(src.read())
            os.replace(tmp, want)
        return bam


class _StoreRawFile(io.RawIOBase):
    """Seekable raw reader over :meth:`ObjectStore.read_block`."""

    def __init__(self, store: ObjectStore, url: str):
        super().__init__()
        self._store = store
        self.url = url
        self._size, self._ident = store.stat(url)
        if self._size < 0:
            raise StoreError(
                f"{url!r}: server did not report an object size "
                "(Content-Length missing on HEAD)"
            )
        self._pos = 0

    def readable(self) -> bool:
        return True

    def seekable(self) -> bool:
        return True

    def seek(self, offset: int, whence: int = io.SEEK_SET) -> int:
        if whence == io.SEEK_SET:
            self._pos = offset
        elif whence == io.SEEK_CUR:
            self._pos += offset
        elif whence == io.SEEK_END:
            self._pos = self._size + offset
        else:
            raise ValueError(f"bad whence {whence}")
        if self._pos < 0:
            raise OSError(f"negative seek position {self._pos}")
        return self._pos

    def tell(self) -> int:
        return self._pos

    def readinto(self, b) -> int:
        n = min(len(b), self._size - self._pos)
        if n <= 0:
            return 0
        bb = self._store.block_bytes
        out = bytearray()
        first = self._pos // bb
        last = (self._pos + n - 1) // bb
        for i in range(first, last + 1):
            out.extend(
                self._store.read_block(self.url, i, self._size, self._ident)
            )
        start = self._pos - first * bb
        b[:n] = bytes(out[start:start + n])
        self._pos += n
        return n


class _StoreUploadBuffer(io.BytesIO):
    """Spool-then-commit write handle: ``close()`` uploads atomically
    through :meth:`ObjectStore.put_object`; ``abort()`` discards."""

    def __init__(self, store: ObjectStore, url: str):
        super().__init__()
        self._store = store
        self.url = url
        self._aborted = False
        self._committed = False

    def abort(self) -> None:
        self._aborted = True
        super().close()

    def close(self) -> None:
        if self.closed:
            return
        if not self._aborted and not self._committed:
            data = self.getvalue()
            super().close()
            self._committed = True
            self._store.put_object(self.url, data)
        else:
            super().close()


class _TextUploadWrapper(io.TextIOWrapper):
    """Text-mode face of :class:`_StoreUploadBuffer` (``open_output``
    mode ``"w"``), with ``abort()`` passed through."""

    def __init__(self, buf: _StoreUploadBuffer):
        super().__init__(buf, encoding="utf-8", newline="")
        self._buf = buf

    def abort(self) -> None:
        try:
            self.flush()
        except ValueError:
            pass
        self._buf.abort()


# -- default store wiring (open_input/open_output auto-install) --------------

_default_store: Optional[ObjectStore] = None
_default_lock = threading.Lock()


def _store_from_env() -> ObjectStore:
    env = os.environ
    store = ObjectStore(
        cache_dir=env.get("ROKO_STORE_CACHE") or None,
        cache_bytes=int(env.get("ROKO_STORE_CACHE_BYTES",
                                DEFAULT_CACHE_BYTES)),
        block_bytes=int(env.get("ROKO_STORE_BLOCK_BYTES",
                                DEFAULT_BLOCK_BYTES)),
        timeout_s=float(env.get("ROKO_STORE_TIMEOUT_S", 30.0)),
        hedge_s=float(env.get("ROKO_STORE_HEDGE_S", 0.0)),
        breaker_failures=int(env.get("ROKO_STORE_BREAKER_FAILURES", 5)),
        breaker_reset_s=float(env.get("ROKO_STORE_BREAKER_RESET_S", 30.0)),
        endpoint=env.get("ROKO_STORE_ENDPOINT") or None,
    )
    faults = env.get("ROKO_STORE_FAULTS")
    if faults:
        store.transport = FaultyStore(
            store.transport, parse_fault_spec(faults),
            seed=int(env.get("ROKO_STORE_FAULT_SEED", os.getpid())),
        )
    return store


def default_store() -> ObjectStore:
    """The process-wide client (built from ``ROKO_STORE_*`` env on
    first use; :func:`configure_store` replaces it)."""
    global _default_store
    with _default_lock:
        if _default_store is None:
            _default_store = _store_from_env()
        return _default_store


def install(store: Optional[ObjectStore] = None) -> ObjectStore:
    """Register ``store`` (default: the env-built client) as the
    process-wide opener/writer for every store scheme. Idempotent."""
    from roko_tpu.datapipe import io as dio

    global _default_store
    if store is not None:
        with _default_lock:
            _default_store = store
    store = default_store()
    for scheme in STORE_SCHEMES:
        dio.register_opener(scheme, store.opener)
        dio.register_writer(scheme, store.writer)
    return store


def configure_store(cfg) -> ObjectStore:
    """Build + install the client from a ``StoreConfig`` (CLI path).
    ``ROKO_STORE_FAULTS`` applies on top — fault injection is an
    environment property, not a config one, so a CI lane can wrap ANY
    invocation. ``ROKO_STORE_ENDPOINT``/``ROKO_STORE_CACHE`` fill in
    fields the config left unset, same reason."""
    env = os.environ
    store = ObjectStore(
        cache_dir=cfg.cache_dir or env.get("ROKO_STORE_CACHE") or None,
        cache_bytes=cfg.cache_bytes,
        block_bytes=cfg.block_bytes,
        timeout_s=cfg.timeout_s,
        retry=RetryPolicy(
            max_attempts=cfg.max_attempts, base_delay_s=0.2,
            max_delay_s=10.0, retryable=(StoreError, OSError),
        ),
        hedge_s=cfg.hedge_s,
        breaker_failures=cfg.breaker_failures,
        breaker_reset_s=cfg.breaker_reset_s,
        endpoint=cfg.endpoint or env.get("ROKO_STORE_ENDPOINT") or None,
    )
    faults = os.environ.get("ROKO_STORE_FAULTS")
    if faults:
        store.transport = FaultyStore(
            store.transport, parse_fault_spec(faults),
            seed=int(os.environ.get("ROKO_STORE_FAULT_SEED", os.getpid())),
        )
    return install(store)


# -- the stub object-store server (tests + CI storage-gate) ------------------

class _StubHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # quiet under pytest
        pass

    def _local(self) -> Optional[str]:
        rel = urllib.parse.unquote(self.path.lstrip("/"))
        root = os.path.realpath(self.server.root)
        full = os.path.realpath(os.path.join(root, rel))
        if full != root and not full.startswith(root + os.sep):
            return None
        return full

    def _scripted_fault(self) -> Optional[Dict[str, Any]]:
        with self.server.faults_lock:
            if self.server.faults:
                return self.server.faults.pop(0)
        return None

    def _apply_fault(self, fault: Dict[str, Any], data: bytes):
        kind = fault.get("kind", "status")
        if kind == "sleep":
            import time as _t

            _t.sleep(float(fault.get("s", 1.0)))
            return None, data  # sleep then serve normally
        status = int(fault.get("status", 500))
        # the faulted reply may leave an unread request body on the
        # socket (PUT): drop the connection so it can't be misparsed
        # as a next request
        self.close_connection = True
        self.send_response(status)
        if fault.get("retry_after") is not None:
            self.send_header("Retry-After", str(fault["retry_after"]))
        self.send_header("Content-Length", "0")
        self.end_headers()
        return status, data

    def _serve(self, head_only: bool) -> None:
        full = self._local()
        if full is None or not os.path.isfile(full):
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        with open(full, "rb") as fh:
            data = fh.read()
        size = len(data)
        sha = hashlib.sha256(data).hexdigest()
        fault = self._scripted_fault()
        truncate = fault is not None and fault.get("kind") == "truncate"
        if fault is not None and not truncate:
            handled, data = self._apply_fault(fault, data)
            if handled is not None:
                return
        status, body = 200, data
        rng = self.headers.get("Range")
        content_range = None
        if rng and rng.startswith("bytes=") and not head_only:
            try:
                start_s, _, end_s = rng[len("bytes="):].partition("-")
                start = int(start_s)
                end = min(int(end_s) if end_s else size - 1, size - 1)
            except ValueError:
                start, end = 0, size - 1
            body = body[start:end + 1]
            status = 206
            content_range = f"bytes {start}-{end}/{size}"
        if truncate:
            # truncate the bytes actually requested — a ranged read must
            # see the fault too, not just whole-object GETs
            body = body[: len(body) // 2]
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.send_header(CHECKSUM_HEADER, sha)
        self.send_header("ETag", f'"{sha}"')
        self.send_header("Accept-Ranges", "bytes")
        if content_range:
            self.send_header("Content-Range", content_range)
        self.end_headers()
        if not head_only:
            self.wfile.write(body)

    def do_GET(self) -> None:
        self._serve(head_only=False)

    def do_HEAD(self) -> None:
        self._serve(head_only=True)

    def do_PUT(self) -> None:
        full = self._local()
        if full is None:
            self.close_connection = True
            self.send_response(403)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        fault = self._scripted_fault()
        if fault is not None and fault.get("kind", "status") == "status":
            self._apply_fault(fault, b"")
            return
        length = int(self.headers.get("Content-Length", 0))
        data = self.rfile.read(length)
        want = self.headers.get(CHECKSUM_HEADER)
        if want and hashlib.sha256(data).hexdigest() != want:
            # the server-side torn-write refusal: the object is NOT
            # committed — "never a torn remote object"
            self.send_response(422)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        os.makedirs(os.path.dirname(full), exist_ok=True)
        tmp = f"{full}.tmp.{threading.get_ident()}"
        with open(tmp, "wb") as fh:
            fh.write(data)
        os.replace(tmp, full)
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()


class StubObjectStore(ThreadingHTTPServer):
    """In-process object-store stub over a directory: Range GET / HEAD
    / checksum-verified atomic PUT, plus a scripted fault queue
    (``fail_next``) for deterministic fault-matrix tests."""

    daemon_threads = True

    def __init__(self, root: str, host: str = "127.0.0.1", port: int = 0):
        self.root = root
        self.faults: list = []
        self.faults_lock = threading.Lock()
        super().__init__((host, port), _StubHandler)

    @property
    def url(self) -> str:
        host, port = self.server_address[:2]
        return f"http://{host}:{port}"

    def fail_next(self, times: int = 1, *, status: int = 500,
                  retry_after: Optional[float] = None) -> None:
        with self.faults_lock:
            self.faults.extend(
                {"kind": "status", "status": status,
                 "retry_after": retry_after}
                for _ in range(times)
            )

    def truncate_next(self, times: int = 1) -> None:
        with self.faults_lock:
            self.faults.extend({"kind": "truncate"} for _ in range(times))

    def delay_next(self, seconds: float, times: int = 1) -> None:
        with self.faults_lock:
            self.faults.extend(
                {"kind": "sleep", "s": seconds} for _ in range(times)
            )

    def start(self) -> "StubObjectStore":
        threading.Thread(target=self.serve_forever, daemon=True).start()
        return self


def main(argv=None) -> int:
    """``python -m roko_tpu.datapipe.store --root DIR [--port N]`` —
    the standalone stub server the CI ``storage-gate`` lane runs."""
    import argparse

    ap = argparse.ArgumentParser(description=main.__doc__)
    ap.add_argument("--root", required=True, help="directory to serve")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--port-file", default=None,
        help="write the bound port here (for 0 = ephemeral)",
    )
    args = ap.parse_args(argv)
    server = StubObjectStore(args.root, host=args.host, port=args.port)
    port = server.server_address[1]
    print(f"stub object store: {server.url} root={args.root}", flush=True)
    if args.port_file:
        with open(args.port_file, "w") as fh:
            fh.write(str(port))
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
