"""Persistent XLA compilation cache management.

JAX ships a disk-backed compilation cache (the machinery t5x-scale
training stacks lean on — PAPERS.md: compile caching as a prerequisite
for iterating at scale): a compiled executable is keyed by a hash of the
(HLO module, compile options, backend, jax version) and written to a
directory; any later compile of an identical program — another process,
a crash-resume, a ``--hang-fallback cpu`` fail-over child, the next
serve start — is a disk read instead of an XLA run.

This module turns it on **by default** and makes it operable:

- :func:`enable_persistent_cache` — idempotent process-wide enable,
  layered resolution: ``ROKO_COMPILE_CACHE`` env (a path, or
  ``off``/``0``/``none`` to disable) > ``CompileConfig.cache_dir`` >
  the default ``~/.cache/roko-tpu/xla-cache``. Size-bounded via JAX's
  built-in LRU eviction (``CompileConfig.cache_max_mb``).
- :func:`cache_counters` — process-wide persistent-cache hit/miss
  counts fed by JAX's monitoring events; surfaced as
  ``roko_compile_cache_hits``/``_misses`` on serve ``/metrics`` and in
  the bench coldstart suite.
- :func:`cache_entry_count` / :func:`cache_total_bytes` — cheap disk
  inventory for ``tools/cache_probe.py`` and the healthz payload.

The cache stores *device code*, so entries are backend- and
jax-version-specific by construction — a stale entry can mis-hit only if
XLA's own cache key breaks, which is exactly the contract every
production JAX stack already relies on.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional, Tuple

Log = Callable[[str], None]

#: env var: a cache directory path, or one of :data:`_OFF_VALUES` to
#: disable the persistent cache entirely (the documented opt-out)
ENV_CACHE = "ROKO_COMPILE_CACHE"

_OFF_VALUES = frozenset({"", "0", "off", "none", "disable", "disabled"})

_DEFAULT_DIR = os.path.join("~", ".cache", "roko-tpu", "xla-cache")

_lock = threading.Lock()
_active_dir: Optional[str] = None
_configured = False  # enable_persistent_cache ran (even if it disabled)

_hits = 0
_requests = 0
_listener_registered = False

# jax (0.4.x) emits no explicit miss event: every compile that consults
# the persistent cache records a request, and only the successful reads
# record a hit — misses are the difference
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"


def _on_event(event: str, **_kw) -> None:
    global _hits, _requests
    if event == _HIT_EVENT:
        _hits += 1
    elif event == _REQUEST_EVENT:
        _requests += 1


def _register_listener() -> None:
    global _listener_registered
    if _listener_registered:
        return
    try:
        from jax._src import monitoring

        monitoring.register_event_listener(_on_event)
        _listener_registered = True
    except Exception:  # pragma: no cover - jax internals drift
        pass  # counters stay zero; metrics render 0, nothing breaks


def cache_counters() -> Tuple[int, int]:
    """(hits, misses) of the persistent compilation cache in this
    process, across every backend/program. Monotonic; snapshot before
    and after a phase to attribute counts to it."""
    return _hits, max(0, _requests - _hits)


def resolve_cache_dir(ccfg=None) -> Optional[str]:
    """The cache directory the layered config resolves to, or ``None``
    when the persistent cache is disabled. Resolution order:
    ``ROKO_COMPILE_CACHE`` env > ``CompileConfig`` > built-in default."""
    env = os.environ.get(ENV_CACHE)
    if env is not None:
        if env.strip().lower() in _OFF_VALUES:
            return None
        return os.path.expanduser(env)
    if ccfg is not None and not ccfg.enabled:
        return None
    if ccfg is not None and ccfg.cache_dir:
        return os.path.expanduser(ccfg.cache_dir)
    return os.path.expanduser(_DEFAULT_DIR)


def enable_persistent_cache(ccfg=None, *, log: Optional[Log] = None) -> Optional[str]:
    """Enable JAX's persistent compilation cache process-wide (idempotent;
    the first caller's directory wins — one process, one cache). Returns
    the active cache directory, or ``None`` when disabled.

    ``ccfg`` is a :class:`roko_tpu.config.CompileConfig` (or ``None`` for
    its defaults). Every runtime entry point — serve, both polish paths,
    ``run_inference``, the bench, ``tools/chip_probe.py`` — calls this
    before its first compile, so the cache is on unless explicitly
    opted out.
    """
    global _active_dir, _configured
    with _lock:
        if _configured:
            want = resolve_cache_dir(ccfg)
            if log is not None and want != _active_dir:
                log(
                    f"compile cache already configured at {_active_dir!r}; "
                    f"ignoring later request for {want!r}"
                )
            return _active_dir
        _configured = True
        d = resolve_cache_dir(ccfg)
        if d is None:
            _active_dir = None
            return None

        import jax

        os.makedirs(d, exist_ok=True)
        max_mb = ccfg.cache_max_mb if ccfg is not None else 1024
        min_compile_s = ccfg.min_compile_time_s if ccfg is not None else 0.0
        jax.config.update("jax_enable_compilation_cache", True)
        jax.config.update("jax_compilation_cache_dir", d)
        # jax initializes its cache lazily at the FIRST compile and then
        # never re-reads the directory config; if anything compiled
        # before this call (params restore, a probe canary), that
        # initialization latched "no dir" and every later read/write
        # silently no-ops. Reset so the next compile re-initializes
        # against the directory configured above.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:  # pragma: no cover - jax internals drift
            pass
        # cache even fast compiles by default: a serve ladder is many
        # small programs and the cold start pays all of them
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", float(min_compile_s)
        )
        # LRU eviction against the size budget (jax maintains -atime
        # files per entry); <= 0 = unbounded
        jax.config.update(
            "jax_compilation_cache_max_size",
            int(max_mb) * 2**20 if max_mb and max_mb > 0 else -1,
        )
        _register_listener()
        _active_dir = d
        if log is not None:
            log(f"persistent compile cache: {d}")
        return d


def active_cache_dir() -> Optional[str]:
    """The directory :func:`enable_persistent_cache` activated (None =
    not enabled / disabled)."""
    return _active_dir


def _entry_files(cache_dir: str):
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return
    for name in names:
        if name.endswith("-atime"):  # LRU bookkeeping, not an entry
            continue
        yield os.path.join(cache_dir, name)


def cache_entry_count(cache_dir: Optional[str] = None) -> int:
    """Number of cached executables on disk (0 for a missing dir)."""
    d = cache_dir or _active_dir
    if not d:
        return 0
    return sum(1 for _ in _entry_files(d))


def cache_total_bytes(cache_dir: Optional[str] = None) -> int:
    """Total bytes the cached executables occupy."""
    d = cache_dir or _active_dir
    if not d:
        return 0
    total = 0
    for path in _entry_files(d):
        try:
            total += os.stat(path).st_size
        except OSError:
            continue
    return total


def _reset_for_tests() -> None:
    """Forget the process-wide enable so a test can exercise resolution
    again. Does NOT restore jax.config — tests that enable a real cache
    point it at a tmpdir and leave it (harmless: later compiles just
    keep caching there)."""
    global _configured, _active_dir
    with _lock:
        _configured = False
        _active_dir = None
