"""AOT executable bundles: pre-compiled predict ladders on disk.

``roko-tpu compile`` lowers the predict step (``infer.make_predict_step``
— the exact program serve/polish/inference run) for every ladder rung
with **abstract** inputs (``jax.eval_shape`` over ``model.init``, so no
checkpoint is needed — the compiled program depends only on shapes), runs
the full XLA pipeline once, and serializes each executable
(``jax.experimental.serialize_executable``) into a directory::

    <bundle>/manifest.json      identity + digest + rung inventory
    <bundle>/rung_00032.aotx    pickled (serialized_exec, in_tree, out_tree)
    <bundle>/rung_00128.aotx    ...

A loading process (``PolishSession.warmup``, ``pipeline/stream.py``,
``infer.run_inference``) deserializes the executables instead of
compiling — cold-start cost collapses to a disk read — but ONLY when the
bundle's identity digest matches the running process exactly. The digest
covers everything that changes the compiled program or would make its
outputs wrong: the full ModelConfig (window geometry lives there), the
mesh shape (dp/tp/sp), backend platform, device kind, and jax version.
A mismatch raises :class:`BundleMismatch` naming the differing fields —
loudly refused, never silently recompiled into wrong results (the same
refuse-don't-guess contract as the resume journal's identity check,
``resilience/journal.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import numpy as np

BUNDLE_MANIFEST = "manifest.json"
BUNDLE_VERSION = 1

Log = Callable[[str], None]


class BundleMismatch(RuntimeError):
    """An AOT bundle does not match the running process. Carrying on
    would run a program compiled for a DIFFERENT model/geometry/backend
    — wrong results, not just wrong speed — so loading refuses."""


def _canonical(obj: Any) -> Any:
    """JSON-normalize (tuples -> lists, etc.) so identity comparison and
    digesting are stable across load/dump round trips."""
    return json.loads(json.dumps(obj, sort_keys=True))


def bundle_identity(cfg, mesh=None, *, backend: Optional[str] = None) -> Dict[str, Any]:
    """Everything the compiled predict program (and the correctness of
    its outputs) depends on. ``mesh`` defaults to the config's mesh over
    the live devices."""
    from roko_tpu.parallel.mesh import make_mesh

    mesh = mesh or make_mesh(cfg.mesh)
    dev = np.asarray(mesh.devices).flat[0]
    platform = backend or dev.platform
    return _canonical(
        {
            "bundle_version": BUNDLE_VERSION,
            "jax_version": jax.__version__,
            "backend": platform,
            "device_kind": dev.device_kind,
            "mesh": dict(mesh.shape),
            # compute_dtype="auto" digests as the CONCRETE dtype it
            # resolves to on this backend (bf16 on TPU, f32 elsewhere):
            # an "auto" session and an explicit one compile the same
            # program and must share a digest, while a bf16 bundle
            # loaded into an f32 session refuses naming
            # model.compute_dtype (quantize rides in the same dict)
            "model": dataclasses.asdict(cfg.model.resolve(platform)),
        }
    )


def bundle_digest(identity: Dict[str, Any]) -> str:
    """sha256 over the canonical identity JSON."""
    blob = json.dumps(_canonical(identity), sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()


def _identity_diff(ours: Any, theirs: Any, prefix: str = "") -> list:
    """Human-actionable field-level diff between two identities."""
    if isinstance(ours, dict) and isinstance(theirs, dict):
        out = []
        for key in sorted(set(ours) | set(theirs)):
            out += _identity_diff(
                ours.get(key, "<absent>"),
                theirs.get(key, "<absent>"),
                f"{prefix}{key}.",
            )
        return out
    if ours != theirs:
        return [f"{prefix[:-1]}: bundle={theirs!r} run={ours!r}"]
    return []


def _rung_file(rung: int) -> str:
    return f"rung_{rung:05d}.aotx"


def _abstract_predict_args(cfg, mesh):
    """Abstract (params, x) for lowering one predict rung — no real
    params needed: ``jax.eval_shape`` walks ``model.init`` without
    computing, so ``roko-tpu compile`` works straight from a config."""
    from roko_tpu.models.model import RokoModel
    from roko_tpu.parallel.mesh import data_sharding, replicated_sharding

    model = RokoModel(cfg.model)
    repl = replicated_sharding(mesh)
    data = data_sharding(mesh)
    shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    params_abs = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=repl), shapes
    )

    def x_abs(rung: int):
        return jax.ShapeDtypeStruct(
            (rung, cfg.model.window_rows, cfg.model.window_cols),
            np.uint8,
            sharding=data,
        )

    return model, params_abs, x_abs


def export_bundle(
    out_dir: str,
    cfg,
    *,
    mesh=None,
    ladder: Optional[Sequence[int]] = None,
    log: Log = print,
) -> Dict[str, Any]:
    """Compile every ladder rung of the predict step and serialize the
    executables into ``out_dir``; returns the manifest. Files are
    written atomically and the manifest last, so a crashed export never
    looks loadable.

    The persistent compilation cache is DISABLED for the export's own
    compiles: serializing an executable that XLA deserialized from the
    cache writes a stub missing its compiled symbols — on a warm-cache
    machine (any box that has served this config before) the bundle
    would look fine and then fail every load with an INTERNAL
    "Symbols not found". Export always runs real XLA compiles;
    ``roko-tpu compile`` verifies the result in a fresh process."""
    import jax as _jax
    from jax.experimental import serialize_executable

    from roko_tpu.config import resolve_ladder, validate_ladder
    from roko_tpu.infer import make_predict_step
    from roko_tpu.parallel.mesh import AXIS_DP, make_mesh

    mesh = mesh or make_mesh(cfg.mesh)
    dp = mesh.shape[AXIS_DP]
    # same denomination rule as PolishSession: explicit rungs are GLOBAL
    # batch sizes; None = the config ladder (auto default: per-device
    # base x dp), so a bundle exported on this mesh loads into a session
    # on this mesh by construction
    rungs = (
        resolve_ladder(cfg.serve, dp)
        if ladder is None
        else tuple(sorted(set(ladder)))
    )
    if not rungs:
        raise ValueError("bundle ladder must name at least one batch size")
    validate_ladder(rungs, dp)

    model, params_abs, x_abs = _abstract_predict_args(cfg, mesh)
    step = make_predict_step(model, mesh)
    identity = bundle_identity(cfg, mesh)
    os.makedirs(out_dir, exist_ok=True)

    files: Dict[str, str] = {}
    t0 = time.perf_counter()
    cache_was_on = bool(_jax.config.jax_enable_compilation_cache)
    if cache_was_on:
        _jax.config.update("jax_enable_compilation_cache", False)
    try:
        for rung in rungs:
            t_r = time.perf_counter()
            compiled = step.lower(params_abs, x_abs(rung)).compile()
            ser, in_tree, out_tree = serialize_executable.serialize(compiled)
            name = _rung_file(rung)
            tmp = os.path.join(out_dir, name + ".tmp")
            with open(tmp, "wb") as f:
                pickle.dump((ser, in_tree, out_tree), f)
            os.replace(tmp, os.path.join(out_dir, name))
            files[str(rung)] = name
            log(
                f"compile: rung {rung} lowered+compiled+serialized in "
                f"{time.perf_counter() - t_r:.1f}s ({name})"
            )
    finally:
        if cache_was_on:
            _jax.config.update("jax_enable_compilation_cache", True)

    manifest = {
        "bundle_version": BUNDLE_VERSION,
        "identity": identity,
        "digest": bundle_digest(identity),
        "rungs": list(rungs),
        "files": files,
        "created_unix": int(time.time()),
    }
    tmp = os.path.join(out_dir, BUNDLE_MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, os.path.join(out_dir, BUNDLE_MANIFEST))
    log(
        f"compile: bundle {out_dir} ready — {len(rungs)} rung(s) in "
        f"{time.perf_counter() - t0:.1f}s, digest {manifest['digest'][:12]}"
    )
    return manifest


def read_manifest(bundle_dir: str) -> Dict[str, Any]:
    """The bundle's manifest dict (``FileNotFoundError`` with an
    actionable message when the directory is not a bundle)."""
    path = os.path.join(bundle_dir, BUNDLE_MANIFEST)
    try:
        with open(path) as f:
            return json.load(f)
    except FileNotFoundError:
        raise FileNotFoundError(
            f"{bundle_dir!r} is not an AOT bundle (no {BUNDLE_MANIFEST}); "
            "create one with `roko-tpu compile <out_dir>`"
        ) from None


def load_bundle(
    bundle_dir: str,
    cfg,
    *,
    mesh=None,
    rungs: Optional[Sequence[int]] = None,
    require_all: bool = False,
    log: Log = print,
) -> Dict[int, Callable]:
    """Deserialize the bundle's executables: ``{rung: compiled}``, each
    callable as ``compiled(params, x)`` exactly like the jitted predict
    step (same program, same shardings — outputs are bit-identical).

    Refuses loudly (:class:`BundleMismatch`) when the bundle's identity
    digest differs from this process's, or — with ``require_all`` — when
    a requested rung is missing. ``rungs=None`` loads everything the
    bundle has; otherwise only the intersection is loaded (the batch
    paths fall back to jit for one-off tail shapes).
    """
    from jax.experimental import serialize_executable

    from roko_tpu.parallel.mesh import make_mesh

    mesh = mesh or make_mesh(cfg.mesh)
    manifest = read_manifest(bundle_dir)
    theirs = manifest.get("identity", {})
    ours = bundle_identity(cfg, mesh)
    if bundle_digest(ours) != manifest.get("digest"):
        diff = _identity_diff(ours, theirs)
        raise BundleMismatch(
            f"AOT bundle {bundle_dir!r} was built for a different "
            "program; refusing to load it (a mismatched executable would "
            "produce wrong results, not just wrong speed). Differing "
            "fields:\n  " + "\n  ".join(diff or ["<digest mismatch only>"])
            + "\nRe-export with `roko-tpu compile` under the current "
            "config/backend, or drop --bundle to compile normally."
        )

    have = {int(r) for r in manifest.get("rungs", [])}
    want = set(int(r) for r in rungs) if rungs is not None else set(have)
    missing = sorted(want - have)
    if missing and require_all:
        raise BundleMismatch(
            f"AOT bundle {bundle_dir!r} has rungs {sorted(have)} but this "
            f"ladder needs {sorted(want)} (missing {missing}); re-export "
            f"with `roko-tpu compile --ladder "
            f"{','.join(str(r) for r in sorted(want))}`"
        )

    execs: Dict[int, Callable] = {}
    t0 = time.perf_counter()
    # rungs deserialize SERIALLY on purpose: unlike compilation,
    # deserialize_and_load races the backend's executable-symbol
    # registry when called concurrently (CPU backend: intermittent
    # "Symbols not found" INTERNAL errors) — warmup_ladder's
    # concurrency is for compiles only
    for rung in sorted(want & have):
        path = os.path.join(bundle_dir, manifest["files"][str(rung)])
        with open(path, "rb") as f:
            ser, in_tree, out_tree = pickle.load(f)
        execs[rung] = serialize_executable.deserialize_and_load(
            ser, in_tree, out_tree
        )
    if execs:
        log(
            f"AOT bundle: loaded {len(execs)} executable(s) "
            f"{sorted(execs)} from {bundle_dir} in "
            f"{time.perf_counter() - t0:.2f}s (digest "
            f"{manifest['digest'][:12]})"
        )
    return execs


def verify_main(bundle_dir: str, cfg_json_path: str) -> None:
    """Child half of the ``roko-tpu compile`` post-export check: in THIS
    (fresh) process, deserialize every rung and run it on a zero batch.
    A same-process check cannot catch a stub bundle — deserialization
    finds the exporting process's still-registered symbols — so the CLI
    runs this in a subprocess with the compile cache off."""
    import jax as _jax

    from roko_tpu.config import RokoConfig
    from roko_tpu.models.model import RokoModel

    with open(cfg_json_path) as f:
        cfg = RokoConfig.from_json(f.read())
    manifest = read_manifest(bundle_dir)
    rungs = [int(r) for r in manifest["rungs"]]
    execs = load_bundle(
        bundle_dir, cfg, rungs=rungs, require_all=True, log=lambda m: None
    )
    params = RokoModel(cfg.model).init(_jax.random.PRNGKey(0))
    shape = (cfg.model.window_rows, cfg.model.window_cols)
    for rung in rungs:
        out = execs[rung](params, np.zeros((rung,) + shape, np.uint8))
        _jax.block_until_ready(out)
    print(f"verified {len(rungs)} rung(s): {rungs}")


def wrap_predict(step: Callable, execs: Dict[int, Callable]) -> Callable:
    """Dispatch-by-batch-rows: a bundled executable when the padded
    batch size has one, the jitted ``step`` otherwise (one-off tail
    shapes). Signature-compatible with ``make_predict_step``'s jit."""
    if not execs:
        return step

    def predict(params, x):
        fn = execs.get(int(x.shape[0]))
        return fn(params, x) if fn is not None else step(params, x)

    return predict
