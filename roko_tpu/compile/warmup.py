"""Parallel ladder warmup: compile every rung concurrently.

The old ``PolishSession.warmup`` compiled the ladder serially — rung
after rung of dead chip time, because XLA compilation runs in native
code and **releases the GIL**: N host cores can compile N rungs at once.
This helper is the one shared implementation: callers hand it a
``compile_rung(rung)`` callable (a zero-batch dispatch, an AOT-validate
call, a ``lower().compile()`` — whatever makes that rung hot) and get
back a :class:`WarmupReport` with wall/per-rung timings and the
persistent-cache hit/miss delta, which serve surfaces as
``roko_serve_warmup_seconds`` / ``roko_compile_cache_*`` metrics and the
bench records in its coldstart suite.

A rung failure (including a watchdog :class:`~roko_tpu.resilience.HangError`
from a guarded ``compile_rung``) cancels the rest and re-raises — a
half-warm service must fail its start loudly, not limp."""

from __future__ import annotations

import os
import time
from concurrent.futures import FIRST_EXCEPTION, ThreadPoolExecutor, wait
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Sequence

from roko_tpu.compile.cache import cache_counters

Log = Callable[[str], None]


@dataclass
class WarmupReport:
    """What a ladder warmup cost and where the executables came from."""

    seconds: float = 0.0
    mode: str = "serial"  # "serial" | "parallel" | "aot"
    per_rung_s: Dict[int, float] = field(default_factory=dict)
    #: persistent-cache deltas across the warmup window
    cache_hits: int = 0
    cache_misses: int = 0

    def as_dict(self) -> Dict[str, object]:
        return {
            "seconds": round(self.seconds, 3),
            "mode": self.mode,
            "per_rung_s": {
                str(r): round(s, 3) for r, s in sorted(self.per_rung_s.items())
            },
            "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
        }


def warmup_ladder(
    rungs: Sequence[int],
    compile_rung: Callable[[int], object],
    *,
    parallel: bool = True,
    max_workers: int = 0,
    mode: Optional[str] = None,
    log: Optional[Log] = None,
) -> WarmupReport:
    """Make every rung in ``rungs`` hot by calling ``compile_rung`` for
    each — concurrently when ``parallel`` (and more than one rung), in
    order otherwise. ``max_workers`` 0 = one per rung capped at the host
    core count. Deadlines are the *caller's* job: ``compile_rung``
    should already be guarded (the session routes through its watchdog
    ``DeadlinePolicy``), so a hung compile raises here instead of
    wedging the pool."""
    rungs = list(rungs)
    hits0, misses0 = cache_counters()
    t0 = time.perf_counter()
    report = WarmupReport(mode=mode or ("parallel" if parallel else "serial"))

    def one(rung: int) -> None:
        t_r = time.perf_counter()
        compile_rung(rung)
        report.per_rung_s[rung] = time.perf_counter() - t_r

    if parallel and len(rungs) > 1:
        # floor at 2: compiles block in XLA with the GIL released, so
        # parallel warmup must overlap rungs even on a 1-core host —
        # otherwise "parallel" silently degrades to serial there
        workers = max_workers or min(len(rungs), max(os.cpu_count() or 1, 2))
        workers = max(1, min(workers, len(rungs)))
        with ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="roko-warmup"
        ) as pool:
            futs = {pool.submit(one, r): r for r in rungs}
            done, not_done = wait(futs, return_when=FIRST_EXCEPTION)
            failed = [f for f in done if f.exception() is not None]
            if failed:
                for f in not_done:
                    f.cancel()
                raise failed[0].exception()
    else:
        if mode is None and len(rungs) <= 1:
            report.mode = "serial"
        for r in rungs:
            one(r)

    hits1, misses1 = cache_counters()
    report.seconds = time.perf_counter() - t0
    report.cache_hits = hits1 - hits0
    report.cache_misses = misses1 - misses0
    if log is not None:
        log(
            f"warmup: {len(rungs)} rung(s) ready in {report.seconds:.1f}s "
            f"({report.mode}; cache hits={report.cache_hits} "
            f"misses={report.cache_misses})"
        )
    return report
