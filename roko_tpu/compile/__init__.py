"""Compiled-executable lifecycle (docs/SERVING.md "Cold start & compile
cache"): the cold-start elimination subsystem.

Every ``roko-tpu serve`` / ``polish`` / ``inference`` start used to pay
the full XLA compile of the predict step once per ladder rung, serially,
from scratch — minutes of dead chip time before the first base is
polished, recurring on every crash-resume, CPU fail-over, and bench
child. Three cooperating tiers kill it:

1. **Persistent compilation cache** (:mod:`cache`) — JAX's disk cache,
   on by default, so recompiling an identical (program, backend,
   jax-version) pays a disk read, not an XLA run. Opt out with
   ``ROKO_COMPILE_CACHE=off`` or ``--no-compile-cache``.
2. **AOT executable bundles** (:mod:`bundle`) — ``roko-tpu compile``
   pre-lowers and serializes the predict step for every ladder rung into
   a versioned bundle keyed by a digest of (ModelConfig incl. window
   geometry, mesh, backend, device_kind, jax version); the serving
   session and both polish paths load a matching bundle instead of
   compiling, and refuse a stale one loudly (:class:`BundleMismatch`).
3. **Parallel ladder warmup** (:mod:`warmup`) — when no bundle exists,
   ladder rungs compile concurrently (XLA compilation releases the GIL)
   instead of the old serial loop.
"""

from roko_tpu.compile.cache import (
    cache_counters,
    cache_entry_count,
    cache_total_bytes,
    enable_persistent_cache,
    resolve_cache_dir,
)
from roko_tpu.compile.bundle import (
    BUNDLE_MANIFEST,
    BundleMismatch,
    bundle_digest,
    bundle_identity,
    export_bundle,
    load_bundle,
    read_manifest,
    wrap_predict,
)
from roko_tpu.compile.warmup import WarmupReport, warmup_ladder

__all__ = [
    "BUNDLE_MANIFEST",
    "BundleMismatch",
    "WarmupReport",
    "bundle_digest",
    "bundle_identity",
    "cache_counters",
    "cache_entry_count",
    "cache_total_bytes",
    "enable_persistent_cache",
    "export_bundle",
    "load_bundle",
    "read_manifest",
    "resolve_cache_dir",
    "warmup_ladder",
    "wrap_predict",
]
