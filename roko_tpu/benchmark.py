"""Benchmark: polished-bases/sec/chip for flagship-model inference.

Measures the jitted forward+argmax path (the device-side hot loop of
`roko_tpu/infer.py`) on whatever accelerator JAX sees — the TPU chip in
the driver run. `vs_baseline` compares against the reference
architecture executed in torch on CPU (BASELINE.json configs[0] is a
"CPU reference run"; the reference publishes no throughput numbers at
all, SURVEY.md §6), timed here on an identically-shaped model.

Each window advances the genome by WINDOW_STRIDE=30 columns, so
bases/sec = windows/sec x 30 (SURVEY.md §5.7 window decomposition).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import numpy as np

BATCH = 128
WARMUP = 3
ITERS = 20
TORCH_ITERS = 3


def _bench_config(cfg) -> float:
    import jax

    from roko_tpu import constants as C
    from roko_tpu.models.model import RokoModel

    model = RokoModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    @jax.jit
    def predict(params, x):
        return jax.numpy.argmax(
            model.apply(params, x, deterministic=True), axis=-1
        )

    rng = np.random.default_rng(0)
    x = rng.integers(
        0, C.FEATURE_VOCAB, (BATCH, C.WINDOW_ROWS, C.WINDOW_COLS)
    ).astype(np.uint8)
    x = jax.device_put(x)

    # sync via an actual device->host fetch: on the tunneled TPU platform
    # block_until_ready returns at dispatch, not compute completion, so a
    # block_until_ready-based timer reads ~1000x too fast
    for _ in range(WARMUP):
        np.asarray(predict(params, x))
    t0 = time.perf_counter()
    outs = [predict(params, x) for _ in range(ITERS)]
    np.asarray(outs[-1])
    dt = time.perf_counter() - t0
    return BATCH * ITERS / dt  # windows/sec


def bench_jax() -> float:
    """Best of the two device recurrence paths (lax.scan vs the fused
    Pallas kernel) — which wins varies with chip generation."""
    import jax

    from roko_tpu.config import ModelConfig

    rates = [_bench_config(ModelConfig(compute_dtype="bfloat16"))]
    if jax.default_backend() == "tpu":
        try:
            rates.append(
                _bench_config(
                    ModelConfig(compute_dtype="bfloat16", use_pallas=True)
                )
            )
        except Exception:
            pass  # pallas path unavailable on this chip: scan result stands
    return max(rates)


def bench_torch_reference() -> float:
    """The reference's architecture (roko/rnn_model.py:24-59 semantics) in
    torch on CPU — the only hardware the reference runs on in this image."""
    import torch

    class RefModel(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.embedding = torch.nn.Embedding(12, 50)
            self.fc1 = torch.nn.Linear(200, 100)
            self.fc2 = torch.nn.Linear(100, 10)
            self.gru = torch.nn.GRU(
                500, 128, 3, batch_first=True, bidirectional=True, dropout=0.2
            )
            self.head = torch.nn.Linear(256, 5)

        def forward(self, x):
            e = self.embedding(x)  # [B,200,90,50]
            e = e.permute(0, 2, 3, 1)  # [B,90,50,200]
            h = torch.relu(self.fc1(e))
            h = torch.relu(self.fc2(h))  # [B,90,50,10]
            h = h.reshape(-1, 90, 500)
            h, _ = self.gru(h)
            return self.head(h)

    model = RefModel().eval()
    x = torch.randint(0, 12, (BATCH, 200, 90))
    with torch.no_grad():
        model(x)  # warmup
        t0 = time.perf_counter()
        for _ in range(TORCH_ITERS):
            out = model(x)
        dt = time.perf_counter() - t0
    del out
    return BATCH * TORCH_ITERS / dt  # windows/sec


def main() -> None:
    from roko_tpu import constants as C

    windows_per_sec = bench_jax()
    ref_windows_per_sec = bench_torch_reference()
    bases_per_sec = windows_per_sec * C.WINDOW_STRIDE
    print(
        json.dumps(
            {
                "metric": "polished_bases_per_sec_per_chip",
                "value": round(bases_per_sec, 1),
                "unit": "bases/s",
                "vs_baseline": round(
                    windows_per_sec / ref_windows_per_sec, 2
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
