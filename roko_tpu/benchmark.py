"""Benchmarks: inference throughput (driver metric), train step-time,
and the scan-depth / transformer variants that fill BASELINE.md.

Driver contract (``python bench.py``): ONE JSON line with
``{"metric", "value", "unit", "vs_baseline"}`` — polished-bases/sec/chip
for flagship-model inference, measured on whatever accelerator JAX sees
(the TPU chip in the driver run). ``vs_baseline`` compares against the
reference architecture executed in torch on CPU (BASELINE.json
configs[0]; the reference publishes no throughput numbers at all,
SURVEY.md §6), timed here on an identically-shaped model. A ``detail``
object carries the honest breakdown: per-path (lax.scan vs fused
Pallas) rates per swept batch size under ``detail.batch_sweep``, the
best-of headline windows/s + ``best_batch``, model FLOPs/window, and an
MFU estimate — a per-path failure is *reported* in
``detail.batch_sweep.<batch>.{scan,pallas}_error``, never swallowed.

``python -m roko_tpu bench --train`` additionally times the training
step for the flagship GRU (plus its remat and fused-Pallas A/Bs), the
4-layer/2x-hidden scan-depth stress, and the transformer variant
(BASELINE.json configs[1]/[3]/[4]) under ``detail.train``;
``--features`` times host-side extraction; ``--out`` writes the full
result object to a JSON file for the BASELINE.md table.

Each window advances the genome by WINDOW_STRIDE=30 columns, so
bases/sec = windows/sec x 30 (SURVEY.md §5.7 window decomposition).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

BATCH = 512
WARMUP = 3
ITERS = 20
TORCH_ITERS = 10

# bf16 peak per chip, by device_kind substring. Sources: public TPU
# spec sheets (v5e 197 TFLOP/s bf16, v4 275, v5p 459, v6e 918).
_PEAK_BF16 = {
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v5p": 459e12,
    "v6": 918e12,
}


def _device_peak_flops() -> Optional[float]:
    import jax

    if jax.default_backend() != "tpu":
        return None
    kind = jax.devices()[0].device_kind.lower()
    for key, peak in _PEAK_BF16.items():
        if key in kind:
            return peak
    return None


def model_param_bytes(cfg) -> int:
    """Serving-representation bytes of the whole param tree for the
    recurrent consensus models — the companion to
    :func:`model_flops_per_window` that makes the memory-bound claim
    checkable from BENCH_*.json alone (flops / bytes = arithmetic
    intensity). Accounting is STORAGE bytes, i.e. what a predict
    dispatch streams from HBM: float params are stored f32 even under
    ``compute_dtype="bfloat16"`` (the cast happens in-program), so bf16
    changes compute width but NOT these bytes; ``quantize="int8"``
    stores each targeted matmul kernel as 1 B/element plus a 4 B f32
    scale per output channel (models/quant.py) — the actual 4x byte
    cut. Counted off the model's OWN init tree via ``jax.eval_shape``
    (no compute, no params), so it can never drift from what
    ``model.init``/``quantize_params`` actually build — any kind, any
    future layout."""
    import jax

    from roko_tpu.models.model import RokoModel

    shapes = jax.eval_shape(RokoModel(cfg).init, jax.random.PRNGKey(0))
    return sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(shapes)
    )


def model_param_bytes_per_window(cfg, batch: int) -> float:
    """Weight bytes charged to ONE window when a dispatch of ``batch``
    windows streams the params once: ``model_param_bytes / batch``.
    ``model_flops_per_window / this`` is the arithmetic intensity the
    bench precision rows report."""
    return model_param_bytes(cfg) / max(1, batch)


def model_flops_per_window(cfg, *, training: bool = False) -> float:
    """Analytic matmul FLOPs per window for the recurrent consensus
    models (``kind="gru"`` and ``kind="lingru"``). Inference uses the
    one-hot reassociated embed+fc1 fast path; training materialises the
    embedding via a one-hot GEMM (dropout sits between embed and fc1)
    then contracts the read axis (models/model.py apply). Backward pass
    counted as 2x forward for training. The lingru's elementwise
    associative scan (O(T*H*log T) multiply-adds, no matmuls) is
    omitted — it is noise next to the projections."""
    T, R, V = cfg.window_cols, cfg.window_rows, cfg.embed_vocab
    D = cfg.embed_dim
    J1, J2 = cfg.read_mlp
    H, L = cfg.hidden_size, cfg.num_layers
    gin = cfg.gru_in_size

    if training:
        # onehot[B,R,T,V] @ E[V,D], then e[B,R,T,D] x W1[R,J1]
        embed_fc1 = 2 * T * R * V * D + 2 * T * D * J1 * R
    else:
        # einsum brtv,rj + vd,btvj
        embed_fc1 = 2 * T * V * J1 * R + 2 * T * D * J1 * V
    fc2 = 2 * T * J1 * J2 * D
    if cfg.kind == "lingru":
        # gate projections only: [in, 2H] per direction, no hidden
        # matmul anywhere (the recurrence is elementwise)
        rec_in = 2 * T * gin * 4 * H  # both directions, layer 1
        rec_in += (L - 1) * 2 * T * (2 * H) * 4 * H
        rec_h = 0
    else:
        rec_in = 2 * T * gin * 6 * H  # both directions, layer 1
        rec_in += (L - 1) * 2 * T * (2 * H) * 6 * H
        rec_h = L * 2 * T * 2 * H * 3 * H
    head = 2 * T * 2 * H * cfg.num_classes
    fwd = embed_fc1 + fc2 + rec_in + rec_h + head
    return fwd * (3.0 if training else 1.0)


def bench_infer(
    cfg, batch: int = BATCH, iters: int = ITERS,
    detail: Optional[Dict[str, Any]] = None,
) -> float:
    """windows/sec of the jitted forward+argmax path (the device-side
    hot loop of roko_tpu/infer.py). Timing syncs via an actual
    device->host fetch: on the tunneled TPU platform block_until_ready
    returns at dispatch, not compute completion. ``detail`` (if given)
    receives ``warmup_seconds`` — the untimed warmup loop's wall, i.e.
    the first call's compile (or persistent-cache hit) cost — so
    BENCH_*.json tracks the cold-start trajectory alongside throughput."""
    import jax

    from roko_tpu import constants as C
    from roko_tpu.models.model import RokoModel

    model = RokoModel(cfg)
    params = model.init(jax.random.PRNGKey(0))

    @jax.jit
    def predict(params, x):
        return jax.numpy.argmax(
            model.apply(params, x, deterministic=True), axis=-1
        )

    rng = np.random.default_rng(0)
    x = rng.integers(
        0, C.FEATURE_VOCAB, (batch, C.WINDOW_ROWS, C.WINDOW_COLS)
    ).astype(np.uint8)
    x = jax.device_put(x)

    t_w = time.perf_counter()
    for _ in range(WARMUP):
        np.asarray(predict(params, x))
    if detail is not None:
        detail["warmup_seconds"] = round(time.perf_counter() - t_w, 3)
    t0 = time.perf_counter()
    outs = [predict(params, x) for _ in range(iters)]
    np.asarray(outs[-1])
    dt = time.perf_counter() - t0
    return batch * iters / dt


def bench_recurrence(kind: str, batch: int, iters: int) -> float:
    """windows/sec of the RECURRENCE stack alone ([B,T,gru_in] ->
    [B,T,2H], full-size dims, float32): isolates the log-depth
    associative-scan win from the front end + head the kinds share.
    The whole-model per-kind rows are the acceptance metric; this row
    explains them — on hosts where the (kind-independent) front end
    dominates, the whole-model ratio is Amdahl-capped well below the
    recurrence-only ratio, while on TPU the serial GRU chain is nearly
    the whole predict step (ROADMAP item 1)."""
    import jax

    from roko_tpu.config import ModelConfig
    from roko_tpu.models.gru import RokoGRU, bidir_gru_stack
    from roko_tpu.models.lingru import RokoLinGRU, bidir_lingru_stack

    cfg = ModelConfig()
    if kind == "lingru":
        mod = RokoLinGRU(cfg.gru_in_size, cfg.hidden_size, cfg.num_layers, 0.0)
        stack = bidir_lingru_stack
    else:
        mod = RokoGRU(cfg.gru_in_size, cfg.hidden_size, cfg.num_layers, 0.0)
        stack = bidir_gru_stack
    params = mod.init(jax.random.PRNGKey(0))
    step = jax.jit(lambda p, x: stack(p, x))
    rng = np.random.default_rng(0)
    x = rng.standard_normal(
        (batch, cfg.window_cols, cfg.gru_in_size)
    ).astype(np.float32)
    x = jax.device_put(x)
    for _ in range(WARMUP):
        np.asarray(step(params, x))
    t0 = time.perf_counter()
    outs = [step(params, x) for _ in range(iters)]
    np.asarray(outs[-1])
    return batch * iters / (time.perf_counter() - t0)


def bench_precision(
    kind: str, batch: int, iters: int, model_overrides: Optional[Dict] = None
) -> Dict[str, Any]:
    """The precision column (ROADMAP item 1): f32 vs bf16 vs int8
    weight-only windows/sec on identical fixed work, plus the max-abs
    logit delta of each reduced-precision variant against the SAME f32
    (params, batch) — the cheap accuracy-drift bound the held-out Q
    gate (tests/test_precision.py slow lane) refines. Each variant also
    reports its param-bytes-per-window and arithmetic intensity
    (``model_param_bytes`` — int8 is the one that actually cuts the
    bytes; bf16 narrows compute, not storage). bf16 rides the MXU on
    TPU but is EMULATED on CPU, so a CPU artifact can honestly show
    bf16 *slower*, and the int8 dequant-in-matmul similarly only beats
    f32 where weight HBM traffic (not host FLOPs) bounds the step;
    ``env.backend`` disambiguates."""
    import jax
    import jax.numpy as jnp

    from roko_tpu import constants as C
    from roko_tpu.config import ModelConfig
    from roko_tpu.models.model import RokoModel
    from roko_tpu.models.quant import quantize_params

    over = model_overrides or {}
    cfg32 = ModelConfig(kind=kind, compute_dtype="float32", **over)
    cfgbf = ModelConfig(kind=kind, compute_dtype="bfloat16", **over)
    cfg8 = ModelConfig(
        kind=kind, compute_dtype="float32", quantize="int8", **over
    )
    row: Dict[str, Any] = {"model_kind": kind, "batch": batch}
    row["f32_windows_per_sec"] = round(bench_infer(cfg32, batch, iters), 1)
    row["bf16_windows_per_sec"] = round(bench_infer(cfgbf, batch, iters), 1)
    row["int8_windows_per_sec"] = round(bench_infer(cfg8, batch, iters), 1)
    flops = model_flops_per_window(cfg32)
    for tag, c in (("f32", cfg32), ("bf16", cfgbf), ("int8", cfg8)):
        pb = model_param_bytes_per_window(c, batch)
        row[f"{tag}_param_bytes_per_window"] = round(pb, 1)
        row[f"{tag}_flops_per_param_byte"] = round(flops / pb, 1)
    m32, mbf, m8 = RokoModel(cfg32), RokoModel(cfgbf), RokoModel(cfg8)
    params = m32.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = rng.integers(
        0, C.FEATURE_VOCAB,
        (min(batch, 16), cfg32.window_rows, cfg32.window_cols),
    ).astype(np.uint8)
    ref = m32.apply(params, x, deterministic=True)
    delta = jnp.abs(ref - mbf.apply(params, x, deterministic=True))
    row["max_abs_logit_delta"] = round(float(delta.max()), 5)
    delta8 = jnp.abs(
        ref - m8.apply(quantize_params(params, cfg8), x, deterministic=True)
    )
    row["int8_max_abs_logit_delta"] = round(float(delta8.max()), 5)
    return row


def bench_train(
    cfg, batch: int = BATCH, iters: int = ITERS, rng_impl: str = "threefry"
) -> Dict[str, float]:
    """Training step-time (fwd+bwd+Adam) on a single-device mesh:
    returns {"step_ms", "windows_per_sec"}. ``rng_impl`` selects the
    dropout-mask PRNG (TrainConfig.dropout_rng_impl A/B)."""
    import jax
    import jax.numpy as jnp
    import optax

    from roko_tpu import constants as C
    from roko_tpu.config import MeshConfig
    from roko_tpu.models.model import RokoModel
    from roko_tpu.parallel.mesh import make_mesh
    from roko_tpu.training.loop import create_state, make_train_step

    mesh = make_mesh(MeshConfig(dp=-1))
    model = RokoModel(cfg)
    tx = optax.adam(1e-4)
    state = create_state(model, tx, jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh)

    rng = np.random.default_rng(0)
    x = rng.integers(
        0, C.FEATURE_VOCAB, (batch, C.WINDOW_ROWS, C.WINDOW_COLS)
    ).astype(np.uint8)
    y = rng.integers(0, C.NUM_CLASSES, (batch, C.WINDOW_COLS)).astype(np.uint8)
    w = np.ones((batch,), np.float32)
    dropout_rng = (
        jax.random.PRNGKey(1)
        if rng_impl == "threefry"
        else jax.random.key(1, impl=rng_impl)
    )

    params, opt_state = state.params, state.opt_state
    step_no = jnp.zeros((), jnp.int32)
    for _ in range(WARMUP):
        params, opt_state, loss, _ = step(
            params, opt_state, step_no, x, y, w, dropout_rng
        )
        np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, opt_state, loss, _ = step(
            params, opt_state, step_no, x, y, w, dropout_rng
        )
    np.asarray(loss)
    dt = time.perf_counter() - t0
    return {
        "step_ms": 1e3 * dt / iters,
        "windows_per_sec": batch * iters / dt,
    }


def bench_torch_reference(iters: int = TORCH_ITERS, batch: int = 128) -> float:
    """The reference's architecture (roko/rnn_model.py:24-59 semantics) in
    torch on CPU — the only hardware the reference runs on in this image."""
    import torch

    class RefModel(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.embedding = torch.nn.Embedding(12, 50)
            self.fc1 = torch.nn.Linear(200, 100)
            self.fc2 = torch.nn.Linear(100, 10)
            self.gru = torch.nn.GRU(
                500, 128, 3, batch_first=True, bidirectional=True, dropout=0.2
            )
            self.head = torch.nn.Linear(256, 5)

        def forward(self, x):
            e = self.embedding(x)  # [B,200,90,50]
            e = e.permute(0, 2, 3, 1)  # [B,90,50,200]
            h = torch.relu(self.fc1(e))
            h = torch.relu(self.fc2(h))  # [B,90,50,10]
            h = h.reshape(-1, 90, 500)
            h, _ = self.gru(h)
            return self.head(h)

    model = RefModel().eval()
    x = torch.randint(0, 12, (batch, 200, 90))
    with torch.no_grad():
        model(x)  # warmup
        t0 = time.perf_counter()
        for _ in range(iters):
            out = model(x)
        dt = time.perf_counter() - t0
    del out
    return batch * iters / dt  # windows/sec


SWEEP_BATCHES = (BATCH, 2048)


def _pallas_fallback(kind: str) -> str:
    """Off-TPU ``use_pallas`` falls back to the scan path inside the
    model (models/gru.py ``_pallas_backend``); a bench row timed there
    would re-measure the scan under a pallas name. Emit ONE structured
    event naming the fallback (the PR 14 anti-fork rule: every ROKO_*
    line goes through obs.events.emit) and return the row's error
    string so the artifact records it too."""
    from roko_tpu.obs import events as obs_events

    obs_events.emit(
        "bench", "pallas_fallback",
        text=f"bench: use_pallas on a non-TPU backend falls back to the "
        f"{kind} scan path — skipping the pallas row instead of "
        "re-timing the scan under a pallas name",
        kind=kind,
    )
    return "pallas kernels need a TPU backend (scan-path fallback)"


def run_inference_suite(
    batch: Optional[int] = None, progress=None,
    iters: Optional[int] = None,
) -> Dict[str, Any]:
    """Both device recurrence paths (lax.scan vs fused Pallas), on TPU
    across a small batch sweep (the serial recurrence amortises over
    batch rows, so wider batches raise windows/s until the MXU
    saturates). Honest: a per-path failure is recorded under
    ``batch_sweep.<batch>.{scan,pallas}_error``, never hidden, and all
    per-path per-batch rates are reported so the headline is auditable.
    ``progress`` (if given) is called with the in-progress detail dict
    after every measured path so an abandoned child leaves its completed
    rows on disk (r5: the chip can stop answering MID-compile)."""
    import jax

    from roko_tpu.config import ModelConfig, default_compute_dtype

    on_tpu = jax.default_backend() == "tpu"
    # batch=None (the default run) sweeps SWEEP_BATCHES on TPU, with the
    # r2-comparable size first so a failure later in the sweep still
    # leaves the baseline-comparable number in place. An explicit
    # --batch bypasses the sweep; off-TPU the sweep answers no question
    # (no MXU to saturate) and would multiply CPU bench wall time.
    batches = SWEEP_BATCHES if batch is None and on_tpu else (batch or BATCH,)
    # fixed-work mode (--bench-iterations): a pinned, recorded iteration
    # count so cross-round deltas compare identical work (ROADMAP watch
    # item 6 — wall-clock-shaped sampling made r04->r05 uninterpretable)
    iters = ITERS if iters is None else iters
    detail: Dict[str, Any] = {"batch": batches[0], "iterations": iters}
    # the SERVING default dtype per backend (bf16 on TPU, f32 on CPU —
    # one policy, config.default_compute_dtype), so the headline
    # measures what `roko-tpu serve` actually runs. Recorded in the
    # artifact: a cross-round compare whose headline dtype changed is a
    # DEFINITION change, not a perf delta, and must say so
    dtype = default_compute_dtype()
    detail["compute_dtype"] = dtype
    cfg = ModelConfig(compute_dtype=dtype)
    cfg_p = ModelConfig(compute_dtype=dtype, use_pallas=True)
    best, best_batch, sweep = 0.0, None, {}
    detail["batch_sweep"] = sweep
    from roko_tpu.compile.cache import active_cache_dir, cache_counters

    hits0, misses0 = cache_counters()
    for b in batches:
        rates: Dict[str, Any] = {}
        sweep[str(b)] = rates
        try:
            d_s: Dict[str, Any] = {}
            rates["scan"] = round(bench_infer(cfg, b, iters, detail=d_s), 1)
            rates["scan_warmup_seconds"] = d_s.get("warmup_seconds")
        except Exception as e:
            rates["scan_error"] = f"{type(e).__name__}: {e}"[:300]
        if progress is not None:
            progress(detail)
        if on_tpu:
            try:
                d_p: Dict[str, Any] = {}
                rates["pallas"] = round(
                    bench_infer(cfg_p, b, iters, detail=d_p), 1
                )
                rates["pallas_warmup_seconds"] = d_p.get("warmup_seconds")
            except Exception as e:  # report, never swallow (VERDICT r2)
                rates["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
            if progress is not None:
                progress(detail)
        top = max(rates.get("scan", 0.0), rates.get("pallas", 0.0))
        if top > best:
            best, best_batch = top, b
    if best == 0.0:
        raise RuntimeError(f"all inference paths failed: {sweep}")

    # -- per-kind recurrence rows (ISSUE 8): torch-exact GRU vs the
    # associative-scan linear GRU on IDENTICAL fixed work (same batch,
    # same pinned iteration count), each row carrying its model_kind.
    b0 = batches[0]
    first = sweep[str(b0)]
    kinds: Dict[str, Any] = {}
    detail["model_kinds"] = kinds
    gru_row: Dict[str, Any] = {
        "model_kind": "gru", "batch": b0, "iterations": iters,
    }
    if "scan" in first:
        # the sweep's scan row IS the gru measurement (same config,
        # batch, and iteration count) — reuse it rather than paying a
        # duplicate full measurement
        gru_row["scan_windows_per_sec"] = first["scan"]
    else:
        gru_row["error"] = first.get("scan_error", "scan row failed")
    kinds["gru"] = gru_row
    lin_row: Dict[str, Any] = {
        "model_kind": "lingru", "batch": b0, "iterations": iters,
    }
    try:
        d_l: Dict[str, Any] = {}
        lin_row["scan_windows_per_sec"] = round(
            bench_infer(
                ModelConfig(kind="lingru", compute_dtype=dtype),
                b0, iters, detail=d_l,
            ),
            1,
        )
        lin_row["warmup_seconds"] = d_l.get("warmup_seconds")
    except Exception as e:  # report, never swallow
        lin_row["error"] = f"{type(e).__name__}: {e}"[:300]
    # fused Pallas lingru column (ISSUE 17): same never-swallowed
    # contract as the GRU sweep — on TPU the row measures the fused
    # kernel, off TPU it records the structured fallback instead of
    # silently re-timing the scan path under a pallas name
    if on_tpu:
        try:
            d_lp: Dict[str, Any] = {}
            lin_row["pallas_windows_per_sec"] = round(
                bench_infer(
                    ModelConfig(
                        kind="lingru", compute_dtype=dtype, use_pallas=True
                    ),
                    b0, iters, detail=d_lp,
                ),
                1,
            )
            lin_row["pallas_warmup_seconds"] = d_lp.get("warmup_seconds")
        except Exception as e:  # report, never swallow
            lin_row["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
    else:
        lin_row["pallas_error"] = _pallas_fallback("lingru")
    kinds["lingru"] = lin_row
    if progress is not None:
        progress(detail)
    if "scan_windows_per_sec" in gru_row and "scan_windows_per_sec" in lin_row:
        detail["lingru_speedup_vs_gru"] = round(
            lin_row["scan_windows_per_sec"] / gru_row["scan_windows_per_sec"],
            2,
        )
    # recurrence-isolated A/B: the log-depth win without the shared
    # front end diluting it (whole-model rows above stay the headline)
    try:
        rec_g = bench_recurrence("gru", b0, iters)
        rec_l = bench_recurrence("lingru", b0, iters)
        detail["recurrence_only"] = {
            "batch": b0,
            "iterations": iters,
            "gru_windows_per_sec": round(rec_g, 1),
            "lingru_windows_per_sec": round(rec_l, 1),
            "lingru_speedup_vs_gru": round(rec_l / rec_g, 2),
        }
    except Exception as e:  # report, never swallow
        detail["recurrence_only"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    if progress is not None:
        progress(detail)

    # -- precision column (seeds ROADMAP item 4): f32 vs bf16 per kind +
    # max-abs logit delta, at a bounded batch so the column can't eat
    # the suite's budget on emulating-bf16 hosts
    prec: Dict[str, Any] = {}
    detail["precision"] = prec
    for kind in ("gru", "lingru"):
        try:
            prec[kind] = bench_precision(kind, min(b0, 128), iters)
        except Exception as e:  # report, never swallow
            prec[kind] = {"error": f"{type(e).__name__}: {e}"[:300]}
        if progress is not None:
            progress(detail)

    hits1, misses1 = cache_counters()
    # cold-start trajectory rider: whether this round's compiles came
    # from disk (persistent cache) or paid XLA, next to the throughput
    detail["compile_cache"] = {
        "dir": active_cache_dir(),
        "hits": hits1 - hits0,
        "misses": misses1 - misses0,
    }
    if "scan" in first:
        detail["scan_windows_per_sec"] = first["scan"]
    if "pallas" in first:
        detail["pallas_windows_per_sec"] = first["pallas"]
    detail["windows_per_sec"] = best
    detail["best_batch"] = best_batch
    flops = model_flops_per_window(cfg)
    detail["model_flops_per_window"] = round(flops)
    # arithmetic-intensity companion (ISSUE 11 satellite): total weight
    # bytes a dispatch streams, per-window share at the headline batch,
    # and flops/byte — the memory-bound claim, checkable from the JSON
    detail["model_param_bytes"] = model_param_bytes(cfg)
    pbpw = model_param_bytes_per_window(cfg, best_batch or batches[0])
    detail["param_bytes_per_window"] = round(pbpw, 1)
    detail["flops_per_param_byte"] = round(flops / pbpw, 1)
    peak = _device_peak_flops()
    if peak:
        detail["mfu_pct"] = round(100.0 * best * flops / peak, 2)
    return detail


def run_train_suite(
    batch: int = BATCH, budget_s: Optional[float] = None, progress=None,
    iters: Optional[int] = None,
) -> Dict[str, Any]:
    """Fill the BASELINE.md 'measure & report' rows: flagship GRU train
    step (configs[1]), 4-layer/2x-hidden scan-depth stress (configs[3]),
    transformer variant (configs[4]). ``budget_s`` bounds wall time:
    suites that don't fit are reported as skipped, never hidden (the
    driver's bench run has a deadline; fresh compiles dominate).
    ``progress`` (if given) is called with the in-progress suite dict
    after every row so an abandoned child leaves completed rows on
    disk."""
    from roko_tpu.config import ModelConfig, default_compute_dtype

    import jax

    t0 = time.perf_counter()
    peak = _device_peak_flops()
    dtype = default_compute_dtype()
    iters = ITERS if iters is None else iters
    out: Dict[str, Any] = {"batch": batch, "iterations": iters}
    # Order = information value under a tight budget (each suite costs
    # ~60-90s of fresh compile; the default 480s budget fits four to
    # six — rows that don't fit are reported skipped, never hidden):
    # flagship GRU first, then the three backward-anomaly levers in
    # descending expected effect (remat_frontend, remat_scan, rbg —
    # BASELINE.md "training backward anomaly"), then the remaining
    # BASELINE.md rows; the fused-Pallas row last because r3 measured
    # v2 within noise of the scan path (the v3 kernels may change
    # that).
    # every row trains at the backend's serving-default dtype (ONE
    # policy: config.default_compute_dtype — bf16 on TPU, f32 on CPU
    # where bf16 is emulated)
    suites = {
        "train_gru": ModelConfig(compute_dtype=dtype),
        "train_gru_remat": ModelConfig(
            compute_dtype=dtype, remat_frontend=True
        ),
        # anomaly lever 2: recompute the scan cell's gates in the
        # backward instead of streaming 90 steps of stored activations
        # (ModelConfig.remat_scan)
        "train_gru_remat_scan": ModelConfig(
            compute_dtype=dtype, remat_scan=True
        ),
        # anomaly lever 3: same model, rbg dropout-mask PRNG
        # (TrainConfig.dropout_rng_impl) — three threefry masks per
        # step sit inside the fwd+bwd pipeline
        "train_gru_rbg": ModelConfig(compute_dtype=dtype),
        "train_scan_stress": ModelConfig(
            compute_dtype=dtype, num_layers=4, hidden_size=256
        ),
        "train_transformer": ModelConfig(
            compute_dtype=dtype, kind="transformer", d_model=256
        ),
    }
    if jax.default_backend() == "tpu":
        # off-TPU use_pallas silently falls back to the scan path, so a
        # 'pallas' row would just re-time the scan under a false name.
        suites["train_gru_pallas"] = ModelConfig(
            compute_dtype=dtype, use_pallas=True
        )
    else:
        out["train_gru_pallas"] = {"error": _pallas_fallback("gru")}
    for name, cfg in suites.items():
        if budget_s is not None and time.perf_counter() - t0 > budget_s:
            out[name] = {"error": f"skipped: {budget_s:.0f}s bench budget spent"}
        else:
            try:
                r = bench_train(
                    cfg,
                    batch,
                    iters,
                    rng_impl="rbg" if name.endswith("_rbg") else "threefry",
                )
                r["windows_per_sec"] = round(r["windows_per_sec"], 1)
                r["step_ms"] = round(r["step_ms"], 2)
                if peak and cfg.kind == "gru":
                    flops = model_flops_per_window(cfg, training=True)
                    r["mfu_pct"] = round(
                        100.0 * r["windows_per_sec"] * flops / peak, 2
                    )
                out[name] = r
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
        # skipped rows flush too: a salvaged partial must show what was
        # skipped, not silently omit it (r5 review)
        if progress is not None:
            progress(out)
    # input_stall_fraction: how much of the step the device waits on
    # host data through the real sharded input pipeline (ROADMAP item 5)
    if budget_s is not None and time.perf_counter() - t0 > budget_s:
        out["input_stall"] = {
            "error": f"skipped: {budget_s:.0f}s bench budget spent"
        }
    else:
        try:
            stall = bench_input_stall(
                ModelConfig(compute_dtype=dtype), batch, iters
            )
            out["input_stall"] = stall
            out["input_stall_fraction"] = stall["stall_fraction"]
        except Exception as e:
            out["input_stall"] = {"error": f"{type(e).__name__}: {e}"[:300]}
    if progress is not None:
        progress(out)
    return out


def _write_bench_corpus(out_dir: str, rows: int, files: int) -> None:
    """A multi-file sim training corpus with the real window geometry
    (the input suite must measure real 200x90 uint8 row traffic)."""
    from roko_tpu import constants as C
    from roko_tpu.data.hdf5 import DataWriter

    rng = np.random.default_rng(0)
    per = -(-rows // files)
    done = 0
    for fi in range(files):
        n = min(per, rows - done)
        if n <= 0:
            break
        done += n
        X = rng.integers(
            0, C.FEATURE_VOCAB, (n, C.WINDOW_ROWS, C.WINDOW_COLS)
        ).astype(np.uint8)
        Y = (X.sum(axis=1) % C.NUM_CLASSES).astype(np.int64)
        pos = [
            np.stack([np.arange(C.WINDOW_COLS), np.zeros(C.WINDOW_COLS)], 1)
        ] * n
        with DataWriter(os.path.join(out_dir, f"part{fi}.hdf5"), infer=False) as w:
            w.write_contigs([(f"c{fi}", "ACGT" * 50)])
            w.store(f"c{fi}", pos, list(X), list(Y))


def run_input_suite(
    rows: int = 1536, files: int = 3, batch: int = 128
) -> Dict[str, Any]:
    """Input data plane: samples/sec off the datapipe index layer vs the
    legacy shuffle-buffer streaming reader on the same sim corpus
    (ROADMAP item 5), plus the O(spans skipped) fast-forward vs the
    legacy prefix re-read, the bounded-memory evidence
    (max_resident_rows), and a 2-shard partition sanity check. Host-only
    numbers — meaningful on any box; ``rows`` is the fixed work."""
    import tempfile

    from roko_tpu.datapipe import ReadStats, ShardedDataset
    from roko_tpu.training.lazy_data import StreamingDataset

    # block/mix sized to the bench corpus so skip granularity and
    # residency are visible against `rows` (the real defaults assume a
    # corpus of millions of windows)
    block = max(32, rows // 12)
    mix = 2
    out: Dict[str, Any] = {
        "rows": rows, "files": files, "batch": batch,
        "block_size": block, "mix_blocks": mix,
    }

    def _drain(it) -> int:
        n = 0
        for _x, _y, w in it:
            n += int(w.sum())
        return n

    with tempfile.TemporaryDirectory() as td:
        _write_bench_corpus(td, rows, files)

        legacy = StreamingDataset(td, chunk_size=block, buffer_chunks=16)
        t0 = time.perf_counter()
        n = _drain(
            legacy.legacy_batches(
                batch, rng=np.random.default_rng(0), pad_to=batch
            )
        )
        dt_legacy = time.perf_counter() - t0
        out["legacy_stream"] = {
            "rows_per_sec": round(n / dt_legacy, 1),
            "seconds": round(dt_legacy, 3),
        }

        ds = ShardedDataset(
            td, seed=0, block_size=block, mix_blocks=mix, prefetch_blocks=2
        )
        stats = ReadStats()
        t0 = time.perf_counter()
        n = _drain(
            ds.batches(batch, rng=ds.epoch_rng(0), pad_to=batch, stats=stats)
        )
        dt_pipe = time.perf_counter() - t0
        out["datapipe_stream"] = {
            "rows_per_sec": round(n / dt_pipe, 1),
            "seconds": round(dt_pipe, 3),
            "rows_read": stats.rows_read,
            "max_resident_rows": stats.max_resident_rows,
        }
        out["speedup_vs_legacy"] = round(dt_legacy / max(dt_pipe, 1e-9), 2)

        pre = ShardedDataset(
            td, seed=0, block_size=block, mix_blocks=mix, preload=True
        )
        t0 = time.perf_counter()
        n = _drain(pre.batches(batch, rng=pre.epoch_rng(0), pad_to=batch))
        out["preload_rows_per_sec"] = round(n / (time.perf_counter() - t0), 1)

        # resume fast-forward: skip half the epoch. The index layer
        # must only read what remains; the legacy reader re-reads (and
        # re-shuffles) the whole prefix.
        skip = (rows // batch) // 2
        ff_stats = ReadStats()
        t0 = time.perf_counter()
        _drain(
            ds.batches(
                batch, rng=ds.epoch_rng(0), pad_to=batch,
                skip_batches=skip, stats=ff_stats,
            )
        )
        dt_ff = time.perf_counter() - t0
        t0 = time.perf_counter()
        _drain(
            legacy.legacy_batches(
                batch, rng=np.random.default_rng(0), pad_to=batch,
                skip_batches=skip,
            )
        )
        dt_ff_legacy = time.perf_counter() - t0
        out["fast_forward"] = {
            "skip_batches": skip,
            "datapipe_rows_read": ff_stats.rows_read,
            "datapipe_seconds": round(dt_ff, 3),
            "legacy_seconds": round(dt_ff_legacy, 3),
        }

        # shard partition sanity on the same corpus: 2 shard streams
        # must cover exactly the corpus, disjointly
        n01 = sum(
            _drain(
                ShardedDataset(
                    td, seed=0, block_size=block, mix_blocks=mix,
                    num_shards=2, shard_id=s,
                ).batches(
                    batch, rng=ds.epoch_rng(0), pad_to=batch, equalize=False
                )
            )
            for s in (0, 1)
        )
        out["shard2_union_rows"] = n01
        out["shard2_union_ok"] = bool(n01 == rows)
    return out


def bench_input_stall(cfg, batch: int, iters: int) -> Dict[str, Any]:
    """input_stall_fraction: the fraction of train-step wall time the
    device spends waiting on host data — the same fused train step
    timed (a) fed by the real sharded input pipeline (manifest index,
    span reads, host prefetch, device placement) and (b) on one
    device-resident batch. ``1 - static/piped``, floored at 0."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import optax

    from roko_tpu.config import MeshConfig
    from roko_tpu.datapipe import ShardedDataset
    from roko_tpu.models.model import RokoModel
    from roko_tpu.parallel.mesh import make_mesh
    from roko_tpu.training.data import prefetch_to_device
    from roko_tpu.training.loop import create_state, make_placer, make_train_step

    mesh = make_mesh(MeshConfig(dp=-1))
    model = RokoModel(cfg)
    tx = optax.adam(1e-4)
    state = create_state(model, tx, jax.random.PRNGKey(0))
    step = make_train_step(model, tx, mesh)
    rng_key = jax.random.PRNGKey(1)
    step_no = jnp.zeros((), jnp.int32)

    # size the corpus so warmup + the timed window fit in ONE epoch —
    # an epoch restart mid-measurement (fresh schedule, cold fds, new
    # prefetch thread) would make the stall number track restart cost,
    # not steady-state input stall. The row cap bounds corpus-write
    # time; iters shrinks to fit and the effective count is recorded.
    rows = min((WARMUP + iters) * batch, 6144)
    iters = max(2, min(iters, rows // batch - WARMUP))

    with tempfile.TemporaryDirectory() as td:
        _write_bench_corpus(td, rows, 2)
        ds = ShardedDataset(td, seed=0, block_size=256, prefetch_blocks=2)
        place = make_placer(mesh)

        def piped(n_steps):
            done, epoch = 0, 0
            while done < n_steps:
                it = ds.batches(
                    batch, rng=ds.epoch_rng(epoch), pad_to=batch,
                    drop_remainder=True,
                )
                for b in prefetch_to_device(it, 2, place):
                    yield b
                    done += 1
                    if done >= n_steps:
                        return
                epoch += 1

        params, opt_state = state.params, state.opt_state
        static = None
        for x, y, w in piped(WARMUP):  # warmup: compile + first reads
            params, opt_state, loss, _ = step(
                params, opt_state, step_no, x, y, w, rng_key
            )
            static = (x, y, w)
        np.asarray(loss)

        x, y, w = static
        t0 = time.perf_counter()
        for _ in range(iters):
            params, opt_state, loss, _ = step(
                params, opt_state, step_no, x, y, w, rng_key
            )
        np.asarray(loss)
        dt_static = time.perf_counter() - t0

        t0 = time.perf_counter()
        for x, y, w in piped(iters):
            params, opt_state, loss, _ = step(
                params, opt_state, step_no, x, y, w, rng_key
            )
        np.asarray(loss)
        dt_piped = time.perf_counter() - t0

    return {
        "stall_fraction": round(max(0.0, 1.0 - dt_static / max(dt_piped, 1e-9)), 4),
        "static_step_ms": round(1e3 * dt_static / iters, 2),
        "piped_step_ms": round(1e3 * dt_piped / iters, 2),
        "iterations": iters,
        "batch": batch,
    }


def run_features_suite(
    draft_len: int = 200_000, coverage: int = 30
) -> Dict[str, Any]:
    """Host-side feature-extraction throughput (the CPU stage that feeds
    the chip): synthesises a draft + ~coverage x noisy reads (2% sub /
    1% ins / 1% del with exact CIGARs, roko_tpu.sim) through the
    package's own BAM writer, then times ``run_features`` with the
    native (C++) and pure-Python extractor backends. Reported in
    windows/s and draft-bases/s — CPU numbers, meaningful on any
    host."""
    import random
    import tempfile
    import os

    from roko_tpu.features.pipeline import run_features
    from roko_tpu.io.bam import write_sorted_bam
    from roko_tpu.io.fasta import write_fasta
    from roko_tpu.sim import random_seq, simulate_reads

    rng = random.Random(0)
    draft = random_seq(rng, draft_len)
    read_len = min(3000, max(100, draft_len // 4))
    records = simulate_reads(
        rng, draft, 0, coverage=coverage, read_len=read_len
    )
    out: Dict[str, Any] = {"draft_len": draft_len, "coverage": coverage}
    # build the native .so (if stale/missing) BEFORE the timed window, so
    # a clean host doesn't count the g++ compile as extraction time
    try:
        from roko_tpu.native import binding as _binding

        _binding.is_available()
    except Exception:
        pass
    with tempfile.TemporaryDirectory() as td:
        fasta = os.path.join(td, "draft.fasta")
        bam = os.path.join(td, "reads.bam")
        write_fasta(fasta, [("ctg", draft)])
        write_sorted_bam(bam, [("ctg", draft_len)], records)
        mp_workers = min(4, os.cpu_count() or 1)
        runs = [
            ("native", "0", 1),
            # multicore scaling evidence (ThreadPool over regions); the
            # Python oracle is skipped at >1 worker — GIL-bound, and the
            # single-worker row already anchors the native-vs-Python gap
            (f"native_t{mp_workers}", "0", mp_workers),
            ("python", "1", 1),
        ]
        for name, force_py, workers in runs:
            if name.startswith("native_t") and mp_workers == 1:
                continue  # single-core host: the row would duplicate 'native'
            # the native pass must override, not merely not-set, the
            # force-python debug knob a user may have exported
            old = os.environ.get("ROKO_TPU_FORCE_PY_EXTRACTOR")
            os.environ["ROKO_TPU_FORCE_PY_EXTRACTOR"] = force_py
            try:
                t0 = time.perf_counter()
                n = run_features(
                    fasta,
                    bam,
                    os.path.join(td, f"{name}.hdf5"),
                    seed=0,
                    workers=workers,
                    log=lambda *a, **k: None,
                )
                dt = time.perf_counter() - t0
                out[name] = {
                    "workers": workers,
                    "windows_per_sec": round(n / dt, 1),
                    "draft_bases_per_sec": round(draft_len / dt, 1),
                    "seconds": round(dt, 2),
                }
            except Exception as e:
                out[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
            finally:
                if old is None:
                    os.environ.pop("ROKO_TPU_FORCE_PY_EXTRACTOR", None)
                else:
                    os.environ["ROKO_TPU_FORCE_PY_EXTRACTOR"] = old
    return out


def _measure(args) -> Dict[str, Any]:
    """Run the actual measurement in THIS process and return the driver
    result object. Assumes the JAX backend in this process is usable —
    callers that cannot assume that (the driver path) go through the
    orchestrated ``main`` below, which probes and falls back instead of
    letting a sick backend turn the round's artifact into a traceback
    (VERDICT r3: BENCH_r03.json rc=1, parsed null)."""
    import os
    import sys

    # parse the env knob BEFORE any measurement so a typo can't discard
    # minutes of completed TPU work on a late ValueError
    try:
        train_budget = float(os.environ.get("ROKO_BENCH_TRAIN_BUDGET", "480"))
    except ValueError:
        train_budget = 480.0

    # persistent compile cache on for the measurement process (honors
    # ROKO_COMPILE_CACHE=off): round N+1's warmup_seconds rows then show
    # the warm-start trajectory, not an artifact of rebuilt jit caches
    from roko_tpu.compile.cache import enable_persistent_cache

    enable_persistent_cache()

    # stderr progress stamps: the orchestrated parent captures the child
    # log, so a timed-out/abandoned child's tail shows which suite ate
    # the budget instead of a bare platform warning (r5 post-mortem aid)
    t_start = time.perf_counter()

    def _stamp(suite: str) -> None:
        print(
            f"[bench] +{time.perf_counter() - t_start:7.1f}s {suite}",
            file=sys.stderr,
            flush=True,
        )

    # partial-result flush: every completed measurement is written
    # (atomically) to --out as {"partial": true, "detail": ...} and the
    # final result overwrites it. If this process is later abandoned
    # mid-suite — the r5 failure mode is a chip that stops answering
    # mid-COMPILE, unkillable-safe but unfinishable — the orchestrating
    # parent recovers the completed rows instead of discarding the whole
    # TPU session (r3/r4 shipped zero TPU evidence for exactly this).
    running_detail: Dict[str, Any] = {}

    def _flush_partial(fragment_key=None, fragment=None):
        if fragment_key is not None:
            running_detail[fragment_key] = fragment
        if not getattr(args, "out", None):
            return
        tmp = args.out + ".tmp"
        # TypeError/ValueError too: a non-serializable or circular row
        # must degrade to a skipped flush, not abort the measurement run
        # mid-suite (ADVICE — the flush is best-effort by design)
        try:
            with open(tmp, "w") as f:
                json.dump({"partial": True, "detail": running_detail}, f)
            os.replace(tmp, args.out)
        except (OSError, TypeError, ValueError):
            pass

    def _merge_flush(d):
        # inference-suite fields live at detail's top level in the final
        # layout; mirror that in the partial so recovery needs no remap
        try:
            running_detail.update(json.loads(json.dumps(d)))
        except (TypeError, ValueError):
            return  # non-serializable fragment: skip it, keep measuring
        _flush_partial()

    bench_iters = getattr(args, "bench_iterations", None)
    _stamp("inference suite (batch sweep)")
    detail = run_inference_suite(
        args.batch, progress=_merge_flush, iters=bench_iters
    )
    running_detail.update(detail)
    _flush_partial()
    # the driver's end-of-round run invokes plain `python bench.py`; on
    # TPU, spend a bounded extra budget capturing the train step-times
    # BASELINE.md needs (ROKO_BENCH_TRAIN_BUDGET=0 disables)
    import jax

    train_progress = lambda d: _flush_partial("train", dict(d))  # noqa: E731
    if args.train:
        _stamp("train suite (unbounded)")
        detail["train"] = run_train_suite(
            args.batch or BATCH, progress=train_progress, iters=bench_iters
        )
    elif jax.default_backend() == "tpu" and train_budget > 0:
        _stamp(f"train suite (budget {train_budget:.0f}s)")
        detail["train"] = run_train_suite(
            args.batch or BATCH, budget_s=train_budget,
            progress=train_progress, iters=bench_iters,
        )
    if args.features:
        _stamp("features suite")
        detail["features"] = run_features_suite()
        _flush_partial("features", detail["features"])
    e2e_draft = getattr(args, "e2e_draft", None)
    if e2e_draft is None:
        # default scale by backend: a real slice on the chip, a token
        # one on CPU (where model inference is ~1000x slower) — 0
        # disables entirely
        e2e_draft = 2_000_000 if jax.default_backend() == "tpu" else 60_000
    if e2e_draft:
        _stamp(f"end-to-end suite (draft {e2e_draft})")
        try:
            detail["end_to_end"] = run_e2e_suite(e2e_draft)
        except Exception as e:  # report, never swallow
            detail["end_to_end"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("end_to_end", detail["end_to_end"])
    pipeline_draft = getattr(args, "pipeline_draft", None)
    if pipeline_draft is None:
        # default follows the e2e suite's resolved scale decision: a
        # run that disabled e2e (--e2e-draft 0 — the cheap contract
        # mode tests use) skips this suite too, while the driver's
        # plain `python bench.py` gets both. Sized below e2e because
        # this suite runs the same stages TWICE (staged + streaming).
        if not e2e_draft:
            pipeline_draft = 0
        else:
            pipeline_draft = (
                500_000 if jax.default_backend() == "tpu" else 60_000
            )
    if pipeline_draft:
        _stamp(f"pipeline suite (staged vs streaming, draft {pipeline_draft})")
        try:
            detail["pipeline"] = run_pipeline_suite(pipeline_draft)
        except Exception as e:  # report, never swallow
            detail["pipeline"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("pipeline", detail["pipeline"])
    cascade_draft = getattr(args, "cascade_draft", None)
    if cascade_draft is None:
        # default follows the e2e scale decision (as the pipeline
        # suite): contract-mode runs (--e2e-draft 0) skip it. Sized
        # small — the suite runs inference four times (reference,
        # threshold-0 identity, cold + warm cascade) on the same corpus.
        cascade_draft = 40_000 if e2e_draft else 0
    if cascade_draft:
        _stamp(f"cascade suite (tier router + window cache, draft {cascade_draft})")
        try:
            detail["cascade"] = run_cascade_suite(cascade_draft)
        except Exception as e:  # report, never swallow
            detail["cascade"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("cascade", detail["cascade"])
    coldstart_ladder = getattr(args, "coldstart_ladder", None)
    if coldstart_ladder is None:
        # default follows the e2e scale decision (as the pipeline
        # suite): contract-mode runs (--e2e-draft 0) skip it, the
        # driver's plain `python bench.py` measures it
        coldstart_ladder = DEFAULT_COLDSTART_LADDER if e2e_draft else ()
    if coldstart_ladder:
        _stamp(f"coldstart suite (ladder {tuple(coldstart_ladder)})")
        try:
            detail["coldstart"] = run_coldstart_suite(coldstart_ladder)
        except Exception as e:  # report, never swallow
            detail["coldstart"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("coldstart", detail["coldstart"])
    input_rows = getattr(args, "input_rows", None)
    if input_rows is None:
        # default follows the e2e scale decision (as coldstart): the
        # cheap contract-mode runs skip it, the driver's plain run
        # measures it. Host-only fixed work — backend-independent.
        input_rows = 1536 if e2e_draft else 0
    if input_rows:
        _stamp(f"input suite (datapipe vs legacy reader, {input_rows} rows)")
        try:
            detail["input"] = run_input_suite(input_rows)
        except Exception as e:  # report, never swallow
            detail["input"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("input", detail["input"])
    mesh_devices = getattr(args, "mesh_devices", None)
    if mesh_devices is None:
        # default follows the e2e scale decision (as coldstart):
        # contract-mode runs skip it, the driver's plain run measures
        # the one-session-every-chip scaling rows (ROADMAP item 2;
        # always CPU-simulated devices — real-TPU rows are item 6 debt)
        mesh_devices = DEFAULT_MESH_DEVICES if e2e_draft else ()
    if mesh_devices:
        _stamp(f"mesh suite (simulated devices {tuple(mesh_devices)})")
        try:
            detail["mesh"] = run_mesh_suite(
                mesh_devices, iterations=bench_iters
            )
        except Exception as e:  # report, never swallow
            detail["mesh"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("mesh", detail["mesh"])
    # an EXPLICIT --serve-mix also threads the mixed workload through
    # the fleet suite (per-size-class latency + per-worker padding
    # efficiency for both batching modes); the default driver run keeps
    # the fleet suite's flat single-size cost
    explicit_mix = getattr(args, "serve_mix", None)
    if explicit_mix in ("0", "off", ""):
        explicit_mix = None
    fleet_workers = getattr(args, "fleet_workers", None)
    if fleet_workers is None:
        # default follows the e2e scale decision (as coldstart):
        # contract-mode runs skip it, the driver's plain run measures it
        fleet_workers = (1, 2) if e2e_draft else ()
    if fleet_workers:
        _stamp(f"fleet suite (workers {tuple(fleet_workers)})")
        try:
            detail["fleet"] = run_fleet_suite(
                fleet_workers, iterations=bench_iters or FLEET_ITERS,
                mix=explicit_mix,
            )
        except Exception as e:  # report, never swallow
            detail["fleet"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("fleet", detail["fleet"])
    serve_mix = getattr(args, "serve_mix", None)
    if serve_mix is None:
        # default follows the e2e scale decision; the large class
        # scales to the backend (a 256-window request is cheap on TPU,
        # signal-burying on the 2-core CPU box)
        serve_mix = (
            (SERVE_MIX_DEFAULT_TPU if jax.default_backend() == "tpu"
             else SERVE_MIX_DEFAULT_CPU)
            if e2e_draft else ""
        )
    if serve_mix and serve_mix not in ("0", "off"):
        _stamp(f"serve suite (mixed sizes {serve_mix}, both batching modes)")
        try:
            detail["serve"] = run_serve_suite(
                serve_mix, iterations=bench_iters or SERVE_SUITE_REQUESTS
            )
        except Exception as e:  # report, never swallow
            detail["serve"] = {"error": f"{type(e).__name__}: {e}"[:300]}
        _flush_partial("serve", detail["serve"])
    _stamp("torch reference")
    ref_windows_per_sec = bench_torch_reference()
    # provenance: which stack produced this artifact (BENCH_r{N}.json is
    # compared across rounds; backend/device drift must be visible)
    detail["env"] = {
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "jax": jax.__version__,
        "git": _git_rev(),
    }
    return _assemble_result(detail, ref_windows_per_sec)


def _assemble_result(
    detail: Dict[str, Any], ref_windows_per_sec: float
) -> Dict[str, Any]:
    """The one place the driver artifact's top-level shape is built —
    shared by the full in-process run and the partial-salvage path so
    the two can never drift (r5 review)."""
    from roko_tpu import constants as C

    detail["torch_cpu_ref_windows_per_sec"] = round(ref_windows_per_sec, 1)
    windows_per_sec = detail["windows_per_sec"]
    return {
        "metric": "polished_bases_per_sec_per_chip",
        "value": round(windows_per_sec * C.WINDOW_STRIDE, 1),
        "unit": "bases/s",
        "vs_baseline": round(windows_per_sec / ref_windows_per_sec, 2),
        "detail": detail,
    }


#: cross-round deltas inside this band are flagged ``noise``, never
#: regressions: scan_windows_per_sec moved 117.5 -> 93.4 between r04 and
#: r05 with no plausible code cause (the torch CPU reference moved the
#: same direction) — single-digit-% moves on a shared noisy box track
#: the box, not the code (ROADMAP watch item 6)
NOISE_BAND_PCT = 10.0


def compare_to_previous(
    result: Dict[str, Any],
    prev: Dict[str, Any],
    noise_band_pct: float = NOISE_BAND_PCT,
) -> Dict[str, Any]:
    """Attach a ``detail.vs_previous`` block comparing this artifact's
    headline metrics (incl. the per-kind ``model_kinds`` rows and the
    cross-round ``vs_baseline`` ratio) against a previous BENCH_*.json.
    Each metric reports current/previous/delta_pct plus ``noise: true``
    when the delta sits inside the noise band; only a drop BEYOND the
    band is marked ``regression``."""
    cur_d = result.get("detail") or {}
    prev_d = prev.get("detail") or {}
    pairs: Dict[str, Tuple[Any, Any]] = {
        "value": (result.get("value"), prev.get("value")),
        "vs_baseline": (result.get("vs_baseline"), prev.get("vs_baseline")),
        "windows_per_sec": (
            cur_d.get("windows_per_sec"), prev_d.get("windows_per_sec"),
        ),
        "scan_windows_per_sec": (
            cur_d.get("scan_windows_per_sec"),
            prev_d.get("scan_windows_per_sec"),
        ),
        "pallas_windows_per_sec": (
            cur_d.get("pallas_windows_per_sec"),
            prev_d.get("pallas_windows_per_sec"),
        ),
    }
    for kind, row in (cur_d.get("model_kinds") or {}).items():
        prow = (prev_d.get("model_kinds") or {}).get(kind) or {}
        for col in ("scan_windows_per_sec", "pallas_windows_per_sec"):
            pairs[f"model_kinds.{kind}.{col}"] = (
                (row or {}).get(col), prow.get(col),
            )
    # ragged-vs-continuous serve rows (ISSUE 17): padding efficiency +
    # req/s of the masked top-rung path, same noise discipline
    for col in ("padding_efficiency", "req_per_s", "req_per_s_vs_continuous"):
        pairs[f"serve.ragged.{col}"] = (
            ((cur_d.get("serve") or {}).get("ragged") or {}).get(col),
            ((prev_d.get("serve") or {}).get("ragged") or {}).get(col),
        )
    # tenant-mix rows (ISSUE 19): the fair-share isolation headline plus
    # the per-tenant fair-ON latency/throughput, same noise discipline
    cur_tm = (cur_d.get("serve") or {}).get("tenant_mix") or {}
    prev_tm = (prev_d.get("serve") or {}).get("tenant_mix") or {}
    for col in ("interactive_p99_improvement", "bulk_req_per_s_retained"):
        pairs[f"serve.tenant_mix.{col}"] = (cur_tm.get(col), prev_tm.get(col))
    for tname in ("interactive", "bulk"):
        crow = ((cur_tm.get("fair") or {}).get("tenants") or {}).get(tname) or {}
        prow = ((prev_tm.get("fair") or {}).get("tenants") or {}).get(tname) or {}
        for col in ("p99_s", "req_per_s"):
            pairs[f"serve.tenant_mix.fair.{tname}.{col}"] = (
                crow.get(col), prow.get(col),
            )
    # autoscale rows (ISSUE 19): per-phase measured req/s across the
    # load step — the decision trajectory itself is asserted by tests,
    # only throughput is noise-compared
    cur_ph = {
        p.get("phase"): p
        for p in ((cur_d.get("serve") or {}).get("autoscale") or {}).get(
            "phases"
        ) or []
    }
    prev_ph = {
        p.get("phase"): p
        for p in ((prev_d.get("serve") or {}).get("autoscale") or {}).get(
            "phases"
        ) or []
    }
    for phase in ("flood",):
        pairs[f"serve.autoscale.{phase}.req_per_s"] = (
            (cur_ph.get(phase) or {}).get("req_per_s"),
            (prev_ph.get(phase) or {}).get("req_per_s"),
        )
    # precision rows (ISSUE 11): the f32/bf16/int8 columns compare
    # cross-round on the same fixed work, same noise discipline
    for kind, row in (cur_d.get("precision") or {}).items():
        prow = (prev_d.get("precision") or {}).get(kind) or {}
        for col in (
            "f32_windows_per_sec",
            "bf16_windows_per_sec",
            "int8_windows_per_sec",
        ):
            pairs[f"precision.{kind}.{col}"] = (
                (row or {}).get(col), prow.get(col),
            )
    # cascade rows (ISSUE 16): reference vs cascaded throughput plus
    # the routing-quality columns, same noise discipline
    for col in (
        "reference_windows_per_sec",
        "cascade_windows_per_sec",
        "escalation_pct",
        "cache_hit_rate",
    ):
        pairs[f"cascade.{col}"] = (
            (cur_d.get("cascade") or {}).get(col),
            (prev_d.get("cascade") or {}).get(col),
        )
    # mesh rows (ROADMAP item 2): per-device-count windows/sec on the
    # same fixed global work, same noise discipline
    for n, row in ((cur_d.get("mesh") or {}).get("rows") or {}).items():
        prow = ((prev_d.get("mesh") or {}).get("rows") or {}).get(n) or {}
        pairs[f"mesh.{n}.windows_per_sec"] = (
            (row or {}).get("windows_per_sec"),
            prow.get("windows_per_sec"),
        )
    metrics: Dict[str, Any] = {}
    for name, (cur, old) in pairs.items():
        if (
            not isinstance(cur, (int, float))
            or not isinstance(old, (int, float))
            or not old
        ):
            continue
        delta_pct = 100.0 * (cur - old) / old
        row = {
            "current": cur,
            "previous": old,
            "delta_pct": round(delta_pct, 2),
            "noise": abs(delta_pct) < noise_band_pct,
        }
        if delta_pct <= -noise_band_pct:
            row["regression"] = True
        metrics[name] = row
    # comparisons are only interpretable on identical fixed work AND an
    # identical measurement regime: record both sides' pinned iteration
    # counts and headline compute dtypes so a mismatch is visible
    block = {
        "noise_band_pct": noise_band_pct,
        "iterations": cur_d.get("iterations"),
        "previous_iterations": prev_d.get("iterations"),
        "compute_dtype": cur_d.get("compute_dtype"),
        "previous_compute_dtype": prev_d.get("compute_dtype"),
        "metrics": metrics,
    }
    cur_dtype, prev_dtype = block["compute_dtype"], block["previous_compute_dtype"]
    if cur_dtype is not None and prev_dtype != cur_dtype:
        # headline dtype moved (or the previous artifact predates the
        # record — pre-PR-11 CPU headlines hardcoded bf16): the deltas
        # above compare different PROGRAMS, not code speed
        block["regime_change"] = (
            f"headline compute dtype is {cur_dtype!r} but the previous "
            f"artifact's was {prev_dtype!r}"
            + (
                " (absent = pre-precision-plane artifact; its CPU "
                "headline measured emulated bfloat16)"
                if prev_dtype is None
                else ""
            )
            + " — deltas reflect the dtype change, not a code regression"
        )
    result.setdefault("detail", {})["vs_previous"] = block
    return block


def _apply_compare(result: Dict[str, Any], compare_path: str) -> None:
    """Best-effort ``--compare``: an unreadable previous artifact is
    reported inside the result, never allowed to void it."""
    try:
        with open(compare_path) as f:
            prev = json.load(f)
    except (OSError, ValueError) as e:
        result.setdefault("detail", {})["vs_previous"] = {
            "error": f"could not read {compare_path!r}: {e}"[:300]
        }
        return
    block = compare_to_previous(result, prev)
    block["file"] = compare_path


def _git_rev() -> str:
    """Short sha of the measured tree (cross-round artifact provenance);
    'unknown' outside a git checkout."""
    import os
    import subprocess

    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=10,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _emit(result: Dict[str, Any], out_path) -> None:
    if out_path:
        with open(out_path, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    print(json.dumps(result))


# The probe/abandon machinery lives in roko_tpu.resilience.probe now
# (shared with tools/chip_probe.py — ONE deadline implementation); the
# private aliases stay so the orchestration below and the contract
# tests keep their names.
from roko_tpu.resilience.probe import (  # noqa: E402
    last_probe_tail as _last_probe_tail,
    probe_backend as _probe_backend,
    spawn_logged as _spawn_logged,
    tail_file as _tail,
    wait_no_kill as _wait_no_kill,
)

#: memoized probe verdict for this process: ``(ok, why, platform)``.
#: The subprocess probe costs up to ROKO_BENCH_PROBE_TIMEOUT seconds —
#: a run must pay it ONCE, never once per suite.
_PROBE_VERDICT: "Optional[tuple]" = None


def _probe_backend_once(timeout_s: float, log) -> "tuple":
    """Probe the backend at most once per run, cache the verdict, and
    emit ONE structured ``backend_probe`` event (the PR 14 anti-fork
    rule: every ROKO_* observability line goes through obs.events.emit)
    so orchestration logs record what the probe saw — machine-parsable,
    beside the human stderr line."""
    global _PROBE_VERDICT
    if _PROBE_VERDICT is not None:
        return _PROBE_VERDICT
    ok, why, platform = _probe_backend(timeout_s, log)
    _PROBE_VERDICT = (ok, why, platform)
    from roko_tpu.obs import events as obs_events

    obs_events.emit(
        "bench", "backend_probe",
        text=f"bench: backend probe "
        + (f"ok on {platform}" if ok else f"failed: {why[:200]}"),
        ok=ok, platform=platform or "unknown",
        why=(why or "")[:200],
        # the probe child's own stderr/stdout tail as a structured
        # field: a wedged-probe post-mortem reads the event log, not a
        # deleted temp file
        tail=("" if ok else _last_probe_tail()[-600:]),
    )
    return _PROBE_VERDICT


def _probe_verdict_detail() -> "Optional[Dict[str, Any]]":
    """The cached probe verdict as an artifact-embeddable dict (None
    when no probe ran, e.g. the explicit-CPU path)."""
    if _PROBE_VERDICT is None:
        return None
    ok, why, platform = _PROBE_VERDICT
    return {
        "ok": bool(ok),
        "platform": platform or "unknown",
        "why": (why or "")[:600],
    }


def _run_child_bench(args, budget_s: float, log, platform: str = "tpu"):
    """Run the full measurement in a child process (same env, live
    backend) with a wall-clock budget, so a mid-suite relay death can at
    worst cost the budget — never the artifact. Returns the child's
    result dict, or None. ``platform`` is the backend the probe actually
    saw — threaded into any salvaged partial so a CPU measurement can
    never be labelled as a chip one (r5 review)."""
    import os
    import sys
    import tempfile

    out_json = tempfile.NamedTemporaryFile(suffix=".json", delete=False).name
    try:
        cmd = [sys.executable, "-m", "roko_tpu.benchmark", "--in-process"]
        cmd += ["--out", out_json]
        if args.train:
            cmd.append("--train")
        if args.features:
            cmd.append("--features")
        if args.batch is not None:
            cmd += ["--batch", str(args.batch)]
        if getattr(args, "e2e_draft", None) is not None:
            cmd += ["--e2e-draft", str(args.e2e_draft)]
        if getattr(args, "pipeline_draft", None) is not None:
            cmd += ["--pipeline-draft", str(args.pipeline_draft)]
        if getattr(args, "cascade_draft", None) is not None:
            cmd += ["--cascade-draft", str(args.cascade_draft)]
        if getattr(args, "coldstart_ladder", None) is not None:
            cmd += [
                "--coldstart-ladder",
                ",".join(str(r) for r in args.coldstart_ladder) or "0",
            ]
        if getattr(args, "fleet_workers", None) is not None:
            cmd += [
                "--fleet-workers",
                ",".join(str(n) for n in args.fleet_workers) or "0",
            ]
        if getattr(args, "mesh_devices", None) is not None:
            cmd += [
                "--mesh-devices",
                ",".join(str(n) for n in args.mesh_devices) or "0",
            ]
        if getattr(args, "bench_iterations", None) is not None:
            cmd += ["--bench-iterations", str(args.bench_iterations)]
        if getattr(args, "serve_mix", None) is not None:
            cmd += ["--serve-mix", args.serve_mix]
        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        rc, out = _spawn_logged(cmd, budget_s, cwd=repo_root)
        if rc == 0:
            try:
                with open(out_json) as f:
                    result = json.load(f)
                if not result.get("partial"):
                    return result
                # _recover_partial re-reads the file below
                log("[bench] child rc=0 but left only a partial result")
            except (OSError, ValueError) as e:
                log(f"[bench] child rc=0 but result unreadable: {e}")
                return None
        how = "timed out (abandoned)" if rc is None else f"rc={rc}"
        log(f"[bench] TPU child {how}; log tail:\n{out[-1500:]}")
        # The child flushes every completed measurement to --out as it
        # goes (see _measure._flush_partial). Salvage whatever the chip
        # answered before going dark: a partial TPU artifact with real
        # sweep rows beats a complete CPU fallback (r3/r4 lesson — the
        # headline is a TPU number or it is nothing).
        return _recover_partial(out_json, how, log, platform)
    finally:
        # delete=False temp: every exit path above — full result,
        # unreadable result, failed salvage — must drop the file, not
        # just the successful-salvage path (temp-file leak otherwise)
        try:
            os.unlink(out_json)
        except OSError:
            pass


def _recover_partial(out_json: str, how: str, log, platform: str = "tpu"):
    """Build a full driver result from an abandoned child's partial
    flush, if it contains at least one successful inference rate."""
    import os

    try:
        with open(out_json) as f:
            raw = json.load(f)
    except (OSError, ValueError):
        return None
    if not raw.get("partial"):
        return None
    detail = raw.get("detail") or {}
    sweep = detail.get("batch_sweep") or {}
    rates = [
        (max(r.get("scan", 0.0), r.get("pallas", 0.0)), int(b))
        for b, r in sweep.items()
    ]
    best, best_batch = max(rates, default=(0.0, None))
    if not best:
        log("[bench] partial result had no completed inference row")
        return None
    try:
        os.unlink(out_json)
    except OSError:
        pass
    detail["windows_per_sec"] = detail.get("windows_per_sec", best) or best
    detail.setdefault("best_batch", best_batch)
    # env was never written (it is stamped at the end of a full run);
    # the child only measures on the backend the probe cleared, so
    # backend is known — but mark the artifact loudly as partial
    detail.setdefault("env", {})
    detail["env"].setdefault("backend", platform)
    detail["env"].setdefault("git", _git_rev())
    detail["partial"] = (
        f"child {how} mid-suite; completed measurements salvaged from "
        "the incremental flush, remaining suites missing"
    )
    log(f"[bench] salvaged partial TPU result: {best:.1f} windows/s")
    return _assemble_result(detail, bench_torch_reference())


def _force_cpu_backend() -> None:
    """Point THIS process (and any children) at the CPU backend, even if
    a sitecustomize already imported jax and registered the TPU plugin."""
    import os

    from roko_tpu.cli import _honor_jax_platforms_env

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    _honor_jax_platforms_env()


def run_e2e_suite(draft_len: int = 2_000_000, coverage: int = 20) -> Dict[str, Any]:
    """Whole-pipeline throughput (VERDICT r3 task 3): synthesize a
    draft + reads, then run the REAL ``features -> run_inference ->
    stitch`` path and report end-to-end bases/s with the per-stage
    breakdown — so the device-only headline is checked against what
    the full pipeline (HDF5 slab reads, host vote accumulation,
    stitching) actually sustains. Ref semantics:
    roko/inference.py:90-154; the reference splits the same two stages
    (features.py precompute, then inference.py over HDF5)."""
    import os
    import random
    import tempfile

    import jax

    from roko_tpu.config import (
        ModelConfig,
        RokoConfig,
        default_compute_dtype,
    )
    from roko_tpu.features.pipeline import run_features
    from roko_tpu.infer import run_inference
    from roko_tpu.io.bam import write_sorted_bam
    from roko_tpu.io.fasta import write_fasta
    from roko_tpu.models.model import RokoModel
    from roko_tpu.sim import random_seq, simulate_reads

    out: Dict[str, Any] = {"draft_len": draft_len, "coverage": coverage}
    stages: Dict[str, float] = {}
    rng = random.Random(0)
    with tempfile.TemporaryDirectory() as td:
        fasta = os.path.join(td, "draft.fasta")
        bam = os.path.join(td, "reads.bam")
        h5 = os.path.join(td, "infer.hdf5")
        t0 = time.perf_counter()
        draft = random_seq(rng, draft_len)
        read_len = min(3000, max(100, draft_len // 4))
        records = simulate_reads(
            rng, draft, 0, coverage=coverage, read_len=read_len
        )
        write_fasta(fasta, [("ctg", draft)])
        write_sorted_bam(bam, [("ctg", draft_len)], records)
        sim_s = time.perf_counter() - t0
        stages["sim_s"] = round(sim_s, 3)

        t0 = time.perf_counter()
        n = run_features(
            fasta,
            bam,
            h5,
            seed=0,
            workers=max(1, os.cpu_count() or 1),
            log=lambda *a, **k: None,
        )
        features_s = time.perf_counter() - t0
        stages["features_s"] = round(features_s, 3)
        out["windows"] = n
        out["features_windows_per_sec"] = round(n / features_s, 1)

        cfg = RokoConfig(
            model=ModelConfig(compute_dtype=default_compute_dtype())
        )
        model = RokoModel(cfg.model)
        params = model.init(jax.random.PRNGKey(0))
        lines: list = []
        t0 = time.perf_counter()
        polished = run_inference(
            h5, params, cfg, batch_size=512, prefetch=4, log=lines.append
        )
        inference_s = time.perf_counter() - t0
        stages["inference_s"] = round(inference_s, 3)
    out["stages"] = stages
    # inference-stage rate is the number comparable to the device-only
    # headline: same windows, but through HDF5 reads + voting + stitch
    from roko_tpu import constants as C

    out["inference_windows_per_sec"] = round(n / inference_s, 1)
    out["inference_bases_per_sec"] = round(
        n * C.WINDOW_STRIDE / inference_s, 1
    )
    # the pipeline a user actually runs starts from an existing
    # FASTA+BAM: features + inference. sim_s is harness-only cost and
    # stays out of the rate (it is still reported under stages).
    out["pipeline_bases_per_sec"] = round(
        draft_len / (features_s + inference_s), 1
    )
    out["polished_contigs"] = len(polished)
    out["stage_breakdown"] = lines[-6:]  # StageTimer report lines
    return out


def run_cascade_suite(
    draft_len: int = 40_000, coverage: int = 20, threshold: float = 0.05
) -> Dict[str, Any]:
    """Adaptive-compute cascade (ISSUE 16): the same sim corpus through
    plain ``run_inference`` (reference), through the cascade at
    threshold 0 (every window escalates — output must be sha256-identical
    to the reference, the byte-identity gate), and through the cascade at
    the working threshold twice against one on-disk window-cache sidecar
    (cold, then warm — the warm run's hit rate is what a distpolish
    fleet sharing the sidecar would see). Reports windows/sec for both
    paths, the escalation fraction, and cold/warm cache hit rates."""
    import dataclasses
    import hashlib
    import os
    import random
    import tempfile

    import jax

    from roko_tpu.config import (
        CascadeConfig,
        ModelConfig,
        RokoConfig,
        default_compute_dtype,
    )
    from roko_tpu.features.pipeline import run_features
    from roko_tpu.infer import run_inference
    from roko_tpu.io.bam import write_sorted_bam
    from roko_tpu.io.fasta import write_fasta
    from roko_tpu.models.model import RokoModel
    from roko_tpu.sim import random_seq, simulate_reads

    def _sha(polished: Dict[str, str]) -> str:
        h = hashlib.sha256()
        for name in sorted(polished):
            h.update(name.encode())
            h.update(b"\x00")
            h.update(polished[name].encode())
            h.update(b"\x00")
        return h.hexdigest()

    quiet = lambda *a, **k: None  # noqa: E731
    out: Dict[str, Any] = {
        "draft_len": draft_len, "coverage": coverage, "threshold": threshold,
    }
    rng = random.Random(0)
    with tempfile.TemporaryDirectory() as td:
        fasta = os.path.join(td, "draft.fasta")
        bam = os.path.join(td, "reads.bam")
        h5 = os.path.join(td, "infer.hdf5")
        draft = random_seq(rng, draft_len)
        read_len = min(3000, max(100, draft_len // 4))
        records = simulate_reads(
            rng, draft, 0, coverage=coverage, read_len=read_len
        )
        write_fasta(fasta, [("ctg", draft)])
        write_sorted_bam(bam, [("ctg", draft_len)], records)
        n = run_features(
            fasta, bam, h5, seed=0,
            workers=max(1, os.cpu_count() or 1), log=quiet,
        )
        out["windows"] = n

        cfg = RokoConfig(
            model=ModelConfig(compute_dtype=default_compute_dtype())
        )
        params = RokoModel(cfg.model).init(jax.random.PRNGKey(0))

        t0 = time.perf_counter()
        ref = run_inference(
            h5, params, cfg, batch_size=512, prefetch=4, log=quiet
        )
        ref_s = time.perf_counter() - t0
        ref_sha = _sha(ref)
        out["reference_windows_per_sec"] = round(n / ref_s, 1)

        # byte-identity gate: threshold 0 escalates EVERY window, so the
        # cascade path must reproduce the plain session path bit-for-bit
        zero_cfg = dataclasses.replace(
            cfg, cascade=CascadeConfig(enabled=True, threshold=0.0)
        )
        zero = run_inference(
            h5, params, zero_cfg, batch_size=512, prefetch=4, log=quiet
        )
        out["threshold0_identical"] = _sha(zero) == ref_sha

        cache_dir = os.path.join(td, "wcache")
        casc_cfg = dataclasses.replace(
            cfg,
            cascade=CascadeConfig(
                enabled=True, threshold=threshold, cache_dir=cache_dir
            ),
        )
        cold_stats: Dict[str, Any] = {}
        t0 = time.perf_counter()
        run_inference(
            h5, params, casc_cfg, batch_size=512, prefetch=4,
            log=quiet, cascade_stats=cold_stats,
        )
        casc_s = time.perf_counter() - t0
        out["cascade_windows_per_sec"] = round(n / casc_s, 1)
        out["speedup_vs_reference"] = round(ref_s / casc_s, 2)
        out["escalation_pct"] = round(
            100.0 * cold_stats.get("escalation_fraction", 0.0), 1
        )
        out["cold_cache_hit_rate"] = round(
            cold_stats.get("cache_hit_rate", 0.0), 3
        )
        # warm: a fresh router over the SAME sidecar (what a second
        # distpolish worker sharing the coordinator's cache sees)
        warm_stats: Dict[str, Any] = {}
        run_inference(
            h5, params, casc_cfg, batch_size=512, prefetch=4,
            log=quiet, cascade_stats=warm_stats,
        )
        out["cache_hit_rate"] = round(
            warm_stats.get("cache_hit_rate", 0.0), 3
        )
    return out


def run_pipeline_suite(
    draft_len: int = 60_000, coverage: int = 40, workers: Optional[int] = None
) -> Dict[str, Any]:
    """Staged vs STREAMING polish on the same sim inputs (ISSUE 2
    tentpole evidence): the staged path runs ``run_features`` (HDF5)
    then ``run_inference`` serially; the streaming engine
    (roko_tpu/pipeline) overlaps extraction, host batching, and device
    predict. Reports both wall times, the streaming StageTimer span
    totals (sum > wall == stages actually overlapped), and
    ``overlap_efficiency`` = staged serial sum / streaming wall — > 1
    means the pipeline beat the sum of its stages. Also asserts the two
    outputs match (``outputs_identical``); a mismatch is reported, not
    raised, so a bench artifact always lands."""
    import os
    import random
    import tempfile

    import jax

    from roko_tpu.config import (
        ModelConfig,
        RokoConfig,
        default_compute_dtype,
    )
    from roko_tpu.features.pipeline import run_features
    from roko_tpu.infer import run_inference
    from roko_tpu.io.bam import write_sorted_bam
    from roko_tpu.io.fasta import write_fasta
    from roko_tpu.models.model import RokoModel
    from roko_tpu.pipeline import run_streaming_polish
    from roko_tpu.sim import random_seq, simulate_reads
    from roko_tpu.utils.profiling import StageTimer

    if workers is None:
        workers = max(1, os.cpu_count() or 1)
    out: Dict[str, Any] = {
        "draft_len": draft_len, "coverage": coverage, "workers": workers,
    }
    rng = random.Random(0)
    with tempfile.TemporaryDirectory() as td:
        fasta = os.path.join(td, "draft.fasta")
        bam = os.path.join(td, "reads.bam")
        h5 = os.path.join(td, "features.hdf5")
        draft = random_seq(rng, draft_len)
        read_len = min(3000, max(100, draft_len // 4))
        records = simulate_reads(
            rng, draft, 0, coverage=coverage, read_len=read_len
        )
        write_fasta(fasta, [("ctg", draft)])
        write_sorted_bam(bam, [("ctg", draft_len)], records)

        # the backend's fast dtype: bf16 rides the MXU on TPU but is
        # EMULATED on CPU (~3x slower than f32) — the suite measures
        # stage overlap, not dtype emulation. ONE policy for the whole
        # bench: config.default_compute_dtype
        cfg = RokoConfig(
            model=ModelConfig(compute_dtype=default_compute_dtype())
        )
        params = RokoModel(cfg.model).init(jax.random.PRNGKey(0))
        quiet = lambda *a, **k: None  # noqa: E731

        # both timed windows include one fresh predict-step compile
        # (each run builds its own jit closure), so the one-off XLA
        # cost appears on BOTH sides of the ratio instead of biasing it
        staged: Dict[str, Any] = {}
        t0 = time.perf_counter()
        n = run_features(fasta, bam, h5, seed=0, workers=workers, log=quiet)
        staged["features_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        staged_polished = run_inference(
            h5, params, cfg, batch_size=BATCH, log=quiet
        )
        staged["inference_s"] = round(time.perf_counter() - t0, 3)
        staged["serial_sum_s"] = round(
            staged["features_s"] + staged["inference_s"], 3
        )
        out["windows"] = n
        out["staged"] = staged

        from roko_tpu.serve.metrics import ServeMetrics

        timer = StageTimer()
        stream_metrics = ServeMetrics()
        t0 = time.perf_counter()
        stream_polished = run_streaming_polish(
            fasta, bam, params, cfg, seed=0, workers=workers,
            batch_size=BATCH, log=quiet, timer=timer,
            metrics=stream_metrics,
        )
        wall = time.perf_counter() - t0
        spans = {k: round(v, 3) for k, v in sorted(timer.totals.items())}
        fill = stream_metrics.fill_ratio()
        streaming = {
            "wall_s": round(wall, 3),
            "stage_spans_s": spans,
            "span_sum_s": round(sum(timer.totals.values()), 3),
            # the SAME ServeMetrics series serve exports (one batching
            # plane): real windows / padded rows the ContinuousBatcher
            # dispatched for this whole polish. The old deadline
            # batcher padded each flushed partial up to a rung; dense
            # packing makes this the number to watch.
            "padding_efficiency": None if fill is None else round(fill, 4),
        }
        out["streaming"] = streaming
        out["overlap_efficiency"] = round(staged["serial_sum_s"] / wall, 3)
        out["outputs_identical"] = staged_polished == stream_polished
    return out


# Micro rungs on purpose: the suite isolates COMPILE cost, and on a
# CPU bench box executing a 128-window batch costs more than compiling
# it — serve-sized rungs would bury the cold-start signal under
# proving-dispatch execution time that is identical in every mode
# (on TPU the imbalance runs the other way: minutes of compile, ms of
# execution). Four rungs = four distinct XLA programs, the thing the
# cache and bundles actually eliminate. Measure a production ladder
# with --coldstart-ladder 32,128,512.
DEFAULT_COLDSTART_LADDER = (2, 4, 6, 8)


def _coldstart_ladder_type(text: str):
    """argparse type for --coldstart-ladder: comma-separated rungs, or
    0/empty to disable the suite."""
    text = text.strip()
    if text in ("", "0"):
        return ()
    try:
        return tuple(sorted({int(t) for t in text.split(",")}))
    except ValueError:
        import argparse

        raise argparse.ArgumentTypeError(
            f"expected comma-separated integers or 0, got {text!r}"
        ) from None


def _coldstart_child(spec_path: str) -> None:
    """Child half of :func:`run_coldstart_suite` — runs in its OWN
    process so the jit caches are genuinely cold; the persistent cache
    directory (or ``off``) arrives via ``ROKO_COMPILE_CACHE`` set by the
    parent. Modes: ``export`` writes the AOT bundle; ``measure`` warms a
    ``PolishSession`` (AOT when the spec names a bundle) and reports
    time-to-first-prediction."""
    import dataclasses

    with open(spec_path) as f:
        spec = json.load(f)

    import jax

    from roko_tpu import constants as C
    from roko_tpu.config import CompileConfig, RokoConfig

    ladder = tuple(spec["ladder"])
    # tests shrink the model through the spec; the bench measures the
    # default (flagship serve) config
    cfg = (
        RokoConfig.from_json(json.dumps(spec["config"]))
        if spec.get("config")
        else RokoConfig()
    )
    cfg = dataclasses.replace(
        cfg,
        serve=dataclasses.replace(cfg.serve, ladder=ladder),
        compile=CompileConfig(bundle_dir=spec.get("bundle")),
    )
    if spec["mode"] == "export":
        from roko_tpu.compile import export_bundle

        t0 = time.perf_counter()
        export_bundle(
            spec["bundle_out"], cfg, ladder=ladder, log=lambda m: None
        )
        out = {"export_s": round(time.perf_counter() - t0, 3)}
    else:
        from roko_tpu.compile.cache import enable_persistent_cache
        from roko_tpu.models.model import RokoModel
        from roko_tpu.serve.session import PolishSession

        # enable before the FIRST compile (params init), as the serve
        # CLI does before loading the checkpoint
        enable_persistent_cache(cfg.compile)
        params = RokoModel(cfg.model).init(jax.random.PRNGKey(0))
        session = PolishSession(params, cfg)
        t0 = time.perf_counter()
        session.warmup(parallel=spec.get("parallel", True))
        warmup_s = time.perf_counter() - t0
        rng = np.random.default_rng(0)
        x = rng.integers(
            0, C.FEATURE_VOCAB, (ladder[0], C.WINDOW_ROWS, C.WINDOW_COLS)
        ).astype(np.uint8)
        t1 = time.perf_counter()
        session.predict(x)
        first_s = time.perf_counter() - t1
        out = {
            "warmup_s": round(warmup_s, 3),
            "first_predict_s": round(first_s, 3),
            # the operator-visible number: params ready -> first
            # prediction back on the host
            "ttfp_s": round(warmup_s + first_s, 3),
            "warmup": session.warmup_report.as_dict(),
        }
    tmp = spec["out"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f)
    os.replace(tmp, spec["out"])


def run_coldstart_suite(
    ladder=DEFAULT_COLDSTART_LADDER,
    child_budget_s: float = 900.0,
    config_json: Optional[str] = None,
) -> Dict[str, Any]:
    """Time-to-first-prediction for the SAME serve ladder under four
    start modes, each in a fresh child process (an in-process measure
    would hide the cold path behind this process's jit caches):

    - ``cold``          — empty persistent cache, SERIAL rung compiles:
      the pre-compile-subsystem every-start cost (the baseline every
      speedup below is measured against; its compiles also populate the
      cache dir ``warm_cache`` then hits);
    - ``cold_parallel`` — no cache, concurrent rung compiles: what the
      parallel-warmup tier buys on its own;
    - ``warm_cache``    — second start against ``cold``'s cache dir:
      disk hits instead of XLA runs;
    - ``aot``           — ``roko-tpu compile`` bundle: deserialization
      only, no compile at all (``export_seconds`` reports what building
      the bundle cost, once).

    The ISSUE acceptance bar — warm-cache or AOT start >= 5x faster to
    first prediction than cold — is read straight off
    ``speedup_warm_cache`` / ``speedup_aot`` in BENCH_*.json."""
    import subprocess  # noqa: F401 - spawn via resilience.probe helper
    import sys
    import tempfile

    results: Dict[str, Any] = {"ladder": list(ladder)}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with tempfile.TemporaryDirectory() as td:
        cache = os.path.join(td, "xla-cache")
        bundle = os.path.join(td, "bundle")

        def child(tag: str, mode: str, cache_env: str, use_bundle: bool,
                  parallel: bool = True):
            spec = {
                "mode": mode,
                "ladder": list(ladder),
                "out": os.path.join(td, f"{tag}.json"),
                "bundle_out": bundle,
                "parallel": parallel,
            }
            if config_json:
                spec["config"] = json.loads(config_json)
            if use_bundle:
                spec["bundle"] = bundle
            spec_path = os.path.join(td, f"{tag}.spec.json")
            with open(spec_path, "w") as f:
                json.dump(spec, f)
            env = dict(os.environ)
            env["ROKO_COMPILE_CACHE"] = cache_env
            cmd = [
                sys.executable,
                "-c",
                "import sys; from roko_tpu.benchmark import "
                "_coldstart_child; _coldstart_child(sys.argv[1])",
                spec_path,
            ]
            rc, out = _spawn_logged(cmd, child_budget_s, cwd=repo_root, env=env)
            if rc != 0:
                raise RuntimeError(
                    f"coldstart child {tag} "
                    f"{'timed out' if rc is None else f'rc={rc}'}; log "
                    f"tail:\n{out[-800:]}"
                )
            with open(spec["out"]) as f:
                return json.load(f)

        results["cold"] = child(
            "cold", "measure", cache, False, parallel=False
        )
        results["cold_parallel"] = child(
            "coldp", "measure", "off", False
        )
        results["warm_cache"] = child("warm", "measure", cache, False)
        # bundle export in its own child too: the parent process may be
        # mid-bench on a live backend, and export compiles everything
        results["export_seconds"] = child("export", "export", "off", False)[
            "export_s"
        ]
        results["aot"] = child("aot", "measure", "off", True)
    for key in ("cold_parallel", "warm_cache", "aot"):
        denom = results[key]["ttfp_s"]
        if denom > 0:
            results[f"speedup_{key}"] = round(
                results["cold"]["ttfp_s"] / denom, 2
            )
    return results


#: mesh suite: simulated device counts (--mesh-devices), fixed-work
#: timed iterations (--bench-iterations overrides), and the fixed
#: GLOBAL batch every count shards (divisible by every default count)
DEFAULT_MESH_DEVICES = (1, 2, 4)
MESH_SUITE_ITERS = 8
MESH_SUITE_GLOBAL_BATCH = 128


def _mesh_child(spec_path: str) -> None:
    """Child half of :func:`run_mesh_suite` — runs in its OWN process
    because the simulated device count
    (``--xla_force_host_platform_device_count``, set by the parent via
    the env) is fixed at backend init. Builds ONE mesh-sharded
    PolishSession over every visible device (dp = all), times the fixed
    global batch, and reports windows/sec plus a sha256 of the
    predictions so the parent can assert sharded == single-device
    byte-identity."""
    import dataclasses
    import hashlib

    with open(spec_path) as f:
        spec = json.load(f)

    import jax

    from roko_tpu import constants as C
    from roko_tpu.config import MeshConfig, RokoConfig
    from roko_tpu.models.model import RokoModel
    from roko_tpu.serve.session import PolishSession

    cfg = (
        RokoConfig.from_json(json.dumps(spec["config"]))
        if spec.get("config")
        else RokoConfig()
    )
    n_dev = len(jax.devices())
    cfg = dataclasses.replace(cfg, mesh=MeshConfig(dp=n_dev, tp=1, sp=1))
    gb = int(spec["global_batch"])
    params = RokoModel(cfg.model).init(jax.random.PRNGKey(0))
    session = PolishSession(params, cfg, ladder=(gb,))
    session.warmup()
    rows = cfg.model.window_rows
    cols = cfg.model.window_cols
    rng = np.random.default_rng(0)  # same seed in every child: same work
    x = rng.integers(0, C.FEATURE_VOCAB, (gb, rows, cols)).astype(np.uint8)
    preds = session.predict(x)  # proving dispatch outside the clock
    iters = int(spec["iterations"])
    t0 = time.perf_counter()
    for _ in range(iters):
        session.predict(x)
    wall = time.perf_counter() - t0
    out = {
        "devices": n_dev,
        "mesh_dp": session.dp,
        "global_batch": gb,
        "per_device_batch": gb // session.dp,
        "iterations": iters,
        "wall_s": round(wall, 3),
        "windows_per_sec": round(iters * gb / max(wall, 1e-9), 1),
        # identical across device counts == the mesh-sharded predict is
        # byte-identical to the 1-device predict on the same
        # windows/params (ISSUE acceptance)
        "preds_sha256": hashlib.sha256(
            np.ascontiguousarray(preds).tobytes()
        ).hexdigest(),
    }
    tmp = spec["out"] + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f)
    os.replace(tmp, spec["out"])


def run_mesh_suite(
    device_counts=DEFAULT_MESH_DEVICES,
    iterations: Optional[int] = None,
    global_batch: int = MESH_SUITE_GLOBAL_BATCH,
    child_budget_s: float = 900.0,
    config_json: Optional[str] = None,
) -> Dict[str, Any]:
    """ONE session, every chip (ROADMAP item 2): windows/sec for the
    SAME fixed global work sharded over 1/2/4 SIMULATED devices
    (``--xla_force_host_platform_device_count``; each count gets a fresh
    child process because the count is fixed at backend init, always on
    the CPU backend — the real-TPU row is ROADMAP item 6 debt).

    ``scaling_efficiency`` here is windows/sec at N devices over
    windows/sec at the SMALLEST requested count (1 by default; recorded
    as ``efficiency_vs_devices`` so a 1-less run cannot be misread):
    fake devices add NO silicon, so the ideal is 1.0 and the number
    reads as 1 - sharding overhead (the ISSUE acceptance bar is >= 0.7
    vs the 1-device row). On real chips the same rows read against N x
    the compute. ``byte_identical`` asserts every count produced the
    same predictions on the same windows/params."""
    import sys
    import tempfile

    from roko_tpu.parallel.mesh import fleet_worker_env

    counts = tuple(sorted(set(int(c) for c in device_counts)))
    bad = [c for c in counts if c < 1 or global_batch % c]
    if bad:
        raise ValueError(
            f"mesh suite device counts {bad} must be >= 1 and divide "
            f"the fixed global batch {global_batch}"
        )
    iters = iterations or MESH_SUITE_ITERS
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results: Dict[str, Any] = {
        "device_counts": list(counts),
        "global_batch": global_batch,
        "iterations": iters,
        "backend": "cpu (simulated devices)",
        "rows": {},
    }
    with tempfile.TemporaryDirectory() as td:
        for n in counts:
            spec = {
                "global_batch": global_batch,
                "iterations": iters,
                "out": os.path.join(td, f"mesh{n}.json"),
            }
            if config_json:
                spec["config"] = json.loads(config_json)
            spec_path = os.path.join(td, f"mesh{n}.spec.json")
            with open(spec_path, "w") as f:
                json.dump(spec, f)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            # the canonical fake-device overlay (strips any inherited
            # forced count before pinning this child's)
            env.update(fleet_worker_env(0, 1, n, backend="cpu"))
            env["ROKO_COMPILE_CACHE"] = "off"
            cmd = [
                sys.executable,
                "-c",
                "import sys; from roko_tpu.benchmark import _mesh_child; "
                "_mesh_child(sys.argv[1])",
                spec_path,
            ]
            rc, out = _spawn_logged(cmd, child_budget_s, cwd=repo_root, env=env)
            if rc != 0:
                raise RuntimeError(
                    f"mesh suite child ({n} device(s)) "
                    f"{'timed out' if rc is None else f'rc={rc}'}; log "
                    f"tail:\n{out[-800:]}"
                )
            with open(spec["out"]) as f:
                results["rows"][str(n)] = json.load(f)
    digests = {r["preds_sha256"] for r in results["rows"].values()}
    results["byte_identical"] = len(digests) == 1
    # efficiency denominates against the smallest requested count —
    # record WHICH, so a `--mesh-devices 2,4` run (no 1-device row)
    # cannot be misread against the vs-1-device >= 0.7 acceptance bar
    results["efficiency_vs_devices"] = counts[0]
    base = results["rows"].get(str(counts[0]), {}).get("windows_per_sec")
    if base:
        results["scaling_efficiency"] = {
            str(n): round(
                results["rows"][str(n)]["windows_per_sec"] / base, 3
            )
            for n in counts[1:]
        }
    return results


#: fleet suite fixed work per client (overridden by --bench-iterations)
FLEET_ITERS = 25
FLEET_CLIENTS = 3
#: windows per request — one bottom-ladder rung, so every request is a
#: single padded dispatch and req/s compares across worker counts
FLEET_REQUEST_WINDOWS = 8

#: mixed-size serve suite defaults (ISSUE: 90% small / 10% large; the
#: large class scales to the backend — a 256-window request on the
#: 2-core CPU box would bury the scheduling signal under raw compute)
SERVE_MIX_DEFAULT_TPU = "4:90,256:10"
SERVE_MIX_DEFAULT_CPU = "4:90,64:10"
#: total requests per batching mode (overridden by --bench-iterations)
SERVE_SUITE_REQUESTS = 48
SERVE_SUITE_CLIENTS = 6


def _parse_mix(spec: str):
    """``"4:90,256:10"`` -> ``((4, 90.0), (256, 10.0))`` — window count
    per request : percent of requests. Percents must sum to ~100."""
    out = []
    try:
        for part in spec.split(","):
            size, pct = part.split(":")
            out.append((int(size), float(pct)))
    except ValueError:
        raise ValueError(
            f"bad --serve-mix {spec!r}; want SIZE:PCT[,SIZE:PCT...] "
            "like 4:90,256:10"
        ) from None
    if not out or any(s <= 0 or p < 0 for s, p in out):
        raise ValueError(f"bad --serve-mix {spec!r}: sizes must be positive")
    total = sum(p for _, p in out)
    if not 99.0 <= total <= 101.0:
        raise ValueError(
            f"--serve-mix percents sum to {total:g}, want ~100"
        )
    return tuple(out)


def _mix_schedule(mix, total_requests: int, seed: int = 0):
    """Deterministic request-size schedule: per-class counts rounded
    from the percents (every named class gets >= 1 request), shuffled
    with a fixed seed so both batching modes replay IDENTICAL work."""
    sizes = []
    for size, pct in mix:
        count = max(1, round(total_requests * pct / 100.0)) if pct else 0
        sizes += [size] * count
    np.random.default_rng(seed).shuffle(sizes)
    return sizes


def _mixed_latency_row(
    wall: float, n_scheduled: int, lat: Dict[int, list]
) -> Dict[str, Any]:
    """One artifact row for a mixed-size run — shared by the serve
    suite and the fleet suite's mixed phase so the two report the
    identical schema. ``req_per_s`` counts COMPLETED requests (the
    per-class samples), not the schedule — errored requests must not
    inflate throughput."""
    completed = sum(len(s) for s in lat.values())
    row: Dict[str, Any] = {
        "wall_s": round(wall, 3),
        "requests_scheduled": n_scheduled,
        "req_per_s": round(completed / wall, 2) if wall else 0.0,
        "size_classes": {},
    }
    for size, samples in sorted(lat.items()):
        if samples:
            row["size_classes"][str(size)] = {
                "requests": len(samples),
                "p50_s": round(float(np.percentile(samples, 50)), 4),
                "p99_s": round(float(np.percentile(samples, 99)), 4),
            }
    return row


def run_serve_suite(
    mix_spec: str,
    iterations: int = SERVE_SUITE_REQUESTS,
    clients: int = SERVE_SUITE_CLIENTS,
    config_json: Optional[str] = None,
) -> Dict[str, Any]:
    """Mixed-size workload A/B of the serve batching policies
    (docs/SERVING.md "Continuous batching"): the SAME fixed, seeded
    request schedule — e.g. 90% 4-window / 10% 256-window — is driven
    closed-loop by ``clients`` threads against one warm PolishSession
    under the deadline coalescer and then the continuous scheduler,
    recording per mode: ``padding_efficiency`` (real windows ÷
    rung×steps), per-size-class p50/p99 latency, req/s, and a
    byte-identity check of every reply against a solo
    ``session.predict`` (the batch-CLI path). Headline comparisons:
    ``small_p99_improvement`` (deadline p99 / continuous p99 for the
    smallest class — the head-of-line-blocking cost) and the two
    padding efficiencies side by side (ISSUE acceptance)."""
    import dataclasses
    import threading

    import jax

    from roko_tpu import constants as C
    from roko_tpu.config import RokoConfig
    from roko_tpu.models.model import RokoModel
    from roko_tpu.serve.batcher import MicroBatcher
    from roko_tpu.serve.metrics import ServeMetrics
    from roko_tpu.serve.scheduler import ContinuousBatcher, RaggedBatcher
    from roko_tpu.serve.session import PolishSession

    mix = _parse_mix(mix_spec)
    cfg = RokoConfig.from_json(config_json) if config_json else RokoConfig()
    large = max(s for s, _ in mix)
    # the flagship ladder SHAPE at suite scale: a bottom rung for
    # sparse-traffic tails, a coarse middle rung, and a top rung sized
    # to the large class — both modes get the identical ladder, so the
    # A/B isolates the scheduling policy, not the rung set
    ladder = tuple(sorted({min(8, large), min(32, large), large}))
    cfg = dataclasses.replace(
        cfg, serve=dataclasses.replace(cfg.serve, ladder=ladder)
    )
    params = RokoModel(cfg.model).init(jax.random.PRNGKey(0))
    session = PolishSession(params, cfg)
    session.warmup()

    rng = np.random.default_rng(0)
    rows, cols = cfg.model.window_rows, cfg.model.window_cols
    payloads = {
        size: rng.integers(0, C.FEATURE_VOCAB, (size, rows, cols)).astype(
            np.uint8
        )
        for size, _ in mix
    }
    expected = {size: session.predict(x) for size, x in payloads.items()}
    schedule = _mix_schedule(mix, iterations)

    def drive(mode: str, session=session, expected=expected) -> Dict[str, Any]:
        metrics = ServeMetrics()
        metrics.size_classes = ladder
        if mode in ("continuous", "ragged"):
            cls = RaggedBatcher if mode == "ragged" else ContinuousBatcher
            batcher = cls(
                session, metrics=metrics, max_queue=clients * 2
            )
        else:
            batcher = MicroBatcher(
                session, metrics=metrics, max_queue=clients * 2
            )
        lat: Dict[int, list] = {size: [] for size, _ in mix}
        mismatches: list = []
        errors: list = []
        lock = threading.Lock()
        work = list(schedule)

        def one_client():
            while True:
                with lock:
                    if not work:
                        return
                    size = work.pop()
                t0 = time.perf_counter()
                try:
                    preds = batcher.predict(payloads[size], timeout=600.0)
                except Exception as e:
                    # a failed request must be COUNTED, not silently
                    # vanish with its thread — byte_identical would
                    # otherwise pass vacuously
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}"[:200])
                    continue
                dt = time.perf_counter() - t0
                ok = np.array_equal(preds, expected[size])
                with lock:
                    lat[size].append(dt)
                    if not ok:
                        mismatches.append(size)

        try:
            # untimed calibration: one request per class warms the
            # throughput EMA and keeps first-dispatch cost off-clock
            for size in payloads:
                batcher.predict(payloads[size], timeout=600.0)
            # snapshot the fill counters so the solo calibration
            # dispatches (heavily padded by construction) can't skew
            # the reported padding_efficiency
            cal_windows, cal_padded = metrics.fill_totals()
            threads = [
                threading.Thread(target=one_client, daemon=True)
                for _ in range(clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            batcher.stop()
        fill_windows, fill_padded = metrics.fill_totals()
        padded = fill_padded - cal_padded
        row = _mixed_latency_row(wall, len(schedule), lat)
        row["padding_efficiency"] = (
            round((fill_windows - cal_windows) / padded, 4) if padded else 0.0
        )
        row["byte_identical"] = not mismatches and not errors
        row["client_errors"] = len(errors)
        if errors:
            row["errors"] = errors[:5]
        return row

    results: Dict[str, Any] = {
        "mix": mix_spec,
        "iterations": len(schedule),
        "clients": clients,
        "ladder": list(ladder),
        "modes": {},
    }
    # calibration order fixed (deadline first) so cross-round artifacts
    # compare like with like; "ragged" drives the same packing plane
    # through the session's ONE masked top-rung executable (ISSUE 17)
    for mode in ("deadline", "continuous", "ragged"):
        results["modes"][mode] = drive(mode)
    small = str(min(s for s, _ in mix))
    try:
        d = results["modes"]["deadline"]["size_classes"][small]["p99_s"]
        c = results["modes"]["continuous"]["size_classes"][small]["p99_s"]
        if c > 0:
            results["small_p99_improvement"] = round(d / c, 3)
    except KeyError:
        pass
    # -- ragged vs continuous headline (ISSUE 17 acceptance): the same
    # seeded schedule, padding efficiency and req/s side by side — the
    # padded ladder's rung quantisation caps continuous near 0.96; the
    # masked ragged step should read >= 0.99
    rg = results["modes"].get("ragged") or {}
    co = results["modes"].get("continuous") or {}
    ragged_row: Dict[str, Any] = {
        "padding_efficiency": rg.get("padding_efficiency"),
        "continuous_padding_efficiency": co.get("padding_efficiency"),
        "req_per_s": rg.get("req_per_s"),
        "continuous_req_per_s": co.get("req_per_s"),
        "byte_identical": rg.get("byte_identical"),
    }
    if rg.get("req_per_s") and co.get("req_per_s"):
        ragged_row["req_per_s_vs_continuous"] = round(
            rg["req_per_s"] / co["req_per_s"], 3
        )
    results["ragged"] = ragged_row

    # -- precision A/B row (ISSUE 11): the SAME seeded mixed schedule,
    # continuous mode, against sessions differing only in precision —
    # the serving-path counterpart of the device-only precision column.
    # The baseline row is the continuous-mode measurement above (the
    # backend's resolved default dtype); int8 weight-only always runs,
    # f32/bf16 alternates join when the default differs from them. Each
    # variant's byte-identity check is against its OWN solo predicts
    # (reduced precision legitimately differs from f32 at the logit
    # level — the held-out-Q slow lane gates that drift).
    resolved = session.model.cfg
    base_tag = resolved.compute_dtype + (
        f"+{resolved.quantize}" if resolved.quantize else ""
    )
    # variant SPECS only here — ModelConfig construction re-validates in
    # __post_init__ and must happen inside the per-variant try, so an
    # invalid combination reports as that variant's error instead of
    # voiding the completed mode measurements above
    variants: Dict[str, Tuple[str, Optional[str]]] = {}
    if resolved.quantize != "int8" and resolved.kind != "transformer":
        # the transformer kind has no int8 path (ModelConfig refuses)
        variants["float32+int8"] = ("float32", "int8")
    if resolved.compute_dtype != "float32" or resolved.quantize:
        variants["float32"] = ("float32", None)
    prec: Dict[str, Any] = {
        "baseline": base_tag,
        "modes": {base_tag: results["modes"]["continuous"]},
    }
    results["precision"] = prec
    for tag, (vdtype, vquant) in variants.items():
        try:
            vcfg = dataclasses.replace(
                cfg,
                model=dataclasses.replace(
                    cfg.model, compute_dtype=vdtype, quantize=vquant
                ),
            )
            # raw f32 params: the session applies the int8 conversion
            # itself, exactly as `serve --quantize int8` would
            vsession = PolishSession(params, vcfg)
            vsession.warmup()
            vexpected = {
                size: vsession.predict(x) for size, x in payloads.items()
            }
            prec["modes"][tag] = drive(
                "continuous", session=vsession, expected=vexpected
            )
        except Exception as e:  # report, never swallow
            prec["modes"][tag] = {"error": f"{type(e).__name__}: {e}"[:300]}
    base_rps = prec["modes"][base_tag].get("req_per_s")
    int8_tag = base_tag if resolved.quantize == "int8" else "float32+int8"
    int8_rps = (prec["modes"].get(int8_tag) or {}).get("req_per_s")
    f32_rps = (
        prec["modes"].get("float32") or {}
    ).get("req_per_s") or (base_rps if base_tag == "float32" else None)
    if int8_rps and f32_rps:
        prec["int8_req_per_s_vs_f32"] = round(int8_rps / f32_rps, 3)

    # -- tenant-mix row (ISSUE 19): an interactive tenant (small
    # requests, high weight) sharing the scheduler with a bulk flood
    # (large requests), fair-share ON vs OFF on identical fixed work.
    # OFF = every request in the default tenant (the old single-tenant
    # grant loop); ON = 4:1 deficit-weighted round-robin. The headline
    # is the interactive p99 ratio — what tenant isolation buys.
    from roko_tpu.config import TenantConfig

    small_sz = min(s for s, _ in mix)
    n_inter = max(6, len(schedule) // 2)
    n_bulk = max(3, len(schedule) // 3)

    def drive_tenants(fair: bool) -> Dict[str, Any]:
        metrics = ServeMetrics()
        metrics.size_classes = ladder
        tenants = (
            (TenantConfig("interactive", weight=4.0),
             TenantConfig("bulk", weight=1.0))
            if fair else ()
        )
        batcher = ContinuousBatcher(
            session, metrics=metrics, max_queue=clients * 4,
            tenants=tenants,
        )
        work = {
            "interactive": [small_sz] * n_inter,
            "bulk": [large] * n_bulk,
        }
        lat: Dict[str, list] = {"interactive": [], "bulk": []}
        errors: list = []
        lock = threading.Lock()

        def one_client(tname: str):
            while True:
                with lock:
                    if not work[tname]:
                        return
                    size = work[tname].pop()
                t0 = time.perf_counter()
                try:
                    preds = batcher.submit(
                        payloads[size], tenant=tname if fair else None
                    ).result(600.0)
                except Exception as e:
                    with lock:
                        errors.append(f"{type(e).__name__}: {e}"[:200])
                    continue
                dt = time.perf_counter() - t0
                ok = np.array_equal(preds, expected[size])
                with lock:
                    lat[tname].append(dt)
                    if not ok:
                        errors.append(f"mismatch:{tname}")

        try:
            for size in payloads:  # untimed EMA calibration
                batcher.submit(payloads[size]).result(600.0)
            threads = [
                threading.Thread(
                    target=one_client, args=(t,), daemon=True
                )
                for t in ("interactive", "interactive", "bulk", "bulk",
                          "bulk")
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
        finally:
            batcher.stop()
        row: Dict[str, Any] = {
            "fair_share": fair,
            "wall_s": round(wall, 3),
            "client_errors": len(errors),
            "tenants": {},
        }
        for tname, samples in sorted(lat.items()):
            if samples:
                row["tenants"][tname] = {
                    "requests": len(samples),
                    "req_per_s": round(len(samples) / wall, 2),
                    "p50_s": round(float(np.percentile(samples, 50)), 4),
                    "p99_s": round(float(np.percentile(samples, 99)), 4),
                }
        return row

    tmix: Dict[str, Any] = {
        "fair": drive_tenants(True),
        "unfair": drive_tenants(False),
    }
    try:
        off = tmix["unfair"]["tenants"]["interactive"]["p99_s"]
        on = tmix["fair"]["tenants"]["interactive"]["p99_s"]
        if on > 0:
            tmix["interactive_p99_improvement"] = round(off / on, 3)
        off_b = tmix["unfair"]["tenants"]["bulk"]["req_per_s"]
        on_b = tmix["fair"]["tenants"]["bulk"]["req_per_s"]
        if off_b > 0:
            tmix["bulk_req_per_s_retained"] = round(on_b / off_b, 3)
    except KeyError:
        pass
    results["tenant_mix"] = tmix

    # -- autoscale row (ISSUE 19): the supervisor's Autoscaler control
    # loop driven by REAL scheduler backlog through a load step (idle →
    # flood → drain), with a shim actuator standing in for worker
    # processes — the in-process suite measures the decision loop
    # (worker-count trajectory, no flapping) beside the measured req/s;
    # the real elastic fleet is exercised end-to-end by the slow
    # autoscale-gate CI lane.
    from roko_tpu.serve.supervisor import Autoscaler

    fc = dataclasses.replace(
        cfg.fleet, workers=2, min_workers=1, max_workers=3,
        autoscale_up_backlog=float(large), autoscale_down_backlog=1.0,
        autoscale_idle_s=3.0, autoscale_cooldown_s=1.0,
        autoscale_ema_beta=0.3,
    )
    metrics = ServeMetrics()
    metrics.size_classes = ladder
    batcher = ContinuousBatcher(
        session, metrics=metrics, max_queue=max(64, clients * 8)
    )

    class _ScaleProbe:
        """Autoscaler actuator shim: real backlog, counted workers."""
        fleet_cfg = fc
        jobs_parked = False
        workers = [0] * fc.workers

        def backlog_windows(self):
            return batcher.backlog_windows()

        def scale_to(self, n, reason=""):
            self.workers = [0] * n
            return n

    probe = _ScaleProbe()
    fake_now = [0.0]
    scaler = Autoscaler(probe, log=lambda m: None, clock=lambda: fake_now[0])
    trajectory = []

    def run_phase(name: str, futures, ticks: int) -> Dict[str, Any]:
        t0 = time.perf_counter()
        for _ in range(ticks):
            fake_now[0] += 1.0
            scaler.tick()
            trajectory.append(len(probe.workers))
            time.sleep(0.01)
        done = [f.result(600.0) for f in futures]
        wall = time.perf_counter() - t0
        return {
            "phase": name,
            "requests": len(done),
            "req_per_s": round(len(done) / wall, 2) if wall else 0.0,
            "workers_after": len(probe.workers),
        }

    auto: Dict[str, Any] = {"min_workers": 1, "max_workers": 3,
                            "phases": []}
    try:
        auto["phases"].append(run_phase("idle", [], ticks=2))
        flood = [
            batcher.submit(payloads[large])
            for _ in range(max(8, len(schedule) // 4))
        ]
        auto["phases"].append(run_phase("flood", flood, ticks=6))
        fake_now[0] += fc.autoscale_idle_s
        auto["phases"].append(run_phase("drain", [], ticks=8))
    finally:
        batcher.stop()
    auto["worker_trajectory"] = trajectory
    auto["scaled_up"] = max(trajectory) > fc.workers
    auto["scaled_down"] = trajectory[-1] < max(trajectory)
    results["autoscale"] = auto
    return results


def run_fleet_suite(
    worker_counts=(1, 2),
    iterations: int = FLEET_ITERS,
    clients: int = FLEET_CLIENTS,
    config_json: Optional[str] = None,
    startup_budget_s: float = 600.0,
    mix: Optional[str] = None,
) -> Dict[str, Any]:
    """Saturation + fault tolerance of the multi-worker serving tier
    (serve/fleet.py): FIXED-WORK closed-loop load — ``clients`` client
    threads each issue ``iterations`` polish requests — against the
    supervised fleet at each worker count, reporting sustained req/s
    and p99 latency, the scaling efficiency between 1 and 2 workers,
    and a forced-fault phase: the same load with one worker SIGKILLed
    mid-run, where ``client_errors`` MUST stay 0 (failover makes the
    kill a latency event) and req/s shows the degradation cost.

    Workers are real subprocesses (full serve stack each); when the
    bench parent owns a TPU the workers are pinned to CPU instead of
    fighting over chips the parent holds — the suite then measures the
    routing/supervision tier, honestly labeled in ``note``.

    ``mix`` (an explicit ``--serve-mix`` spec) adds a mixed-size phase
    at the top worker count for BOTH batching modes on the identical
    seeded schedule: per-size-class p50/p99 plus each worker's scraped
    ``padding_efficiency`` land in ``results["mixed"]``."""
    import dataclasses
    import tempfile
    import threading

    import jax

    from roko_tpu import constants as C
    from roko_tpu.config import RokoConfig
    from roko_tpu.models.model import RokoModel
    from roko_tpu.serve.client import PolishClient
    from roko_tpu.serve.fleet import Fleet
    from roko_tpu.serve.supervisor import make_front_server, worker_command
    from roko_tpu.training.checkpoint import save_params

    cfg = (
        RokoConfig.from_json(config_json) if config_json else RokoConfig()
    )
    # validate the mix spec BEFORE the expensive saturation/kill phases:
    # a typo'd --serve-mix must fail here, not discard minutes of
    # completed real-subprocess measurement at the final mixed phase
    mix_parsed = _parse_mix(mix) if mix else None
    worker_env_extra: Dict[str, str] = {}
    results: Dict[str, Any] = {
        "iterations": iterations,
        "clients": clients,
        "windows_per_request": FLEET_REQUEST_WINDOWS,
        "workers": {},
    }
    if jax.default_backend() == "tpu":
        worker_env_extra["JAX_PLATFORMS"] = "cpu"
        results["note"] = (
            "bench parent holds the TPU; fleet workers ran on CPU — "
            "this row measures the routing/supervision tier, not chip "
            "throughput"
        )
    cfg = dataclasses.replace(
        cfg,
        serve=dataclasses.replace(
            cfg.serve, ladder=(FLEET_REQUEST_WINDOWS,), max_delay_ms=5.0
        ),
        fleet=dataclasses.replace(
            cfg.fleet,
            heartbeat_interval_s=0.25,
            heartbeat_timeout_s=5.0,
            stable_after_s=1.0,
            restart_base_delay_s=0.1,
        ),
    )
    rng = np.random.default_rng(0)
    rows, cols = cfg.model.window_rows, cfg.model.window_cols
    stride = cfg.window.stride
    n_win = FLEET_REQUEST_WINDOWS
    x = rng.integers(0, C.FEATURE_VOCAB, (n_win, rows, cols)).astype(np.uint8)
    positions = np.zeros((n_win, cols, 2), np.int64)
    for i in range(n_win):
        positions[i, :, 0] = np.arange(i * stride, i * stride + cols)
    draft = "".join(
        rng.choice(list("ACGT"), (n_win - 1) * stride + cols + 10)
    )
    params = RokoModel(cfg.model).init(jax.random.PRNGKey(0))

    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "params")
        save_params(ckpt, params)
        cfg_path = os.path.join(td, "worker-config.json")
        with open(cfg_path, "w") as f:
            f.write(
                dataclasses.replace(
                    cfg, fleet=dataclasses.replace(cfg.fleet, workers=0)
                ).to_json()
            )

        def start_fleet(n: int, run_cfg=None, worker_cfg_path=None, tag=""):
            fcfg = dataclasses.replace(
                run_cfg or cfg,
                fleet=dataclasses.replace(cfg.fleet, workers=n),
            )
            fleet = Fleet(
                fcfg,
                worker_command(ckpt, worker_cfg_path or cfg_path),
                worker_env=lambda wid: dict(worker_env_extra),
                runtime_dir=os.path.join(td, f"fleet-{tag}{n}"),
                log=lambda m: None,
            )
            fleet.start()
            # front end binds only after the workers are ready: the
            # timeout path then has no bound socket or serving thread
            # to leak into the rest of the bench process
            deadline = time.monotonic() + startup_budget_s
            while fleet.ready_count() < n:
                if time.monotonic() > deadline:
                    fleet.stop(rolling=False)
                    raise RuntimeError(
                        f"fleet of {n} not ready within "
                        f"{startup_budget_s:.0f}s"
                    )
                time.sleep(0.2)
            server = make_front_server(fleet, port=0)
            thread = threading.Thread(target=server.serve_forever, daemon=True)
            thread.start()
            return fleet, server, thread

        def stop_fleet(fleet, server, thread):
            server.shutdown()
            server.server_close()
            thread.join(10.0)
            fleet.stop(rolling=False)

        def drive(port: int, per_client: int, mid_action=None):
            """Closed-loop fixed work; ``mid_action(done)`` fires after
            every completed request (the kill phase hooks it)."""
            lat: list = []
            errors: list = []
            lock = threading.Lock()

            def one_client():
                client = PolishClient(
                    f"http://127.0.0.1:{port}", timeout=300.0
                )
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    try:
                        client.polish(draft, positions, x, retries=8)
                    except Exception as e:
                        with lock:
                            errors.append(
                                f"{type(e).__name__}: {e}"[:200]
                            )
                    else:
                        with lock:
                            lat.append(time.perf_counter() - t0)
                    if mid_action is not None:
                        with lock:
                            done = len(lat) + len(errors)
                        mid_action(done)

            threads = [
                threading.Thread(target=one_client, daemon=True)
                for _ in range(clients)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0, lat, errors

        for n in worker_counts:
            fleet, server, thread = start_fleet(n)
            try:
                port = server.server_address[1]
                drive(port, 1)  # untimed: first-dispatch costs off-clock
                wall, lat, errors = drive(port, iterations)
                row: Dict[str, Any] = {
                    "req_per_s": round(clients * iterations / wall, 2),
                    "p99_s": round(float(np.percentile(lat, 99)), 4)
                    if lat else None,
                    "mean_s": round(float(np.mean(lat)), 4) if lat else None,
                    "client_errors": len(errors),
                }
                if errors:
                    row["errors"] = errors[:5]
                results["workers"][str(n)] = row
            finally:
                stop_fleet(fleet, server, thread)
        r1 = results["workers"].get("1", {}).get("req_per_s")
        r2 = results["workers"].get("2", {}).get("req_per_s")
        if r1 and r2:
            results["scaling_efficiency"] = round(r2 / (2 * r1), 3)

        # forced-fault phase: SIGKILL one worker mid-load at the top
        # worker count; failover must keep client_errors at 0
        n_kill = max(worker_counts)
        if n_kill >= 2:
            fleet, server, thread = start_fleet(n_kill)
            try:
                port = server.server_address[1]
                drive(port, 1)
                total = clients * iterations
                killed = threading.Event()

                def kill_at_quarter(done: int) -> None:
                    if not killed.is_set() and done >= max(2, total // 4):
                        killed.set()
                        fleet.workers[0].proc.kill()

                wall, lat, errors = drive(
                    port, iterations, mid_action=kill_at_quarter
                )
                rejoined = False
                deadline = time.monotonic() + startup_budget_s
                while time.monotonic() < deadline:
                    if fleet.ready_count() == n_kill:
                        rejoined = True
                        break
                    time.sleep(0.2)
                kill_row: Dict[str, Any] = {
                    "workers": n_kill,
                    "req_per_s_during_kill": round(total / wall, 2),
                    "p99_s": round(float(np.percentile(lat, 99)), 4)
                    if lat else None,
                    "client_errors": len(errors),
                    "failovers": fleet.counter("failovers"),
                    "restarts": fleet.counter("restarts"),
                    "worker_rejoined": rejoined,
                }
                if errors:
                    kill_row["errors"] = errors[:5]
                results["forced_kill"] = kill_row
            finally:
                stop_fleet(fleet, server, thread)

        # mixed-size phase (explicit --serve-mix only): identical seeded
        # schedule through real workers for BOTH batching modes —
        # per-size-class latency + each worker's padding_efficiency
        if mix_parsed:
            large = max(s for s, _ in mix_parsed)
            mixed_ladder = tuple(
                sorted({FLEET_REQUEST_WINDOWS, large})
            )
            schedule = _mix_schedule(mix_parsed, clients * iterations)
            payloads = {}
            for size, _ in mix_parsed:
                mpos = np.zeros((size, cols, 2), np.int64)
                for i in range(size):
                    mpos[i, :, 0] = np.arange(
                        i * stride, i * stride + cols
                    )
                mx = rng.integers(
                    0, C.FEATURE_VOCAB, (size, rows, cols)
                ).astype(np.uint8)
                payloads[size] = (mpos, mx)
            mixed_draft = "".join(
                rng.choice(list("ACGT"), (large - 1) * stride + cols + 10)
            )
            n_top = max(worker_counts)
            results["mixed"] = {
                "mix": mix, "workers": n_top,
                "requests": len(schedule), "modes": {},
            }

            def drive_mixed(port: int, sched):
                work = list(sched)
                lat: Dict[int, list] = {s: [] for s, _ in mix_parsed}
                errors: list = []
                lock = threading.Lock()

                def one_client():
                    client = PolishClient(
                        f"http://127.0.0.1:{port}", timeout=300.0
                    )
                    while True:
                        with lock:
                            if not work:
                                return
                            size = work.pop()
                        mpos, mx = payloads[size]
                        t0 = time.perf_counter()
                        try:
                            client.polish(
                                mixed_draft, mpos, mx, retries=8
                            )
                        except Exception as e:
                            with lock:
                                errors.append(
                                    f"{type(e).__name__}: {e}"[:200]
                                )
                        else:
                            with lock:
                                lat[size].append(
                                    time.perf_counter() - t0
                                )

                threads = [
                    threading.Thread(target=one_client, daemon=True)
                    for _ in range(clients)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                return time.perf_counter() - t0, lat, errors

            for mode in ("deadline", "continuous"):
                mode_cfg = dataclasses.replace(
                    cfg,
                    serve=dataclasses.replace(
                        cfg.serve, ladder=mixed_ladder, batching=mode
                    ),
                )
                mode_cfg_path = os.path.join(td, f"worker-{mode}.json")
                with open(mode_cfg_path, "w") as f:
                    f.write(
                        dataclasses.replace(
                            mode_cfg,
                            fleet=dataclasses.replace(
                                mode_cfg.fleet, workers=0
                            ),
                        ).to_json()
                    )
                fleet, server, thread = start_fleet(
                    n_top, run_cfg=mode_cfg,
                    worker_cfg_path=mode_cfg_path, tag=f"mix-{mode}-",
                )
                try:
                    port = server.server_address[1]

                    def scrape_fill(port):
                        """{worker: (windows, padded)} via the front
                        end's per-worker counter passthrough."""
                        import urllib.request

                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}/metrics", timeout=10
                        ) as r:
                            text = r.read().decode()
                        out: Dict[str, list] = {}
                        for line in text.splitlines():
                            for i, name in enumerate((
                                "roko_serve_fill_windows_total{",
                                "roko_serve_fill_padded_total{",
                            )):
                                if line.startswith(name):
                                    wid = line.split('worker="')[1].split(
                                        '"'
                                    )[0]
                                    out.setdefault(wid, [0, 0])[i] = int(
                                        float(line.rsplit(" ", 1)[1])
                                    )
                        return out

                    drive_mixed(  # untimed calibration, one per class
                        port, [s for s, _ in mix_parsed]
                    )
                    try:
                        fill0 = scrape_fill(port)
                    except Exception:
                        fill0 = {}
                    wall, lat, errors = drive_mixed(port, schedule)
                    row = _mixed_latency_row(wall, len(schedule), lat)
                    row["client_errors"] = len(errors)
                    if errors:
                        row["errors"] = errors[:5]
                    # padding efficiency as the serve suite measures it:
                    # fill-counter DELTAS across the timed phase (the
                    # lifetime ratio would fold in the heavily padded
                    # calibration dispatches), summed over workers
                    try:
                        fill1 = scrape_fill(port)
                        dw = sum(
                            w - fill0.get(wid, [0, 0])[0]
                            for wid, (w, _) in fill1.items()
                        )
                        dp_rows = sum(
                            p - fill0.get(wid, [0, 0])[1]
                            for wid, (_, p) in fill1.items()
                        )
                        if dp_rows > 0:
                            row["padding_efficiency"] = round(
                                dw / dp_rows, 4
                            )
                    except Exception:
                        pass
                    results["mixed"]["modes"][mode] = row
                finally:
                    stop_fleet(fleet, server, thread)
    return results


def main(argv=None) -> None:
    import argparse
    import os
    import sys

    ap = argparse.ArgumentParser(prog="roko-tpu bench")
    ap.add_argument("--train", action="store_true", help="also time training steps")
    ap.add_argument(
        "--features",
        action="store_true",
        help="also time host-side feature extraction (native vs Python)",
    )
    ap.add_argument(
        "--batch",
        type=int,
        default=None,
        help=f"exact batch to bench (default: sweep {SWEEP_BATCHES} on TPU)",
    )
    ap.add_argument(
        "--out", default=None, help="write the full result dict to this JSON file"
    )
    ap.add_argument(
        "--e2e-draft",
        type=int,
        default=None,
        help="draft length for the end-to-end pipeline suite "
        "(default: 2 Mb on TPU, 60 kb elsewhere; 0 disables)",
    )
    ap.add_argument(
        "--pipeline-draft",
        type=int,
        default=None,
        help="draft length for the staged-vs-streaming pipeline suite "
        "(default: 500 kb on TPU, 60 kb elsewhere; 0 disables)",
    )
    ap.add_argument(
        "--cascade-draft",
        type=int,
        default=None,
        help="draft length for the cascade suite (reference vs cascaded "
        "windows/sec, escalation %%, cold/warm window-cache hit rate, "
        "threshold-0 byte-identity; default 40 kb when the e2e suite "
        "runs; 0 disables)",
    )
    ap.add_argument(
        "--coldstart-ladder",
        type=_coldstart_ladder_type,
        default=None,
        help="serve ladder for the coldstart suite (cold vs warm "
        "persistent cache vs AOT bundle time-to-first-prediction; "
        f"default {','.join(str(r) for r in DEFAULT_COLDSTART_LADDER)} "
        "when the e2e suite runs; 0 disables)",
    )
    ap.add_argument(
        "--fleet-workers",
        type=_coldstart_ladder_type,
        default=None,
        help="fleet saturation suite worker counts (sustained req/s + "
        "p99 per count, scaling efficiency, req/s during a forced "
        "worker SIGKILL; default 1,2 when the e2e suite runs; "
        "0 disables)",
    )
    ap.add_argument(
        "--bench-iterations",
        type=int,
        default=None,
        help="fixed-work mode: pin the timed iteration count of the "
        "inference/train suites, the per-client request count of the "
        "fleet suite, and the request count of the mixed-size serve "
        "suite (recorded in the artifact; ROADMAP watch item 6)",
    )
    ap.add_argument(
        "--serve-mix",
        default=None,
        metavar="SIZE:PCT[,SIZE:PCT...]",
        help="mixed-size serve workload, e.g. 4:90,256:10 (90%% "
        "4-window / 10%% 256-window requests): drives the serve suite "
        "A/B of both batching policies on identical fixed work "
        "(padding_efficiency + per-size-class p50/p99) and threads the "
        "same mix through the fleet suite; default "
        f"{SERVE_MIX_DEFAULT_TPU} on TPU / {SERVE_MIX_DEFAULT_CPU} "
        "elsewhere when the e2e suite runs (serve suite only); "
        "0 disables",
    )
    ap.add_argument(
        "--input-rows",
        type=int,
        default=None,
        help="input suite fixed work: sim-corpus rows streamed through "
        "the datapipe index layer vs the legacy streaming reader "
        "(default 1536 when the e2e suite runs; 0 disables)",
    )
    ap.add_argument(
        "--mesh-devices",
        type=_coldstart_ladder_type,
        default=None,
        help="mesh suite: simulated device counts to shard the fixed "
        "global predict batch over (fresh CPU child process per count "
        "via --xla_force_host_platform_device_count), reporting "
        "windows/sec, scaling efficiency vs 1 device, and sharded-vs-"
        "single-device byte-identity; e.g. 1,2,4 (the default when the "
        "e2e suite runs); 0 disables",
    )
    ap.add_argument(
        "--compare",
        default=None,
        metavar="BENCH_JSON",
        help="previous BENCH_*.json to compare against: adds a "
        "detail.vs_previous block with per-metric deltas where moves "
        f"inside the {NOISE_BAND_PCT:.0f}%% band are flagged noise=true, "
        "not regressions, and defaults the run to fixed-work "
        "--bench-iterations so the delta compares identical work "
        "(ROADMAP watch item 6)",
    )
    ap.add_argument(
        "--in-process",
        action="store_true",
        help="measure in this process (no probe/fallback orchestration); "
        "the orchestrated default exists because the driver artifact must "
        "parse even when the TPU relay is wedged",
    )
    args = ap.parse_args(argv)
    if args.compare and args.bench_iterations is None:
        # a cross-round comparison is only interpretable on identical
        # fixed work: pin (and record) the iteration count by default
        args.bench_iterations = ITERS

    log = lambda msg: print(msg, file=sys.stderr, flush=True)  # noqa: E731

    # Only an explicit CPU platform (tests, conftest) runs un-orchestrated:
    # anywhere an accelerator could be claimed — the driver's
    # JAX_PLATFORMS=axon tunnel, or a TPU VM where jax autodetects the
    # chip with no env set — the sick-backend probe/fallback must wrap
    # the measurement, because a wedged backend HANGS in-process init.
    if args.in_process or os.environ.get("JAX_PLATFORMS", "") == "cpu":
        result = _measure(args)
        if args.compare:
            _apply_compare(result, args.compare)
        _emit(result, args.out)
        return

    try:
        # "once per run" = once per main() invocation: a fresh run (or a
        # test calling main() repeatedly in-process) must re-probe, not
        # inherit a verdict cached by a previous run's backend state
        global _PROBE_VERDICT
        _PROBE_VERDICT = None
        try:
            probe_timeout = float(
                os.environ.get("ROKO_BENCH_PROBE_TIMEOUT", "300")
            )
        except ValueError:
            probe_timeout = 300.0
        try:
            tpu_budget = float(os.environ.get("ROKO_BENCH_TPU_BUDGET", "1500"))
        except ValueError:
            tpu_budget = 1500.0

        t0 = time.monotonic()
        ok, why, platform = _probe_backend_once(probe_timeout, log)
        if ok:
            result = _run_child_bench(
                args,
                max(60.0, tpu_budget - (time.monotonic() - t0)),
                log,
                platform=platform or "unknown",
            )
            if result is not None:
                probe_rec = _probe_verdict_detail()
                if probe_rec is not None:
                    result.setdefault("detail", {}).setdefault(
                        "env", {}
                    )["backend_probe"] = probe_rec
                if args.compare:
                    _apply_compare(result, args.compare)
                _emit(result, args.out)
                return
            why = (
                "backend probe ok but the TPU bench child failed or "
                "exceeded its budget (see stderr tail above)"
            )
        # Fallback of record: a CPU run that still produces every field,
        # honestly labelled. Reduced batch keeps it fast; env.backend
        # says "cpu" and tpu_error says why, so the artifact can never
        # masquerade as a chip measurement. The host-extraction suite
        # is included — it is chip-independent evidence and the only
        # genuinely meaningful throughput a CPU run can contribute.
        log(f"[bench] falling back to CPU: {why}")
        _force_cpu_backend()
        if args.batch is None:
            args.batch = 64
        args.features = True
        result = _measure(args)
        result["detail"].setdefault("env", {})["tpu_error"] = why[:600]
        probe_rec = _probe_verdict_detail()
        if probe_rec is not None:
            result["detail"]["env"]["backend_probe"] = probe_rec
        if args.compare:
            _apply_compare(result, args.compare)
        _emit(result, args.out)
    except Exception as e:  # absolute last resort: the artifact must parse
        _emit(
            {
                "metric": "polished_bases_per_sec_per_chip",
                "value": 0.0,
                "unit": "bases/s",
                "vs_baseline": 0.0,
                "detail": {"fatal": f"{type(e).__name__}: {e}"[:600]},
            },
            args.out,
        )


if __name__ == "__main__":
    main()
