"""Build the native extractor shared library.

``python -m roko_tpu.native.build`` compiles ``src/*.cc`` with g++ -O3
into ``_roko_native.so`` next to this file (links only zlib, which every
TPU-VM host image ships). No setuptools involvement — the library is a
plain C-ABI .so consumed via ctypes, so there is nothing Python-version
specific to build.
"""

from __future__ import annotations

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "src")
OUT = os.path.join(HERE, "_roko_native.so")

SOURCES = ["bgzf.cc", "bam.cc", "extract.cc", "align.cc", "capi.cc"]
HEADERS = ["bgzf.h", "bam.h", "extract.h", "align.h"]


def build(verbose: bool = True) -> str:
    # link to a temp path + atomic rename: concurrent pipeline workers
    # may race to build, and a half-written .so must never be dlopen'd
    tmp = f"{OUT}.tmp.{os.getpid()}"
    cmd = [
        "g++",
        "-O3",
        "-std=c++17",
        "-fPIC",
        "-shared",
        "-Wall",
        "-o",
        tmp,
        *[os.path.join(SRC, s) for s in SOURCES],
        "-lz",
    ]
    if verbose:
        print(" ".join(cmd))
    try:
        subprocess.run(cmd, check=True)
        os.replace(tmp, OUT)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return OUT


def is_built() -> bool:
    if not os.path.exists(OUT):
        return False
    src_mtime = max(
        os.path.getmtime(os.path.join(SRC, s)) for s in SOURCES + HEADERS
    )
    return os.path.getmtime(OUT) >= src_mtime


def ensure_built(verbose: bool = False) -> str:
    if not is_built():
        build(verbose=verbose)
    return OUT


if __name__ == "__main__":
    build()
    print(f"built {OUT}")
    sys.exit(0)
