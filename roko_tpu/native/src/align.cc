#include "align.h"

#include <algorithm>
#include <limits>
#include <vector>

namespace roko {
namespace {

// Traceback moves. kDiag covers both match and substitution; the
// walk-back re-compares the bases to split them.
enum Move : uint8_t { kNone = 0, kDiag = 1, kUp = 2, kLeft = 3 };

constexpr int64_t kInf = std::numeric_limits<int64_t>::max() / 4;

}  // namespace

AlignStatus BandedAlign(const char* a, int64_t la, const char* b, int64_t lb,
                        int64_t pad, int64_t max_cells, AlignCounts* counts) {
  // Degenerate segments: one side empty is pure gap.
  if (la == 0 || lb == 0) {
    counts->ins += lb;
    counts->del_ += la;
    counts->hit_band_edge = false;
    return AlignStatus::kOk;
  }
  const int64_t dlo = std::min<int64_t>(0, lb - la) - pad;
  const int64_t dhi = std::max<int64_t>(0, lb - la) + pad;
  const int64_t width = dhi - dlo + 1;
  const int64_t cells = (la + 1) * width;
  if (cells > max_cells) return AlignStatus::kCellsCap;

  // dist[w] holds row i's costs for diagonal d = dlo + w (j = i + d).
  std::vector<int64_t> prev(width, kInf), cur(width, kInf);
  std::vector<uint8_t> moves(cells, kNone);

  // Row 0: j = d, only LEFT moves (insertions) inside the band.
  for (int64_t w = 0; w < width; ++w) {
    const int64_t j = dlo + w;
    if (j < 0 || j > lb) continue;
    prev[w] = j;
    moves[w] = j == 0 ? kNone : kLeft;
  }
  for (int64_t i = 1; i <= la; ++i) {
    uint8_t* row_moves = moves.data() + i * width;
    std::fill(cur.begin(), cur.end(), kInf);
    for (int64_t w = 0; w < width; ++w) {
      const int64_t j = i + dlo + w;
      if (j < 0 || j > lb) continue;
      // UP (delete a[i-1]): same j, previous i -> diagonal d+1.
      int64_t best = w + 1 < width && prev[w + 1] < kInf ? prev[w + 1] + 1 : kInf;
      uint8_t mv = kUp;
      // LEFT (insert b[j-1]): same i, previous j -> diagonal d-1.
      if (w - 1 >= 0 && cur[w - 1] < kInf && cur[w - 1] + 1 < best) {
        best = cur[w - 1] + 1;
        mv = kLeft;
      }
      // DIAG: previous i and j -> same diagonal index.
      if (j - 1 >= 0 && prev[w] < kInf) {
        const int64_t c = prev[w] + (a[i - 1] == b[j - 1] ? 0 : 1);
        if (c <= best) {  // prefer diagonal on ties: canonical paths
          best = c;
          mv = kDiag;
        }
      }
      if (j == 0) {  // column 0: only deletions can reach it
        best = i;
        mv = kUp;
      }
      cur[w] = best;
      row_moves[w] = best >= kInf ? kNone : mv;
    }
    std::swap(prev, cur);
  }

  const int64_t end_w = lb - la - dlo;
  if (end_w < 0 || end_w >= width || prev[end_w] >= kInf)
    return AlignStatus::kUnreachableEnd;

  // Walk back from (la, lb), counting ops and noting band-edge contact.
  AlignCounts c;
  int64_t i = la, w = end_w;
  while (i > 0 || i + dlo + w > 0) {
    const int64_t j = i + dlo + w;
    if ((w == 0 || w == width - 1) && (i > 0 && j > 0)) c.hit_band_edge = true;
    const uint8_t mv = moves[i * width + w];
    if (mv == kDiag) {
      if (a[i - 1] == b[j - 1]) {
        ++c.match;
      } else {
        ++c.sub;
      }
      --i;  // same w: j decreases with i
    } else if (mv == kUp) {
      ++c.del_;
      --i;
      ++w;
    } else if (mv == kLeft) {
      ++c.ins;
      --w;
    } else {
      return AlignStatus::kCorruptTraceback;  // kNone before the origin
    }
  }
  counts->match += c.match;
  counts->sub += c.sub;
  counts->ins += c.ins;
  counts->del_ += c.del_;
  counts->hit_band_edge = c.hit_band_edge;
  return AlignStatus::kOk;
}

}  // namespace roko
