// Banded unit-cost global alignment for assembly assessment.
//
// The assess tool (roko_tpu/eval/assess.py) decomposes a
// polished-vs-truth contig pair into short inter-anchor segments; this
// is the per-segment hot loop: a Needleman-Wunsch DP with unit
// mismatch/gap costs restricted to a diagonal band, with full
// traceback so the edit-op breakdown (match / substitution /
// insertion / deletion) is exact, not approximated from the distance.
//
// The reference's published accuracy table (total error / mismatch /
// deletion / insertion / Qscore, /root/reference/README.md:103-112) is
// produced by the external pomoxis assess_assembly; this module gives
// the framework a built-in equivalent so the north-star metric is
// self-measurable.
#ifndef ROKO_ALIGN_H_
#define ROKO_ALIGN_H_

#include <cstdint>

namespace roko {

struct AlignCounts {
  int64_t match = 0;
  int64_t sub = 0;    // diagonal step, a[i] != b[j]
  int64_t ins = 0;    // consumes b only (extra base in b)
  int64_t del_ = 0;   // consumes a only (base of a missing from b)
  bool hit_band_edge = false;  // optimal path touched the band limit
};

// Distinct failure modes so the binding can map the resource cap to a
// retryable MemoryError while genuine aligner bugs surface loudly
// instead of degrading into plausible-looking worst-case counts
// (ADVICE r3). kUnreachableEnd / kCorruptTraceback cannot happen for
// valid inputs (the end diagonal lies inside the band by construction
// and the band is contiguous) — they indicate an internal bug.
enum class AlignStatus {
  kOk = 0,
  kCellsCap = 1,         // (la+1) * band_width > max_cells
  kUnreachableEnd = 2,   // end cell not reached: internal bug
  kCorruptTraceback = 3  // kNone move before the origin: internal bug
};

// Global alignment of a[0:la) vs b[0:lb) with a band of diagonals
// j - i in [min(0, lb-la) - pad, max(0, lb-la) + pad].
// On kCellsCap the counts are untouched.
AlignStatus BandedAlign(const char* a, int64_t la, const char* b, int64_t lb,
                        int64_t pad, int64_t max_cells, AlignCounts* counts);

}  // namespace roko

#endif  // ROKO_ALIGN_H_
