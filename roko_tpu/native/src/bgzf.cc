#include "bgzf.h"

#include <zlib.h>

#include <cstring>

namespace roko {

namespace {
constexpr size_t kHeaderSize = 12;  // fixed gzip header through XLEN
}

BgzfReader::BgzfReader(const std::string& path) : path_(path) {
  file_ = std::fopen(path.c_str(), "rb");
  if (!file_) throw BgzfError(path + ": cannot open");
  try {
    if (!LoadBlockAt(0)) eof_ = true;
  } catch (...) {
    // destructor won't run for a partially constructed object
    std::fclose(file_);
    file_ = nullptr;
    throw;
  }
}

BgzfReader::~BgzfReader() {
  if (file_) std::fclose(file_);
}

bool BgzfReader::LoadBlockAt(uint64_t coffset) {
  if (std::fseek(file_, static_cast<long>(coffset), SEEK_SET) != 0)
    throw BgzfError(path_ + ": seek failed");

  uint8_t header[kHeaderSize];
  size_t got = std::fread(header, 1, kHeaderSize, file_);
  if (got == 0) return false;  // clean EOF
  if (got < kHeaderSize) throw BgzfError(path_ + ": truncated BGZF header");
  if (header[0] != 0x1f || header[1] != 0x8b)
    throw BgzfError(path_ + ": not a gzip stream");
  if (!(header[3] & 0x04))
    throw BgzfError(path_ + ": gzip member without FEXTRA (not BGZF)");

  uint16_t xlen = static_cast<uint16_t>(header[10] | (header[11] << 8));
  std::vector<uint8_t> extra(xlen);
  if (std::fread(extra.data(), 1, xlen, file_) != xlen)
    throw BgzfError(path_ + ": truncated FEXTRA");

  // find the BC subfield carrying BSIZE (total block size - 1)
  int bsize = -1;
  for (size_t i = 0; i + 4 <= extra.size();) {
    uint8_t si1 = extra[i], si2 = extra[i + 1];
    uint16_t slen = static_cast<uint16_t>(extra[i + 2] | (extra[i + 3] << 8));
    if (si1 == 'B' && si2 == 'C' && slen == 2 && i + 6 <= extra.size()) {
      bsize = extra[i + 4] | (extra[i + 5] << 8);
    }
    i += 4 + slen;
  }
  if (bsize < 0) throw BgzfError(path_ + ": BGZF BC subfield missing");
  if (static_cast<size_t>(bsize) + 1 < kHeaderSize + xlen + 8)
    throw BgzfError(path_ + ": corrupt BGZF block size");

  size_t cdata_len =
      static_cast<size_t>(bsize) + 1 - kHeaderSize - xlen - 8;  // minus CRC+ISIZE
  std::vector<uint8_t> cdata(cdata_len);
  if (std::fread(cdata.data(), 1, cdata_len, file_) != cdata_len)
    throw BgzfError(path_ + ": truncated CDATA");

  uint8_t tail[8];
  if (std::fread(tail, 1, 8, file_) != 8)
    throw BgzfError(path_ + ": truncated CRC/ISIZE");
  uint32_t isize = static_cast<uint32_t>(tail[4]) | (tail[5] << 8) |
                   (tail[6] << 16) | (static_cast<uint32_t>(tail[7]) << 24);

  block_.assign(isize, 0);
  if (isize > 0) {
    z_stream zs;
    std::memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, -15) != Z_OK)
      throw BgzfError(path_ + ": inflateInit2 failed");
    zs.next_in = cdata.data();
    zs.avail_in = static_cast<uInt>(cdata.size());
    zs.next_out = block_.data();
    zs.avail_out = static_cast<uInt>(block_.size());
    int rc = inflate(&zs, Z_FINISH);
    inflateEnd(&zs);
    if (rc != Z_STREAM_END)
      throw BgzfError(path_ + ": corrupt BGZF block (inflate rc=" +
                      std::to_string(rc) + ")");
    uint32_t crc = crc32(0L, block_.data(), static_cast<uInt>(block_.size()));
    uint32_t want = static_cast<uint32_t>(tail[0]) | (tail[1] << 8) |
                    (tail[2] << 16) | (static_cast<uint32_t>(tail[3]) << 24);
    if (crc != want) throw BgzfError(path_ + ": BGZF CRC mismatch");
  }

  block_coffset_ = coffset;
  next_coffset_ = coffset + static_cast<uint64_t>(bsize) + 1;
  block_pos_ = 0;
  eof_ = false;
  return true;
}

size_t BgzfReader::Read(uint8_t* out, size_t n) {
  size_t done = 0;
  while (done < n) {
    if (block_pos_ >= block_.size()) {
      if (eof_ || !LoadBlockAt(next_coffset_)) {
        eof_ = true;
        break;
      }
      // empty EOF-marker blocks: keep advancing
      continue;
    }
    size_t take = std::min(n - done, block_.size() - block_pos_);
    std::memcpy(out + done, block_.data() + block_pos_, take);
    block_pos_ += take;
    done += take;
  }
  return done;
}

uint64_t BgzfReader::TellVirtual() const {
  // a fully consumed block addresses the *next* block's start: BGZF
  // blocks may hold exactly 65536 bytes, where (coffset, 65536) would
  // alias (coffset, 0) under the 16-bit uoffset mask
  if (block_pos_ >= block_.size() && !eof_)
    return next_coffset_ << 16;
  return (block_coffset_ << 16) | static_cast<uint64_t>(block_pos_ & 0xFFFF);
}

void BgzfReader::SeekVirtual(uint64_t voffset) {
  uint64_t coffset = voffset >> 16;
  size_t uoffset = static_cast<size_t>(voffset & 0xFFFF);
  if (coffset != block_coffset_ || eof_ || block_.empty()) {
    if (!LoadBlockAt(coffset)) throw BgzfError(path_ + ": seek past EOF");
  }
  if (uoffset > block_.size())
    throw BgzfError(path_ + ": virtual offset beyond block");
  block_pos_ = uoffset;
  eof_ = false;
}

bool BgzfReader::AtEof() {
  if (block_pos_ < block_.size()) return false;
  if (eof_) return true;
  if (!LoadBlockAt(next_coffset_)) {
    eof_ = true;
    return true;
  }
  return block_pos_ >= block_.size() && AtEof();
}

}  // namespace roko
