#include "bam.h"

#include <algorithm>
#include <cstring>

namespace roko {

namespace {

constexpr char kBamMagic[4] = {'B', 'A', 'M', 1};
constexpr char kBaiMagic[4] = {'B', 'A', 'I', 1};
constexpr int kLinearShift = 14;

// ops that consume the reference: M, D, N, =, X
inline bool ConsumesRef(uint32_t op) {
  return op == 0 || op == 2 || op == 3 || op == 7 || op == 8;
}

uint32_t ReadU32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;  // BAM is little-endian; so are our targets
}

int32_t ReadI32(const uint8_t* p) {
  int32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

}  // namespace

int32_t BamRecord::ReferenceEnd() const {
  int64_t n = 0;
  for (uint32_t c : cigar) {
    if (ConsumesRef(c & 0xF)) n += c >> 4;
  }
  return n > 0 ? static_cast<int32_t>(pos + n) : pos + 1;
}

BamReader::BamReader(const std::string& path) : path_(path) {
  bgzf_.reset(new BgzfReader(path));
  uint8_t magic[4];
  if (bgzf_->Read(magic, 4) != 4 || std::memcmp(magic, kBamMagic, 4) != 0)
    throw BgzfError(path + ": not a BAM file");
  uint8_t buf[4];
  if (bgzf_->Read(buf, 4) != 4) throw BgzfError(path + ": truncated header");
  int32_t l_text = ReadI32(buf);
  std::vector<uint8_t> text(l_text);
  if (bgzf_->Read(text.data(), l_text) != static_cast<size_t>(l_text))
    throw BgzfError(path + ": truncated header text");
  if (bgzf_->Read(buf, 4) != 4) throw BgzfError(path + ": truncated n_ref");
  int32_t n_ref = ReadI32(buf);
  references_.reserve(n_ref);
  for (int32_t i = 0; i < n_ref; ++i) {
    if (bgzf_->Read(buf, 4) != 4) throw BgzfError(path + ": truncated ref");
    int32_t l_name = ReadI32(buf);
    std::vector<uint8_t> name(l_name);
    if (bgzf_->Read(name.data(), l_name) != static_cast<size_t>(l_name))
      throw BgzfError(path + ": truncated ref name");
    if (bgzf_->Read(buf, 4) != 4) throw BgzfError(path + ": truncated ref len");
    std::string sname(reinterpret_cast<char*>(name.data()), l_name - 1);
    tid_by_name_[sname] = static_cast<int>(references_.size());
    references_.emplace_back(std::move(sname), ReadI32(buf));
  }
  first_record_voffset_ = bgzf_->TellVirtual();
}

int BamReader::TidByName(const std::string& name) const {
  auto it = tid_by_name_.find(name);
  return it == tid_by_name_.end() ? -1 : it->second;
}

namespace {

// Scan the tag region for a CG:B,I array (the real CIGAR of reads whose
// op count overflows the 16-bit n_cigar field; the fixed field then
// holds the placeholder "<l_seq>S<ref_len>N", SAM spec §4.2.2).
bool FindCgTag(const uint8_t* tags, size_t len, std::vector<uint32_t>* out) {
  size_t off = 0;
  while (off + 3 <= len) {
    char t0 = static_cast<char>(tags[off]);
    char t1 = static_cast<char>(tags[off + 1]);
    char type = static_cast<char>(tags[off + 2]);
    off += 3;
    size_t size = 0;
    switch (type) {
      case 'A': case 'c': case 'C': size = 1; break;
      case 's': case 'S': size = 2; break;
      case 'i': case 'I': case 'f': size = 4; break;
      case 'Z': case 'H': {
        while (off < len && tags[off] != 0) ++off;
        ++off;
        continue;
      }
      case 'B': {
        if (off + 5 > len) return false;
        char elem = static_cast<char>(tags[off]);
        uint32_t count = ReadU32(tags + off + 1);
        size_t esize = (elem == 'c' || elem == 'C') ? 1
                       : (elem == 's' || elem == 'S') ? 2
                                                      : 4;
        if (t0 == 'C' && t1 == 'G' && elem == 'I') {
          if (off + 5 + 4ull * count > len) return false;
          out->resize(count);
          std::memcpy(out->data(), tags + off + 5, 4ull * count);
          return true;
        }
        off += 5 + esize * count;
        continue;
      }
      default:
        return false;  // unknown tag type: stop scanning
    }
    off += size;
  }
  return false;
}

}  // namespace

bool BamReader::ReadRecord(BamRecord* rec) {
  uint8_t buf[4];
  if (bgzf_->Read(buf, 4) < 4) return false;
  int32_t block_size = ReadI32(buf);
  if (block_size < 32) throw BgzfError(path_ + ": invalid record size");
  std::vector<uint8_t> body(block_size);
  if (bgzf_->Read(body.data(), block_size) != static_cast<size_t>(block_size))
    throw BgzfError(path_ + ": truncated record");

  const uint8_t* p = body.data();
  rec->tid = ReadI32(p + 0);
  rec->pos = ReadI32(p + 4);
  uint8_t l_read_name = p[8];
  rec->mapq = p[9];
  uint16_t n_cigar;
  std::memcpy(&n_cigar, p + 12, 2);
  std::memcpy(&rec->flag, p + 14, 2);
  rec->l_seq = ReadI32(p + 16);
  if (rec->l_seq < 0 || l_read_name < 1)
    throw BgzfError(path_ + ": malformed record");
  size_t need = 32ull + l_read_name + 4ull * n_cigar +
                (static_cast<size_t>(rec->l_seq) + 1) / 2 +
                static_cast<size_t>(rec->l_seq);
  if (need > static_cast<size_t>(block_size))
    throw BgzfError(path_ + ": record fields exceed block size");
  // next_tid (20), next_pos (24), tlen (28) unused by the extractor
  size_t off = 32;
  rec->name.assign(reinterpret_cast<const char*>(p + off), l_read_name - 1);
  off += l_read_name;
  rec->cigar.resize(n_cigar);
  for (uint16_t i = 0; i < n_cigar; ++i, off += 4)
    rec->cigar[i] = ReadU32(p + off);
  rec->seq_nib.resize(rec->l_seq);
  for (int32_t i = 0; i < rec->l_seq; ++i) {
    uint8_t byte = p[off + (i >> 1)];
    rec->seq_nib[i] = (i % 2 == 0) ? (byte >> 4) : (byte & 0xF);
  }
  off += (static_cast<size_t>(rec->l_seq) + 1) / 2;
  off += static_cast<size_t>(rec->l_seq);  // qual unused

  // ultralong-read CIGAR overflow: placeholder kS mN + CG:B,I tag
  if (rec->cigar.size() == 2 && (rec->cigar[0] & 0xF) == 4 /*S*/ &&
      (rec->cigar[1] & 0xF) == 3 /*N*/ &&
      static_cast<int32_t>(rec->cigar[0] >> 4) == rec->l_seq) {
    std::vector<uint32_t> real_cigar;
    if (FindCgTag(p + off, block_size - off, &real_cigar))
      rec->cigar = std::move(real_cigar);
  }
  return true;
}

const std::vector<BamReader::RefIndex>* BamReader::LoadIndex() {
  if (index_loaded_) return index_present_ ? &index_ : nullptr;
  index_loaded_ = true;
  std::string bai_path = path_ + ".bai";
  std::FILE* fh = std::fopen(bai_path.c_str(), "rb");
  if (!fh) return nullptr;
  std::fseek(fh, 0, SEEK_END);
  long size = std::ftell(fh);
  std::fseek(fh, 0, SEEK_SET);
  std::vector<uint8_t> data(size);
  if (std::fread(data.data(), 1, size, fh) != static_cast<size_t>(size)) {
    std::fclose(fh);
    throw BgzfError(bai_path + ": short read");
  }
  std::fclose(fh);
  if (size < 8 || std::memcmp(data.data(), kBaiMagic, 4) != 0)
    throw BgzfError(bai_path + ": not a BAI index");
  const size_t n = data.size();
  size_t off = 4;
  auto need = [&](size_t count) {
    if (off + count > n) throw BgzfError(bai_path + ": truncated BAI index");
  };
  need(4);
  int32_t n_ref = ReadI32(data.data() + off);
  off += 4;
  if (n_ref < 0) throw BgzfError(bai_path + ": corrupt BAI index");
  index_.resize(n_ref);
  for (int32_t r = 0; r < n_ref; ++r) {
    need(4);
    int32_t n_bin = ReadI32(data.data() + off);
    off += 4;
    if (n_bin < 0) throw BgzfError(bai_path + ": corrupt BAI index");
    for (int32_t b = 0; b < n_bin; ++b) {
      need(8);
      uint32_t bin_id;
      std::memcpy(&bin_id, data.data() + off, 4);
      int32_t n_chunk = ReadI32(data.data() + off + 4);
      if (n_chunk < 0) throw BgzfError(bai_path + ": corrupt BAI index");
      need(8 + 16ul * n_chunk);
      off += 8;
      auto& chunks = index_[r].bins[bin_id];
      chunks.reserve(n_chunk);
      for (int32_t c = 0; c < n_chunk; ++c) {
        uint64_t beg, cend;
        std::memcpy(&beg, data.data() + off, 8);
        std::memcpy(&cend, data.data() + off + 8, 8);
        off += 16;
        chunks.emplace_back(beg, cend);
      }
    }
    need(4);
    int32_t n_intv = ReadI32(data.data() + off);
    off += 4;
    if (n_intv < 0) throw BgzfError(bai_path + ": corrupt BAI index");
    need(8ul * n_intv);
    index_[r].ioffsets.resize(n_intv);
    std::memcpy(index_[r].ioffsets.data(), data.data() + off, 8ul * n_intv);
    off += 8ul * n_intv;
  }
  index_present_ = true;
  return &index_;
}

namespace {
// Candidate bins possibly holding records overlapping [beg, end)
// (SAM spec §5.3 recurrence).
void Reg2Bins(int64_t beg, int64_t end, std::vector<uint32_t>* bins) {
  --end;
  bins->push_back(0);
  static constexpr struct { uint32_t base; int shift; } kLevels[] = {
      {1, 26}, {9, 23}, {73, 20}, {585, 17}, {4681, 14}};
  for (const auto& lv : kLevels)
    for (int64_t k = lv.base + (beg >> lv.shift);
         k <= lv.base + (end >> lv.shift); ++k)
      bins->push_back(static_cast<uint32_t>(k));
}

uint64_t LinearMinVoffset(const std::vector<uint64_t>& ioffsets,
                          int64_t start) {
  if (ioffsets.empty()) return 0;
  int64_t i = std::min<int64_t>(start >> kLinearShift,
                                static_cast<int64_t>(ioffsets.size()) - 1);
  while (i >= 0 && ioffsets[i] == 0) --i;
  return i >= 0 ? ioffsets[i] : 0;
}
}  // namespace

bool BamReader::RegionChunks(int tid, int64_t start, int64_t end,
                             std::vector<std::pair<uint64_t, uint64_t>>* out) {
  const auto* index = LoadIndex();
  if (!index || tid >= static_cast<int>(index->size())) return false;
  const RefIndex& ref = (*index)[tid];
  if (ref.bins.empty()) return false;  // linear-only .bai
  uint64_t min_voff = LinearMinVoffset(ref.ioffsets, start);
  std::vector<uint32_t> bins;
  Reg2Bins(start, end, &bins);
  std::vector<std::pair<uint64_t, uint64_t>> chunks;
  for (uint32_t b : bins) {
    auto it = ref.bins.find(b);
    if (it == ref.bins.end()) continue;
    for (const auto& ch : it->second)
      if (ch.second > min_voff)
        chunks.emplace_back(std::max(ch.first, min_voff), ch.second);
  }
  std::sort(chunks.begin(), chunks.end());
  out->clear();
  for (const auto& ch : chunks) {
    if (!out->empty() && ch.first <= out->back().second)
      out->back().second = std::max(out->back().second, ch.second);
    else
      out->push_back(ch);
  }
  return true;
}

std::vector<BamRecord> BamReader::Fetch(const std::string& contig,
                                        int64_t start, int64_t end) {
  int tid = TidByName(contig);
  if (tid < 0) throw BgzfError(path_ + ": unknown contig " + contig);
  if (end < 0) end = references_[tid].second;

  std::vector<BamRecord> out;
  BamRecord rec;

  std::vector<std::pair<uint64_t, uint64_t>> chunks;
  if (RegionChunks(tid, start, end, &chunks)) {
    // binned query: read only the region's chunk list (htslib shape)
    for (const auto& ch : chunks) {
      bgzf_->SeekVirtual(ch.first);
      while (bgzf_->TellVirtual() < ch.second && ReadRecord(&rec)) {
        if (rec.tid != tid) {
          if (rec.tid > tid || rec.tid < 0) return out;  // sorted: past
          continue;
        }
        if (rec.pos >= end) return out;
        if (rec.IsUnmapped()) continue;
        if (rec.ReferenceEnd() > start) out.push_back(rec);
      }
    }
    return out;
  }

  uint64_t voffset = first_record_voffset_;
  const auto* index = LoadIndex();
  if (index && tid < static_cast<int>(index->size())) {
    uint64_t lin = LinearMinVoffset((*index)[tid].ioffsets, start);
    if (lin) voffset = lin;
  }
  bgzf_->SeekVirtual(voffset);

  while (ReadRecord(&rec)) {
    if (rec.tid != tid) {
      if (rec.tid > tid || rec.tid < 0) break;  // coordinate-sorted
      continue;
    }
    if (rec.pos >= end) break;
    if (rec.IsUnmapped()) continue;
    if (rec.ReferenceEnd() > start) out.push_back(rec);
  }
  return out;
}

}  // namespace roko
