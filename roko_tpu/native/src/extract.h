// Pileup sweep + 200x90 window tensorizer (the host hot path).
//
// Native implementation of the feature extractor with the exact
// semantics of the reference's generate.cpp:28-158 (window queue, GAP vs
// UNKNOWN bounds rule, with-replacement row sampling) as specified by
// the Python oracle in roko_tpu/features/extract.py + pileup.py; golden
// tests assert bit-identical output between the two. Sampling uses the
// shared SplitMix64 stream (roko_tpu/utils/rng.py) instead of the
// reference's wall-clock srand (ref: gen.cpp:11).
#pragma once

#include <cstdint>
#include <string>
#include <vector>

#include "bam.h"

namespace roko {

struct ExtractConfig {
  int rows = 200;
  int cols = 90;
  int stride = 30;
  int max_ins = 3;
  // first ref_rows rows carry the DRAFT base per column (GAP at
  // insertion slots, forward-strand encoding) — the reference's
  // REF_ROWS block (generate.cpp:109-119); needs ref_seq when > 0
  int ref_rows = 0;
  int min_mapq = 10;
  uint16_t filter_flag = 0xF04;  // UNMAP|SECONDARY|QCFAIL|DUP|SUPPLEMENTARY
  bool require_proper_pair = true;
};

struct ExtractResult {
  int64_t n_windows = 0;
  std::vector<int64_t> positions;  // [n_windows, cols, 2]
  std::vector<uint8_t> matrix;     // [n_windows, rows, cols]
};

// SplitMix64, identical to roko_tpu/utils/rng.py::SplitMix64.
class SplitMix64 {
 public:
  explicit SplitMix64(uint64_t seed) : state_(seed) {}
  uint64_t NextU64() {
    state_ += 0x9E3779B97F4A7C15ull;
    uint64_t z = state_;
    z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
    z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
    return z ^ (z >> 31);
  }
  uint64_t NextBelow(uint64_t n) { return NextU64() % n; }

 private:
  uint64_t state_;
};

// ref_seq: draft contig bytes starting at absolute position ref_off and
// covering at least [start, end); only read when cfg.ref_rows > 0. The
// offset lets region callers pass just their slice (O(region) IPC).
ExtractResult ExtractWindows(const std::string& bam_path,
                             const std::string& contig, int64_t start,
                             int64_t end, uint64_t seed,
                             const ExtractConfig& cfg,
                             const std::string& ref_seq = std::string(),
                             int64_t ref_off = 0);

}  // namespace roko
