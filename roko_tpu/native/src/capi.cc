// C ABI for the native extractor, consumed via ctypes
// (roko_tpu/native/binding.py). One call per region; the caller copies
// the returned buffers into numpy arrays and frees them.
#include <cstdlib>
#include <cstring>
#include <string>

#include "align.h"
#include "extract.h"

namespace {
thread_local std::string g_last_error;
}

extern "C" {

// Compile-time geometry/encoding constants, asserted against
// roko_tpu/constants.py at binding load (single source of truth).
// v2: roko_extract_windows gained (ref_seq, ref_len, ref_rows).
int roko_native_abi_version() { return 2; }

struct RokoResult {
  int64_t n_windows;
  int64_t* positions;  // [n_windows, cols, 2], malloc'd
  uint8_t* matrix;     // [n_windows, rows, cols], malloc'd
};

const char* roko_last_error() { return g_last_error.c_str(); }

// Returns 0 on success, nonzero on error (message via roko_last_error).
// ref_seq/ref_len: draft contig bytes (starting at absolute position
// ref_off) for the ref_rows draft-base rows; pass nullptr/0/0 when
// ref_rows == 0.
int roko_extract_windows(const char* bam_path, const char* contig,
                         int64_t start, int64_t end, uint64_t seed, int rows,
                         int cols, int stride, int max_ins, int min_mapq,
                         int filter_flag, int require_proper_pair,
                         const char* ref_seq, int64_t ref_len,
                         int64_t ref_off, int ref_rows, RokoResult* out) {
  try {
    roko::ExtractConfig cfg;
    cfg.rows = rows;
    cfg.cols = cols;
    cfg.stride = stride;
    cfg.max_ins = max_ins;
    cfg.ref_rows = ref_rows;
    cfg.min_mapq = min_mapq;
    cfg.filter_flag = static_cast<uint16_t>(filter_flag);
    cfg.require_proper_pair = require_proper_pair != 0;

    roko::ExtractResult res = roko::ExtractWindows(
        bam_path, contig, start, end, seed, cfg,
        ref_seq ? std::string(ref_seq, static_cast<size_t>(ref_len))
                : std::string(),
        ref_off);

    out->n_windows = res.n_windows;
    out->positions = nullptr;
    out->matrix = nullptr;
    if (res.n_windows > 0) {
      out->positions = static_cast<int64_t*>(
          std::malloc(res.positions.size() * sizeof(int64_t)));
      out->matrix = static_cast<uint8_t*>(std::malloc(res.matrix.size()));
      if (!out->positions || !out->matrix) {
        std::free(out->positions);
        std::free(out->matrix);
        g_last_error = "out of memory";
        return 2;
      }
      std::memcpy(out->positions, res.positions.data(),
                  res.positions.size() * sizeof(int64_t));
      std::memcpy(out->matrix, res.matrix.data(), res.matrix.size());
    }
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return 1;
  }
}

// Banded global alignment of a vs b (roko_tpu/eval/assess.py segment
// hot loop). out8 receives [match, sub, ins, del, hit_band_edge, 0, 0,
// 0]. Returns 0 on success; 3 ONLY when the band x length working set
// exceeds max_cells (retryable: caller shrinks the segment or widens
// in steps); 1 for internal aligner bugs, which the binding raises as
// RuntimeError rather than letting the caller degrade them into
// plausible worst-case counts (ADVICE r3).
int roko_align_counts(const char* a, int64_t la, const char* b, int64_t lb,
                      int64_t pad, int64_t max_cells, int64_t* out8) {
  try {
    roko::AlignCounts c;
    switch (roko::BandedAlign(a, la, b, lb, pad, max_cells, &c)) {
      case roko::AlignStatus::kOk:
        break;
      case roko::AlignStatus::kCellsCap:
        g_last_error = "alignment working set exceeds max_cells";
        return 3;
      case roko::AlignStatus::kUnreachableEnd:
        g_last_error = "internal aligner error: end cell unreachable";
        return 1;
      case roko::AlignStatus::kCorruptTraceback:
        g_last_error = "internal aligner error: corrupt traceback";
        return 1;
    }
    out8[0] = c.match;
    out8[1] = c.sub;
    out8[2] = c.ins;
    out8[3] = c.del_;
    out8[4] = c.hit_band_edge ? 1 : 0;
    out8[5] = out8[6] = out8[7] = 0;
    return 0;
  } catch (const std::exception& e) {
    g_last_error = e.what();
    return 1;
  }
}

void roko_free_result(RokoResult* res) {
  if (!res) return;
  std::free(res->positions);
  std::free(res->matrix);
  res->positions = nullptr;
  res->matrix = nullptr;
  res->n_windows = 0;
}

}  // extern "C"
