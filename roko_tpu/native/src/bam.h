// BAM container parsing + full BAI (bin + linear) region fetch.
//
// Native replacement for the reference's htslib usage (readBAM /
// sam_itr_querys / bam_itr pattern, ref: models.cpp:37-101): parses the
// BAM binary layout (SAM spec §4.2) directly over roko::BgzfReader and
// serves coordinate-order region queries via the .bai distributed bins
// pruned by the linear index — the htslib query shape — mirroring
// roko_tpu/io/bam.py::BamReader.fetch (linear-only indexes still work;
// no index falls back to a full scan).
#pragma once

#include <cstdint>
#include <memory>
#include <string>
#include <unordered_map>
#include <vector>

#include "bgzf.h"

namespace roko {

struct BamRecord {
  std::string name;
  uint16_t flag = 0;
  int32_t tid = -1;
  int32_t pos = -1;  // 0-based leftmost
  uint8_t mapq = 0;
  std::vector<uint32_t> cigar;    // (len << 4) | op
  std::vector<uint8_t> seq_nib;   // 4-bit codes, one per base
  int32_t l_seq = 0;

  int32_t ReferenceEnd() const;  // one past last aligned ref pos (>= pos+1)
  bool IsUnmapped() const { return flag & 0x4; }
  bool IsReverse() const { return flag & 0x10; }
};

class BamReader {
 public:
  explicit BamReader(const std::string& path);

  const std::vector<std::pair<std::string, int64_t>>& References() const {
    return references_;
  }
  int TidByName(const std::string& name) const;  // -1 if unknown

  // All mapped records overlapping [start, end) on contig, file order.
  std::vector<BamRecord> Fetch(const std::string& contig, int64_t start,
                               int64_t end);

 private:
  struct RefIndex {
    std::unordered_map<uint32_t, std::vector<std::pair<uint64_t, uint64_t>>>
        bins;  // bin id -> [(chunk_beg, chunk_end)] virtual offsets
    std::vector<uint64_t> ioffsets;  // 16 kb linear index
  };

  bool ReadRecord(BamRecord* rec);  // false at EOF
  const std::vector<RefIndex>* LoadIndex();
  // Merged chunk list for [start, end) on tid; false when the index (or
  // its bin section) is unavailable and the caller must linear-scan.
  bool RegionChunks(int tid, int64_t start, int64_t end,
                    std::vector<std::pair<uint64_t, uint64_t>>* out);

  std::string path_;
  std::unique_ptr<BgzfReader> bgzf_;
  std::vector<std::pair<std::string, int64_t>> references_;
  std::unordered_map<std::string, int> tid_by_name_;
  uint64_t first_record_voffset_ = 0;
  std::vector<RefIndex> index_;
  bool index_loaded_ = false;
  bool index_present_ = false;
};

}  // namespace roko
