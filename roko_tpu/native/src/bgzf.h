// BGZF (blocked gzip) reader over zlib raw inflate.
//
// TPU-host native I/O layer: replaces the reference's vendored htslib
// BGZF machinery (SURVEY.md §2.13) with a from-scratch implementation of
// the BGZF spec (SAM spec §4.1): concatenated gzip members carrying a
// BC extra subfield with the compressed block size. Supports virtual
// offsets (coffset << 16 | uoffset) for BAI-indexed seeks.
#pragma once

#include <cstdint>
#include <cstdio>
#include <stdexcept>
#include <string>
#include <vector>

namespace roko {

class BgzfError : public std::runtime_error {
 public:
  explicit BgzfError(const std::string& msg) : std::runtime_error(msg) {}
};

class BgzfReader {
 public:
  explicit BgzfReader(const std::string& path);
  ~BgzfReader();
  BgzfReader(const BgzfReader&) = delete;
  BgzfReader& operator=(const BgzfReader&) = delete;

  // Read exactly n bytes unless EOF; returns bytes read.
  size_t Read(uint8_t* out, size_t n);
  // Virtual offset of the next byte to be read.
  uint64_t TellVirtual() const;
  void SeekVirtual(uint64_t voffset);
  bool AtEof();

 private:
  bool LoadBlockAt(uint64_t coffset);  // false at EOF

  std::FILE* file_;
  std::string path_;
  uint64_t block_coffset_ = 0;     // file offset of the current block
  uint64_t next_coffset_ = 0;      // file offset of the next block
  std::vector<uint8_t> block_;     // inflated payload of current block
  size_t block_pos_ = 0;           // cursor within block_
  bool eof_ = false;
};

}  // namespace roko
