#include "extract.h"

#include <algorithm>
#include <deque>
#include <stdexcept>
#include <unordered_map>
#include <unordered_set>

namespace roko {

namespace {

constexpr uint8_t kGap = 4;
constexpr uint8_t kUnknown = 5;
constexpr uint8_t kStrandOffset = 6;
constexpr uint8_t kInvalid = 0xFF;

// BAM seq nibble -> encoded base, matching the oracle's nibble -> char ->
// CHAR_TO_CODE chain (roko_tpu/io/bam.py _SEQ_CODES + constants.py
// CHAR_TO_CODE; ref nibble decode: include/models.h:120-138). Ambiguity
// codes other than N are errors there, so kInvalid here.
constexpr uint8_t kNibbleToCode[16] = {
    kInvalid, 0,        1,        kInvalid,  // -, A, C, M
    2,        kInvalid, kInvalid, kInvalid,  // G, R, S, V
    3,        kInvalid, kInvalid, kInvalid,  // T, W, Y, H
    kInvalid, kInvalid, kInvalid, kUnknown,  // K, D, B, N
};

struct ColState {
  int32_t qpos;
  bool is_del;
  bool is_refskip;
  int32_t indel;  // >0 insertion after this column; <0 deletion; 0 none
};

struct ReadInfo {
  int id;
  int32_t pos;
  int32_t ref_end;   // exclusive (htslib bam_endpos)
  bool reverse;
  const std::vector<uint8_t>* seq_nib;
  std::vector<ColState> states;
};

// Mirrors roko_tpu/features/pileup.py::_column_states (htslib pileup
// semantics: indel flagged on the last column before an I/D op; D/N
// columns carry the qpos of the preceding aligned base).
std::vector<ColState> ColumnStates(const BamRecord& rec) {
  std::vector<ColState> states;
  int32_t qpos = 0;
  for (uint32_t c : rec.cigar) {
    uint32_t op = c & 0xF;
    int32_t length = static_cast<int32_t>(c >> 4);
    switch (op) {
      case 0:  // M
      case 7:  // =
      case 8:  // X
        for (int32_t i = 0; i < length; ++i)
          states.push_back({qpos + i, false, false, 0});
        qpos += length;
        break;
      case 1:  // I
        if (!states.empty()) states.back().indel = length;
        qpos += length;
        break;
      case 2:  // D
        if (!states.empty() && states.back().indel <= 0)
          states.back().indel = -length;
        for (int32_t i = 0; i < length; ++i)
          states.push_back({std::max(qpos - 1, 0), true, false, 0});
        break;
      case 3:  // N
        for (int32_t i = 0; i < length; ++i)
          states.push_back({std::max(qpos - 1, 0), true, true, 0});
        break;
      case 4:  // S
        qpos += length;
        break;
      default:  // H, P consume nothing
        break;
    }
  }
  return states;
}

bool PassesFilter(const BamRecord& rec, const ExtractConfig& cfg) {
  if (rec.flag & cfg.filter_flag) return false;
  if (cfg.require_proper_pair && (rec.flag & 0x1) && !(rec.flag & 0x2))
    return false;
  if (rec.mapq < cfg.min_mapq) return false;
  return true;
}

}  // namespace

ExtractResult ExtractWindows(const std::string& bam_path,
                             const std::string& contig, int64_t start,
                             int64_t end, uint64_t seed,
                             const ExtractConfig& cfg) {
  BamReader reader(bam_path);
  ExtractResult result;

  // storage owns the records; ReadInfo borrows seq_nib pointers, so it
  // must stay alive for the whole sweep
  std::vector<BamRecord> storage;
  std::vector<ReadInfo> reads;
  {
    std::vector<BamRecord> records = reader.Fetch(contig, start, end);
    storage.reserve(records.size());
    for (auto& rec : records) {
      if (!PassesFilter(rec, cfg)) continue;
      storage.push_back(std::move(rec));
    }
    int next_id = 0;
    reads.reserve(storage.size());
    for (auto& rec : storage) {
      ReadInfo info;
      info.id = next_id++;
      info.pos = rec.pos;
      info.ref_end = rec.ReferenceEnd();
      info.reverse = rec.IsReverse();
      info.seq_nib = &rec.seq_nib;
      info.states = ColumnStates(rec);
      reads.push_back(std::move(info));
    }
  }
  if (reads.empty()) return result;

  const int slots = cfg.max_ins + 1;
  auto key_of = [slots](int64_t rpos, int ins) -> int64_t {
    return rpos * slots + ins;
  };

  SplitMix64 rng(seed);
  std::deque<int64_t> pos_queue;
  // (rpos, ins) -> per-read first-seen code; insertion into the inner
  // vector preserves "setdefault" (first write wins) via Seen lookup
  struct ColInfo {
    std::vector<std::pair<int, uint8_t>> codes;  // (rid, code), rid unique
    // The sweep visits each (read, column) pair exactly once (one
    // ColState per covered column), so rids are unique per key by
    // construction — a plain append matches the oracle's dict setdefault
    // without the O(coverage) membership scan.
    void SetDefault(int rid, uint8_t code) { codes.emplace_back(rid, code); }
  };
  std::unordered_map<int64_t, ColInfo> align_info;
  // rid -> (ref bounds, strand), recorded at first non-refskip entry
  struct Bounds {
    int32_t lo, hi;
    bool fwd;
  };
  std::unordered_map<int, Bounds> bounds;

  int64_t lo = reads.front().pos;
  for (const auto& r : reads) lo = std::min<int64_t>(lo, r.pos);
  int64_t hi = 0;
  for (const auto& r : reads)
    hi = std::max<int64_t>(hi, r.pos + static_cast<int64_t>(r.states.size()));

  std::vector<size_t> active;
  size_t nxt = 0;

  auto encode_base = [&](const ReadInfo& r, int32_t q) -> uint8_t {
    uint8_t code = kNibbleToCode[(*r.seq_nib)[q] & 0xF];
    if (code == kInvalid)
      throw std::runtime_error("unexpected base nibble in read sequence");
    return code;
  };

  // Reused per-window scratch: one dense row per read seen in the window,
  // built in a single pass over the columns (the per-sampled-read lazy
  // row construction the Python oracle uses is O(cols * coverage) per
  // sampled read; with 200 samples over ~coverage reads nearly every
  // read is materialised anyway, so batch-building is strictly cheaper).
  constexpr uint8_t kUnset = 0xFE;
  std::unordered_map<int, size_t> rid_slot;
  std::vector<int> slot_rid;
  std::vector<std::vector<uint8_t>> rows_buf;
  std::vector<bool> slot_valid;

  auto emit_windows = [&]() {
    while (static_cast<int>(pos_queue.size()) >= cfg.cols) {
      rid_slot.clear();
      slot_rid.clear();
      rows_buf.clear();
      slot_valid.clear();

      for (int c = 0; c < cfg.cols; ++c) {
        const ColInfo& info = align_info[pos_queue[c]];
        for (const auto& p : info.codes) {
          auto it = rid_slot.find(p.first);
          size_t slot;
          if (it == rid_slot.end()) {
            slot = rows_buf.size();
            rid_slot.emplace(p.first, slot);
            slot_rid.push_back(p.first);
            rows_buf.emplace_back(cfg.cols, kUnset);
            slot_valid.push_back(false);
          } else {
            slot = it->second;
          }
          rows_buf[slot][c] = p.second;
          if (p.second != kUnknown) slot_valid[slot] = true;
        }
      }

      // valid reads: any non-UNKNOWN code within the window, sorted by id
      std::vector<int> valid;
      for (size_t s = 0; s < slot_rid.size(); ++s)
        if (slot_valid[s]) valid.push_back(slot_rid[s]);
      std::sort(valid.begin(), valid.end());

      if (!valid.empty()) {
        const size_t n_valid = valid.size();
        // complete the rows: bounds rule for unset columns, strand offset
        for (size_t s = 0; s < rows_buf.size(); ++s) {
          const Bounds& b = bounds.at(slot_rid[s]);
          std::vector<uint8_t>& row = rows_buf[s];
          for (int c = 0; c < cfg.cols; ++c) {
            if (row[c] == kUnset) {
              int64_t p = pos_queue[c] / slots;
              // NB: b.hi is htslib's exclusive bam_endpos but the test is
              // `p > hi`, reproducing the reference's off-by-one where the
              // one-past-the-end position reads as in-bounds GAP
              // (ref: generate.cpp:135, kept by the Python oracle)
              row[c] = (p < b.lo || p > b.hi) ? kUnknown : kGap;
            }
            if (!b.fwd) row[c] = static_cast<uint8_t>(row[c] + kStrandOffset);
          }
        }

        size_t pos_base = result.positions.size();
        result.positions.resize(pos_base + 2ul * cfg.cols);
        for (int c = 0; c < cfg.cols; ++c) {
          int64_t key = pos_queue[c];
          result.positions[pos_base + 2 * c] = key / slots;
          result.positions[pos_base + 2 * c + 1] = key % slots;
        }

        size_t mat_base = result.matrix.size();
        result.matrix.resize(mat_base +
                             static_cast<size_t>(cfg.rows) * cfg.cols);
        for (int r = 0; r < cfg.rows; ++r) {
          int rid = valid[rng.NextBelow(n_valid)];
          const std::vector<uint8_t>& row = rows_buf[rid_slot.at(rid)];
          std::copy(row.begin(), row.end(),
                    result.matrix.begin() + mat_base +
                        static_cast<size_t>(r) * cfg.cols);
        }
        result.n_windows += 1;
      }
      // slide by stride (empty valid set: skip but still slide)
      for (int s = 0; s < cfg.stride; ++s) {
        align_info.erase(pos_queue.front());
        pos_queue.pop_front();
      }
    }
  };

  for (int64_t rpos = lo; rpos < hi; ++rpos) {
    while (nxt < reads.size() && reads[nxt].pos <= rpos) active.push_back(nxt++);
    // compact: drop exhausted reads, preserving file order
    size_t w = 0;
    bool any_entry = false;
    for (size_t i = 0; i < active.size(); ++i) {
      const ReadInfo& r = reads[active[i]];
      int64_t col = rpos - r.pos;
      if (col >= static_cast<int64_t>(r.states.size())) continue;
      active[w++] = active[i];
      any_entry = true;
    }
    active.resize(w);
    if (!any_entry) {
      if (active.empty() && nxt >= reads.size()) break;
      continue;
    }
    if (rpos < start) continue;
    if (rpos >= end) break;

    for (size_t idx : active) {
      const ReadInfo& r = reads[idx];
      const ColState& st = r.states[static_cast<size_t>(rpos - r.pos)];
      if (st.is_refskip) continue;
      if (bounds.find(r.id) == bounds.end())
        bounds.emplace(r.id, Bounds{r.pos, r.ref_end, !r.reverse});

      int64_t base_key = key_of(rpos, 0);
      auto ai = align_info.find(base_key);
      if (ai == align_info.end()) {
        ai = align_info.emplace(base_key, ColInfo{}).first;
        pos_queue.push_back(base_key);
      }
      if (st.is_del) {
        ai->second.SetDefault(r.id, kGap);
      } else {
        ai->second.SetDefault(r.id, encode_base(r, st.qpos));
        int32_t n_ins = std::min(st.indel, cfg.max_ins);
        for (int32_t i = 1; i <= n_ins; ++i) {
          int64_t ikey = key_of(rpos, i);
          auto ii = align_info.find(ikey);
          if (ii == align_info.end()) {
            ii = align_info.emplace(ikey, ColInfo{}).first;
            pos_queue.push_back(ikey);
          }
          ii->second.SetDefault(r.id, encode_base(r, st.qpos + i));
        }
      }
    }
    emit_windows();
  }

  return result;
}

}  // namespace roko
