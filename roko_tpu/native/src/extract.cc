#include "extract.h"

#include <algorithm>
#include <deque>
#include <stdexcept>
#include <unordered_map>
#include <unordered_set>

namespace roko {

namespace {

constexpr uint8_t kGap = 4;
constexpr uint8_t kUnknown = 5;
constexpr uint8_t kStrandOffset = 6;
constexpr uint8_t kInvalid = 0xFF;

// BAM seq nibble -> encoded base, matching the oracle's nibble -> char ->
// CHAR_TO_CODE chain (roko_tpu/io/bam.py _SEQ_CODES + constants.py
// CHAR_TO_CODE; ref nibble decode: include/models.h:120-138). Ambiguity
// codes other than N are errors there, so kInvalid here.
constexpr uint8_t kNibbleToCode[16] = {
    kInvalid, 0,        1,        kInvalid,  // -, A, C, M
    2,        kInvalid, kInvalid, kInvalid,  // G, R, S, V
    3,        kInvalid, kInvalid, kInvalid,  // T, W, Y, H
    kInvalid, kInvalid, kInvalid, kUnknown,  // K, D, B, N
};

struct ColState {
  int32_t qpos;
  bool is_del;
  bool is_refskip;
  int32_t indel;  // >0 insertion after this column; <0 deletion; 0 none
};

struct ReadInfo {
  int id;
  int32_t pos;
  int32_t ref_end;   // exclusive (htslib bam_endpos)
  bool reverse;
  const std::vector<uint8_t>* seq_nib;
  std::vector<ColState> states;
};

// Mirrors roko_tpu/features/pileup.py::_column_states (htslib pileup
// semantics: indel flagged on the last column before an I/D op; D/N
// columns carry the qpos of the preceding aligned base).
std::vector<ColState> ColumnStates(const BamRecord& rec) {
  std::vector<ColState> states;
  int32_t qpos = 0;
  for (uint32_t c : rec.cigar) {
    uint32_t op = c & 0xF;
    int32_t length = static_cast<int32_t>(c >> 4);
    switch (op) {
      case 0:  // M
      case 7:  // =
      case 8:  // X
        for (int32_t i = 0; i < length; ++i)
          states.push_back({qpos + i, false, false, 0});
        qpos += length;
        break;
      case 1:  // I
        if (!states.empty()) states.back().indel = length;
        qpos += length;
        break;
      case 2:  // D
        if (!states.empty() && states.back().indel <= 0)
          states.back().indel = -length;
        for (int32_t i = 0; i < length; ++i)
          states.push_back({std::max(qpos - 1, 0), true, false, 0});
        break;
      case 3:  // N
        for (int32_t i = 0; i < length; ++i)
          states.push_back({std::max(qpos - 1, 0), true, true, 0});
        break;
      case 4:  // S
        qpos += length;
        break;
      default:  // H, P consume nothing
        break;
    }
  }
  return states;
}

// Draft FASTA char -> encoded base, matching constants.py CHAR_TO_CODE
// (same mapping as the reference's get_base: include/models.h:148-169).
uint8_t EncodeRefChar(char ch) {
  switch (ch) {
    case 'A': case 'a': return 0;
    case 'C': case 'c': return 1;
    case 'G': case 'g': return 2;
    case 'T': case 't': return 3;
    case 'N': case 'n': case '-': return kUnknown;
    case '*': return kGap;
    default:
      throw std::runtime_error("unexpected base in draft sequence");
  }
}

bool PassesFilter(const BamRecord& rec, const ExtractConfig& cfg) {
  if (rec.flag & cfg.filter_flag) return false;
  if (cfg.require_proper_pair && (rec.flag & 0x1) && !(rec.flag & 0x2))
    return false;
  if (rec.mapq < cfg.min_mapq) return false;
  return true;
}

}  // namespace

ExtractResult ExtractWindows(const std::string& bam_path,
                             const std::string& contig, int64_t start,
                             int64_t end, uint64_t seed,
                             const ExtractConfig& cfg,
                             const std::string& ref_seq, int64_t ref_off) {
  if (cfg.ref_rows < 0 || cfg.ref_rows > cfg.rows)
    throw std::runtime_error("ref_rows must be in [0, rows]");
  if (cfg.ref_rows > 0 &&
      (ref_off > start ||
       static_cast<int64_t>(ref_seq.size()) < end - ref_off))
    throw std::runtime_error(
        "ref_rows > 0 needs the draft sequence covering [start, end)");
  BamReader reader(bam_path);
  ExtractResult result;

  // storage owns the records; ReadInfo borrows seq_nib pointers, so it
  // must stay alive for the whole sweep
  std::vector<BamRecord> storage;
  std::vector<ReadInfo> reads;
  {
    std::vector<BamRecord> records = reader.Fetch(contig, start, end);
    storage.reserve(records.size());
    for (auto& rec : records) {
      if (!PassesFilter(rec, cfg)) continue;
      storage.push_back(std::move(rec));
    }
    int next_id = 0;
    reads.reserve(storage.size());
    for (auto& rec : storage) {
      ReadInfo info;
      info.id = next_id++;
      info.pos = rec.pos;
      info.ref_end = rec.ReferenceEnd();
      info.reverse = rec.IsReverse();
      info.seq_nib = &rec.seq_nib;
      info.states = ColumnStates(rec);
      reads.push_back(std::move(info));
    }
  }
  if (reads.empty()) return result;

  // generous output preallocation: growth reallocations re-copy the
  // whole accumulated matrix (tens of MB per 100 kb region). Insertion
  // columns can push the window count past span/stride, so reserve
  // with slack — reserve is a hint, not a cap.
  {
    const size_t est_windows =
        static_cast<size_t>((end - start) / cfg.stride + 2) * 5 / 4;
    result.positions.reserve(2ul * cfg.cols * est_windows);
    result.matrix.reserve(
        static_cast<size_t>(cfg.rows) * cfg.cols * est_windows);
  }

  const int slots = cfg.max_ins + 1;
  auto key_of = [slots](int64_t rpos, int ins) -> int64_t {
    return rpos * slots + ins;
  };

  SplitMix64 rng(seed);
  std::deque<int64_t> pos_queue;
  // (rpos, ins) -> per-read first-seen code list. The sweep visits each
  // (read, column) pair exactly once (one ColState per covered column),
  // so rids are unique per key by construction — a plain append matches
  // the oracle's dict setdefault without the O(coverage) membership
  // scan. The per-key code vectors are POOLED: a region touches
  // hundreds of thousands of keys, and allocating/destroying a short
  // vector per key was steady-state malloc churn in the r4 extraction
  // profile — recycled vectors keep their capacity instead.
  using Codes = std::vector<std::pair<int, uint8_t>>;  // (rid, code)
  std::vector<Codes> code_pool;
  std::vector<uint32_t> pool_free;
  auto pool_acquire = [&]() -> uint32_t {
    if (!pool_free.empty()) {
      uint32_t i = pool_free.back();
      pool_free.pop_back();
      return i;
    }
    code_pool.emplace_back();
    return static_cast<uint32_t>(code_pool.size() - 1);
  };
  std::unordered_map<int64_t, uint32_t> align_info;  // key -> pool index
  // rid -> (ref bounds, strand), recorded at first non-refskip entry.
  // rids are dense 0..n-1, so a flat array beats a hash map in the
  // per-column hot loop.
  struct Bounds {
    int32_t lo, hi;
    bool fwd;
  };
  std::vector<Bounds> bounds(reads.size());
  std::vector<bool> have_bounds(reads.size(), false);

  int64_t lo = reads.front().pos;
  for (const auto& r : reads) lo = std::min<int64_t>(lo, r.pos);
  int64_t hi = 0;
  for (const auto& r : reads)
    hi = std::max<int64_t>(hi, r.pos + static_cast<int64_t>(r.states.size()));

  std::vector<size_t> active;
  size_t nxt = 0;

  auto encode_base = [&](const ReadInfo& r, int32_t q) -> uint8_t {
    uint8_t code = kNibbleToCode[(*r.seq_nib)[q] & 0xF];
    if (code == kInvalid)
      throw std::runtime_error("unexpected base nibble in read sequence");
    return code;
  };

  // Reused per-window scratch: one dense row per read seen in the window,
  // built in a single pass over the columns (the per-sampled-read lazy
  // row construction the Python oracle uses is O(cols * coverage) per
  // sampled read; with 200 samples over ~coverage reads nearly every
  // read is materialised anyway, so batch-building is strictly cheaper).
  // All scratch persists ACROSS windows: the row vectors keep their
  // capacity (fresh per-window allocations were the top line of the r4
  // extraction profile), and rid->slot is a flat array over the dense
  // rid space reset via the touched list instead of a rebuilt hash map.
  constexpr uint8_t kUnset = 0xFE;
  constexpr int32_t kNoSlot = -1;
  std::vector<int32_t> rid_slot(reads.size(), kNoSlot);
  std::vector<int> slot_rid;
  std::vector<std::vector<uint8_t>> rows_buf;
  std::vector<bool> slot_valid;
  std::vector<int> valid;
  std::vector<uint8_t> ref_row;  // per-window draft row (ref_rows > 0)

  auto emit_windows = [&]() {
    while (static_cast<int>(pos_queue.size()) >= cfg.cols) {
      for (int rid : slot_rid) rid_slot[rid] = kNoSlot;
      slot_rid.clear();
      slot_valid.clear();
      size_t rows_used = 0;

      for (int c = 0; c < cfg.cols; ++c) {
        // .at(): every queued position must already own a pool slot
        // (enqueued together in the column sweep). operator[] would
        // default-insert index 0 on a broken invariant and silently
        // alias another column's codes; throwing is caught at the C-ABI
        // boundary and surfaced as a distinct error code instead.
        const Codes& codes = code_pool[align_info.at(pos_queue[c])];
        for (const auto& p : codes) {
          int32_t slot = rid_slot[p.first];
          if (slot == kNoSlot) {
            slot = static_cast<int32_t>(rows_used);
            rid_slot[p.first] = slot;
            slot_rid.push_back(p.first);
            if (rows_used == rows_buf.size())
              rows_buf.emplace_back(cfg.cols, kUnset);
            else
              rows_buf[rows_used].assign(cfg.cols, kUnset);
            ++rows_used;
            slot_valid.push_back(false);
          }
          rows_buf[slot][c] = p.second;
          if (p.second != kUnknown) slot_valid[slot] = true;
        }
      }

      // valid reads: any non-UNKNOWN code within the window, sorted by id
      valid.clear();
      for (size_t s = 0; s < slot_rid.size(); ++s)
        if (slot_valid[s]) valid.push_back(slot_rid[s]);
      std::sort(valid.begin(), valid.end());

      if (!valid.empty()) {
        const size_t n_valid = valid.size();
        // complete the rows: bounds rule for unset columns, strand offset
        for (size_t s = 0; s < rows_used; ++s) {
          const Bounds& b = bounds[slot_rid[s]];
          std::vector<uint8_t>& row = rows_buf[s];
          for (int c = 0; c < cfg.cols; ++c) {
            if (row[c] == kUnset) {
              int64_t p = pos_queue[c] / slots;
              // NB: b.hi is htslib's exclusive bam_endpos but the test is
              // `p > hi`, reproducing the reference's off-by-one where the
              // one-past-the-end position reads as in-bounds GAP
              // (ref: generate.cpp:135, kept by the Python oracle)
              row[c] = (p < b.lo || p > b.hi) ? kUnknown : kGap;
            }
            if (!b.fwd) row[c] = static_cast<uint8_t>(row[c] + kStrandOffset);
          }
        }

        size_t pos_base = result.positions.size();
        result.positions.resize(pos_base + 2ul * cfg.cols);
        for (int c = 0; c < cfg.cols; ++c) {
          int64_t key = pos_queue[c];
          result.positions[pos_base + 2 * c] = key / slots;
          result.positions[pos_base + 2 * c + 1] = key % slots;
        }

        // draft-base rows first (reference's REF_ROWS block,
        // generate.cpp:109-119): GAP at insertion slots, draft base
        // elsewhere, always forward-strand encoding
        if (cfg.ref_rows > 0) {
          ref_row.clear();
          for (int c = 0; c < cfg.cols; ++c) {
            int64_t key = pos_queue[c];
            ref_row.push_back(
                key % slots != 0
                    ? kGap
                    : EncodeRefChar(ref_seq[key / slots - ref_off]));
          }
          for (int r = 0; r < cfg.ref_rows; ++r)
            result.matrix.insert(result.matrix.end(), ref_row.begin(),
                                 ref_row.end());
        }

        // append row copies with insert (plain memcpy): resize would
        // zero-fill 18 kB per window only to overwrite it — the r4
        // profile put the sampling block at ~half of extraction time
        for (int r = cfg.ref_rows; r < cfg.rows; ++r) {
          int rid = valid[rng.NextBelow(n_valid)];
          const std::vector<uint8_t>& row = rows_buf[rid_slot[rid]];
          result.matrix.insert(result.matrix.end(), row.begin(), row.end());
        }
        result.n_windows += 1;
      }
      // slide by stride (empty valid set: skip but still slide)
      for (int s = 0; s < cfg.stride; ++s) {
        auto it = align_info.find(pos_queue.front());
        code_pool[it->second].clear();  // keep capacity for reuse
        pool_free.push_back(it->second);
        align_info.erase(it);
        pos_queue.pop_front();
      }
    }
  };

  for (int64_t rpos = lo; rpos < hi; ++rpos) {
    while (nxt < reads.size() && reads[nxt].pos <= rpos) active.push_back(nxt++);
    // compact: drop exhausted reads, preserving file order
    size_t w = 0;
    bool any_entry = false;
    for (size_t i = 0; i < active.size(); ++i) {
      const ReadInfo& r = reads[active[i]];
      int64_t col = rpos - r.pos;
      if (col >= static_cast<int64_t>(r.states.size())) continue;
      active[w++] = active[i];
      any_entry = true;
    }
    active.resize(w);
    if (!any_entry) {
      if (active.empty() && nxt >= reads.size()) break;
      continue;
    }
    if (rpos < start) continue;
    if (rpos >= end) break;

    // the base (ins=0) column key is shared by every read at this
    // rpos: resolve it at most once per rpos, not once per read
    // (lazily, so an all-refskip column still creates no key). Index,
    // not pointer — pool growth during insertion handling would
    // invalidate a pointer.
    constexpr uint32_t kNoIdx = ~0u;
    uint32_t base_idx = kNoIdx;
    for (size_t idx : active) {
      const ReadInfo& r = reads[idx];
      const ColState& st = r.states[static_cast<size_t>(rpos - r.pos)];
      if (st.is_refskip) continue;
      if (!have_bounds[r.id]) {
        bounds[r.id] = Bounds{r.pos, r.ref_end, !r.reverse};
        have_bounds[r.id] = true;
      }

      if (base_idx == kNoIdx) {
        int64_t base_key = key_of(rpos, 0);
        auto ai = align_info.find(base_key);
        if (ai == align_info.end()) {
          ai = align_info.emplace(base_key, pool_acquire()).first;
          pos_queue.push_back(base_key);
        }
        base_idx = ai->second;
      }
      if (st.is_del) {
        code_pool[base_idx].emplace_back(r.id, kGap);
      } else {
        code_pool[base_idx].emplace_back(r.id, encode_base(r, st.qpos));
        int32_t n_ins = std::min(st.indel, cfg.max_ins);
        for (int32_t i = 1; i <= n_ins; ++i) {
          int64_t ikey = key_of(rpos, i);
          auto ii = align_info.find(ikey);
          if (ii == align_info.end()) {
            ii = align_info.emplace(ikey, pool_acquire()).first;
            pos_queue.push_back(ikey);
          }
          code_pool[ii->second].emplace_back(r.id, encode_base(r, st.qpos + i));
        }
      }
    }
    emit_windows();
  }

  return result;
}

}  // namespace roko
