"""ctypes binding for the native extractor (no pybind11 in the image; the
C ABI + ctypes keeps the build a single g++ invocation)."""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional

import numpy as np

from roko_tpu.config import ReadFilterConfig, WindowConfig
from roko_tpu.features.extract import Window
from roko_tpu.native import build as _build


class _RokoResult(ctypes.Structure):
    _fields_ = [
        ("n_windows", ctypes.c_int64),
        ("positions", ctypes.POINTER(ctypes.c_int64)),
        ("matrix", ctypes.POINTER(ctypes.c_uint8)),
    ]


_lib: Optional[ctypes.CDLL] = None
_load_error: Optional[Exception] = None
_lib_lock = threading.Lock()


def _load() -> ctypes.CDLL:
    global _lib, _load_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            # don't re-run a failing g++ per region (thousands of calls)
            raise _load_error
        try:
            return _load_locked()
        except Exception as e:
            _load_error = e
            raise


def _load_locked() -> ctypes.CDLL:
    global _lib
    path = _build.ensure_built()
    lib = ctypes.CDLL(path)
    lib.roko_native_abi_version.restype = ctypes.c_int
    lib.roko_last_error.restype = ctypes.c_char_p
    lib.roko_extract_windows.restype = ctypes.c_int
    lib.roko_extract_windows.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_uint64,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_int,
        ctypes.c_char_p,  # ref_seq (NULL when ref_rows == 0)
        ctypes.c_int64,   # ref_len
        ctypes.c_int64,   # ref_off (absolute position of ref_seq[0])
        ctypes.c_int,     # ref_rows
        ctypes.POINTER(_RokoResult),
    ]
    lib.roko_free_result.argtypes = [ctypes.POINTER(_RokoResult)]
    lib.roko_align_counts.restype = ctypes.c_int
    lib.roko_align_counts.argtypes = [
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_char_p,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64),
    ]
    if lib.roko_native_abi_version() != 2:
        raise RuntimeError("native extractor ABI mismatch; rebuild")
    _lib = lib
    return lib


def is_available() -> bool:
    try:
        _load()
        return True
    except Exception:
        return False


def extract_windows_arrays(
    bam_path: str,
    contig: str,
    start: int,
    end: int,
    seed: int,
    window_cfg: Optional[WindowConfig] = None,
    filter_cfg: Optional[ReadFilterConfig] = None,
    ref_seq: Optional[str] = None,
    ref_seq_offset: int = 0,
):
    """Stacked form: (positions int64[N,cols,2], matrix uint8[N,rows,cols]).
    The preferred interface — the multiprocess pipeline ships these two
    contiguous buffers per region across the worker boundary instead of
    thousands of per-window arrays. ``ref_seq`` (draft contig bytes from
    absolute position ``ref_seq_offset``, covering at least
    ``[start, end)``) is required when ``window_cfg.ref_rows > 0``."""
    wcfg = window_cfg or WindowConfig()
    fcfg = filter_cfg or ReadFilterConfig()
    if wcfg.ref_rows > 0 and ref_seq is None:
        raise ValueError("ref_rows > 0 requires ref_seq")
    ref_b = ref_seq.encode() if (ref_seq and wcfg.ref_rows > 0) else None
    lib = _load()
    res = _RokoResult()
    rc = lib.roko_extract_windows(
        bam_path.encode(),
        contig.encode(),
        start,
        end,
        seed & (2**64 - 1),
        wcfg.rows,
        wcfg.cols,
        wcfg.stride,
        wcfg.max_ins,
        fcfg.min_mapq,
        fcfg.filter_flag,
        1 if fcfg.require_proper_pair else 0,
        ref_b,
        len(ref_b) if ref_b is not None else 0,
        ref_seq_offset,
        wcfg.ref_rows,
        ctypes.byref(res),
    )
    if rc != 0:
        msg = lib.roko_last_error().decode(errors="replace")
        raise RuntimeError(f"native extractor failed ({rc}): {msg}")
    try:
        n = int(res.n_windows)
        if n == 0:
            pos = np.empty((0, wcfg.cols, 2), np.int64)
            mat = np.empty((0, wcfg.rows, wcfg.cols), np.uint8)
        else:
            pos = np.ctypeslib.as_array(res.positions, shape=(n, wcfg.cols, 2)).copy()
            mat = np.ctypeslib.as_array(
                res.matrix, shape=(n, wcfg.rows, wcfg.cols)
            ).copy()
    finally:
        lib.roko_free_result(ctypes.byref(res))
    return pos, mat


def align_counts(a: bytes, b: bytes, pad: int, max_cells: int):
    """Banded global alignment op counts for the assess tool's segment
    hot loop: returns (match, sub, ins, del, hit_band_edge). Raises
    MemoryError when band x length exceeds ``max_cells`` (the caller
    widens the band in steps, so this bounds the retry cost)."""
    lib = _load()
    out = (ctypes.c_int64 * 8)()
    rc = lib.roko_align_counts(a, len(a), b, len(b), pad, max_cells, out)
    if rc == 3:
        raise MemoryError("alignment working set exceeds max_cells")
    if rc != 0:
        msg = lib.roko_last_error().decode(errors="replace")
        raise RuntimeError(f"native aligner failed ({rc}): {msg}")
    return out[0], out[1], out[2], out[3], bool(out[4])


def extract_windows(
    bam_path: str,
    contig: str,
    start: int,
    end: int,
    seed: int,
    window_cfg: Optional[WindowConfig] = None,
    filter_cfg: Optional[ReadFilterConfig] = None,
    ref_seq: Optional[str] = None,
    ref_seq_offset: int = 0,
) -> List[Window]:
    """Native equivalent of roko_tpu.features.extract.extract_windows;
    bit-identical output (tests/test_native.py)."""
    pos, mat = extract_windows_arrays(
        bam_path, contig, start, end, seed, window_cfg, filter_cfg,
        ref_seq, ref_seq_offset,
    )
    return [
        Window(positions=pos[i], matrix=mat[i]) for i in range(pos.shape[0])
    ]
