"""Native (C++) host-side extractor: BGZF/BAM I/O, pileup engine, and the
200x90 window tensorizer, compiled to a C-ABI shared library and bound
via ctypes. The Python implementation in roko_tpu/features/ is the
semantic oracle; this package is the production hot path on the TPU-VM
host (SURVEY.md §2 "Native components" note)."""
