"""Persistent polishing service (docs/SERVING.md).

The batch CLI path (`cli.py` -> `infer.run_inference`) pays model load +
XLA compile on every invocation. This package keeps one warm
:class:`~roko_tpu.serve.session.PolishSession` resident — params loaded
once, the predict step pre-compiled for a small ladder of padded batch
sizes — and puts a dynamic micro-batcher plus a stdlib HTTP front end
over it, the structure LLM-serving stacks use to turn one jit'd step
into a service (PAPERS.md: t5x arxiv 2203.17189; dynamic batching of
heterogeneous requests per Ragged Paged Attention, arxiv 2604.15464).

Modules:

- ``session``  — warm params + shape-ladder predict dispatch, recompile-free
- ``batcher``  — bounded-queue dynamic micro-batching with a latency
  deadline and explicit backpressure
- ``metrics``  — Prometheus-style text counters over
  :class:`roko_tpu.utils.profiling.StageTimer`
- ``server``   — ``ThreadingHTTPServer`` front end
  (``POST /polish``, ``GET /healthz``, ``GET /metrics``)
- ``client``   — stdlib urllib client used by tests and ``tools/``
"""

from roko_tpu.serve.batcher import Backpressure, MicroBatcher
from roko_tpu.serve.client import PolishClient, ServerBusy
from roko_tpu.serve.metrics import ServeMetrics
from roko_tpu.serve.server import drain, make_server, serve_forever
from roko_tpu.serve.session import PolishSession

__all__ = [
    "Backpressure",
    "MicroBatcher",
    "PolishClient",
    "PolishSession",
    "ServeMetrics",
    "ServerBusy",
    "drain",
    "make_server",
    "serve_forever",
]
