"""Persistent polishing service (docs/SERVING.md).

The batch CLI path (`cli.py` -> `infer.run_inference`) pays model load +
XLA compile on every invocation. This package keeps one warm
:class:`~roko_tpu.serve.session.PolishSession` resident — params loaded
once, the predict step pre-compiled for a small ladder of padded batch
sizes — and puts a dynamic micro-batcher plus a stdlib HTTP front end
over it, the structure LLM-serving stacks use to turn one jit'd step
into a service (PAPERS.md: t5x arxiv 2203.17189; dynamic batching of
heterogeneous requests per Ragged Paged Attention, arxiv 2604.15464).

Modules:

- ``session``  — warm params + shape-ladder predict dispatch, recompile-free
- ``batcher``  — bounded-queue dynamic micro-batching with a latency
  deadline and explicit backpressure (the "deadline" policy)
- ``scheduler`` — continuous ragged batching: windows from many
  requests packed densely into ladder-rung device steps, freed slots
  refilled as requests complete (the default "continuous" policy)
- ``metrics``  — Prometheus-style text counters over
  :class:`roko_tpu.utils.profiling.StageTimer`
- ``server``   — ``ThreadingHTTPServer`` front end
  (``POST /polish``, ``GET /healthz``, ``GET /metrics``, plus the
  observability surfaces ``GET /tracez`` and ``POST /profilez`` —
  request tracing, mergeable histograms, and the structured event
  plane live in :mod:`roko_tpu.obs`, docs/OBSERVABILITY.md)
- ``client``   — stdlib urllib client used by tests and ``tools/``
- ``fleet``    — multi-worker tier: process supervision (heartbeats,
  restart backoff, restart-storm breaker) + failover routing
- ``supervisor`` — the ``--workers N`` front end over a ``fleet``
  (admission control, rolling SIGTERM drain, metrics aggregation)
- ``registry`` — named model versions: AOT bundle digest + params
  manifest, written by ``roko-tpu compile --register``
- ``rollout``  — health-gated zero-downtime rolling weight rollout
  with automatic rollback and a crash-consistent journal
"""

from roko_tpu.serve.batcher import Backpressure, MicroBatcher, QuotaExceeded
from roko_tpu.serve.client import (
    FleetDraining,
    PolishClient,
    QuotaExceededBusy,
    ServerBusy,
    ServiceUnavailable,
)
from roko_tpu.serve.fleet import Fleet, WorkerHandle, WorkerLaunchSpec
from roko_tpu.serve.metrics import ServeMetrics
from roko_tpu.serve.registry import (
    RegistryError,
    RegistryMismatch,
    list_models,
    register_model,
    resolve_model,
)
from roko_tpu.serve.rollout import (
    RolloutController,
    RolloutJournal,
    recover_rollout,
)
from roko_tpu.serve.scheduler import ContinuousBatcher, RaggedBatcher
from roko_tpu.serve.server import drain, make_server, serve_forever
from roko_tpu.serve.session import PolishSession
from roko_tpu.serve.supervisor import (
    Autoscaler,
    make_front_server,
    run_supervisor,
)

__all__ = [
    "Autoscaler",
    "Backpressure",
    "ContinuousBatcher",
    "Fleet",
    "FleetDraining",
    "MicroBatcher",
    "PolishClient",
    "PolishSession",
    "QuotaExceeded",
    "QuotaExceededBusy",
    "RaggedBatcher",
    "RegistryError",
    "RegistryMismatch",
    "RolloutController",
    "RolloutJournal",
    "ServeMetrics",
    "ServerBusy",
    "ServiceUnavailable",
    "WorkerHandle",
    "WorkerLaunchSpec",
    "drain",
    "list_models",
    "make_front_server",
    "make_server",
    "recover_rollout",
    "register_model",
    "resolve_model",
    "run_supervisor",
    "serve_forever",
]
