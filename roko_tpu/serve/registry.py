"""Model registry: named model versions for the serving fleet
(docs/SERVING.md "Model lifecycle").

A *model version* is the pair the fleet actually runs: an AOT bundle
(the compiled predict program, identified by its sha256 digest —
``compile/bundle.py``) plus the params it executes (identified by a
sha256-per-file manifest, the same checkpoint-identity discipline PR 5's
``roko_manifest.json`` applies to training checkpoints, following
t5x/seqio practice). The registry is a directory of one JSON entry per
name::

    <registry>/<name>.json
        {"name", "bundle_dir", "bundle_digest",
         "params_path", "params_manifest": {"tree_digest", "files"},
         "model": {kind, compute_dtype, quantize}, "registered_unix"}

written atomically by ``roko-tpu compile --register NAME`` and listed by
``tools/cache_probe.py --registry``. Resolution RE-VERIFIES both halves
against the disk before a rollout may use them: a bundle whose manifest
digest drifted, or params whose bytes no longer hash to the registered
manifest, refuse loudly with the differing detail named
(:class:`RegistryMismatch`) — the same refuse-don't-guess contract as
``BundleMismatch`` and the resume journal. A half-written entry can
never resolve (atomic rename), and a resolved entry pins exactly which
bytes every rolled worker will run.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional

from roko_tpu.compile.bundle import read_manifest

Log = Callable[[str], None]

_FORMAT = 1

#: registry entry names double as filenames and metric label values
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class RegistryError(RuntimeError):
    """A registry operation cannot proceed (unknown name, bad name,
    re-register without --force, unreadable entry)."""


class RegistryMismatch(RegistryError):
    """A registered version no longer matches the bytes on disk —
    rolling a fleet onto it would serve an unaudited model. Refused,
    never served on faith."""


def default_registry_dir() -> str:
    """Layering mirrors the compile cache: ``ROKO_REGISTRY`` env >
    config/CLI value > ``~/.cache/roko-tpu/registry``."""
    env = os.environ.get("ROKO_REGISTRY")
    if env:
        return env
    return os.path.join(
        os.path.expanduser("~"), ".cache", "roko-tpu", "registry"
    )


def resolve_registry_dir(explicit: Optional[str] = None) -> str:
    env = os.environ.get("ROKO_REGISTRY")
    return env or explicit or default_registry_dir()


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def params_manifest(params_path: str) -> Dict[str, Any]:
    """``{"tree_digest", "files": {rel: {sha256, bytes}}}`` over a
    checkpoint directory (or a single params file — torch ``.pth``,
    saved arrays): the PR 5 checkpoint-manifest discipline applied to
    whatever ``roko-tpu serve MODEL`` accepts."""
    entries: Dict[str, Dict[str, Any]] = {}
    if os.path.isfile(params_path):
        entries[os.path.basename(params_path)] = {
            "sha256": _sha256_file(params_path),
            "bytes": os.path.getsize(params_path),
        }
    elif os.path.isdir(params_path):
        for dirpath, dirnames, filenames in os.walk(params_path):
            dirnames.sort()
            for name in sorted(filenames):
                path = os.path.join(dirpath, name)
                rel = os.path.relpath(path, params_path)
                entries[rel] = {
                    "sha256": _sha256_file(path),
                    "bytes": os.path.getsize(path),
                }
    else:
        raise RegistryError(
            f"params path {params_path!r} does not exist; a registered "
            "version must pin the exact checkpoint bytes it serves"
        )
    if not entries:
        raise RegistryError(
            f"params path {params_path!r} is empty; nothing to pin"
        )
    lines = [f"{rel}:{entries[rel]['sha256']}" for rel in sorted(entries)]
    return {
        "tree_digest": hashlib.sha256("\n".join(lines).encode()).hexdigest(),
        "files": entries,
    }


def _verify_params(params_path: str, manifest: Dict[str, Any]) -> None:
    """Re-hash the params against the registered manifest; any drift —
    missing, truncated, mutated, or ADDED file — raises
    RegistryMismatch. Extra files matter as much as changed ones: the
    checkpoint loader picks the best/latest step dynamically across
    whatever the directory holds, so an unregistered step dir dropped
    in later would ship unaudited bytes through a 'verified' rollout."""
    want = manifest.get("files", {})
    if os.path.isdir(params_path):
        have = set()
        for dirpath, dirnames, filenames in os.walk(params_path):
            dirnames.sort()
            for name in sorted(filenames):
                have.add(
                    os.path.relpath(
                        os.path.join(dirpath, name), params_path
                    )
                )
        extra = sorted(have - set(want))
        if extra:
            raise RegistryMismatch(
                f"registered params dir {params_path!r} grew "
                f"{len(extra)} file(s) not in the manifest (e.g. "
                f"{extra[0]!r}) — the loader would pick checkpoint "
                "steps dynamically, so unaudited bytes could ship; "
                "re-register the version"
            )
    root = params_path if os.path.isdir(params_path) else os.path.dirname(
        params_path
    )
    for rel, entry in sorted(want.items()):
        path = (
            params_path
            if os.path.isfile(params_path)
            and rel == os.path.basename(params_path)
            else os.path.join(root, rel)
        )
        if not os.path.isfile(path):
            raise RegistryMismatch(
                f"registered params file {rel!r} is missing under "
                f"{params_path!r}"
            )
        if os.path.getsize(path) != entry["bytes"]:
            raise RegistryMismatch(
                f"registered params file {rel!r} is "
                f"{os.path.getsize(path)} bytes, manifest says "
                f"{entry['bytes']} — checkpoint changed since registration"
            )
        if _sha256_file(path) != entry["sha256"]:
            raise RegistryMismatch(
                f"registered params file {rel!r} sha256 mismatch — "
                "checkpoint changed since registration; re-register "
                "the version"
            )


def _entry_path(registry_dir: str, name: str) -> str:
    if not _NAME_RE.match(name or ""):
        raise RegistryError(
            f"bad model version name {name!r}: use letters, digits, "
            "'.', '_', '-' (max 64 chars, no leading punctuation)"
        )
    return os.path.join(registry_dir, f"{name}.json")


def register_model(
    registry_dir: str,
    name: str,
    bundle_dir: str,
    params_path: Optional[str] = None,
    *,
    force: bool = False,
    log: Log = print,
) -> Dict[str, Any]:
    """Pin (bundle digest, params manifest) under ``name``. The bundle
    must be a verified export (its manifest carries the digest);
    ``params_path`` is optional — a bundle-only version rolls out
    against the fleet's incumbent checkpoint. Re-registering an
    existing name refuses unless ``force`` (an operator overwriting a
    version under a fleet's feet should have to say so)."""
    path = _entry_path(registry_dir, name)
    manifest = read_manifest(bundle_dir)  # refuses a non-bundle loudly
    entry: Dict[str, Any] = {
        "format": _FORMAT,
        "name": name,
        "bundle_dir": os.path.abspath(bundle_dir),
        "bundle_digest": manifest["digest"],
        "rungs": manifest.get("rungs", []),
        "model": (manifest.get("identity") or {}).get("model", {}),
        "params_path": (
            os.path.abspath(params_path) if params_path else None
        ),
        "params_manifest": (
            params_manifest(params_path) if params_path else None
        ),
        "registered_unix": int(time.time()),
    }
    if os.path.exists(path) and not force:
        try:
            with open(path) as f:
                have = json.load(f)
        except (OSError, ValueError) as e:
            raise RegistryError(
                f"existing registry entry {path!r} is unreadable ({e}); "
                "pass --force to overwrite it"
            ) from None
        same = (
            have.get("bundle_digest") == entry["bundle_digest"]
            and (have.get("params_manifest") or {}).get("tree_digest")
            == (entry["params_manifest"] or {}).get("tree_digest")
            and have.get("params_path") == entry["params_path"]
        )
        if not same:
            raise RegistryError(
                f"model version {name!r} is already registered with a "
                "different bundle/params identity; pick a new name or "
                "pass --force to overwrite"
            )
    os.makedirs(registry_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(entry, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    pdigest = (entry["params_manifest"] or {}).get("tree_digest", "")
    log(
        f"registry: {name} -> bundle {entry['bundle_digest'][:12]} "
        f"params {pdigest[:12] or '(incumbent)'} ({path})"
    )
    return entry


def resolve_model(
    registry_dir: str, name: str, *, verify: bool = True
) -> Dict[str, Any]:
    """Load ``name``'s entry; with ``verify`` (the default, and what
    every rollout uses) re-check the on-disk bundle digest and re-hash
    the params against the registered manifest first."""
    path = _entry_path(registry_dir, name)
    try:
        with open(path) as f:
            entry = json.load(f)
    except FileNotFoundError:
        known = ", ".join(sorted(e["name"] for e in list_models(registry_dir)))
        raise RegistryError(
            f"no model version {name!r} in registry {registry_dir!r}"
            + (f" (known: {known})" if known else " (registry is empty)")
            + "; register one with `roko-tpu compile --register NAME`"
        ) from None
    except ValueError as e:
        raise RegistryError(
            f"registry entry {path!r} is unreadable ({e}); re-register"
        ) from None
    if verify:
        manifest = read_manifest(entry["bundle_dir"])
        if manifest.get("digest") != entry.get("bundle_digest"):
            raise RegistryMismatch(
                f"model version {name!r} pins bundle digest "
                f"{entry.get('bundle_digest', '?')[:12]} but "
                f"{entry['bundle_dir']!r} now holds "
                f"{manifest.get('digest', '?')[:12]} — the bundle was "
                "re-exported since registration; re-register the version"
            )
        if entry.get("params_path"):
            _verify_params(entry["params_path"], entry["params_manifest"])
    return entry


def list_models(registry_dir: str) -> List[Dict[str, Any]]:
    """Every readable entry, sorted by name (unreadable/half-written
    files are skipped — listing is an inventory, not a gate)."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(registry_dir))
    except OSError:
        return out
    for fname in names:
        if not fname.endswith(".json"):
            continue
        try:
            with open(os.path.join(registry_dir, fname)) as f:
                entry = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(entry, dict) and entry.get("name"):
            out.append(entry)
    return out
