"""Continuous ragged batching: device batch shape decoupled from
request boundaries (docs/SERVING.md "Continuous batching").

The deadline coalescer (``serve/batcher.py``) batches at REQUEST
granularity: a request's windows travel together, so a 4-window request
behind a 512-window one waits for the whole large dispatch (head-of-line
blocking), and a partial batch pads all the way up to the next ladder
rung (device cycles burned on zeros). :class:`ContinuousBatcher` takes
the TPU-native idiom from Ragged Paged Attention (PAPERS.md): treat the
precompiled ladder rungs as a rolling pool of WINDOW SLOTS, pack windows
from many requests densely into each device step via a per-request
segment vector, and slot newly arrived requests into freed capacity the
moment earlier requests' windows complete — requests finish
incrementally across steps, and batch shape is whatever keeps the rungs
full.

Scheduling policy, applied each cycle over the queued-window backlog:

1. **full top rung** — backlog >= the top rung dispatches a completely
   full top-rung batch (the steady-state path; zero padding);
2. **exact/near fit** — otherwise the backlog pads to the smallest rung
   that fits, but ONLY when it fills at least ``rung_upgrade_fill`` of
   it (rung-upgrade hysteresis — padding efficiency over batch size);
3. **full smaller rung** — else, if a smaller rung can be filled
   COMPLETELY, dispatch that and leave the remainder queued (its age
   keeps counting);
4. **age flush** — else wait for arrivals until the oldest queued window
   is ``max_queue_age_ms`` old, then dispatch padded (latency floor for
   sparse traffic, the continuous analogue of ``max_delay_ms``).

Slots inside a step are granted FAIR-SHARE over requests in arrival
order: every request with unpacked windows gets ~k/active slots per
step, so a small request entering while a huge one is mid-flight packs
into the very next step, and a huge request under a sustained stream of
small ones still progresses every step — starvation-free in both
directions (tests/test_scheduler.py pins both).

The slot pool denominates in the session's GLOBAL ladder (docs/
SERVING.md "Mesh-sharded sessions"): on an N-device dp mesh the auto
ladder resolves per-device base rungs x N, so one step is
``rung * n_devices`` window slots and the slot-slab, occupancy gauge,
and Retry-After throughput EMA all scale with the mesh automatically.
The streaming polish pipeline (pipeline/stream.py) drives this same
class — serve and ``roko-tpu polish`` share ONE batching plane and one
``padding_efficiency`` metric.

All dispatches go through ``PolishSession.predict``, so only ladder
shapes ever reach the device — the zero-steady-state-recompile contract
is untouched. Backpressure is explicit (:class:`Backpressure`, mapped
to 503 by the HTTP layer) with a ``Retry-After`` computed from the live
backlog and the scheduler's observed windows/sec — not the deadline
batcher's fixed queue-drain guess (ISSUE satellite; the same stale-hint
failure shape PR 4 fixed for warming).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from roko_tpu.config import DEFAULT_TENANT, TenantConfig
from roko_tpu.resilience import CircuitBreaker
from roko_tpu.serve.batcher import (
    _REQUEST_ERRORS,
    Backpressure,
    PredictFuture,
    QuotaExceeded,
)
from roko_tpu.serve.metrics import ServeMetrics
from roko_tpu.serve.session import PolishSession

#: Retry-After clamp for the computed hint: never promise a sub-100 ms
#: poll loop, never more than the breaker-reset order of magnitude
_RETRY_AFTER_MIN_S = 0.1
_RETRY_AFTER_MAX_S = 30.0

#: EMA decay for the observed dispatch throughput (windows/sec) behind
#: the Retry-After estimate — a few dispatches of history, quick to
#: track load shifts
_THROUGHPUT_BETA = 0.7


class _Slot:
    """One submitted request riding the slot pool: its windows, the
    incrementally filled prediction buffer, and pack/fill cursors.
    ``next`` advances as windows are packed into device steps (may take
    many steps); ``filled`` as their predictions scatter back. The
    future resolves when every window is filled."""

    __slots__ = (
        "x", "preds", "next", "filled", "done", "error", "t_submit",
        "trace", "tenant",
    )

    def __init__(self, x: np.ndarray, trace=None, tenant: str = DEFAULT_TENANT):
        self.x = x
        self.preds = np.empty((x.shape[0], x.shape[2]), np.int32)
        self.next = 0       # windows handed to a device step so far
        self.filled = 0     # windows whose predictions are back
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        #: optional per-request obs.trace.RequestTrace (queue-wait /
        #: pack / device-step / scatter spans — docs/OBSERVABILITY.md)
        self.trace = trace
        #: tenant id for deficit-round-robin slot granting + quotas
        self.tenant = tenant

    @property
    def n(self) -> int:
        return self.x.shape[0]


#: a planned device step: (slot, request-window offset, count, batch
#: offset) spans — the per-request segment/index vector of one packed
#: batch
Span = Tuple[_Slot, int, int, int]


class ContinuousBatcher:
    """Drop-in alternative to :class:`~roko_tpu.serve.batcher.
    MicroBatcher` (same ``submit``/``predict``/``stop`` surface, same
    :class:`Backpressure`/:class:`PredictFuture` types) scheduling at
    WINDOW granularity instead of request granularity."""

    #: policy name reported in /healthz (``ServeConfig.batching`` value
    #: that selects this class in ``make_server``)
    BATCHING_MODE = "continuous"

    def __init__(
        self,
        session: PolishSession,
        *,
        max_queue: Optional[int] = None,
        max_queue_age_ms: Optional[float] = None,
        rung_upgrade_fill: Optional[float] = None,
        retry_after_s: Optional[float] = None,
        metrics: Optional[ServeMetrics] = None,
        breaker: Optional[CircuitBreaker] = None,
        tenants: Optional[Tuple[TenantConfig, ...]] = None,
        start: bool = True,
    ):
        serve_cfg = session.cfg.serve
        self.session = session
        self.breaker = breaker
        self.metrics = metrics
        self.max_queue = serve_cfg.max_queue if max_queue is None else max_queue
        self.max_queue_age_s = (
            serve_cfg.max_queue_age_ms
            if max_queue_age_ms is None
            else max_queue_age_ms
        ) / 1e3
        self.rung_upgrade_fill = (
            serve_cfg.rung_upgrade_fill
            if rung_upgrade_fill is None
            else rung_upgrade_fill
        )
        #: static floor for the Retry-After hint, used verbatim until the
        #: first dispatch teaches the scheduler its throughput
        self.base_retry_after_s = (
            serve_cfg.retry_after_s if retry_after_s is None else retry_after_s
        )
        #: requests with windows not yet packed into a device step,
        #: arrival order (the admission bound counts THESE — a fully
        #: packed request occupies device steps, not queue capacity)
        self._pool: List[_Slot] = []
        self._cv = threading.Condition()
        self._running = False
        self._stopped = False
        self._thread: Optional[threading.Thread] = None
        #: reusable top-rung slot slab: spans copy into it densely each
        #: step, so steady state allocates nothing per dispatch
        self._slab: Optional[np.ndarray] = None
        #: device steps dispatched so far (trace step ids) and the
        #: bounded rung history the /tracez scheduler snapshot serves
        self._steps = 0
        self._rung_history: deque = deque(maxlen=64)
        #: live requests (submitted, not yet complete) keyed by id() —
        #: the /tracez in-flight segment view; removal on completion,
        #: error, and stop keeps it bounded
        self._live: Dict[int, _Slot] = {}
        # derived from config, not the session's private attribute, so
        # session stand-ins (tests, tools) need only carry a cfg
        w = session.cfg.model
        self._window_shape = getattr(
            session, "_window_shape", (w.window_rows, w.window_cols)
        )
        self._ema_wps: Optional[float] = None
        #: tenant fair-share state (docs/SERVING.md "Multi-tenant &
        #: elastic fleet"): the config table (unlisted tenants default
        #: to weight 1, no caps), the DRR credit counters the slot-grant
        #: loop spends, and a per-tenant drain-rate EMA feeding the
        #: per-tenant Retry-After hint
        table = serve_cfg.tenants if tenants is None else tenants
        self._tenant_cfg: Dict[str, TenantConfig] = {
            t.name: t for t in table
        }
        self._deficit: Dict[str, float] = {}
        self._tenant_wps: Dict[str, float] = {}
        if metrics is not None:
            metrics.queue_depth = lambda: len(self._pool)
            metrics.queue_windows = self.backlog_windows
            metrics.occupancy = self.occupancy
            metrics.tenant_backlogs = self.tenant_backlogs
        if start:
            self.start()

    # -- observation ---------------------------------------------------------

    def backlog_windows(self) -> int:
        """Windows queued but not yet packed into a device step."""
        with self._cv:
            return sum(s.n - s.next for s in self._pool)

    def occupancy(self) -> float:
        """Queued-window backlog as a fraction of one top-rung step —
        instantaneous demand vs one step of device capacity (the
        ``roko_serve_scheduler_occupancy`` gauge; >1 means the next
        step is already oversubscribed)."""
        return self.backlog_windows() / self.session.ladder[-1]

    def tenant_backlogs(self) -> Dict[str, int]:
        """Queued-not-yet-packed windows per tenant — the healthz
        ``tenants`` block and the ``roko_serve_tenant_backlog`` gauge
        (the fleet derives per-tenant Retry-After from these)."""
        out: Dict[str, int] = {}
        with self._cv:
            for s in self._pool:
                out[s.tenant] = out.get(s.tenant, 0) + (s.n - s.next)
        return out

    def _tenant_weight(self, tenant: str) -> float:
        cfg = self._tenant_cfg.get(tenant)
        return cfg.weight if cfg is not None else 1.0

    def snapshot(self) -> Dict[str, Any]:
        """The live scheduler state ``GET /tracez`` serves beside the
        trace ring (docs/OBSERVABILITY.md): queued-window backlog,
        in-flight request segments (windows packed vs filled per live
        request), the observed throughput EMA, and the bounded
        rung-dispatch history."""
        with self._cv:
            live = list(self._live.values())
            backlog = sum(s.n - s.next for s in self._pool)
            history = list(self._rung_history)
            ema = self._ema_wps
            steps = self._steps
            tenant_backlog: Dict[str, int] = {}
            tenant_inflight: Dict[str, int] = {}
            for s in self._pool:
                tenant_backlog[s.tenant] = (
                    tenant_backlog.get(s.tenant, 0) + (s.n - s.next)
                )
            for s in self._live.values():
                tenant_inflight[s.tenant] = (
                    tenant_inflight.get(s.tenant, 0) + 1
                )
            tenants = {
                t: {
                    "backlog_windows": tenant_backlog.get(t, 0),
                    "inflight": tenant_inflight.get(t, 0),
                    "deficit": round(self._deficit.get(t, 0.0), 4),
                    "weight": self._tenant_weight(t),
                    "ema_windows_per_s": (
                        round(self._tenant_wps[t], 2)
                        if t in self._tenant_wps else None
                    ),
                }
                for t in sorted(
                    set(tenant_backlog) | set(tenant_inflight)
                    | set(self._deficit)
                )
            }
        return {
            "mode": self.BATCHING_MODE,
            "backlog_windows": backlog,
            "occupancy": round(backlog / self.session.ladder[-1], 4),
            "steps": steps,
            "ema_windows_per_s": round(ema, 2) if ema else None,
            "ladder": list(self.session.ladder),
            "in_flight": [
                {
                    "request_id": (
                        s.trace.request_id if s.trace is not None else None
                    ),
                    "windows": s.n,
                    "packed": s.next,
                    "filled": s.filled,
                    "age_s": round(time.perf_counter() - s.t_submit, 4),
                }
                for s in live
            ],
            "tenants": tenants,
            "rung_history": history,
        }

    @property
    def retry_after_s(self) -> float:
        """Live Retry-After hint: the queued backlog divided by the
        observed dispatch throughput (EMA windows/sec), clamped — a
        rejected client is told when capacity will actually free up,
        not the deadline batcher's fixed 1 s queue-drain guess. Before
        any dispatch has calibrated the throughput, the configured
        static value is all there is."""
        with self._cv:
            backlog = sum(s.n - s.next for s in self._pool)
            wps = self._ema_wps
        if not wps or wps <= 0:
            return self.base_retry_after_s
        # +1 top rung: even an empty queue waits out the step in flight
        est = (backlog + self.session.ladder[-1]) / wps
        return min(_RETRY_AFTER_MAX_S, max(_RETRY_AFTER_MIN_S, est))

    def tenant_retry_after_s(self, tenant: Optional[str] = None) -> float:
        """Retry-After from ONE tenant's backlog and ITS observed drain
        rate (ISSUE satellite): an interactive tenant rejected while a
        bulk tenant holds the global queue is told its own short wait,
        not the bulk tenant's. Falls back to the global hint when the
        tenant has no drain history yet."""
        if not tenant:
            return self.retry_after_s
        with self._cv:
            backlog = sum(
                s.n - s.next for s in self._pool if s.tenant == tenant
            )
            wps = self._tenant_wps.get(tenant)
            active = {s.tenant for s in self._pool} | {tenant}
        if not wps or wps <= 0:
            return self.retry_after_s
        # the tenant's fair slice of the step in flight stands in for
        # the global hint's +1 top rung
        wsum = sum(self._tenant_weight(t) for t in active)
        slice_ = self.session.ladder[-1] * self._tenant_weight(tenant) / wsum
        est = (backlog + slice_) / wps
        return min(_RETRY_AFTER_MAX_S, max(_RETRY_AFTER_MIN_S, est))

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="roko-continuous-batcher", daemon=True
        )
        self._thread.start()

    def scheduler_alive(self) -> bool:
        """True while the scheduling thread can still complete futures —
        callers that block on a future without their own deadline (the
        streaming polish pipeline) poll this instead of guessing a
        wall-clock bound for work whose step count they cannot know."""
        thread = self._thread
        return bool(thread is not None and thread.is_alive())

    def stop(self, timeout: float = 5.0) -> None:
        """Stop the scheduler: the worker finishes the device step in
        flight (its windows scatter back), then every request that is
        not yet complete — queued OR mid-flight across steps — fails
        loudly with "batcher stopped" instead of stranding its future.
        The server's graceful drain orders this AFTER the in-flight
        HTTP handlers finish, so a clean drain never hits the failure
        path (docs/SERVING.md "Failure handling")."""
        with self._cv:
            self._stopped = True
            self._running = False
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self._fail_incomplete()

    def _fail_incomplete(self) -> None:
        with self._cv:
            pool, self._pool = self._pool, []
            live, self._live = list(self._live.values()), {}
        for slot in {id(s): s for s in pool + live}.values():
            if not slot.done.is_set():
                slot.error = RuntimeError("batcher stopped")
                slot.done.set()

    # -- client side ---------------------------------------------------------

    def submit(
        self, x: np.ndarray, trace=None, tenant: Optional[str] = None
    ) -> PredictFuture:
        """Admit one window batch into the slot pool; raises
        :class:`Backpressure` (with the computed Retry-After) when the
        pool is at capacity, :class:`QuotaExceeded` (mapped to 429)
        when the TENANT's own queue/inflight cap is hit, and
        ``ValueError`` on bad window geometry — validated HERE so a
        malformed request can never poison the shared device step it
        would have been packed into (the deadline batcher fails a whole
        coalesced batch on one bad member; dense packing must not)."""
        if self._stopped:
            raise RuntimeError("batcher stopped")
        x = np.ascontiguousarray(x, dtype=np.uint8)
        if x.ndim != 3 or x.shape[1:] != self._window_shape:
            raise ValueError(
                f"windows shaped {x.shape}, want (n,) + "
                f"{self._window_shape}"
            )
        tenant = tenant or DEFAULT_TENANT
        slot = _Slot(x, trace, tenant)
        if slot.n == 0:
            # nothing to schedule: complete immediately (the empty reply
            # is still well-formed). Decided BEFORE the breaker check —
            # a dispatch-free request must never claim (and then leak)
            # the breaker's single half-open probe slot.
            slot.done.set()
            if self.metrics is not None:
                self.metrics.inc("requests")
            return PredictFuture(slot, self.metrics)
        if self.breaker is not None and not self.breaker.allow():
            if self.metrics is not None:
                self.metrics.inc("rejected")
            raise Backpressure(
                max(self.breaker.retry_after_s(), self.base_retry_after_s),
                reason="circuit breaker open (device failing)",
            )
        with self._cv:
            if self._stopped:
                raise RuntimeError("batcher stopped")
            tcfg = self._tenant_cfg.get(tenant)
            if tcfg is not None and (tcfg.max_queue or tcfg.max_inflight):
                queued = sum(
                    s.n - s.next for s in self._pool if s.tenant == tenant
                )
                inflight = sum(
                    1 for s in self._live.values() if s.tenant == tenant
                )
                over = (
                    tcfg.max_queue and queued + slot.n > tcfg.max_queue
                ) or (tcfg.max_inflight and inflight >= tcfg.max_inflight)
                if over:
                    # the TENANT's quota said no, not global overload:
                    # 429 with the tenant's own drain estimate (other
                    # tenants' backlogs never inflate this hint)
                    if self.breaker is not None:
                        self.breaker.cancel_probe()
                    if self.metrics is not None:
                        self.metrics.inc("rejected")
                        self.metrics.inc_tenant_rejected(tenant)
                    raise QuotaExceeded(
                        self.tenant_retry_after_s(tenant),
                        tenant,
                        "queue quota exceeded"
                        if tcfg.max_queue
                        and queued + slot.n > tcfg.max_queue
                        else "inflight quota exceeded",
                    )
            if len(self._pool) >= self.max_queue:
                if self.breaker is not None:
                    # a half-open allow() claimed the probe slot for a
                    # request that never made it in — release it
                    self.breaker.cancel_probe()
                if self.metrics is not None:
                    self.metrics.inc("rejected")
                raise Backpressure(self.retry_after_s)
            self._pool.append(slot)
            self._live[id(slot)] = slot
            self._cv.notify()
        if self.metrics is not None:
            self.metrics.inc("requests")
            self.metrics.inc("windows", slot.n)
        return PredictFuture(slot, self.metrics)

    def predict(
        self, x: np.ndarray, timeout: Optional[float] = None, trace=None
    ) -> np.ndarray:
        """submit + result in one call (the HTTP handler's path)."""
        return self.submit(x, trace=trace).result(timeout)

    # -- scheduling ----------------------------------------------------------

    def _plan(self, now: float) -> Tuple[Optional[int], Optional[float]]:
        """Decide this cycle's dispatch size under the lock. Returns
        ``(k, wait)``: ``k`` windows to pack now (None = nothing yet),
        ``wait`` seconds to sleep for arrivals (None = until woken).
        Policy steps 1-4 of the module docstring."""
        pending = sum(s.n - s.next for s in self._pool)
        if pending == 0:
            return None, None
        ladder = self.session.ladder
        top = ladder[-1]
        if pending >= top:
            return top, None
        fit = self.session.rung_for(pending)
        if pending == fit or pending >= self.rung_upgrade_fill * fit:
            # exact fit, or close enough that upgrading to the larger
            # rung beats splitting (hysteresis knob)
            return pending, None
        full = max((r for r in ladder if r <= pending), default=None)
        if full is not None:
            # a completely full smaller rung: dispatch it, remainder
            # stays queued with its age intact
            return full, None
        oldest = min(s.t_submit for s in self._pool if s.next < s.n)
        age_left = self.max_queue_age_s - (now - oldest)
        if age_left <= 0:
            return pending, None  # age flush: pad rather than wait more
        return None, age_left

    def _take(self, k: int) -> List[Span]:
        """Pack ``k`` window slots from the pool under the lock —
        deficit-weighted round-robin over TENANTS (each round splits the
        remaining slots by tenant weight into credit; a tenant spends
        whole-window credit, fractions carry to the next round), and
        fair-share over each tenant's requests in arrival order inside
        its grant. Adjacent spans of one request merge. With a single
        tenant the credit split is the full remainder and the loop
        reduces exactly to the old per-request fair share. Exhausted
        requests leave the pool; they complete when their scattered
        predictions arrive. Tenants whose backlog drains forfeit
        residual credit — an idle tenant never hoards a burst."""
        spans: List[Span] = []
        off = 0
        now = time.perf_counter()

        def pack(slot: _Slot, take: int) -> None:
            nonlocal off
            if slot.next == 0:
                # first window of this request packs now: the
                # queue-wait span ends here (mergeable histogram +
                # the request's own trace)
                wait = now - slot.t_submit
                if slot.trace is not None:
                    slot.trace.add("queue_wait", wait)
                if self.metrics is not None:
                    self.metrics.hist_queue_wait.observe(wait)
            if spans and spans[-1][0] is slot and (
                spans[-1][1] + spans[-1][2] == slot.next
            ):
                prev = spans[-1]
                spans[-1] = (slot, prev[1], prev[2] + take, prev[3])
            else:
                spans.append((slot, slot.next, take, off))
            slot.next += take
            off += take

        while off < k:
            # group pending requests by tenant, both levels in arrival
            # order (first-seen tenant order is itself arrival order)
            order: List[str] = []
            by_tenant: Dict[str, List[_Slot]] = {}
            for s in self._pool:
                if s.next >= s.n:
                    continue
                if s.tenant not in by_tenant:
                    by_tenant[s.tenant] = []
                    order.append(s.tenant)
                by_tenant[s.tenant].append(s)
            if not order:
                break
            # split the remaining slots into per-tenant credit by
            # weight: total inflow == remaining capacity, so deficits
            # hover near zero under load instead of growing unboundedly
            remaining = k - off
            wsum = sum(self._tenant_weight(t) for t in order)
            for t in order:
                self._deficit[t] = (
                    self._deficit.get(t, 0.0)
                    + remaining * self._tenant_weight(t) / wsum
                )
            for t in order:
                budget = min(int(self._deficit[t]), k - off)
                granted = 0
                slots = by_tenant[t]
                # per-request fair share inside the tenant's grant
                while granted < budget:
                    t_live = [s for s in slots if s.next < s.n]
                    if not t_live:
                        break
                    share = max(1, (budget - granted) // len(t_live))
                    for slot in t_live:
                        take = min(
                            share, slot.n - slot.next, budget - granted
                        )
                        if take <= 0:
                            continue
                        pack(slot, take)
                        granted += take
                        if granted >= budget:
                            break
                self._deficit[t] -= granted
                if off >= k:
                    break
        self._pool = [s for s in self._pool if s.next < s.n]
        # drained tenants forfeit leftover credit (classic DRR reset)
        active = {s.tenant for s in self._pool}
        for t in list(self._deficit):
            if t not in active:
                self._deficit[t] = 0.0
        return spans

    def _predict_slab(self, total: int) -> np.ndarray:
        """Device step for ``total`` densely packed slab rows — the one
        seam :class:`RaggedBatcher` overrides (ladder-padded here,
        masked top-rung ragged there)."""
        return self.session.predict(self._slab[:total])

    def _device_slots(self, total: int) -> int:
        """Device slots the step actually paid for — denominates the
        batch-fill / padding-efficiency metrics (padded rung size here,
        dp-granular mask occupancy on the ragged path)."""
        return self.session.padded_size(total)

    def _dispatch(self, spans: List[Span]) -> None:
        """One packed device step: copy spans densely into the slot
        slab, predict (``PolishSession`` pads to the ladder — only
        precompiled shapes reach the device), scatter predictions back
        per segment, and resolve every request whose last window just
        landed (freed capacity is re-packed next cycle)."""
        total = sum(c for _, _, c, _ in spans)
        if total == 0:
            return
        if self._slab is None:
            self._slab = np.empty(
                (self.session.ladder[-1],) + self._window_shape, np.uint8
            )
        t_pack = time.perf_counter()
        for slot, src, count, off in spans:
            self._slab[off : off + count] = slot.x[src : src + count]
        t0 = time.perf_counter()
        try:
            preds = self._predict_slab(total)
        except BaseException as e:
            if self.breaker is not None:
                if isinstance(e, _REQUEST_ERRORS):
                    # submit() validated geometry, so a request-shaped
                    # error here is session misuse, not device illness
                    self.breaker.cancel_probe()
                else:
                    self.breaker.record_failure()
            # fail every request with windows in this step (their other
            # windows may have completed in earlier steps; the error
            # wins) and drop their remainders from the pool
            failed = {id(s) for s, _, _, _ in spans}
            with self._cv:
                self._pool = [
                    s for s in self._pool if id(s) not in failed
                ]
                for sid in failed:
                    self._live.pop(sid, None)
            for slot, _, _, _ in spans:
                if not slot.done.is_set():
                    slot.error = e
                    slot.done.set()
            return
        dt = time.perf_counter() - t0
        if self.breaker is not None:
            self.breaker.record_success()
        rung = max(1, self._device_slots(total))
        dp = getattr(self.session, "dp", 1)
        self._steps += 1
        step_id = self._steps
        t_scatter = time.perf_counter()
        done_ids = []
        for slot, src, count, off in spans:
            slot.preds[src : src + count] = preds[off : off + count]
            slot.filled += count
            if slot.filled == slot.n:
                done_ids.append(id(slot))
        dt_scatter = time.perf_counter() - t_scatter
        # span accounting per UNIQUE slot: fair-share may pack one
        # request as several non-adjacent segments of this step, and
        # double-adding the step's duration would break the
        # span-sum~wall invariant the reply's timings promise
        per_slot: Dict[int, Tuple[_Slot, int]] = {}
        for slot, src, count, off in spans:
            if slot.trace is not None:
                prev = per_slot.get(id(slot))
                per_slot[id(slot)] = (
                    slot, count + (prev[1] if prev else 0)
                )
        for slot, count in per_slot.values():
            slot.trace.add("pack", t0 - t_pack)
            slot.trace.add_step(
                dt, rung=rung, step=step_id,
                occupancy=total / rung, dp=dp, windows=count,
            )
            slot.trace.add("scatter", dt_scatter)
        # done is set only AFTER the trace spans landed: a handler
        # reading timings() the instant result() wakes must see this
        # step, not race it
        for slot, _, _, _ in spans:
            if slot.filled == slot.n and not slot.done.is_set():
                slot.done.set()
        tenant_windows: Dict[str, int] = {}
        for slot, _, count, _ in spans:
            tenant_windows[slot.tenant] = (
                tenant_windows.get(slot.tenant, 0) + count
            )
        with self._cv:
            wps = total / max(dt, 1e-6)
            self._ema_wps = (
                wps
                if self._ema_wps is None
                else _THROUGHPUT_BETA * self._ema_wps
                + (1 - _THROUGHPUT_BETA) * wps
            )
            # per-tenant drain rate: the tenant's windows in THIS step
            # over the step time — what its Retry-After divides by
            for t, n in tenant_windows.items():
                t_wps = n / max(dt, 1e-6)
                prev = self._tenant_wps.get(t)
                self._tenant_wps[t] = (
                    t_wps
                    if prev is None
                    else _THROUGHPUT_BETA * prev
                    + (1 - _THROUGHPUT_BETA) * t_wps
                )
            for sid in done_ids:
                self._live.pop(sid, None)
            self._rung_history.append({
                "step": step_id,
                "rung": rung,
                "windows": total,
                "fill": round(total / rung, 4),
                "segments": len(spans),
                "device_s": round(dt, 6),
            })
        if self.metrics is not None:
            self.metrics.inc("batches")
            self.metrics.hist_device.observe(dt)
            self.metrics.observe_fill(total, rung)

    def _loop(self) -> None:
        while True:
            with self._cv:
                spans: Optional[List[Span]] = None
                while self._running:
                    k, wait = self._plan(time.perf_counter())
                    if k is not None:
                        spans = self._take(k)
                        break
                    self._cv.wait(wait)
                if spans is None:  # stopped
                    return
            self._dispatch(spans)


class RaggedBatcher(ContinuousBatcher):
    """Ragged packed dispatch (``ServeConfig.batching == "ragged"``,
    docs/SERVING.md "Ragged dispatch"): the same slot pool, fair-share
    packing, segment scatter, backpressure, and breaker plumbing as
    :class:`ContinuousBatcher`, but every device step runs the session's
    ONE top-rung ragged executable with an explicit valid-row count
    instead of padding to a ladder rung.

    What that deletes from the scheduling policy: the padded path's
    steps 2-3 (rung-upgrade hysteresis and the full-smaller-rung
    split) exist only to trade padding waste against batch size — with
    a masked step there is no padded rung to waste, so
    ``rung_upgrade_fill`` is dead config on this path and ``_plan``
    reduces to *full top rung or age flush*. Occupancy accounting is
    dp-granular (``PolishSession.ragged_slots``): the shared
    ``padding_efficiency`` metric reads real windows / masked slots and
    sits at ~1.0 where the ladder path is rung-quantised to ~0.96."""

    BATCHING_MODE = "ragged"

    def _predict_slab(self, total: int) -> np.ndarray:
        # full slab, not a [:total] view: the shape is always the top
        # rung, and the device masks rows at/past `total` (stale slab
        # rows never reach the model)
        return self.session.predict_ragged(self._slab, total)

    def _device_slots(self, total: int) -> int:
        return self.session.ragged_slots(total)

    def _plan(self, now: float) -> Tuple[Optional[int], Optional[float]]:
        """Full top rung, else wait for arrivals until the oldest
        queued window hits ``max_queue_age_ms``, then dispatch exactly
        the pending count — the padded path's policy steps 1 and 4 with
        the padding-driven middle steps removed."""
        pending = sum(s.n - s.next for s in self._pool)
        if pending == 0:
            return None, None
        top = self.session.ladder[-1]
        if pending >= top:
            return top, None
        oldest = min(s.t_submit for s in self._pool if s.next < s.n)
        age_left = self.max_queue_age_s - (now - oldest)
        if age_left <= 0:
            return pending, None
        return None, age_left
