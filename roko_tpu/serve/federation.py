"""Multi-host fleet federation (docs/SERVING.md "Multi-host federation").

Everything below one host — worker failover (PR 6), rollout (PR 12),
autoscaling (PR 19) — discovers workers through an announce FILE in a
shared runtime dir, which stops at the host boundary. This module is
the supervisor-of-supervisors seam: it federates many per-host fleets
behind one front end over TCP, built to survive the thing that fails
first at that scale — the network.

Topology::

    client ──HTTP──▶ federation front (this module)
                       │  lease/epoch registry + per-host breakers
          ┌────────────┼────────────┐
          ▼            ▼            ▼
      host agent   host agent   host agent   (roko-tpu serve --host-agent)
       Fleet(N)     Fleet(N)     Fleet(N)    (PR 6 spawn/storm/drain, unchanged)
        workers      workers      workers

- **Host agent** (:func:`run_host_agent`): a full supervisor — same
  Fleet, same rollout journal recovery, same autoscaler — that
  additionally *joins* a federation front (``--join HOST:PORT``) and
  keeps its registration alive.
- **Lease/epoch registry** (:class:`HostRegistry`): registration is a
  lease (TTL renewed by agent heartbeat; expiry ⇒ out of rotation).
  Re-registration bumps an **epoch**. Relays carry the epoch
  (``X-Roko-Fed-Epoch``) and every agent reply echoes its own: a
  zombie from a stale lease is *fenced* — it refuses mismatched
  relays with 409, and even a zombie that ignores the header has its
  reply refused at the front end when the echoed epoch is stale. A
  fenced reply is NEVER served.
- **Partition-tolerant routing** (:meth:`FederationFront.post_polish`):
  per-host :class:`~roko_tpu.resilience.CircuitBreaker`, mid-request
  failover across hosts preserving ``request_id`` (the PR 14 contract,
  one level up), degraded mode on survivors with loud ``federation``
  obs events, per-host state on ``/healthz``.
- **Chaos**: both the agent's heartbeat socket and the front end's
  relay socket go through :mod:`roko_tpu.serve.transport`, so
  ``ROKO_FED_FAULTS`` drives real multi-process fleets through
  scripted drops/delays/duplicates/partitions on loopback.
- **Host-dimension rollout & autoscale**: ``POST /rollout`` at the
  front rolls one host at a time through each agent's own
  drain/bake/canary gates; :class:`HostAutoscaler` resizes worker
  counts per host through the agent's ``POST /scale``.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from roko_tpu.config import RokoConfig
from roko_tpu.obs import events as obs_events
from roko_tpu.obs.hist import (
    merge_histogram_rows,
    parse_histogram_rows,
    render_histogram_rows,
)
from roko_tpu.obs.trace import new_request_id
from roko_tpu.resilience import CircuitBreaker
from roko_tpu.serve.fleet import write_announce
from roko_tpu.serve.metrics import (
    HISTOGRAM_SERIES,
    parse_metric_values,
)
from roko_tpu.serve.server import (
    _NAME_RE,
    JsonRequestHandler,
    drain,
    init_lifecycle,
    request_tenant,
    serve_forever,
)
from roko_tpu.serve.transport import transport_from_env

#: the fencing token: relays carry the registry's epoch for the target
#: host; agents refuse mismatches and echo their own epoch on every
#: reply so the front end can refuse a stale reply it did not fence at
#: the source.
FED_EPOCH_HEADER = "X-Roko-Fed-Epoch"

#: which host served a reply (set by the front end on the way out) —
#: lets clients and gates observe cross-host failover without parsing
#: logs.
FED_HOST_HEADER = "X-Roko-Host"

_CONN_ERRORS = (OSError, http.client.HTTPException)

#: /metrics gauge encoding for per-host state
HOST_STATE_CODES = {"live": 0, "breaker-open": 1, "expired": 2}

FEDERATION_COUNTERS = (
    "registrations", "lease_expiries", "fence_refusals", "relays",
    "failovers",
)


class HostLease:
    """One registered host: address, lease token, epoch, breaker."""

    def __init__(
        self,
        host_id: str,
        host: str,
        port: int,
        *,
        epoch: int,
        lease_id: str,
        expires_at: float,
        breaker: CircuitBreaker,
        workers: int = 0,
        pid: Optional[int] = None,
    ):
        self.host_id = host_id
        self.host = host
        self.port = port
        self.epoch = epoch
        self.lease_id = lease_id
        self.expires_at = expires_at
        self.breaker = breaker
        self.workers = workers
        self.pid = pid
        self.expired = False

    def state(self) -> str:
        if self.expired:
            return "expired"
        if self.breaker.state == "open":
            return "breaker-open"
        return "live"

    def addr(self) -> str:
        return f"{self.host}:{self.port}"


class HostRegistry:
    """The front end's worker registry, one level up from the announce
    file: hosts register over TCP and stay in rotation only while
    their lease is renewed.

    Lease semantics (the edge matrix tests pin every row):

    - expiry takes a host out of rotation for NEW picks; an in-flight
      relay's reply is still served (the epoch did not change —
      expiry alone proves nothing about staleness);
    - renewal with a stale/unknown ``lease_id`` — or against an
      expired lease — is refused, forcing the agent to re-register;
    - re-registration (restarted agent, healed partition) bumps the
      host's **epoch** and replaces the lease in place: one entry per
      ``host_id``, never duplicates;
    - only an epoch mismatch *fences* — the zombie-from-a-stale-lease
      case, refused at the agent AND on reply at the front end.
    """

    def __init__(
        self,
        ttl_s: float = 10.0,
        *,
        breaker_failures: int = 3,
        breaker_reset_s: float = 10.0,
        clock: Callable[[], float] = time.monotonic,
        log: Callable[[str], None] = print,
    ):
        if ttl_s <= 0:
            raise ValueError("lease ttl_s must be > 0")
        self.ttl_s = ttl_s
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = breaker_reset_s
        self._clock = clock
        self._log = log
        self._lock = threading.Lock()
        self._hosts: Dict[str, HostLease] = {}
        #: epochs survive lease replacement AND removal: a host that
        #: flaps through many partitions keeps bumping monotonically,
        #: so no stale process can ever collide back into validity
        self._epochs: Dict[str, int] = {}
        self._rr = 0
        self._counters = {k: 0 for k in FEDERATION_COUNTERS}

    def inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] += n

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters[name]

    def register(
        self,
        host_id: str,
        host: str,
        port: int,
        *,
        workers: int = 0,
        pid: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Grant (or re-grant) a lease. Returns the body the agent
        stores: ``{lease_id, epoch, ttl_s}``."""
        with self._lock:
            epoch = self._epochs.get(host_id, 0) + 1
            self._epochs[host_id] = epoch
            rejoin = host_id in self._hosts
            lease = HostLease(
                host_id, host, port,
                epoch=epoch,
                lease_id=os.urandom(8).hex(),
                expires_at=self._clock() + self.ttl_s,
                # a fresh breaker per registration: the host just
                # proved it can reach us, so it re-enters rotation
                # clean instead of inheriting an open breaker from its
                # previous life
                breaker=CircuitBreaker(
                    self._breaker_failures,
                    self._breaker_reset_s,
                    clock=self._clock,
                ),
                workers=workers,
                pid=pid,
            )
            self._hosts[host_id] = lease
            self._counters["registrations"] += 1
        obs_events.emit(
            "federation",
            "host_rejoined" if rejoin else "host_joined",
            log=self._log,
            host=host_id, addr=lease.addr(), epoch=epoch,
            workers=workers,
        )
        return {
            "lease_id": lease.lease_id,
            "epoch": epoch,
            "ttl_s": self.ttl_s,
        }

    def renew(
        self, host_id: str, lease_id: str
    ) -> Optional[Dict[str, Any]]:
        """Extend a live lease. None = refused (unknown host, stale
        lease_id, or expired lease) — the agent must re-register and
        adopt the bumped epoch."""
        with self._lock:
            lease = self._hosts.get(host_id)
            if (
                lease is None
                or lease.lease_id != lease_id
                or lease.expired
            ):
                return None
            lease.expires_at = self._clock() + self.ttl_s
            return {"ttl_s": self.ttl_s, "epoch": lease.epoch}

    def sweep(self, now: Optional[float] = None) -> List[str]:
        """Expire overdue leases (out of rotation for new picks; the
        epoch is NOT bumped — see class docstring). Returns the newly
        expired host ids."""
        now = self._clock() if now is None else now
        expired: List[str] = []
        with self._lock:
            for lease in self._hosts.values():
                if not lease.expired and lease.expires_at < now:
                    lease.expired = True
                    self._counters["lease_expiries"] += 1
                    expired.append(lease.host_id)
        for host_id in expired:
            obs_events.emit(
                "federation", "lease_expired", log=self._log,
                suffix="— host out of rotation until it re-registers",
                host=host_id,
            )
        return expired

    def current_epoch(self, host_id: str) -> int:
        with self._lock:
            return self._epochs.get(host_id, 0)

    def hosts(self) -> List[HostLease]:
        with self._lock:
            return list(self._hosts.values())

    def live(self) -> List[HostLease]:
        with self._lock:
            return [l for l in self._hosts.values() if not l.expired]

    def get(self, host_id: str) -> Optional[HostLease]:
        with self._lock:
            return self._hosts.get(host_id)

    def pick(self, exclude: Tuple[str, ...] = ()) -> Optional[HostLease]:
        """Round-robin over unexpired hosts whose breaker admits a
        request (half-open claims the probe slot, same contract as the
        worker-level breaker)."""
        with self._lock:
            candidates = [
                l for l in self._hosts.values()
                if not l.expired and l.host_id not in exclude
            ]
            self._rr += 1
            offset = self._rr
        n = len(candidates)
        for i in range(n):
            lease = candidates[(offset + i) % n]
            if lease.breaker.allow():
                return lease
        return None


class FederationRollout:
    """Host-dimension rollout: relay ``POST /rollout`` to one agent at
    a time and wait for its own drain/bake/canary gates to land before
    touching the next host — a canary failure on host 0 never reaches
    host 1."""

    def __init__(
        self,
        front: "FederationFront",
        payload: Dict[str, Any],
        *,
        log: Callable[[str], None] = print,
    ):
        self.front = front
        self.payload = dict(payload)
        self.name = str(payload.get("name", ""))
        self._log = log
        self.state = "idle"
        self.hosts: Dict[str, Dict[str, Any]] = {}
        self._thread: Optional[threading.Thread] = None

    def active(self) -> bool:
        return self.state == "rolling"

    def status(self) -> Dict[str, Any]:
        return {
            "state": self.state,
            "name": self.name,
            "hosts": dict(self.hosts),
        }

    def start(self) -> None:
        self.state = "rolling"
        self._thread = threading.Thread(
            target=self._run, name="roko-federation-rollout", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        front = self.front
        timeout = front.fleet_cfg.rollout_ready_timeout_s
        for lease in front.registry.live():
            hid = lease.host_id
            obs_events.emit(
                "federation", "host_rollout", log=self._log,
                host=hid, version=self.name,
            )
            try:
                code, _, data = front.transport(
                    "POST", lease.host, lease.port, "/rollout",
                    headers={"Content-Type": "application/json"},
                    body=json.dumps(self.payload).encode(),
                    timeout=10.0, peer=hid,
                )
            except _CONN_ERRORS as e:
                self.hosts[hid] = {"state": "unreachable",
                                   "error": type(e).__name__}
                self.state = "failed"
                return
            if code != 202:
                self.hosts[hid] = {
                    "state": "refused", "code": code,
                    "error": data.decode(errors="replace")[:300],
                }
                self.state = "failed"
                return
            final = self._await_host(lease, timeout)
            self.hosts[hid] = final
            if final.get("state") != "done":
                # the host's own gates rolled it back (or it vanished):
                # stop the wave — the remaining hosts keep the incumbent
                self.state = "failed"
                obs_events.emit(
                    "federation", "host_rollout_failed", log=self._log,
                    host=hid, version=self.name,
                    state=str(final.get("state")),
                )
                return
        self.state = "done"

    def _await_host(
        self, lease: HostLease, timeout_s: float
    ) -> Dict[str, Any]:
        front = self.front
        deadline = time.monotonic() + timeout_s
        last: Dict[str, Any] = {"state": "unknown"}
        while time.monotonic() < deadline:
            try:
                _, _, data = front.transport(
                    "GET", lease.host, lease.port, "/rollout",
                    timeout=5.0, peer=lease.host_id,
                )
                last = json.loads(data.decode() or "{}")
            except (_CONN_ERRORS, ValueError):
                time.sleep(0.5)
                continue
            if last.get("state") in (
                "done", "failed", "rolled_back", "idle"
            ):
                return last
            time.sleep(0.5)
        last.setdefault("state", "timeout")
        if last.get("state") == "rolling":
            last["state"] = "timeout"
        return last


class FederationFront:
    """The federated router: lease registry + per-host breakers +
    cross-host failover, surfaced over the same front-end HTTP shape
    the single-host supervisor serves."""

    def __init__(
        self,
        cfg: RokoConfig,
        *,
        transport=None,
        clock: Callable[[], float] = time.monotonic,
        log: Callable[[str], None] = print,
    ):
        fc = cfg.fleet
        self.cfg = cfg
        self.fleet_cfg = fc
        self._log = log
        self._clock = clock
        self.registry = HostRegistry(
            fc.lease_ttl_s,
            breaker_failures=fc.fed_breaker_failures,
            breaker_reset_s=fc.fed_breaker_reset_s,
            clock=clock,
            log=log,
        )
        self.transport = transport or transport_from_env("front")
        self.rollout: Optional[FederationRollout] = None
        self.autoscaler: Optional[HostAutoscaler] = None
        self._rollout_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Spin the lease sweeper (and the host autoscaler when the
        config leaves room)."""

        def sweep_loop() -> None:
            while not self._stop.is_set():
                try:
                    self.registry.sweep()
                except Exception as e:  # pragma: no cover - defensive
                    self._log(f"roko federation: sweep failed: {e!r}")
                self._stop.wait(max(0.05, self.registry.ttl_s / 4.0))

        t = threading.Thread(
            target=sweep_loop, name="roko-federation-sweep", daemon=True
        )
        t.start()
        self._threads.append(t)
        scaler = HostAutoscaler(self, log=self._log, clock=self._clock)
        if scaler.enabled:
            self.autoscaler = scaler

            def scale_loop() -> None:
                while not self._stop.is_set():
                    try:
                        scaler.tick()
                    except Exception as e:  # pragma: no cover
                        self._log(
                            f"roko federation: autoscale tick failed: {e!r}"
                        )
                    self._stop.wait(self.fleet_cfg.autoscale_interval_s)

            ts = threading.Thread(
                target=scale_loop, name="roko-federation-autoscale",
                daemon=True,
            )
            ts.start()
            self._threads.append(ts)

    def stop(self) -> None:
        self._stop.set()

    # -- routing -------------------------------------------------------------

    def _breaker_failure(self, lease: HostLease, why: str) -> None:
        prev = lease.breaker.state
        lease.breaker.record_failure()
        if lease.breaker.state == "open" and prev != "open":
            obs_events.emit(
                "federation", "host_down", log=self._log,
                suffix="— breaker open; serving on the survivors",
                host=lease.host_id, error=why,
            )

    def _breaker_success(self, lease: HostLease) -> None:
        prev = lease.breaker.state
        lease.breaker.record_success()
        if prev != "closed":
            obs_events.emit(
                "federation", "host_up", log=self._log,
                host=lease.host_id,
            )

    def post_polish(
        self,
        body: bytes,
        timeout: Optional[float] = None,
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
        model_version: Optional[str] = None,
        pinned: bool = False,
    ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one polish body to a host agent with cross-host
        failover. The contract matches :meth:`Fleet.post_polish` one
        level up: ``request_id`` rides every dispatch (including the
        failover re-dispatch to ANOTHER HOST), connection failures try
        the next host, 503s collect the largest Retry-After, and a
        reply whose echoed epoch does not match the relay's is a
        **fence refusal** — counted, logged loudly, and never served."""
        registry = self.registry
        tried: List[str] = []
        retry_after: Optional[float] = None
        attempts = max(1, self.fleet_cfg.failover_attempts)
        for _ in range(attempts):
            lease = registry.pick(exclude=tuple(tried))
            if lease is None:
                break
            tried.append(lease.host_id)
            epoch = lease.epoch
            if request_id is not None:
                obs_events.emit(
                    "federation", "dispatch", quiet=True,
                    request_id=request_id, host=lease.host_id,
                    epoch=epoch, attempt=len(tried),
                )
            headers = {
                "Content-Type": "application/json",
                FED_EPOCH_HEADER: str(epoch),
            }
            if request_id is not None:
                headers["X-Roko-Request-Id"] = request_id
            if tenant is not None:
                headers["X-Roko-Tenant"] = tenant
            if pinned and model_version is not None:
                headers["X-Roko-Model"] = model_version
            try:
                code, hdrs, reply = self.transport(
                    "POST", lease.host, lease.port, "/polish",
                    headers=headers, body=body,
                    timeout=120.0 if timeout is None else timeout,
                    peer=lease.host_id,
                )
            except _CONN_ERRORS as e:
                registry.inc("failovers")
                self._breaker_failure(lease, type(e).__name__)
                self._log(
                    f"roko federation: host {lease.host_id} dropped a "
                    f"request ({type(e).__name__}); failing over"
                )
                if request_id is not None:
                    obs_events.emit(
                        "federation", "failover", quiet=True,
                        request_id=request_id, host=lease.host_id,
                        error=type(e).__name__,
                    )
                continue
            hdrs = {k.title(): v for k, v in hdrs.items()}
            echo = hdrs.get(FED_EPOCH_HEADER.title())
            if code == 409 and b"fenced" in reply:
                # the agent fenced the relay at the source: its epoch
                # does not match the registry's — a zombie (or a racing
                # re-registration). Never serve; the request fails over.
                registry.inc("fence_refusals")
                lease.breaker.cancel_probe()
                obs_events.emit(
                    "federation", "fence_refusal", log=self._log,
                    request_id=request_id, host=lease.host_id,
                    expected_epoch=epoch, where="agent",
                )
                continue
            if echo is not None and echo != str(epoch):
                # the reply came back under the WRONG epoch: a stale
                # process answered on a recycled address. Refusing here
                # is the last line of the fence — the reply is dropped,
                # never served.
                registry.inc("fence_refusals")
                lease.breaker.cancel_probe()
                obs_events.emit(
                    "federation", "fence_refusal", log=self._log,
                    suffix="— stale-epoch reply refused, never served",
                    request_id=request_id, host=lease.host_id,
                    expected_epoch=epoch, reply_epoch=echo,
                    where="reply",
                )
                continue
            if code == 503:
                # the host answered — alive, just saturated/draining
                self._breaker_success(lease)
                hint = 0.0
                try:
                    hint = float(hdrs.get("Retry-After", 0))
                except ValueError:
                    pass
                try:
                    hint = max(
                        hint,
                        float(json.loads(reply.decode() or "{}").get(
                            "retry_after_s", 0)),
                    )
                except (ValueError, AttributeError):
                    pass
                retry_after = max(retry_after or 0.0, hint)
                continue
            self._breaker_success(lease)
            if code == 429:
                keep = {
                    k: v for k, v in hdrs.items()
                    if k.lower() == "retry-after"
                }
                keep[FED_HOST_HEADER] = lease.host_id
                return code, reply, keep
            registry.inc("relays")
            return code, reply, {FED_HOST_HEADER: lease.host_id}
        if retry_after is None:
            retry_after = float(self.cfg.serve.retry_after_s)
        body_out = json.dumps({
            "error": "no federated host available "
                     "(all hosts down, fenced, or saturated)",
            "retry_after_s": retry_after,
        }).encode()
        return 503, body_out, {
            "Retry-After": f"{max(1, round(retry_after))}"
        }

    # -- registration plumbing (the /fed/* handlers) -------------------------

    def handle_register(
        self, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        host_id = payload.get("host_id")
        port = payload.get("port")
        if not isinstance(host_id, str) or not host_id:
            return 400, {"error": "host_id must be a non-empty string"}
        if not isinstance(port, int) or not (0 < port < 65536):
            return 400, {"error": "port must be an int in (0, 65536)"}
        host = payload.get("host") or "127.0.0.1"
        workers = payload.get("workers") or 0
        pid = payload.get("pid")
        return 200, self.registry.register(
            host_id, str(host), port,
            workers=int(workers),
            pid=int(pid) if isinstance(pid, int) else None,
        )

    def handle_renew(
        self, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        host_id = payload.get("host_id")
        lease_id = payload.get("lease_id")
        if not isinstance(host_id, str) or not isinstance(lease_id, str):
            return 400, {"error": "body must carry host_id and lease_id"}
        out = self.registry.renew(host_id, lease_id)
        if out is None:
            return 404, {
                "error": f"no live lease for host {host_id!r} — "
                         "re-register",
            }
        return 200, out

    # -- operator surfaces ---------------------------------------------------

    def start_rollout(
        self, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        name = payload.get("name")
        if not isinstance(name, str) or not name:
            return 400, {"error": "body must carry the model version "
                                  '{"name": "<registered name>"}'}
        with self._rollout_lock:
            if self.rollout is not None and self.rollout.active():
                return 409, {
                    "error": "a federation rollout is already in progress",
                    "status": self.rollout.status(),
                }
            if not self.registry.live():
                return 503, {"error": "no live host to roll"}
            ctl = FederationRollout(self, payload, log=self._log)
            self.rollout = ctl
            ctl.start()
            return 202, ctl.status()

    def rollout_status(self) -> Dict[str, Any]:
        ctl = self.rollout
        return ctl.status() if ctl is not None else {"state": "idle"}

    def scale_host(
        self, host_id: str, workers: int
    ) -> Tuple[int, Dict[str, Any]]:
        """Relay a worker-count change to one host's agent."""
        lease = self.registry.get(host_id)
        if lease is None or lease.expired:
            return 404, {"error": f"no live host {host_id!r}"}
        try:
            code, _, data = self.transport(
                "POST", lease.host, lease.port, "/scale",
                headers={"Content-Type": "application/json"},
                body=json.dumps({"workers": workers}).encode(),
                timeout=10.0, peer=host_id,
            )
        except _CONN_ERRORS as e:
            return 503, {"error": f"host {host_id!r} unreachable: "
                                  f"{type(e).__name__}"}
        try:
            body = json.loads(data.decode() or "{}")
        except ValueError:
            body = {}
        return code, body

    # -- observation ---------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        """The federation ``/healthz`` body: per-host state map +
        degraded-mode aggregate (same shape one level up from
        ``Fleet.summary``)."""
        hosts = self.registry.hosts()
        states = {
            l.host_id: {
                "state": l.state(),
                "addr": l.addr(),
                "epoch": l.epoch,
                "breaker": l.breaker.state,
                "workers": l.workers,
            }
            for l in hosts
        }
        up = sum(1 for l in hosts if l.state() == "live")
        if not hosts:
            status, code = "warming", 503
        elif up == len(hosts):
            status, code = "ok", 200
        elif up >= 1:
            status, code = "degraded", 200
        else:
            status, code = "unhealthy", 503
        return {
            "status": status,
            "code": code,
            "hosts": states,
            "hosts_up": up,
            "federation": {
                k: self.registry.counter(k) for k in FEDERATION_COUNTERS
            },
        }

    def _scrape(self, path: str) -> Dict[str, str]:
        """GET ``path`` from every unexpired host agent; unanswering
        hosts are simply absent (same contract as the fleet's worker
        scrape)."""
        out: Dict[str, str] = {}
        for lease in self.registry.live():
            try:
                _, _, data = self.transport(
                    "GET", lease.host, lease.port, path,
                    timeout=self.fleet_cfg.heartbeat_timeout_s,
                    peer=lease.host_id,
                )
                out[lease.host_id] = data.decode(errors="replace")
            except _CONN_ERRORS:
                continue
        return out

    def render_metrics(self) -> str:
        """The federation ``/metrics`` body: ``roko_federation_*``
        gauges/counters, per-host fleet gauges re-labeled
        ``host="h"``, and the third level of the mergeable-histogram
        ladder — federation rows are the bucket-wise sum of the
        host-merged rows, which are themselves worker sums
        (fleet ← host ← worker)."""
        hosts = self.registry.hosts()
        p = "roko_federation_"
        up = sum(1 for l in hosts if l.state() == "live")
        lines = [
            f"# TYPE {p}hosts gauge",
            f"{p}hosts {len(hosts)}",
            f"# TYPE {p}hosts_up gauge",
            f"{p}hosts_up {up}",
        ]
        for name in FEDERATION_COUNTERS:
            lines.append(f"# TYPE {p}{name}_total counter")
            lines.append(
                f"{p}{name}_total {self.registry.counter(name)}"
            )
        lines.append(f"# TYPE {p}host_state gauge")
        for l in hosts:
            lines.append(
                f'{p}host_state{{host="{l.host_id}"}} '
                f"{HOST_STATE_CODES.get(l.state(), 9)}"
            )
        lines.append(f"# TYPE {p}host_epoch gauge")
        for l in hosts:
            lines.append(
                f'{p}host_epoch{{host="{l.host_id}"}} {l.epoch}'
            )
        bodies = self._scrape("/metrics")
        # per-host fleet sizing, re-labeled by host
        for name in ("roko_fleet_workers", "roko_fleet_workers_up"):
            rows = [
                (hid, vals[name])
                for hid, body in sorted(bodies.items())
                for vals in [parse_metric_values(body, (name,))]
                if name in vals
            ]
            if not rows:
                continue
            lines.append(f"# TYPE {name} gauge")
            for hid, val in rows:
                lines.append(f'{name}{{host="{hid}"}} {val}')
        # the histogram ladder's top rung: each agent body's UNLABELED
        # rows are already its worker-merged fleet rows, so the
        # federation row is their bucket-wise sum; every host's full
        # row set (including worker="i" rows) re-exports beside it
        # with host="h" appended
        for name in HISTOGRAM_SERIES:
            per_host = {
                hid: parse_histogram_rows(body, name)
                for hid, body in sorted(bodies.items())
            }
            merged = merge_histogram_rows(
                {
                    k: v for k, v in rows.items()
                    if "worker" not in dict(k)
                    and all(lk in ("__series__", "le")
                            for lk, _ in k)
                }
                for rows in per_host.values()
            )
            if not merged:
                continue
            lines.append(f"# TYPE {name} histogram")
            lines.extend(render_histogram_rows(name, merged))
            for hid, rows in per_host.items():
                lines.extend(
                    render_histogram_rows(
                        name, rows, extra=f'host="{hid}"'
                    )
                )
        return "\n".join(lines) + "\n"

    def tracez(self, query: str = "") -> Dict[str, Any]:
        """Aggregate trace view keyed by host id — one request_id greps
        across the whole federation, hosts included."""
        out: Dict[str, Any] = {}
        path = f"/tracez?{query}" if query else "/tracez"
        for hid, body in self._scrape(path).items():
            try:
                out[hid] = json.loads(body)
            except ValueError:
                out[hid] = {"error": "unparseable tracez body"}
        return out


class HostAutoscaler:
    """The PR 19 autoscaler lifted to the host dimension: per-host
    backlog EMA with the same hysteresis band (up fast past
    ``autoscale_up_backlog``, down only after a continuous
    ``autoscale_idle_s`` stretch at or under ``autoscale_down_backlog``),
    actuated through each agent's ``POST /scale``. Per-host state —
    one saturated host scales up without touching its idle peers."""

    def __init__(
        self,
        front: FederationFront,
        *,
        log: Callable[[str], None] = print,
        clock: Callable[[], float] = time.monotonic,
    ):
        fc = front.fleet_cfg
        self.front = front
        self.fc = fc
        self.min_workers = max(1, fc.min_workers or fc.workers or 1)
        self.max_workers = max(
            self.min_workers, fc.max_workers or fc.workers or 1
        )
        self.enabled = self.max_workers > self.min_workers
        self._log = log
        self._clock = clock
        self.ema: Dict[str, float] = {}
        self._idle_since: Dict[str, float] = {}
        self._last_change: Dict[str, float] = {}

    def _host_load(
        self, lease: HostLease
    ) -> Optional[Tuple[int, float]]:
        """(worker_count, backlog_windows) from the agent's /healthz —
        None when the host does not answer (the breaker/routing path
        owns that failure; sizing just skips a beat)."""
        try:
            _, _, data = self.front.transport(
                "GET", lease.host, lease.port, "/healthz",
                timeout=self.fc.heartbeat_timeout_s,
                peer=lease.host_id,
            )
            body = json.loads(data.decode() or "{}")
        except (_CONN_ERRORS, ValueError):
            return None
        workers = body.get("workers")
        n = len(workers) if isinstance(workers, dict) else 0
        try:
            backlog = float(body.get("backlog_windows", 0.0))
        except (TypeError, ValueError):
            backlog = 0.0
        return max(1, n), backlog

    def tick(self, now: Optional[float] = None) -> Dict[str, str]:
        """One sizing pass over every live host; returns
        ``{host_id: "up"|"down"}`` for the hosts resized."""
        fc = self.fc
        now = self._clock() if now is None else now
        actions: Dict[str, str] = {}
        for lease in self.front.registry.live():
            hid = lease.host_id
            load = self._host_load(lease)
            if load is None:
                continue
            n, backlog = load
            per = backlog / n
            prev = self.ema.get(hid)
            ema = (
                float(per) if prev is None
                else fc.autoscale_ema_beta * prev
                + (1.0 - fc.autoscale_ema_beta) * per
            )
            self.ema[hid] = ema
            last = self._last_change.get(hid)
            cooled = last is None or now - last >= fc.autoscale_cooldown_s
            if ema > fc.autoscale_up_backlog:
                self._idle_since.pop(hid, None)
                if n < self.max_workers and cooled:
                    code, _ = self.front.scale_host(hid, n + 1)
                    if code == 200:
                        self._last_change[hid] = now
                        actions[hid] = "up"
                        obs_events.emit(
                            "federation", "host_scale", log=self._log,
                            host=hid, workers=n + 1, direction="up",
                            backlog=round(ema, 1),
                        )
                continue
            if ema > fc.autoscale_down_backlog:
                self._idle_since.pop(hid, None)
                continue
            since = self._idle_since.setdefault(hid, now)
            if (
                n > self.min_workers
                and cooled
                and now - since >= fc.autoscale_idle_s
            ):
                code, _ = self.front.scale_host(hid, n - 1)
                if code == 200:
                    self._last_change[hid] = now
                    self._idle_since[hid] = now
                    actions[hid] = "down"
                    obs_events.emit(
                        "federation", "host_scale", log=self._log,
                        host=hid, workers=n - 1, direction="down",
                        backlog=round(ema, 1),
                    )
        return actions


class _FederationHandler(JsonRequestHandler):
    """The federation front end's HTTP surface — the supervisor front
    shape one level up, plus the ``/fed/*`` registration plane."""

    front: FederationFront  # set by make_federation_server

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        path = self.path.split("?", 1)[0]
        if path == "/healthz":
            body = self.front.summary()
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                body["status"], body["code"] = "draining", 503
            code = body.pop("code")
            self._reply_json(code, body)
        elif path == "/metrics":
            self._reply(
                200,
                self.front.render_metrics().encode(),
                content_type="text/plain; version=0.0.4",
            )
        elif path == "/rollout":
            self._reply_json(200, self.front.rollout_status())
        elif path == "/tracez":
            parts = self.path.split("?", 1)
            self._reply_json(
                200,
                self.front.tracez(parts[1] if len(parts) > 1 else ""),
            )
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def _json_post(
        self, fn: Callable[[Dict[str, Any]], Tuple[int, Dict[str, Any]]]
    ) -> None:
        raw = self._read_body()
        if raw is None:
            return
        try:
            payload = json.loads(raw.decode() or "{}")
            if not isinstance(payload, dict):
                raise ValueError("payload must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            self._reply_json(400, {"error": str(e)})
            return
        code, body = fn(payload)
        self._reply_json(code, body)

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        front = self.front
        if self.path == "/fed/register":
            self._json_post(front.handle_register)
            return
        if self.path == "/fed/renew":
            self._json_post(front.handle_renew)
            return
        if self.path == "/rollout":
            self._json_post(front.start_rollout)
            return
        if self.path == "/scale":
            def scale(payload: Dict[str, Any]):
                host = payload.get("host")
                workers = payload.get("workers")
                if not isinstance(host, str) or not host:
                    return 400, {"error": "body must name the host"}
                if not isinstance(workers, int) or workers < 1:
                    return 400, {"error": "workers must be an int >= 1"}
                return front.scale_host(host, workers)

            self._json_post(scale)
            return
        if self.path != "/polish":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        try:
            tenant = request_tenant(self.headers, {})
        except ValueError as e:
            self._reply_json(400, {"error": str(e)})
            return
        model = self.headers.get("X-Roko-Model")
        pinned = model is not None
        if pinned and not _NAME_RE.match(model):
            self._reply_json(
                400,
                {"error": "model name must match "
                          "[A-Za-z0-9][A-Za-z0-9._-]{0,63}"},
            )
            return
        with self._track_inflight():
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                self.close_connection = True
                retry = float(self.front.cfg.serve.retry_after_s)
                self._reply_json(
                    503,
                    {"error": "federation draining",
                     "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            try:
                body = self._read_body()
            except TimeoutError:
                self.close_connection = True
                self._reply_json(
                    503, {"error": "timed out reading the request"}
                )
                return
            if body is None:
                return
            rid = (
                self.headers.get("X-Roko-Request-Id") or new_request_id()
            )
            code, reply, extra = front.post_polish(
                body, request_id=rid, tenant=tenant,
                model_version=model, pinned=pinned,
            )
            if code == 503:
                self.close_connection = True
            self._reply(code, reply, extra=extra)


def make_federation_server(
    front: FederationFront,
    host: Optional[str] = None,
    port: Optional[int] = None,
) -> ThreadingHTTPServer:
    """Bind the federation front end (port 0 = ephemeral); the caller
    runs ``serve_forever``. Lifecycle state matches the worker/
    supervisor servers so :func:`roko_tpu.serve.server.drain` works
    unchanged."""
    serve_cfg = front.cfg.serve
    handler = type(
        "RokoFederationHandler", (_FederationHandler,), {"front": front}
    )
    server = ThreadingHTTPServer(
        (serve_cfg.host if host is None else host,
         serve_cfg.port if port is None else port),
        handler,
    )
    server.front = front  # type: ignore[attr-defined]
    init_lifecycle(server, front.cfg.resilience.drain_deadline_s)
    return server


def run_federation_front(
    cfg: RokoConfig,
    *,
    announce: Optional[str] = None,
    log=print,
) -> int:
    """The ``roko-tpu serve --federation`` entry point: bind the
    registry + router front end and serve until SIGTERM/Ctrl-C. Loads
    no model and claims no device — hosts bring their own fleets."""
    front = FederationFront(cfg, log=log)
    server = make_federation_server(front)
    if announce:
        write_announce(announce, server.server_address[1])
    log(
        "roko federation: front end binding "
        f"(lease ttl {front.registry.ttl_s:g}s; hosts join with "
        "`roko-tpu serve MODEL --host-agent --join HOST:PORT`)"
    )
    front.start()
    try:
        serve_forever(
            server,
            log=log,
            drain_fn=lambda: drain(server, log=log),
        )
    finally:
        front.stop()
    return 0


# ---------------------------------------------------------------------------
# host agent
# ---------------------------------------------------------------------------


class HostAgent:
    """The per-host side of the federation: keeps this host's lease
    alive at the front end and owns the host's fencing epoch.

    The join loop registers, then renews every ``ttl/3``. A refused
    renewal (lease expired during a partition, or the front end
    restarted) re-registers and **adopts the bumped epoch** — from
    that moment the previous epoch is fenced, including any zombie
    process still claiming it."""

    def __init__(
        self,
        fleet,
        cfg: RokoConfig,
        *,
        host_id: Optional[str] = None,
        join: Optional[str] = None,
        advertise_host: str = "127.0.0.1",
        transport=None,
        log: Callable[[str], None] = print,
    ):
        fc = cfg.fleet
        self.fleet = fleet
        self.cfg = cfg
        self.host_id = host_id or fc.host_id or f"host-{os.getpid()}"
        join = join or fc.join
        if not join or ":" not in join:
            raise ValueError(
                "host agent needs the federation front as --join "
                "HOST:PORT (or fleet.join in the config)"
            )
        fh, _, fp = join.rpartition(":")
        self.front_addr = (fh, int(fp))
        self.advertise_host = advertise_host
        self.transport = transport or transport_from_env(self.host_id)
        self._log = log
        self.epoch = 0
        self.lease_id: Optional[str] = None
        self.ttl_s = float(fc.lease_ttl_s)
        self.port: Optional[int] = None
        self._stop = threading.Event()

    # -- front-end RPC -------------------------------------------------------

    def _call_front(
        self, path: str, payload: Dict[str, Any]
    ) -> Tuple[int, Dict[str, Any]]:
        fh, fp = self.front_addr
        code, _, data = self.transport(
            "POST", fh, fp, path,
            headers={"Content-Type": "application/json"},
            body=json.dumps(payload).encode(),
            timeout=max(2.0, self.ttl_s / 2.0),
            peer="front",
        )
        try:
            body = json.loads(data.decode() or "{}")
        except ValueError:
            body = {}
        return code, body

    def register(self) -> bool:
        code, body = self._call_front("/fed/register", {
            "host_id": self.host_id,
            "host": self.advertise_host,
            "port": self.port,
            "workers": len(self.fleet.workers),
            "pid": os.getpid(),
        })
        if code != 200 or "lease_id" not in body:
            return False
        self.lease_id = str(body["lease_id"])
        self.epoch = int(body.get("epoch", 0))
        self.ttl_s = float(body.get("ttl_s", self.ttl_s))
        obs_events.emit(
            "federation", "joined", log=self._log,
            host=self.host_id, epoch=self.epoch,
            front=f"{self.front_addr[0]}:{self.front_addr[1]}",
        )
        return True

    def renew(self) -> bool:
        """One renewal; False = refused (must re-register)."""
        code, body = self._call_front("/fed/renew", {
            "host_id": self.host_id,
            "lease_id": self.lease_id or "",
        })
        if code != 200:
            return False
        self.ttl_s = float(body.get("ttl_s", self.ttl_s))
        return True

    # -- lifecycle -----------------------------------------------------------

    def start(self, port: int) -> None:
        self.port = port
        threading.Thread(
            target=self._join_loop,
            name=f"roko-federation-join-{self.host_id}",
            daemon=True,
        ).start()

    def stop(self) -> None:
        self._stop.set()

    def _join_loop(self) -> None:
        stop = self._stop
        registered = False
        while not stop.is_set():
            try:
                if not registered:
                    registered = self.register()
                    if not registered:
                        stop.wait(min(1.0, self.ttl_s / 3.0))
                        continue
                elif not self.renew():
                    # refused: the lease died (partition outlived the
                    # TTL, or the front end restarted). Re-register NOW
                    # — the bump fences whatever still claims the old
                    # epoch.
                    obs_events.emit(
                        "federation", "lease_refused", log=self._log,
                        suffix="— re-registering",
                        host=self.host_id, epoch=self.epoch,
                    )
                    registered = False
                    continue
            except _CONN_ERRORS:
                # partition: the lease may still be live at the front —
                # keep the lease_id and retry; an expired lease turns
                # into a refused renewal above once the net heals
                stop.wait(min(1.0, self.ttl_s / 3.0))
                continue
            stop.wait(self.ttl_s / 3.0)


def make_agent_handler(agent: HostAgent):
    """The host agent's HTTP surface: the full supervisor front
    (``_FrontHandler`` — relays, rollout, jobs, metrics) with the
    federation plane layered on: every reply echoes the agent's epoch,
    ``/polish`` fences mismatched relays with 409, ``/scale`` resizes
    the local fleet, and ``/healthz`` carries the host identity +
    backlog the front-end autoscaler reads."""
    from roko_tpu.serve.supervisor import _FrontHandler

    class _AgentHandler(_FrontHandler):
        def _reply(self, code, body, content_type="application/json",
                   extra=None):
            extra = dict(extra or {})
            # the echo is unconditional — fencing at the front end
            # must work on every path, including errors
            extra[FED_EPOCH_HEADER] = str(self.agent.epoch)
            super()._reply(
                code, body, content_type=content_type, extra=extra
            )

        def do_GET(self):  # noqa: N802 - http.server API
            if self.path == "/healthz":
                body = self.fleet.summary()
                body["host_id"] = self.agent.host_id
                body["epoch"] = self.agent.epoch
                if self.server._draining.is_set():  # type: ignore[attr-defined]
                    body["status"], body["code"] = "draining", 503
                code = body.pop("code")
                self._reply_json(code, body)
                return
            super().do_GET()

        def do_POST(self):  # noqa: N802 - http.server API
            if self.path == "/scale":
                raw = self._read_body()
                if raw is None:
                    return
                try:
                    payload = json.loads(raw.decode() or "{}")
                    workers = payload.get("workers")
                    if not isinstance(workers, int) or workers < 1:
                        raise ValueError("workers must be an int >= 1")
                except (ValueError, UnicodeDecodeError) as e:
                    self._reply_json(400, {"error": str(e)})
                    return
                self.fleet.scale_to(workers, reason="federation")
                self._reply_json(
                    200,
                    {"host_id": self.agent.host_id, "workers": workers},
                )
                return
            if self.path == "/polish":
                want = self.headers.get(FED_EPOCH_HEADER)
                mine = self.agent.epoch
                if want is not None and mine and want != str(mine):
                    # the registry knows a newer epoch than this
                    # process: we ARE the zombie (stale lease) — refuse
                    # at the source, never touch a worker
                    obs_events.emit(
                        "federation", "fenced", log=self.agent._log,
                        request_id=self.headers.get("X-Roko-Request-Id"),
                        host=self.agent.host_id,
                        relay_epoch=want, agent_epoch=mine,
                    )
                    self._reply_json(
                        409,
                        {"error": f"fenced: relay epoch {want} != "
                                  f"agent epoch {mine}",
                         "fenced": True},
                    )
                    return
            super().do_POST()

    _AgentHandler.agent = agent
    return _AgentHandler


def run_host_agent(
    model_path: str,
    cfg: RokoConfig,
    *,
    announce: Optional[str] = None,
    log=print,
) -> int:
    """The ``roko-tpu serve MODEL --host-agent --join HOST:PORT`` entry
    point: a full supervisor (fleet + rollout recovery + autoscaler +
    rolling SIGTERM drain — :func:`~roko_tpu.serve.supervisor.boot_fleet`
    machinery, unchanged) that additionally joins a federation front
    and speaks the lease/epoch protocol."""
    import dataclasses as _dc

    from roko_tpu.parallel.mesh import resolve_fleet_topology
    from roko_tpu.serve.supervisor import (
        boot_fleet,
        make_front_server,
        make_rollout_starter,
        rolling_drain,
        start_autoscaler,
    )

    fc = resolve_fleet_topology(cfg.fleet)
    if fc is not cfg.fleet:
        cfg = _dc.replace(cfg, fleet=fc)
    fleet, journal, recovery, boot_version, boot_model, boot_cfg = (
        boot_fleet(model_path, cfg, log=log)
    )
    agent = HostAgent(fleet, cfg, log=log)
    server = make_front_server(
        fleet, handler_base=make_agent_handler(agent)
    )
    if cfg.fleet.ab_version and cfg.fleet.ab_fraction > 0:
        server._ab_lane = (  # type: ignore[attr-defined]
            cfg.fleet.ab_version, cfg.fleet.ab_fraction
        )
    server._start_rollout = make_rollout_starter(  # type: ignore[attr-defined]
        fleet, journal, boot_model, boot_cfg, log=log
    )
    from roko_tpu.pipeline.distpolish import make_job_starter

    server._start_job = make_job_starter(  # type: ignore[attr-defined]
        fleet, boot_cfg, log=log
    )
    if announce:
        write_announce(announce, server.server_address[1])
    log(
        f"roko federation: host agent {agent.host_id!r} supervising "
        f"{cfg.fleet.workers} worker(s), joining "
        f"{agent.front_addr[0]}:{agent.front_addr[1]} "
        f"(version {boot_version})"
    )
    fleet.start()
    if recovery is not None:
        journal.delete()
    autoscale_stop = threading.Event()
    fleet.autoscaler = start_autoscaler(  # type: ignore[attr-defined]
        fleet, autoscale_stop, log=log
    )
    agent.start(server.server_address[1])
    try:
        serve_forever(
            server,
            log=log,
            drain_fn=lambda: rolling_drain(server, fleet, log=log),
        )
    finally:
        agent.stop()
        autoscale_stop.set()
        fleet.stop(rolling=False)
    return 0
