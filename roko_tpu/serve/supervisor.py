"""Supervising front end for the multi-worker serving tier
(docs/SERVING.md "Multi-worker topology & failure handling").

``roko-tpu serve CKPT --workers N`` runs THIS process instead of a
PolishSession: it forks N ``roko-tpu serve`` worker processes (each a
full warm single-process stack pinned to its device slice, sharing one
AOT bundle) via :class:`~roko_tpu.serve.fleet.Fleet`, and puts a thin
HTTP surface over the fleet:

- ``POST /polish`` — admission control (bounded in-flight, 503 +
  ``Retry-After`` past it) then failover routing: the body is relayed
  verbatim to a ready worker; a worker dying mid-request is retried on
  another worker transparently (polish is idempotent), so clients see
  latency, never the crash.
- ``GET /healthz`` — fleet aggregate (``ok`` / ``degraded`` with 200,
  ``warming`` / ``unhealthy`` / ``draining`` with 503) plus the
  per-worker state map.
- ``GET /metrics`` — ``roko_fleet_*`` series plus selected per-worker
  gauges re-labeled by worker id.

The supervisor process NEVER initialises a jax backend: on TPU it must
not claim the chips its workers need, so it loads no params, builds no
mesh, and computes device slices with the pure
``parallel.mesh.fleet_worker_env`` helper.

SIGTERM is a rolling drain: the front end stops admitting and finishes
in-flight relays first, then workers are SIGTERMed one at a time (each
drains its own in-flight under ``--drain-deadline``, escalating to
SIGKILL after ``term_grace_s``) — no mid-request connection resets on
the way down.
"""

from __future__ import annotations

import os
import sys
from http.server import ThreadingHTTPServer
from typing import Callable, List, Optional

from roko_tpu.config import RokoConfig
from roko_tpu.parallel.mesh import fleet_worker_env
from roko_tpu.serve.fleet import Fleet, write_announce
from roko_tpu.serve.server import (
    JsonRequestHandler,
    drain,
    init_lifecycle,
    serve_forever,
)


class _FrontHandler(JsonRequestHandler):
    # set by make_front_server on the class copy
    fleet: Fleet

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path == "/healthz":
            body = self.fleet.summary()
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                body["status"], body["code"] = "draining", 503
            code = body.pop("code")
            self._reply_json(code, body)
        elif self.path == "/metrics":
            self._reply(
                200,
                self.fleet.render_metrics().encode(),
                content_type="text/plain; version=0.0.4",
            )
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path != "/polish":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        fleet = self.fleet
        retry = fleet.cfg.serve.retry_after_s
        with self._track_inflight():
            # draining checked AFTER the increment (same TOCTOU rule as
            # the worker server: drain() watches the counter)
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                self.close_connection = True
                self._reply_json(
                    503,
                    {"error": "fleet draining", "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            with self.server._inflight_lock:  # type: ignore[attr-defined]
                inflight = self.server._inflight  # type: ignore[attr-defined]
            if inflight > fleet.max_inflight:
                # admission control: past the fleet's aggregate queue
                # capacity, shed here instead of stacking relays behind
                # workers that will 503 anyway
                fleet.inc("rejected")
                self._reply_json(
                    503,
                    {"error": "fleet at capacity",
                     "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            try:
                body = self._read_body()
            except TimeoutError:
                # peer stalled mid-body past the socket timeout
                self.close_connection = True
                self._reply_json(
                    503, {"error": "timed out reading the request"}
                )
                return
            if body is None:
                return  # error reply already sent
            fleet.inc("requests")
            code, reply, extra = fleet.post_polish(body)
            if code == 503:
                self.close_connection = True
            self._reply(code, reply, extra=extra)


def make_front_server(
    fleet: Fleet,
    host: Optional[str] = None,
    port: Optional[int] = None,
) -> ThreadingHTTPServer:
    """Bind the supervisor front end (port 0 = ephemeral) and return
    the server; the caller runs ``serve_forever``. The fleet rides on
    the server object (``.fleet``) and the lifecycle state matches the
    worker server's, so :func:`roko_tpu.serve.server.drain` works on
    it unchanged."""
    serve_cfg = fleet.cfg.serve
    handler = type("RokoFleetHandler", (_FrontHandler,), {"fleet": fleet})
    server = ThreadingHTTPServer(
        (serve_cfg.host if host is None else host,
         serve_cfg.port if port is None else port),
        handler,
    )
    server.fleet = fleet  # type: ignore[attr-defined]
    init_lifecycle(server, fleet.cfg.resilience.drain_deadline_s)
    return server


def worker_command(
    model_path: str, config_path: str
) -> Callable[[int, str], List[str]]:
    """argv builder for real ``roko-tpu serve`` workers: ephemeral
    loopback port, port announced through ``announce_path``, config via
    the shared JSON (``--worker-id`` keeps the child out of supervisor
    mode)."""

    def build(worker_id: int, announce_path: str) -> List[str]:
        return [
            sys.executable, "-m", "roko_tpu", "serve", model_path,
            "--config", config_path,
            "--host", "127.0.0.1", "--port", "0",
            "--worker-id", str(worker_id),
            "--announce", announce_path,
        ]

    return build


def rolling_drain(
    server: ThreadingHTTPServer, fleet: Fleet, log=print
) -> None:
    """SIGTERM path: drain the front end (reject new, finish in-flight
    relays, stop the accept loop), THEN terminate workers one at a
    time — each worker drains its own in-flight before the next is
    touched."""
    drain(server, log=log)
    log("roko fleet: rolling worker drain")
    fleet.stop(rolling=True)


def run_supervisor(
    model_path: str,
    cfg: RokoConfig,
    *,
    announce: Optional[str] = None,
    log=print,
) -> int:
    """The ``roko-tpu serve --workers N`` entry point: spawn the fleet,
    bind the front end, serve until SIGTERM/Ctrl-C. ``announce`` (used
    by tests/automation) writes ``{"pid", "port"}`` once the front-end
    socket is bound — the same contract workers honour."""
    fc = cfg.fleet
    # the worker config: fleet.workers zeroed so a worker can never
    # recurse into supervisor mode, everything else (model geometry,
    # serve ladder, AOT bundle, resilience knobs) shared verbatim
    import dataclasses

    fleet = Fleet(
        cfg,
        worker_command=(lambda *_: []),  # bound below, needs runtime_dir
        worker_env=lambda wid: fleet_worker_env(
            wid, fc.workers, fc.devices_per_worker
        ),
        log=log,
    )
    os.makedirs(fleet.runtime_dir, exist_ok=True)
    config_path = os.path.join(fleet.runtime_dir, "worker-config.json")
    worker_cfg = dataclasses.replace(
        cfg, fleet=dataclasses.replace(fc, workers=0)
    )
    with open(config_path, "w") as f:
        f.write(worker_cfg.to_json())
    fleet._command = worker_command(model_path, config_path)

    server = make_front_server(fleet)
    if announce:
        write_announce(announce, server.server_address[1])
    log(
        f"roko fleet: supervising {fc.workers} worker(s) "
        f"(runtime dir {fleet.runtime_dir}); front end binding"
    )
    fleet.start()
    try:
        serve_forever(
            server,
            log=log,
            drain_fn=lambda: rolling_drain(server, fleet, log=log),
        )
    finally:
        # Ctrl-C / accept-loop exit: make sure no worker outlives the
        # supervisor (stop() is idempotent — a completed rolling drain
        # already did this)
        fleet.stop(rolling=False)
    return 0
