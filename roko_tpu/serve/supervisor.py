"""Supervising front end for the multi-worker serving tier
(docs/SERVING.md "Multi-worker topology & failure handling").

``roko-tpu serve CKPT --workers N`` runs THIS process instead of a
PolishSession: it forks N ``roko-tpu serve`` worker processes (each a
full warm single-process stack pinned to its device slice, sharing one
AOT bundle) via :class:`~roko_tpu.serve.fleet.Fleet`, and puts a thin
HTTP surface over the fleet:

- ``POST /polish`` — admission control (bounded in-flight, 503 +
  ``Retry-After`` past it) then failover routing: the body is relayed
  verbatim to a ready worker; a worker dying mid-request is retried on
  another worker transparently (polish is idempotent), so clients see
  latency, never the crash.
- ``GET /healthz`` — fleet aggregate (``ok`` / ``degraded`` with 200,
  ``warming`` / ``unhealthy`` / ``draining`` with 503) plus the
  per-worker state map.
- ``GET /metrics`` — ``roko_fleet_*`` series plus selected per-worker
  gauges re-labeled by worker id.
- ``POST /rollout`` / ``GET /rollout`` — start / observe a
  health-gated zero-downtime rollout onto a registered model version
  (``serve/rollout.py``, docs/SERVING.md "Model lifecycle").

Every front-end 503 (draining, at capacity, no worker available)
carries the LARGEST live worker ``Retry-After`` hint (each worker
estimates its own from live backlog over observed throughput and
reports it in ``/healthz``); the static ``serve.retry_after_s`` is only
the fallback when no worker has answered.

The supervisor process NEVER initialises a jax backend: on TPU it must
not claim the chips its workers need, so it loads no params, builds no
mesh, and computes device slices with the pure
``parallel.mesh.fleet_worker_env`` helper.

SIGTERM is a rolling drain: the front end stops admitting and finishes
in-flight relays first, then workers are SIGTERMed one at a time (each
drains its own in-flight under ``--drain-deadline``, escalating to
SIGKILL after ``term_grace_s``) — no mid-request connection resets on
the way down.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from roko_tpu.config import ModelConfig, RokoConfig
from roko_tpu.obs import events as obs_events
from roko_tpu.obs.trace import new_request_id
from roko_tpu.parallel.mesh import fleet_worker_env, resolve_fleet_topology
from roko_tpu.serve.fleet import (
    BOOT_VERSION,
    Fleet,
    WorkerLaunchSpec,
    write_announce,
)
from roko_tpu.serve.registry import (
    RegistryError,
    resolve_model,
    resolve_registry_dir,
)
from roko_tpu.serve.rollout import (
    CurrentVersionFile,
    RolloutController,
    RolloutJournal,
    recover_rollout,
)
from roko_tpu.serve.server import (
    JsonRequestHandler,
    drain,
    init_lifecycle,
    serve_forever,
)


class _FrontHandler(JsonRequestHandler):
    # set by make_front_server on the class copy
    fleet: Fleet

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] == "/tracez":
            # aggregate view: every worker's trace ring + scheduler
            # snapshot keyed by worker id (docs/OBSERVABILITY.md) — the
            # request_id assigned here at the front end is what each
            # worker's records carry, so one id greps across the fleet
            parts = self.path.split("?", 1)
            self._reply_json(
                200, self.fleet.tracez(parts[1] if len(parts) > 1 else "")
            )
        elif self.path == "/healthz":
            body = self.fleet.summary()
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                body["status"], body["code"] = "draining", 503
            code = body.pop("code")
            self._reply_json(code, body)
        elif self.path == "/metrics":
            self._reply(
                200,
                self.fleet.render_metrics().encode(),
                content_type="text/plain; version=0.0.4",
            )
        elif self.path == "/rollout":
            ctl = self.fleet.rollout
            self._reply_json(
                200, ctl.status() if ctl is not None else {"state": "idle"}
            )
        elif self.path == "/jobz":
            # distributed-polish job status: per-unit state table
            # (docs/PIPELINE.md "Distributed polish")
            job = getattr(self.fleet, "job", None)
            self._reply_json(
                200, job.snapshot() if job is not None else {"state": "idle"}
            )
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def _handle_starter(self, attr: str, what: str) -> None:
        """Shared POST plumbing for the operator surfaces whose
        implementation run_supervisor wires onto the server object
        (``/rollout`` and ``/job``): 501 when unconfigured, bounded
        body read, JSON-object validation, then ``(code, body)`` from
        the starter."""
        starter = getattr(self.server, attr, None)
        if starter is None:
            self._reply_json(
                501,
                {"error": f"{what} is not configured on this front end "
                          "(run via `roko-tpu serve --workers N`)"},
            )
            return
        raw = self._read_body()
        if raw is None:
            return  # error reply already sent
        try:
            payload = json.loads(raw.decode() or "{}")
            if not isinstance(payload, dict):
                raise ValueError("payload must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            self._reply_json(400, {"error": str(e)})
            return
        code, body = starter(payload)
        self._reply_json(code, body)

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path == "/rollout":
            self._handle_starter("_start_rollout", "rollout")
            return
        if self.path == "/job":
            # submit a whole-genome distributed polish (server-side
            # ref/bam/out paths) over THIS fleet (docs/PIPELINE.md
            # "Distributed polish"); observe with GET /jobz
            self._handle_starter(
                "_start_job", "distributed polish jobs"
            )
            return
        if self.path != "/polish":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        fleet = self.fleet
        with self._track_inflight():
            # draining checked AFTER the increment (same TOCTOU rule as
            # the worker server: drain() watches the counter)
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                self.close_connection = True
                # live hint: the max Retry-After any up worker last
                # reported (static config value when none have
                # answered) — computed only on the 503 paths, never the
                # hot success path (it sweeps every worker's waitpid)
                retry = fleet.live_retry_after_s()
                self._reply_json(
                    503,
                    {"error": "fleet draining", "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            with self.server._inflight_lock:  # type: ignore[attr-defined]
                inflight = self.server._inflight  # type: ignore[attr-defined]
            if inflight > fleet.max_inflight:
                # admission control: past the fleet's aggregate queue
                # capacity, shed here instead of stacking relays behind
                # workers that will 503 anyway
                fleet.inc("rejected")
                retry = fleet.live_retry_after_s()
                self._reply_json(
                    503,
                    {"error": "fleet at capacity",
                     "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            try:
                body = self._read_body()
            except TimeoutError:
                # peer stalled mid-body past the socket timeout
                self.close_connection = True
                self._reply_json(
                    503, {"error": "timed out reading the request"}
                )
                return
            if body is None:
                return  # error reply already sent
            fleet.inc("requests")
            # the request id is minted HERE (or honored from the
            # client's header) and preserved across failover
            # re-dispatch: the reply, the worker's /tracez record, and
            # the event log all carry the front end's id
            rid = (
                self.headers.get("X-Roko-Request-Id") or new_request_id()
            )
            code, reply, extra = fleet.post_polish(body, request_id=rid)
            if code == 503:
                self.close_connection = True
            self._reply(code, reply, extra=extra)


def make_front_server(
    fleet: Fleet,
    host: Optional[str] = None,
    port: Optional[int] = None,
) -> ThreadingHTTPServer:
    """Bind the supervisor front end (port 0 = ephemeral) and return
    the server; the caller runs ``serve_forever``. The fleet rides on
    the server object (``.fleet``) and the lifecycle state matches the
    worker server's, so :func:`roko_tpu.serve.server.drain` works on
    it unchanged."""
    serve_cfg = fleet.cfg.serve
    handler = type("RokoFleetHandler", (_FrontHandler,), {"fleet": fleet})
    server = ThreadingHTTPServer(
        (serve_cfg.host if host is None else host,
         serve_cfg.port if port is None else port),
        handler,
    )
    server.fleet = fleet  # type: ignore[attr-defined]
    #: POST /rollout implementation; run_supervisor wires the real one
    #: (needs the registry + journal), bare front ends answer 501
    server._start_rollout = None  # type: ignore[attr-defined]
    #: POST /job implementation (distributed polish); run_supervisor
    #: wires it, bare front ends answer 501
    server._start_job = None  # type: ignore[attr-defined]
    init_lifecycle(server, fleet.cfg.resilience.drain_deadline_s)
    return server


def worker_command(
    model_path: str, config_path: str
) -> Callable[[int, str], List[str]]:
    """argv builder for real ``roko-tpu serve`` workers: ephemeral
    loopback port, port announced through ``announce_path``, config via
    the shared JSON (``--worker-id`` keeps the child out of supervisor
    mode)."""

    def build(worker_id: int, announce_path: str) -> List[str]:
        return [
            sys.executable, "-m", "roko_tpu", "serve", model_path,
            "--config", config_path,
            "--host", "127.0.0.1", "--port", "0",
            "--worker-id", str(worker_id),
            "--announce", announce_path,
        ]

    return build


def worker_launch_spec(
    version: str,
    model_path: str,
    cfg: RokoConfig,
    runtime_dir: str,
    *,
    meta: Optional[Dict[str, Any]] = None,
) -> WorkerLaunchSpec:
    """THE builder for what a worker of ``version`` runs: writes the
    per-version worker config JSON (``fleet.workers`` zeroed so a child
    can never recurse into supervisor mode; the version's AOT bundle
    riding in ``compile.bundle_dir``) and returns the spec initial
    spawn, crash restart, and rollout all resolve through —
    ``Fleet._spawn`` reads nothing else, so the three paths cannot
    drift on which bundle/params a worker gets."""
    fc = cfg.fleet
    worker_cfg = dataclasses.replace(
        cfg, fleet=dataclasses.replace(fc, workers=0)
    )
    os.makedirs(runtime_dir, exist_ok=True)
    config_path = os.path.join(
        runtime_dir, f"worker-config-{version}.json"
    )
    with open(config_path, "w") as f:
        f.write(worker_cfg.to_json())
    spec_meta: Dict[str, Any] = {
        "model_path": model_path,
        "bundle_dir": cfg.compile.bundle_dir,
        "model": dataclasses.asdict(cfg.model),
    }
    spec_meta.update(meta or {})
    return WorkerLaunchSpec(
        worker_command(model_path, config_path),
        env=lambda wid: fleet_worker_env(
            wid, fc.workers, fc.devices_per_worker
        ),
        version=version,
        meta=spec_meta,
    )


def _version_config(cfg: RokoConfig, side: Dict[str, Any]) -> RokoConfig:
    """The supervisor config specialised to one version's identity: the
    side dict (a registry entry, or a journal record's from/to block)
    names the bundle dir and — when it carries one — the full
    ModelConfig the bundle was compiled for, so a rollout across model
    kinds or precision variants launches workers whose config matches
    the bundle digest instead of refusing at warmup."""
    out = dataclasses.replace(
        cfg,
        compile=dataclasses.replace(
            cfg.compile, bundle_dir=side.get("bundle_dir")
        ),
    )
    model = side.get("model") or {}
    if model:
        out = dataclasses.replace(
            out,
            model=ModelConfig(
                **{
                    k: tuple(v) if k == "read_mlp" else v
                    for k, v in model.items()
                }
            ),
        )
    return out


def make_rollout_starter(
    fleet: Fleet,
    journal: RolloutJournal,
    model_path: str,
    cfg: RokoConfig,
    log=print,
) -> Callable[[Dict[str, Any]], Tuple[int, Dict[str, Any]]]:
    """The ``POST /rollout`` implementation: resolve+verify the named
    registry version, install its launch spec, and start a
    :class:`RolloutController` — one at a time (409 while one is
    active). Returns ``(http_code, json_body)``."""
    lock = threading.Lock()

    def start(payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        name = payload.get("name")
        if not isinstance(name, str) or not name:
            return 400, {"error": "body must carry the model version "
                                  '{"name": "<registered name>"}'}
        overrides = {}
        for key in ("bake_s", "rollback_error_pct", "rollback_p99_x",
                    "ready_timeout_s"):
            val = payload.get(key)
            if val is None:
                continue
            if not isinstance(val, (int, float)) or val < 0:
                return 400, {"error": f"{key} must be a non-negative "
                                      "number"}
            overrides[key] = float(val)
        with lock:
            ctl = fleet.rollout
            if ctl is not None and ctl.active():
                return 409, {
                    "error": "a rollout is already in progress",
                    "status": ctl.status(),
                }
            job = getattr(fleet, "job", None)
            if job is not None and job.active():
                # a rollout mid-job would splice two versions' contigs
                # into one rc-0 FASTA — the exact mix the distributed
                # journal identity exists to refuse (docs/PIPELINE.md
                # "Distributed polish"); the job side refuses the
                # mirror-image race
                return 409, {
                    "error": "a distributed polish job is running; "
                             "refusing to roll worker versions "
                             "underneath it",
                    "job": job.snapshot(),
                }
            if fleet.active_version == name:
                return 409, {
                    "error": f"fleet is already on version {name!r}",
                }
            try:
                entry = resolve_model(
                    resolve_registry_dir(fleet.fleet_cfg.registry_dir),
                    name,
                )
            except RegistryError as e:
                return 400, {"error": str(e)}
            # ALWAYS rebuild the spec from the freshly verified entry —
            # a version re-registered (--force) since a failed attempt
            # must roll out its NEW bytes, not a stale cached spec. The
            # admission check runs FIRST: building a spec writes the
            # per-version worker config, and a refused swap must not
            # have already changed what a live worker's next
            # crash-restart would run.
            if not fleet.spec_installable(name):
                return 409, {
                    "error": f"launch spec {name!r} is live on the "
                             "fleet; refusing to swap it underneath "
                             "running workers",
                }
            # a bundle-only version (no params pinned) rolls out
            # against the fleet's CURRENT incumbent checkpoint — the
            # active spec's params, which after an earlier rollout is
            # NOT the checkpoint the CLI was started with
            incumbent_params = (
                fleet.launch_spec().meta.get("model_path") or model_path
            )
            try:
                fleet.add_launch_spec(
                    worker_launch_spec(
                        name,
                        entry.get("params_path") or incumbent_params,
                        _version_config(cfg, entry),
                        fleet.runtime_dir,
                        meta={"bundle_digest": entry["bundle_digest"]},
                    )
                )
            except ValueError as e:  # raced; the backstop still holds
                return 409, {"error": str(e)}
            ctl = RolloutController(
                fleet, name, journal=journal, log=log, **overrides
            )
            fleet.rollout = ctl
            ctl.start()
            return 202, ctl.status()

    return start


def rolling_drain(
    server: ThreadingHTTPServer, fleet: Fleet, log=print
) -> None:
    """SIGTERM path: drain the front end (reject new, finish in-flight
    relays, stop the accept loop), THEN terminate workers one at a
    time — each worker drains its own in-flight before the next is
    touched."""
    drain(server, log=log)
    log("roko fleet: rolling worker drain")
    fleet.stop(rolling=True)


def run_supervisor(
    model_path: str,
    cfg: RokoConfig,
    *,
    announce: Optional[str] = None,
    log=print,
) -> int:
    """The ``roko-tpu serve --workers N`` entry point: spawn the fleet,
    bind the front end, serve until SIGTERM/Ctrl-C. ``announce`` (used
    by tests/automation) writes ``{"pid", "port"}`` once the front-end
    socket is bound — the same contract workers honour.

    Before anything spawns, the rollout journal in the runtime dir is
    consulted: a supervisor killed mid-rollout restarts onto ONE
    version — finalized forward when every worker had already rolled,
    reverted to the journaled incumbent otherwise — loudly, never a
    silently mixed fleet (``serve/rollout.py``)."""
    # idempotent for CLI callers (cmd_serve already resolved); the real
    # guard for programmatic users: --workers auto resolves against the
    # visible devices and an oversubscribing worker x mesh combination
    # refuses before anything spawns — all without initialising jax
    fc = resolve_fleet_topology(cfg.fleet)
    if fc is not cfg.fleet:
        cfg = dataclasses.replace(cfg, fleet=fc)
    fleet = Fleet(
        cfg,
        worker_command=(lambda *_: []),  # placeholder; boot spec below
        log=log,
    )
    os.makedirs(fleet.runtime_dir, exist_ok=True)
    journal = RolloutJournal(
        os.path.join(fleet.runtime_dir, RolloutJournal.FILENAME)
    )
    current = CurrentVersionFile(
        os.path.join(fleet.runtime_dir, CurrentVersionFile.FILENAME)
    )
    boot_version, boot_model, boot_cfg = BOOT_VERSION, model_path, cfg
    recovery = recover_rollout(journal, log)
    if recovery is not None:
        rec = recovery["record"]
        side = rec["to"] if recovery["action"] == "finalize" else rec["from"]
        boot_version = side.get("version") or BOOT_VERSION
        boot_model = side.get("model_path") or model_path
        boot_cfg = _version_config(cfg, side)
        # keep the landed-version pointer consistent with the decision
        if boot_version == BOOT_VERSION:
            current.delete()
        else:
            current.write(side)
    else:
        # no interrupted rollout — but a COMPLETED one must survive a
        # plain supervisor restart: re-pin the landed version instead
        # of silently re-booting the CLI-named incumbent
        pinned = current.load(log)
        if pinned and (pinned.get("version") or BOOT_VERSION) != BOOT_VERSION:
            boot_version = pinned["version"]
            boot_model = pinned.get("model_path") or model_path
            boot_cfg = _version_config(cfg, pinned)
            obs_events.emit(
                "rollout", "version_pinned", log=log,
                suffix="— restart re-pins the landed rollout version",
                version=boot_version,
                bundle_digest=str(pinned.get("bundle_digest", "?"))[:12],
            )
    fleet.install_boot_spec(
        worker_launch_spec(
            boot_version, boot_model, boot_cfg, fleet.runtime_dir
        )
    )

    server = make_front_server(fleet)
    # the starter's fallback identity is what the fleet actually BOOTED
    # (a recovered/pinned version, not necessarily the CLI args)
    server._start_rollout = make_rollout_starter(  # type: ignore[attr-defined]
        fleet, journal, boot_model, boot_cfg, log=log
    )
    # distributed-polish jobs over this fleet (POST /job + GET /jobz;
    # docs/PIPELINE.md "Distributed polish") — lazy import: the job
    # starter pulls the pipeline package, which the bare serving path
    # never needs
    from roko_tpu.pipeline.distpolish import make_job_starter

    server._start_job = make_job_starter(  # type: ignore[attr-defined]
        fleet, boot_cfg, log=log
    )
    if announce:
        write_announce(announce, server.server_address[1])
    log(
        f"roko fleet: supervising {fc.workers} worker(s) "
        f"(runtime dir {fleet.runtime_dir}, version {boot_version}); "
        "front end binding"
    )
    fleet.start()
    if recovery is not None:
        # every worker just spawned from the one recovered spec — the
        # fleet is uniform again and the journal has done its job
        journal.delete()
    try:
        serve_forever(
            server,
            log=log,
            drain_fn=lambda: rolling_drain(server, fleet, log=log),
        )
    finally:
        # Ctrl-C / accept-loop exit: make sure no worker outlives the
        # supervisor (stop() is idempotent — a completed rolling drain
        # already did this)
        fleet.stop(rolling=False)
    return 0
