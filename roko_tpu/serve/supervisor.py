"""Supervising front end for the multi-worker serving tier
(docs/SERVING.md "Multi-worker topology & failure handling").

``roko-tpu serve CKPT --workers N`` runs THIS process instead of a
PolishSession: it forks N ``roko-tpu serve`` worker processes (each a
full warm single-process stack pinned to its device slice, sharing one
AOT bundle) via :class:`~roko_tpu.serve.fleet.Fleet`, and puts a thin
HTTP surface over the fleet:

- ``POST /polish`` — admission control (bounded in-flight, 503 +
  ``Retry-After`` past it) then failover routing: the body is relayed
  verbatim to a ready worker; a worker dying mid-request is retried on
  another worker transparently (polish is idempotent), so clients see
  latency, never the crash.
- ``GET /healthz`` — fleet aggregate (``ok`` / ``degraded`` with 200,
  ``warming`` / ``unhealthy`` / ``draining`` with 503) plus the
  per-worker state map.
- ``GET /metrics`` — ``roko_fleet_*`` series plus selected per-worker
  gauges re-labeled by worker id.
- ``POST /rollout`` / ``GET /rollout`` — start / observe a
  health-gated zero-downtime rollout onto a registered model version
  (``serve/rollout.py``, docs/SERVING.md "Model lifecycle").

Every front-end 503 (draining, at capacity, no worker available)
carries the LARGEST live worker ``Retry-After`` hint (each worker
estimates its own from live backlog over observed throughput and
reports it in ``/healthz``); the static ``serve.retry_after_s`` is only
the fallback when no worker has answered.

The supervisor process NEVER initialises a jax backend: on TPU it must
not claim the chips its workers need, so it loads no params, builds no
mesh, and computes device slices with the pure
``parallel.mesh.fleet_worker_env`` helper.

SIGTERM is a rolling drain: the front end stops admitting and finishes
in-flight relays first, then workers are SIGTERMed one at a time (each
drains its own in-flight under ``--drain-deadline``, escalating to
SIGKILL after ``term_grace_s``) — no mid-request connection resets on
the way down.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import sys
import threading
import time
from http.server import ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from roko_tpu.config import ModelConfig, RokoConfig
from roko_tpu.obs import events as obs_events
from roko_tpu.obs.trace import new_request_id
from roko_tpu.parallel.mesh import fleet_worker_env, resolve_fleet_topology
from roko_tpu.serve.fleet import (
    BOOT_VERSION,
    Fleet,
    WorkerLaunchSpec,
    write_announce,
)
from roko_tpu.serve.registry import (
    RegistryError,
    resolve_model,
    resolve_registry_dir,
)
from roko_tpu.serve.rollout import (
    CurrentVersionFile,
    RolloutController,
    RolloutJournal,
    recover_rollout,
)
from roko_tpu.serve.server import (
    _NAME_RE,
    JsonRequestHandler,
    drain,
    init_lifecycle,
    request_tenant,
    serve_forever,
)


class _FrontHandler(JsonRequestHandler):
    # set by make_front_server on the class copy
    fleet: Fleet

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] == "/tracez":
            # aggregate view: every worker's trace ring + scheduler
            # snapshot keyed by worker id (docs/OBSERVABILITY.md) — the
            # request_id assigned here at the front end is what each
            # worker's records carry, so one id greps across the fleet
            parts = self.path.split("?", 1)
            self._reply_json(
                200, self.fleet.tracez(parts[1] if len(parts) > 1 else "")
            )
        elif self.path == "/healthz":
            body = self.fleet.summary()
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                body["status"], body["code"] = "draining", 503
            code = body.pop("code")
            self._reply_json(code, body)
        elif self.path == "/metrics":
            self._reply(
                200,
                self.fleet.render_metrics().encode(),
                content_type="text/plain; version=0.0.4",
            )
        elif self.path == "/rollout":
            ctl = self.fleet.rollout
            self._reply_json(
                200, ctl.status() if ctl is not None else {"state": "idle"}
            )
        elif self.path == "/jobz":
            # distributed-polish job status: per-unit state table
            # (docs/PIPELINE.md "Distributed polish")
            job = getattr(self.fleet, "job", None)
            self._reply_json(
                200, job.snapshot() if job is not None else {"state": "idle"}
            )
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def _handle_starter(self, attr: str, what: str) -> None:
        """Shared POST plumbing for the operator surfaces whose
        implementation run_supervisor wires onto the server object
        (``/rollout`` and ``/job``): 501 when unconfigured, bounded
        body read, JSON-object validation, then ``(code, body)`` from
        the starter."""
        starter = getattr(self.server, attr, None)
        if starter is None:
            self._reply_json(
                501,
                {"error": f"{what} is not configured on this front end "
                          "(run via `roko-tpu serve --workers N`)"},
            )
            return
        raw = self._read_body()
        if raw is None:
            return  # error reply already sent
        try:
            payload = json.loads(raw.decode() or "{}")
            if not isinstance(payload, dict):
                raise ValueError("payload must be a JSON object")
        except (ValueError, UnicodeDecodeError) as e:
            self._reply_json(400, {"error": str(e)})
            return
        code, body = starter(payload)
        self._reply_json(code, body)

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path == "/rollout":
            self._handle_starter("_start_rollout", "rollout")
            return
        if self.path == "/job":
            # submit a whole-genome distributed polish (server-side
            # ref/bam/out paths) over THIS fleet (docs/PIPELINE.md
            # "Distributed polish"); observe with GET /jobz
            self._handle_starter(
                "_start_job", "distributed polish jobs"
            )
            return
        if self.path != "/polish":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        fleet = self.fleet
        # tenant / model-lane identity ride in headers — the front end
        # never parses the (possibly 256 MiB) body to route
        try:
            tenant = request_tenant(self.headers, {})
        except ValueError as e:
            self._reply_json(400, {"error": str(e)})
            return
        model = self.headers.get("X-Roko-Model")
        pinned = model is not None
        if pinned and not _NAME_RE.match(model):
            self._reply_json(
                400,
                {"error": "model name must match "
                          "[A-Za-z0-9][A-Za-z0-9._-]{0,63}"},
            )
            return
        with self._track_inflight():
            # draining checked AFTER the increment (same TOCTOU rule as
            # the worker server: drain() watches the counter)
            if self.server._draining.is_set():  # type: ignore[attr-defined]
                self.close_connection = True
                # live hint: the max Retry-After any up worker last
                # reported (static config value when none have
                # answered) — sized from the REQUESTING tenant's backlog
                # and drain rate when the workers report per-tenant
                # hints; computed only on the 503 paths, never the hot
                # success path (it sweeps every worker's waitpid)
                retry = fleet.live_retry_after_s(tenant)
                self._reply_json(
                    503,
                    {"error": "fleet draining", "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            with self.server._inflight_lock:  # type: ignore[attr-defined]
                inflight = self.server._inflight  # type: ignore[attr-defined]
            if inflight > fleet.max_inflight:
                # admission control: past the fleet's aggregate queue
                # capacity, shed here instead of stacking relays behind
                # workers that will 503 anyway
                fleet.inc("rejected")
                retry = fleet.live_retry_after_s(tenant)
                self._reply_json(
                    503,
                    {"error": "fleet at capacity",
                     "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            if pinned:
                # the pin resolves through the registry HERE — an
                # unregistered or digest-drifted version refuses loudly
                # before any worker sees the request
                err = self.server._verify_model(model)  # type: ignore[attr-defined]
                if err is not None:
                    self._reply_json(400, {"error": err})
                    return
            try:
                body = self._read_body()
            except TimeoutError:
                # peer stalled mid-body past the socket timeout
                self.close_connection = True
                self._reply_json(
                    503, {"error": "timed out reading the request"}
                )
                return
            if body is None:
                return  # error reply already sent
            fleet.inc("requests")
            # the request id is minted HERE (or honored from the
            # client's header) and preserved across failover
            # re-dispatch: the reply, the worker's /tracez record, and
            # the event log all carry the front end's id
            rid = (
                self.headers.get("X-Roko-Request-Id") or new_request_id()
            )
            version = model if pinned else None
            if version is None:
                lane = self.server._ab_lane  # type: ignore[attr-defined]
                if lane is not None:
                    # deterministic split: the request id (stable across
                    # failover) hashes into [0,1) against the configured
                    # fraction — no RNG, replayable from the event log
                    lane_version, fraction = lane
                    h = int(
                        hashlib.sha256(rid.encode()).hexdigest()[:8], 16
                    )
                    if h / float(1 << 32) < fraction:
                        version = lane_version
            code, reply, extra = fleet.post_polish(
                body, request_id=rid, tenant=tenant,
                model_version=version, pinned=pinned,
            )
            if code == 503:
                self.close_connection = True
            self._reply(code, reply, extra=extra)


def make_front_server(
    fleet: Fleet,
    host: Optional[str] = None,
    port: Optional[int] = None,
    *,
    handler_base: Optional[type] = None,
) -> ThreadingHTTPServer:
    """Bind the supervisor front end (port 0 = ephemeral) and return
    the server; the caller runs ``serve_forever``. The fleet rides on
    the server object (``.fleet``) and the lifecycle state matches the
    worker server's, so :func:`roko_tpu.serve.server.drain` works on
    it unchanged. ``handler_base`` swaps in a ``_FrontHandler``
    subclass — the federation host agent layers epoch fencing over the
    same surface this way (``serve/federation.py``)."""
    serve_cfg = fleet.cfg.serve
    handler = type(
        "RokoFleetHandler",
        (handler_base or _FrontHandler,),
        {"fleet": fleet},
    )
    server = ThreadingHTTPServer(
        (serve_cfg.host if host is None else host,
         serve_cfg.port if port is None else port),
        handler,
    )
    server.fleet = fleet  # type: ignore[attr-defined]
    #: POST /rollout implementation; run_supervisor wires the real one
    #: (needs the registry + journal), bare front ends answer 501
    server._start_rollout = None  # type: ignore[attr-defined]
    #: POST /job implementation (distributed polish); run_supervisor
    #: wires it, bare front ends answer 501
    server._start_job = None  # type: ignore[attr-defined]
    #: (version, fraction) when an A/B lane routes a slice of unpinned
    #: traffic to a candidate version; run_supervisor wires it
    server._ab_lane = None  # type: ignore[attr-defined]
    #: X-Roko-Model pin verifier: name -> error string or None (pass);
    #: re-verifies the registry entry (bundle digest + params manifest)
    #: with a short-lived cache so pinned traffic does not re-hash the
    #: checkpoint per request
    server._verify_model = make_model_verifier(fleet)  # type: ignore[attr-defined]
    init_lifecycle(server, fleet.cfg.resilience.drain_deadline_s)
    return server


def make_model_verifier(
    fleet: Fleet, ttl_s: float = 10.0
) -> Callable[[str], Optional[str]]:
    """Front-end ``model=`` pin gate: resolve the named version through
    the registry with full verification (bundle digest + params
    manifest re-hash) and cache the verdict for ``ttl_s`` — drift is
    caught within one TTL, and pinned hot paths do not re-hash a
    checkpoint per request. Returns an error string in the
    RegistryMismatch shape, or None when the pin is valid."""
    cache: Dict[str, Tuple[float, Optional[str]]] = {}
    lock = threading.Lock()

    def verify(name: str) -> Optional[str]:
        now = time.monotonic()
        with lock:
            hit = cache.get(name)
            if hit is not None and hit[0] > now:
                return hit[1]
        try:
            resolve_model(
                resolve_registry_dir(fleet.fleet_cfg.registry_dir), name
            )
            err: Optional[str] = None
        except RegistryError as e:
            # unregistered AND drifted both refuse in the same loud
            # shape — the one thing that never happens is silently
            # serving the incumbent under a pinned name
            err = f"RegistryMismatch: model={name!r} refused: {e}"
        with lock:
            cache[name] = (now + ttl_s, err)
        return err

    return verify


def worker_command(
    model_path: str, config_path: str
) -> Callable[[int, str], List[str]]:
    """argv builder for real ``roko-tpu serve`` workers: ephemeral
    loopback port, port announced through ``announce_path``, config via
    the shared JSON (``--worker-id`` keeps the child out of supervisor
    mode)."""

    def build(worker_id: int, announce_path: str) -> List[str]:
        return [
            sys.executable, "-m", "roko_tpu", "serve", model_path,
            "--config", config_path,
            "--host", "127.0.0.1", "--port", "0",
            "--worker-id", str(worker_id),
            "--announce", announce_path,
        ]

    return build


def worker_launch_spec(
    version: str,
    model_path: str,
    cfg: RokoConfig,
    runtime_dir: str,
    *,
    meta: Optional[Dict[str, Any]] = None,
) -> WorkerLaunchSpec:
    """THE builder for what a worker of ``version`` runs: writes the
    per-version worker config JSON (``fleet.workers`` zeroed so a child
    can never recurse into supervisor mode; the version's AOT bundle
    riding in ``compile.bundle_dir``) and returns the spec initial
    spawn, crash restart, and rollout all resolve through —
    ``Fleet._spawn`` reads nothing else, so the three paths cannot
    drift on which bundle/params a worker gets."""
    fc = cfg.fleet
    worker_cfg = dataclasses.replace(
        cfg, fleet=dataclasses.replace(fc, workers=0)
    )
    os.makedirs(runtime_dir, exist_ok=True)
    config_path = os.path.join(
        runtime_dir, f"worker-config-{version}.json"
    )
    with open(config_path, "w") as f:
        f.write(worker_cfg.to_json())
    spec_meta: Dict[str, Any] = {
        "model_path": model_path,
        "bundle_dir": cfg.compile.bundle_dir,
        "model": dataclasses.asdict(cfg.model),
    }
    spec_meta.update(meta or {})
    # device slices are carved for the fleet's MAX size: an autoscaled
    # worker's fresh id must map to a valid slice, and a fixed-size
    # fleet (max_workers unset) keeps the old denominator (CPU fleets
    # pass devices_per_worker=0 -> empty overlay either way)
    n_slices = max(fc.workers, fc.max_workers or 0)
    return WorkerLaunchSpec(
        worker_command(model_path, config_path),
        env=lambda wid: fleet_worker_env(
            wid, n_slices, fc.devices_per_worker
        ),
        version=version,
        meta=spec_meta,
    )


def _version_config(cfg: RokoConfig, side: Dict[str, Any]) -> RokoConfig:
    """The supervisor config specialised to one version's identity: the
    side dict (a registry entry, or a journal record's from/to block)
    names the bundle dir and — when it carries one — the full
    ModelConfig the bundle was compiled for, so a rollout across model
    kinds or precision variants launches workers whose config matches
    the bundle digest instead of refusing at warmup."""
    out = dataclasses.replace(
        cfg,
        compile=dataclasses.replace(
            cfg.compile, bundle_dir=side.get("bundle_dir")
        ),
    )
    model = side.get("model") or {}
    if model:
        out = dataclasses.replace(
            out,
            model=ModelConfig(
                **{
                    k: tuple(v) if k == "read_mlp" else v
                    for k, v in model.items()
                }
            ),
        )
    return out


def make_rollout_starter(
    fleet: Fleet,
    journal: RolloutJournal,
    model_path: str,
    cfg: RokoConfig,
    log=print,
) -> Callable[[Dict[str, Any]], Tuple[int, Dict[str, Any]]]:
    """The ``POST /rollout`` implementation: resolve+verify the named
    registry version, install its launch spec, and start a
    :class:`RolloutController` — one at a time (409 while one is
    active). Returns ``(http_code, json_body)``."""
    lock = threading.Lock()

    def start(payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        name = payload.get("name")
        if not isinstance(name, str) or not name:
            return 400, {"error": "body must carry the model version "
                                  '{"name": "<registered name>"}'}
        overrides = {}
        for key in ("bake_s", "rollback_error_pct", "rollback_p99_x",
                    "ready_timeout_s"):
            val = payload.get(key)
            if val is None:
                continue
            if not isinstance(val, (int, float)) or val < 0:
                return 400, {"error": f"{key} must be a non-negative "
                                      "number"}
            overrides[key] = float(val)
        with lock:
            ctl = fleet.rollout
            if ctl is not None and ctl.active():
                return 409, {
                    "error": "a rollout is already in progress",
                    "status": ctl.status(),
                }
            job = getattr(fleet, "job", None)
            if job is not None and job.active():
                # a rollout mid-job would splice two versions' contigs
                # into one rc-0 FASTA — the exact mix the distributed
                # journal identity exists to refuse (docs/PIPELINE.md
                # "Distributed polish"); the job side refuses the
                # mirror-image race
                return 409, {
                    "error": "a distributed polish job is running; "
                             "refusing to roll worker versions "
                             "underneath it",
                    "job": job.snapshot(),
                }
            if fleet.active_version == name:
                return 409, {
                    "error": f"fleet is already on version {name!r}",
                }
            try:
                entry = resolve_model(
                    resolve_registry_dir(fleet.fleet_cfg.registry_dir),
                    name,
                )
            except RegistryError as e:
                return 400, {"error": str(e)}
            # ALWAYS rebuild the spec from the freshly verified entry —
            # a version re-registered (--force) since a failed attempt
            # must roll out its NEW bytes, not a stale cached spec. The
            # admission check runs FIRST: building a spec writes the
            # per-version worker config, and a refused swap must not
            # have already changed what a live worker's next
            # crash-restart would run.
            if not fleet.spec_installable(name):
                return 409, {
                    "error": f"launch spec {name!r} is live on the "
                             "fleet; refusing to swap it underneath "
                             "running workers",
                }
            # a bundle-only version (no params pinned) rolls out
            # against the fleet's CURRENT incumbent checkpoint — the
            # active spec's params, which after an earlier rollout is
            # NOT the checkpoint the CLI was started with
            incumbent_params = (
                fleet.launch_spec().meta.get("model_path") or model_path
            )
            try:
                fleet.add_launch_spec(
                    worker_launch_spec(
                        name,
                        entry.get("params_path") or incumbent_params,
                        _version_config(cfg, entry),
                        fleet.runtime_dir,
                        meta={"bundle_digest": entry["bundle_digest"]},
                    )
                )
            except ValueError as e:  # raced; the backstop still holds
                return 409, {"error": str(e)}
            ctl = RolloutController(
                fleet, name, journal=journal, log=log, **overrides
            )
            fleet.rollout = ctl
            ctl.start()
            return 202, ctl.status()

    return start


class Autoscaler:
    """Backlog-driven worker-count control loop (docs/SERVING.md
    "Multi-tenant & elastic fleet").

    Pure decision logic over an injected fleet + clock so tests drive
    it synchronously: each :meth:`tick` smooths backlog-per-worker with
    an EMA, then

    - **scales UP fast** — +1 worker whenever the smoothed backlog
      exceeds ``autoscale_up_backlog`` windows/worker, the cooldown has
      passed, and the fleet is below ``max_workers``;
    - **scales DOWN slowly** — −1 worker only after the smoothed
      backlog has stayed at or below ``autoscale_down_backlog`` for a
      CONTINUOUS ``autoscale_idle_s`` stretch (any excursion above
      resets the stretch), re-arming the stretch per step down;
    - **parks background jobs** — ``fleet.jobs_parked`` flips on when
      interactive backlog spikes past the up threshold and off once it
      falls back under the down threshold; the distpolish journal makes
      park/resume cost at most one contig re-run.

    The up threshold strictly above the down threshold (enforced by
    FleetConfig) plus the idle-stretch requirement is the hysteresis
    band: oscillating load rides inside it without flapping the worker
    count. Enabled only when the configured bounds leave room
    (``max_workers > min_workers``)."""

    def __init__(
        self,
        fleet: Fleet,
        *,
        log: Callable[[str], None] = print,
        clock: Callable[[], float] = time.monotonic,
    ):
        fc = fleet.fleet_cfg
        self.fleet = fleet
        self.fc = fc
        self.min_workers = max(1, fc.min_workers or fc.workers)
        self.max_workers = max(
            self.min_workers, fc.max_workers or fc.workers
        )
        self.enabled = self.max_workers > self.min_workers
        self._log = log
        self._clock = clock
        self.ema: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_change: Optional[float] = None

    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One control decision; returns ``"up"``/``"down"`` when the
        fleet was resized, None otherwise (parking alone returns
        None)."""
        fc = self.fc
        fleet = self.fleet
        now = self._clock() if now is None else now
        n = len(fleet.workers)
        per_worker = fleet.backlog_windows() / max(1, n)
        if self.ema is None:
            self.ema = float(per_worker)
        else:
            self.ema = (
                fc.autoscale_ema_beta * self.ema
                + (1.0 - fc.autoscale_ema_beta) * per_worker
            )
        ema = self.ema
        # park/resume is independent of sizing headroom: even a fleet
        # pinned at max_workers sheds its background job while
        # interactive backlog spikes
        if ema > fc.autoscale_up_backlog:
            if not fleet.jobs_parked:
                fleet.jobs_parked = True
                self._log(
                    f"roko autoscale: backlog {ema:.1f} windows/worker — "
                    "parking background jobs"
                )
        elif ema <= fc.autoscale_down_backlog and fleet.jobs_parked:
            fleet.jobs_parked = False
            self._log(
                "roko autoscale: backlog drained — resuming background "
                "jobs"
            )
        if not self.enabled:
            return None
        cooled = (
            self._last_change is None
            or now - self._last_change >= fc.autoscale_cooldown_s
        )
        if ema > fc.autoscale_up_backlog:
            self._idle_since = None
            if n < self.max_workers and cooled:
                fleet.scale_to(
                    n + 1,
                    reason=f"backlog {ema:.1f} windows/worker > "
                           f"{fc.autoscale_up_backlog:g}",
                )
                self._last_change = now
                return "up"
            return None
        if ema > fc.autoscale_down_backlog:
            # inside the hysteresis band: hold, and any prior idle
            # stretch is void
            self._idle_since = None
            return None
        if self._idle_since is None:
            self._idle_since = now
            return None
        if (
            n > self.min_workers
            and cooled
            and now - self._idle_since >= fc.autoscale_idle_s
        ):
            fleet.scale_to(
                n - 1,
                reason=f"backlog {ema:.1f} windows/worker idle for "
                       f"{now - self._idle_since:.0f}s",
            )
            self._last_change = now
            self._idle_since = now  # next step down needs a fresh stretch
            return "down"
        return None


def start_autoscaler(
    fleet: Fleet,
    stop: threading.Event,
    *,
    log: Callable[[str], None] = print,
) -> Optional[Autoscaler]:
    """Spin the autoscale control thread when the config leaves room
    (``max_workers > min_workers`` effective); returns the Autoscaler
    (or None when fixed-size)."""
    scaler = Autoscaler(fleet, log=log)
    if not scaler.enabled:
        return None

    def loop() -> None:
        while not stop.is_set():
            try:
                scaler.tick()
            except Exception as e:  # pragma: no cover - defensive
                log(f"roko autoscale: tick failed: {e!r}")
            stop.wait(fleet.fleet_cfg.autoscale_interval_s)

    threading.Thread(
        target=loop, name="roko-fleet-autoscale", daemon=True
    ).start()
    log(
        f"roko autoscale: elastic fleet {scaler.min_workers}.."
        f"{scaler.max_workers} workers (up>"
        f"{fleet.fleet_cfg.autoscale_up_backlog:g}, down<="
        f"{fleet.fleet_cfg.autoscale_down_backlog:g} windows/worker)"
    )
    return scaler


def rolling_drain(
    server: ThreadingHTTPServer, fleet: Fleet, log=print
) -> None:
    """SIGTERM path: drain the front end (reject new, finish in-flight
    relays, stop the accept loop), THEN terminate workers one at a
    time — each worker drains its own in-flight before the next is
    touched."""
    drain(server, log=log)
    log("roko fleet: rolling worker drain")
    fleet.stop(rolling=True)


def boot_fleet(
    model_path: str,
    cfg: RokoConfig,
    *,
    log=print,
) -> Tuple[Fleet, RolloutJournal, Optional[Dict[str, Any]], str, str,
           RokoConfig]:
    """Everything between "a config" and "a Fleet ready to start()":
    journal-driven rollout recovery, landed-version re-pinning, the
    boot launch spec, and the A/B lane. Shared by
    :func:`run_supervisor` and the federation host agent
    (``serve/federation.py``) so the two entry points cannot drift on
    what a host boots. Returns ``(fleet, journal, recovery,
    boot_version, boot_model, boot_cfg)``."""
    fleet = Fleet(
        cfg,
        worker_command=(lambda *_: []),  # placeholder; boot spec below
        log=log,
    )
    fc = cfg.fleet
    os.makedirs(fleet.runtime_dir, exist_ok=True)
    journal = RolloutJournal(
        os.path.join(fleet.runtime_dir, RolloutJournal.FILENAME)
    )
    current = CurrentVersionFile(
        os.path.join(fleet.runtime_dir, CurrentVersionFile.FILENAME)
    )
    boot_version, boot_model, boot_cfg = BOOT_VERSION, model_path, cfg
    recovery = recover_rollout(journal, log)
    if recovery is not None:
        rec = recovery["record"]
        side = rec["to"] if recovery["action"] == "finalize" else rec["from"]
        boot_version = side.get("version") or BOOT_VERSION
        boot_model = side.get("model_path") or model_path
        boot_cfg = _version_config(cfg, side)
        # keep the landed-version pointer consistent with the decision
        if boot_version == BOOT_VERSION:
            current.delete()
        else:
            current.write(side)
    else:
        # no interrupted rollout — but a COMPLETED one must survive a
        # plain supervisor restart: re-pin the landed version instead
        # of silently re-booting the CLI-named incumbent
        pinned = current.load(log)
        if pinned and (pinned.get("version") or BOOT_VERSION) != BOOT_VERSION:
            boot_version = pinned["version"]
            boot_model = pinned.get("model_path") or model_path
            boot_cfg = _version_config(cfg, pinned)
            obs_events.emit(
                "rollout", "version_pinned", log=log,
                suffix="— restart re-pins the landed rollout version",
                version=boot_version,
                bundle_digest=str(pinned.get("bundle_digest", "?"))[:12],
            )
    fleet.install_boot_spec(
        worker_launch_spec(
            boot_version, boot_model, boot_cfg, fleet.runtime_dir
        )
    )
    if fc.ab_version:
        # A/B model lane: register the candidate version's launch spec
        # and re-target the highest-id worker slice BEFORE start(), so
        # the lane boots in one spawn sweep. A bad lane config refuses
        # the whole boot — a supervisor silently serving 100% incumbent
        # under a configured experiment is the failure mode to refuse.
        try:
            entry = resolve_model(
                resolve_registry_dir(fc.registry_dir), fc.ab_version
            )
        except RegistryError as e:
            raise RegistryError(
                f"--ab-lane version {fc.ab_version!r} refused: {e}"
            ) from e
        fleet.add_launch_spec(
            worker_launch_spec(
                fc.ab_version,
                entry.get("params_path") or boot_model,
                _version_config(boot_cfg, entry),
                fleet.runtime_dir,
                meta={"bundle_digest": entry["bundle_digest"]},
            )
        )
        n_ab = min(
            max(1, round(fc.ab_fraction * len(fleet.workers))),
            max(0, len(fleet.workers) - 1),
        )
        for w in fleet.workers[len(fleet.workers) - n_ab:]:
            w.version = w.target_version = fc.ab_version
        log(
            f"roko fleet: A/B lane {fc.ab_version!r} on {n_ab} "
            f"worker(s), {fc.ab_fraction:.0%} of unpinned traffic"
        )
    return fleet, journal, recovery, boot_version, boot_model, boot_cfg


def run_supervisor(
    model_path: str,
    cfg: RokoConfig,
    *,
    announce: Optional[str] = None,
    log=print,
) -> int:
    """The ``roko-tpu serve --workers N`` entry point: spawn the fleet,
    bind the front end, serve until SIGTERM/Ctrl-C. ``announce`` (used
    by tests/automation) writes ``{"pid", "port"}`` once the front-end
    socket is bound — the same contract workers honour.

    Before anything spawns, the rollout journal in the runtime dir is
    consulted: a supervisor killed mid-rollout restarts onto ONE
    version — finalized forward when every worker had already rolled,
    reverted to the journaled incumbent otherwise — loudly, never a
    silently mixed fleet (``serve/rollout.py``)."""
    # idempotent for CLI callers (cmd_serve already resolved); the real
    # guard for programmatic users: --workers auto resolves against the
    # visible devices and an oversubscribing worker x mesh combination
    # refuses before anything spawns — all without initialising jax
    fc = resolve_fleet_topology(cfg.fleet)
    if fc is not cfg.fleet:
        cfg = dataclasses.replace(cfg, fleet=fc)
    fleet, journal, recovery, boot_version, boot_model, boot_cfg = (
        boot_fleet(model_path, cfg, log=log)
    )

    server = make_front_server(fleet)
    if fc.ab_version and fc.ab_fraction > 0:
        server._ab_lane = (fc.ab_version, fc.ab_fraction)  # type: ignore[attr-defined]
    # the starter's fallback identity is what the fleet actually BOOTED
    # (a recovered/pinned version, not necessarily the CLI args)
    server._start_rollout = make_rollout_starter(  # type: ignore[attr-defined]
        fleet, journal, boot_model, boot_cfg, log=log
    )
    # distributed-polish jobs over this fleet (POST /job + GET /jobz;
    # docs/PIPELINE.md "Distributed polish") — lazy import: the job
    # starter pulls the pipeline package, which the bare serving path
    # never needs
    from roko_tpu.pipeline.distpolish import make_job_starter

    server._start_job = make_job_starter(  # type: ignore[attr-defined]
        fleet, boot_cfg, log=log
    )
    if announce:
        write_announce(announce, server.server_address[1])
    log(
        f"roko fleet: supervising {fc.workers} worker(s) "
        f"(runtime dir {fleet.runtime_dir}, version {boot_version}); "
        "front end binding"
    )
    fleet.start()
    if recovery is not None:
        # every worker just spawned from the one recovered spec — the
        # fleet is uniform again and the journal has done its job
        journal.delete()
    autoscale_stop = threading.Event()
    fleet.autoscaler = start_autoscaler(  # type: ignore[attr-defined]
        fleet, autoscale_stop, log=log
    )
    try:
        serve_forever(
            server,
            log=log,
            drain_fn=lambda: rolling_drain(server, fleet, log=log),
        )
    finally:
        autoscale_stop.set()
        # Ctrl-C / accept-loop exit: make sure no worker outlives the
        # supervisor (stop() is idempotent — a completed rolling drain
        # already did this)
        fleet.stop(rolling=False)
    return 0
