"""Prometheus-style text metrics for the polishing service.

Counters plus a bounded latency reservoir rendered in the Prometheus
text exposition format (counter/gauge/summary lines), built on
:class:`roko_tpu.utils.profiling.StageTimer` — the same span machinery
the batch pipeline reports with, so serving latency attribution and
batch-job attribution share one implementation.

Exposed series (all prefixed ``roko_serve_``):

- ``requests_total``, ``windows_total``, ``batches_total``,
  ``rejected_total``, ``errors_total`` — monotonic counters;
- ``queue_depth`` — gauge, sampled at scrape time;
- ``cpu_fallback`` — gauge, 1 once a device hang has permanently failed
  the session over to host-CPU predict (degraded but serving);
- ``batch_fill_ratio`` — gauge, windows dispatched / padded rows over
  the service lifetime (how much of each padded device batch was real
  work);
- ``padding_efficiency`` — gauge, the same ratio under the ISSUE's name
  (real windows ÷ rung×steps): the number the continuous scheduler
  exists to push toward 1.0, reported identically for both batching
  modes so the bench serve suite compares them on one series;
- ``queue_windows`` / ``scheduler_occupancy`` — gauges, queued-window
  backlog and backlog ÷ top rung (continuous mode; absent under the
  deadline batcher, which schedules whole requests);
- ``request_latency_seconds{quantile="0.5"|"0.99"}`` + ``_count`` /
  ``_sum`` — summary over the retained sample window, plus per
  size-class rows labeled ``size_class="le{rung}"`` (the ladder rung
  the request's window count buckets into; ``gt{top}`` past the top
  rung) once ``size_classes`` is set — small-request p99 beside
  large-request p99 is the head-of-line-blocking signal. Summaries are
  PER-WORKER-ONLY: percentiles do not merge across processes;
- three MERGEABLE cumulative histograms WITHOUT the serve prefix
  (fleet-level names — the supervisor aggregates them by bucket-sum,
  docs/OBSERVABILITY.md): ``roko_request_latency_seconds_bucket{le=,
  size_class=}`` (+ ``_sum``/``_count``) over the same spans the
  summary sees, and the request-time decomposition
  ``roko_queue_wait_seconds`` (submit -> first pack) and
  ``roko_device_time_seconds`` (one device step), fixed bounds from
  :data:`roko_tpu.obs.hist.DEFAULT_LATENCY_BUCKETS`;
- ``breaker_state`` — gauge, 0 closed / 1 half-open / 2 open — and
  ``breaker_trips_total`` — counter — when a
  :class:`roko_tpu.resilience.CircuitBreaker` is attached
  (docs/SERVING.md "Failure handling");
- ``warmup_seconds`` — gauge, wall time the ladder warmup took (NaN
  while still warming — the cold-start trajectory a fleet dashboard
  watches after each deploy);

plus two compile-tier series WITHOUT the serve prefix (they describe
the process, not the service — docs/SERVING.md "Cold start & compile
cache"): ``roko_compile_cache_hits`` / ``roko_compile_cache_misses``,
persistent-compilation-cache counters from :mod:`roko_tpu.compile`.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple

from roko_tpu.compile.cache import cache_counters
from roko_tpu.obs.hist import HistogramFamily
from roko_tpu.utils.profiling import StageTimer

_PREFIX = "roko_serve_"
_COUNTERS = ("requests", "windows", "batches", "rejected", "errors")

#: the mergeable histogram families every worker renders (and the fleet
#: supervisor bucket-sums into fleet-level rows — serve/fleet.py)
HISTOGRAM_SERIES = (
    "roko_request_latency_seconds",
    "roko_queue_wait_seconds",
    "roko_device_time_seconds",
    "roko_cascade_tier_seconds",
)


#: labeled series the fleet supervisor passes through row-by-row
#: (tenant/model dimensions — serve/fleet.py re-exports each row with a
#: ``worker="i"`` label appended inside the existing braces)
LABELED_SERIES = (
    "roko_serve_tenant_requests_total",
    "roko_serve_tenant_rejected_total",
    "roko_serve_tenant_backlog",
    "roko_serve_model_requests_total",
)


def parse_labeled_rows(text: str, names) -> Dict[str, list]:
    """Extract ``{name: [(label_body, value), ...]}`` for LABELED
    series in a Prometheus text body (``name{labels} value`` lines;
    ``label_body`` is the raw text inside the braces). The companion of
    :func:`parse_metric_values` for the tenant-/model-labeled rows the
    fleet re-exports per worker."""
    wanted = set(names)
    out: Dict[str, list] = {}
    for line in text.splitlines():
        if not line or line.startswith("#") or "{" not in line:
            continue
        name, rest = line.split("{", 1)
        if name not in wanted or "}" not in rest:
            continue
        body, _, value = rest.partition("}")
        value = value.strip()
        if value:
            out.setdefault(name, []).append((body, value))
    return out


def parse_metric_values(text: str, names) -> Dict[str, str]:
    """Extract ``{name: value}`` for unlabeled series in a Prometheus
    text body — the fleet supervisor scrapes each worker's ``/metrics``
    with this and re-exports the selected series labeled by worker id
    (``serve/fleet.py`` PASSTHROUGH_SERIES). Values stay strings: the
    aggregator relays, it does not do arithmetic."""
    wanted = set(names)
    out: Dict[str, str] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 2 and parts[0] in wanted:
            out[parts[0]] = parts[1]
    return out


class ServeMetrics:
    def __init__(self, latency_samples: int = 1024):
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {name: 0 for name in _COUNTERS}
        self.timer = StageTimer(max_samples=latency_samples)
        #: windows actually dispatched / padded rows dispatched
        self._fill_windows = 0
        self._fill_padded = 0
        #: scrape-time gauge; the batcher points this at its queue
        self.queue_depth: Callable[[], int] = lambda: 0
        #: scrape-time gauge; make_server points this at the session's
        #: permanent CPU fail-over flag (``PolishSession.failed_over``)
        self.cpu_fallback: Callable[[], bool] = lambda: False
        #: circuit breaker to render state/trips for (set by make_server)
        self.breaker = None
        #: ladder warmup wall seconds (set once warmup finishes; None
        #: renders NaN — "still warming")
        self.warmup_seconds: Optional[float] = None
        #: request-size latency buckets (the session's ladder rungs, set
        #: by make_server); empty = per-class latency rows disabled
        self.size_classes: Tuple[int, ...] = ()
        #: continuous-scheduler gauges (set by ContinuousBatcher; None =
        #: deadline mode, the series are simply absent)
        self.queue_windows: Optional[Callable[[], int]] = None
        self.occupancy: Optional[Callable[[], float]] = None
        #: per-tenant queued-window gauge source (set by
        #: ContinuousBatcher; None = no tenant backlog series)
        self.tenant_backlogs: Optional[Callable[[], Dict[str, int]]] = None
        #: per-tenant request/rejection counters (tenant-labeled rows)
        self._tenant_requests: Dict[str, int] = {}
        self._tenant_rejected: Dict[str, int] = {}
        #: per-model request counter; ``model_version`` is this worker's
        #: own registry version identity (env ROKO_MODEL_VERSION, set by
        #: the fleet spawn path) — it labels the latency histogram so
        #: A/B lanes compare fleet-merged per-model rows
        self._model_requests: Dict[str, int] = {}
        self.model_version: Optional[str] = None
        #: mergeable cumulative histograms (fixed shared buckets, so the
        #: fleet supervisor can SUM worker rows — docs/OBSERVABILITY.md):
        #: request latency by size class, plus the queue-wait /
        #: device-time decomposition both batching policies feed
        self.hist_latency = HistogramFamily(
            "roko_request_latency_seconds", label="size_class"
        )
        self.hist_queue_wait = HistogramFamily("roko_queue_wait_seconds")
        self.hist_device = HistogramFamily("roko_device_time_seconds")
        #: cascade per-tier time, labeled tier1/tier2 (mergeable like the
        #: rest — a fleet's escalation cost aggregates by bucket-sum)
        self.hist_cascade = HistogramFamily(
            "roko_cascade_tier_seconds", label="tier"
        )
        #: cascade counters (docs/SERVING.md "Adaptive compute"); stay 0
        #: and render only when a router is attached
        self._cascade_windows = 0
        self._cascade_escalated = 0
        self._cascade_cache_hits = 0
        self.cascade_enabled = False

    def size_class(self, windows: int) -> str:
        """Ladder-rung bucket label for an n-window request: ``le{r}``
        for the smallest rung r >= n, ``gt{top}`` past the top rung."""
        for rung in self.size_classes:
            if windows <= rung:
                return f"le{rung}"
        return f"gt{self.size_classes[-1]}"

    def observe_request(
        self,
        windows: int,
        seconds: float,
        tenant: Optional[str] = None,
        model: Optional[str] = None,
    ) -> None:
        """One completed request: the aggregate latency span plus its
        size-class span (PredictFuture.result calls this for both
        batching modes, so the per-class p50/p99 comparison is
        apples-to-apples). ``tenant`` and the worker's own
        ``model_version`` become extra single-label histogram rows, so
        per-tenant and per-model latency merge fleet-wide exactly like
        the size-class rows do."""
        self.timer.record("request", seconds)
        label = self.size_class(windows) if self.size_classes else None
        if label is not None:
            self.timer.record(f"request:{label}", seconds)
        model = model or self.model_version
        extra = []
        if tenant:
            extra.append(("tenant", tenant))
            with self._lock:
                self._tenant_requests[tenant] = (
                    self._tenant_requests.get(tenant, 0) + 1
                )
        if model:
            extra.append(("model", model))
            with self._lock:
                self._model_requests[model] = (
                    self._model_requests.get(model, 0) + 1
                )
        # the histogram sees every request the summary sees, so a
        # bucket-derived fleet p99 is consistent with per-worker data
        self.hist_latency.observe(seconds, label, extra_labels=extra)

    def inc_tenant_rejected(self, tenant: str) -> None:
        with self._lock:
            self._tenant_rejected[tenant] = (
                self._tenant_rejected.get(tenant, 0) + 1
            )

    def observe_cascade(
        self,
        *,
        windows: int = 0,
        escalated: int = 0,
        cache_hits: int = 0,
        tier1_seconds: Optional[float] = None,
        tier2_seconds: Optional[float] = None,
    ) -> None:
        """One routed batch (CascadeRouter calls this): window counters
        plus the per-tier time decomposition."""
        with self._lock:
            self.cascade_enabled = True
            self._cascade_windows += windows
            self._cascade_escalated += escalated
            self._cascade_cache_hits += cache_hits
        if tier1_seconds is not None:
            self.hist_cascade.observe(tier1_seconds, "tier1")
        if tier2_seconds is not None:
            self.hist_cascade.observe(tier2_seconds, "tier2")

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self.counters[name] += by

    def observe_fill(self, windows: int, padded: int) -> None:
        with self._lock:
            self._fill_windows += windows
            self._fill_padded += padded

    def fill_ratio(self) -> Optional[float]:
        with self._lock:
            if not self._fill_padded:
                return None
            return self._fill_windows / self._fill_padded

    def fill_totals(self) -> "Tuple[int, int]":
        """(real windows, padded rows) dispatched so far — the bench
        serve suite snapshots this around its untimed calibration phase
        so calibration dispatches can't skew the reported
        padding_efficiency."""
        with self._lock:
            return self._fill_windows, self._fill_padded

    def render(self) -> str:
        """The ``GET /metrics`` body."""
        lines = []
        for name in _COUNTERS:
            full = f"{_PREFIX}{name}_total"
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {self.counters[name]}")
        lines.append(f"# TYPE {_PREFIX}queue_depth gauge")
        lines.append(f"{_PREFIX}queue_depth {int(self.queue_depth())}")
        fill = self.fill_ratio()
        lines.append(f"# TYPE {_PREFIX}batch_fill_ratio gauge")
        lines.append(
            f"{_PREFIX}batch_fill_ratio "
            + ("NaN" if fill is None else f"{fill:.4f}")
        )
        # the ISSUE's name for the same ratio (real windows / rung*steps)
        lines.append(f"# TYPE {_PREFIX}padding_efficiency gauge")
        lines.append(
            f"{_PREFIX}padding_efficiency "
            + ("NaN" if fill is None else f"{fill:.4f}")
        )
        # the raw numerator/denominator behind the ratio, so a scraper
        # (the bench fleet mixed phase) can DIFF them around a warm-up
        # window instead of settling for the lifetime ratio
        fw, fp = self.fill_totals()
        lines.append(f"# TYPE {_PREFIX}fill_windows_total counter")
        lines.append(f"{_PREFIX}fill_windows_total {fw}")
        lines.append(f"# TYPE {_PREFIX}fill_padded_total counter")
        lines.append(f"{_PREFIX}fill_padded_total {fp}")
        if self.queue_windows is not None:
            lines.append(f"# TYPE {_PREFIX}queue_windows gauge")
            lines.append(f"{_PREFIX}queue_windows {int(self.queue_windows())}")
        if self.occupancy is not None:
            lines.append(f"# TYPE {_PREFIX}scheduler_occupancy gauge")
            lines.append(
                f"{_PREFIX}scheduler_occupancy {self.occupancy():.4f}"
            )
        # tenant/model dimensions (labeled rows; absent until traffic
        # carries a tenant id or the worker has a version identity)
        with self._lock:
            t_req = dict(self._tenant_requests)
            t_rej = dict(self._tenant_rejected)
            m_req = dict(self._model_requests)
        t_backlog = self.tenant_backlogs() if self.tenant_backlogs else {}
        if t_req:
            lines.append(f"# TYPE {_PREFIX}tenant_requests_total counter")
            for t in sorted(t_req):
                lines.append(
                    f'{_PREFIX}tenant_requests_total{{tenant="{t}"}} '
                    f"{t_req[t]}"
                )
        if t_rej:
            lines.append(f"# TYPE {_PREFIX}tenant_rejected_total counter")
            for t in sorted(t_rej):
                lines.append(
                    f'{_PREFIX}tenant_rejected_total{{tenant="{t}"}} '
                    f"{t_rej[t]}"
                )
        if t_backlog:
            lines.append(f"# TYPE {_PREFIX}tenant_backlog gauge")
            for t in sorted(t_backlog):
                lines.append(
                    f'{_PREFIX}tenant_backlog{{tenant="{t}"}} '
                    f"{int(t_backlog[t])}"
                )
        if m_req:
            lines.append(f"# TYPE {_PREFIX}model_requests_total counter")
            for m in sorted(m_req):
                lines.append(
                    f'{_PREFIX}model_requests_total{{model="{m}"}} '
                    f"{m_req[m]}"
                )
        lines.append(f"# TYPE {_PREFIX}cpu_fallback gauge")
        lines.append(f"{_PREFIX}cpu_fallback {int(bool(self.cpu_fallback()))}")
        if self.breaker is not None:
            lines.append(f"# TYPE {_PREFIX}breaker_state gauge")
            lines.append(f"{_PREFIX}breaker_state {self.breaker.state_code()}")
            lines.append(f"# TYPE {_PREFIX}breaker_trips_total counter")
            lines.append(
                f"{_PREFIX}breaker_trips_total {self.breaker.trip_count}"
            )
        lines.append(f"# TYPE {_PREFIX}warmup_seconds gauge")
        lines.append(
            f"{_PREFIX}warmup_seconds "
            + ("NaN" if self.warmup_seconds is None
               else f"{self.warmup_seconds:.3f}")
        )
        hits, misses = cache_counters()
        lines.append("# TYPE roko_compile_cache_hits counter")
        lines.append(f"roko_compile_cache_hits {hits}")
        lines.append("# TYPE roko_compile_cache_misses counter")
        lines.append(f"roko_compile_cache_misses {misses}")
        lat = f"{_PREFIX}request_latency_seconds"
        lines.append(f"# TYPE {lat} summary")
        for q in (50, 99):
            v = self.timer.percentile("request", q)
            if v is not None:
                lines.append(f'{lat}{{quantile="0.{q}"}} {v:.6f}')
        lines.append(f"{lat}_count {self.timer.counts.get('request', 0)}")
        lines.append(f"{lat}_sum {self.timer.totals.get('request', 0.0):.6f}")
        # per-size-class rows (only classes that have seen traffic): the
        # small-vs-large latency split that makes head-of-line blocking
        # visible from a dashboard
        for rung in self.size_classes:
            for label in (f"le{rung}",) + (
                (f"gt{rung}",) if rung == self.size_classes[-1] else ()
            ):
                stage = f"request:{label}"
                if not self.timer.counts.get(stage):
                    continue
                for q in (50, 99):
                    v = self.timer.percentile(stage, q)
                    if v is not None:
                        lines.append(
                            f'{lat}{{quantile="0.{q}",size_class="{label}"}}'
                            f" {v:.6f}"
                        )
                lines.append(
                    f'{lat}_count{{size_class="{label}"}} '
                    f"{self.timer.counts[stage]}"
                )
                lines.append(
                    f'{lat}_sum{{size_class="{label}"}} '
                    f"{self.timer.totals.get(stage, 0.0):.6f}"
                )
        if self.cascade_enabled:
            with self._lock:
                cw, ce, ch = (
                    self._cascade_windows,
                    self._cascade_escalated,
                    self._cascade_cache_hits,
                )
            lines.append(f"# TYPE {_PREFIX}cascade_windows_total counter")
            lines.append(f"{_PREFIX}cascade_windows_total {cw}")
            lines.append(f"# TYPE {_PREFIX}cascade_escalated_total counter")
            lines.append(f"{_PREFIX}cascade_escalated_total {ce}")
            lines.append(f"# TYPE {_PREFIX}cascade_cache_hits_total counter")
            lines.append(f"{_PREFIX}cascade_cache_hits_total {ch}")
            lines.append(f"# TYPE {_PREFIX}cascade_escalation_fraction gauge")
            lines.append(
                f"{_PREFIX}cascade_escalation_fraction "
                + (f"{ce / cw:.4f}" if cw else "NaN")
            )
            lines.append(f"# TYPE {_PREFIX}cascade_cache_hit_rate gauge")
            lines.append(
                f"{_PREFIX}cascade_cache_hit_rate "
                + (f"{ch / cw:.4f}" if cw else "NaN")
            )
        # object-store client counters (process-wide, not per-worker):
        # retries/hedges/breaker trips surface here so a faulted remote
        # data plane is visible without reading logs
        from roko_tpu.datapipe.store import store_metrics_lines

        lines.extend(store_metrics_lines())
        # mergeable histograms last (fleet-level names, no serve prefix:
        # the supervisor bucket-sums these across workers)
        for hist in (self.hist_latency, self.hist_queue_wait,
                     self.hist_device, self.hist_cascade):
            lines.extend(hist.render())
        return "\n".join(lines) + "\n"
