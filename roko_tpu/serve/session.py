"""Warm model session with a pre-compiled padded-batch ladder.

A :class:`PolishSession` is the resident half of the service: it loads
params onto the mesh once, compiles ``infer.make_predict_step`` for a
small ladder of batch sizes up front (``warmup``), and from then on
dispatches every request by padding to the smallest rung that fits —
so steady-state traffic never triggers an XLA recompile, whatever
window counts requests arrive with. Oversized requests are chunked at
the top rung, so one compiled executable set serves any request size.

The compile discipline is observable: ``dispatched_shapes`` records
every padded batch size that reached the device, and ``cache_size()``
reads the jit cache entry count — tests assert both stay fixed after
warmup (ISSUE acceptance: zero recompiles across requests of differing
window counts).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence, Set, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from roko_tpu.compile import load_bundle, warmup_ladder
from roko_tpu.compile.cache import enable_persistent_cache
from roko_tpu.compile.warmup import WarmupReport
from roko_tpu.config import RokoConfig, resolve_ladder, validate_ladder
from roko_tpu.infer import (
    make_cpu_predict,
    make_predict_step,
    make_ragged_predict_step,
    pad_windows,
    rung_for,
)
from roko_tpu.models.model import RokoModel
from roko_tpu.obs import events as obs_events
from roko_tpu.resilience import DeadlinePolicy, HangError, call_with_deadline
from roko_tpu.parallel.mesh import (
    AXIS_DP,
    data_sharding,
    make_mesh,
    replicated_sharding,
)

Params = Dict[str, Any]


class PolishSession:
    """Params + pre-compiled predict ladder; thread-safe dispatch.

    ``predict`` serialises device dispatch with a lock: the batcher owns
    the only steady-state caller, but direct callers (tools, tests, the
    extractor convenience path) may share a session with it.
    """

    def __init__(
        self,
        params: Params,
        cfg: Optional[RokoConfig] = None,
        *,
        mesh: Optional[Mesh] = None,
        ladder: Optional[Sequence[int]] = None,
    ):
        self.cfg = cfg or RokoConfig()
        # persistent compile cache BEFORE the first compile can happen:
        # even a bundle-less cold start then pays XLA at most once per
        # (program, backend, jax version) per machine
        enable_persistent_cache(self.cfg.compile)
        self.mesh = mesh or make_mesh(self.cfg.mesh)
        #: dp extent of the mesh — every global ladder rung shards
        #: rung/dp windows onto each of these devices (params replicated)
        self.dp: int = self.mesh.shape[AXIS_DP]
        #: total local devices this ONE session drives
        self.n_devices: int = int(self.mesh.devices.size)
        # ladder denomination (docs/SERVING.md "Mesh-sharded sessions"):
        # an explicit `ladder` kwarg (and explicit ServeConfig.ladder /
        # --ladder rungs) names GLOBAL batch sizes; the auto default
        # scales the per-device base ladder by dp via resolve_ladder,
        # so one config drives any mesh width
        rungs = (
            resolve_ladder(self.cfg.serve, self.dp)
            if ladder is None
            else tuple(sorted(set(ladder)))
        )
        if not rungs:
            raise ValueError("ladder must name at least one batch size")
        validate_ladder(rungs, self.dp)
        self.ladder: Tuple[int, ...] = rungs
        self.model = RokoModel(self.cfg.model)
        # conversion-time weight-only quantization (models/quant.py):
        # the f32 checkpoint quantizes ONCE at session build; the
        # device then holds int8 kernels + f32 scales, and every
        # dispatch dequantizes in-program (weight-bytes 4x smaller)
        from roko_tpu.models.quant import maybe_quantize

        params = maybe_quantize(params, self.model.cfg)
        self.resilience = self.cfg.resilience
        # host-side params copy for the CPU hang fail-over (taken now,
        # while the device is known-good; after a hang a device_get of
        # the resident params would itself hang)
        self._params_host = (
            params if self.resilience.hang_fallback == "cpu" else None
        )
        self._cpu_predict = None  # built on first fail-over
        self.params = jax.device_put(params, replicated_sharding(self.mesh))
        self._step = make_predict_step(self.model, self.mesh)
        #: ragged dispatch (ServeConfig.batching == "ragged",
        #: docs/SERVING.md "Ragged dispatch"): every device step runs
        #: ONE top-rung executable with an explicit valid-row count the
        #: device masks — no padded-rung ladder, no per-rung compiles
        self.ragged: bool = self.cfg.serve.batching == "ragged"
        # built eagerly in ragged mode (warmup compiles through it) and
        # lazily otherwise, so one warm session can be driven by either
        # batcher — the byte-identity gates depend on that
        self._ragged_step = (
            make_ragged_predict_step(self.model, self.mesh)
            if self.ragged
            else None
        )
        self._sharding = data_sharding(self.mesh)
        self._lock = threading.Lock()
        #: padded batch sizes that have reached the device — after
        #: warmup this must stay a subset of ``ladder`` forever
        self.dispatched_shapes: Set[int] = set()
        #: AOT-bundle executables by rung (filled by ``warmup`` when a
        #: bundle is configured); dispatch prefers these over the jit
        self._aot: Dict[int, Any] = {}
        #: split watchdog budgets: the FIRST dispatch of each padded
        #: shape (which may compile) gets ``compile_deadline_s``, every
        #: later one ``predict_deadline_s`` — a cold cache can no longer
        #: masquerade as a device hang
        self._deadlines = DeadlinePolicy(
            self.resilience.predict_deadline_s,
            self.resilience.compile_deadline_s,
        )
        #: filled by ``warmup``: wall seconds, mode, per-rung timings,
        #: persistent-cache hit/miss deltas (serve /metrics reads it)
        self.warmup_report: Optional[WarmupReport] = None
        w = self.cfg.model
        self._window_shape = (w.window_rows, w.window_cols)

    # -- compile accounting -------------------------------------------------

    def cache_size(self) -> int:
        """jit-cache entry count for the predict step(s) (one per
        compiled batch shape; the ragged step only ever holds one entry
        — occupancy is a traced scalar); falls back to the
        dispatched-shape count if the private jax API ever disappears."""
        try:
            n = int(self._step._cache_size())
            if self._ragged_step is not None:
                n += int(self._ragged_step._cache_size())
            return n
        except AttributeError:  # pragma: no cover - jax version drift
            return len(self.dispatched_shapes)

    def ready_executables(self) -> int:
        """Executables live for this session: AOT-loaded rungs plus jit
        cache entries (a rung is one or the other, never both)."""
        return len(self._aot) + self.cache_size()

    def warmup(
        self,
        *,
        parallel: Optional[bool] = None,
        bundle_dir: Optional[str] = None,
        require_all: bool = True,
        compile_missing: bool = True,
        log=None,
    ) -> int:
        """Make every ladder rung hot; returns the ready-executable
        count. Called once at service start so the first real request
        pays dispatch cost only.

        Three tiers (roko_tpu/compile, cheapest first): a configured AOT
        bundle (``CompileConfig.bundle_dir`` / ``--bundle``) deserializes
        pre-compiled executables — a digest mismatch or missing rung
        refuses loudly (:class:`~roko_tpu.compile.BundleMismatch`), never
        silently recompiles; otherwise rungs compile CONCURRENTLY (XLA
        releases the GIL) through the persistent compilation cache, so
        only the first-ever start of this program on this machine pays
        XLA. Either way each rung dispatches one zero batch, proving the
        executable actually runs before ``/healthz`` flips from
        ``warming`` to ``ok``. Timings + cache hit/miss deltas land in
        ``self.warmup_report``."""
        ccfg = self.cfg.compile
        bundle_dir = ccfg.bundle_dir if bundle_dir is None else bundle_dir
        parallel = ccfg.parallel_warmup if parallel is None else parallel
        if self.ragged:
            # ragged mode compiles ONE top-rung executable (occupancy is
            # a traced scalar, never a shape) — the padded-rung ladder
            # and any AOT bundle of it simply do not apply. A configured
            # bundle is reported loudly rather than half-loaded: its
            # executables have the padded (params, x) signature, not the
            # ragged (params, x, n) one.
            if bundle_dir:
                obs_events.emit(
                    "serve", "ragged_bundle_skipped",
                    text="serve: batching=ragged ignores the AOT bundle "
                    f"at {bundle_dir} — ragged steps compile one "
                    "(params, x, n) executable via the persistent "
                    "cache; padded-ladder bundles cannot serve them",
                    stage="warmup",
                )
            top = self.ladder[-1]

            def compile_ragged(rung: int) -> None:
                self._dispatch_ragged(
                    np.zeros((rung,) + self._window_shape, np.uint8), 0
                )

            self.warmup_report = warmup_ladder(
                [top], compile_ragged, parallel=False, mode="ragged",
                log=log,
            )
            return self.ready_executables()
        mode = None
        if bundle_dir:
            # require_all=False is the streaming-polish posture: rungs
            # the bundle lacks (a --b tail size) fall back to the jit
            # path instead of refusing the whole run; serve keeps the
            # strict default — a half-AOT service start is a config bug
            self._aot.update(
                load_bundle(
                    bundle_dir,
                    self.cfg,
                    mesh=self.mesh,
                    rungs=self.ladder,
                    require_all=require_all,
                    log=log or (lambda m: None),
                )
            )
            mode = "aot"

        def compile_rung(rung: int) -> None:
            self._dispatch(
                np.zeros((rung,) + self._window_shape, np.uint8)
            )

        # compile_missing=False is the batch-pipeline posture: prove the
        # AOT-loaded rungs (a bundle stub must fail the start, not the
        # run) but leave bundle-less rungs to compile lazily on first
        # dispatch — a short polish should not pay XLA for tail rungs it
        # never uses. Serve keeps the strict default: every rung hot
        # before /healthz flips from "warming".
        rungs = (
            self.ladder
            if compile_missing
            else tuple(r for r in self.ladder if r in self._aot)
        )
        self.warmup_report = warmup_ladder(
            rungs,
            compile_rung,
            parallel=parallel,
            max_workers=ccfg.warmup_workers,
            mode=mode,
            log=log,
        )
        return self.ready_executables()

    # -- dispatch -----------------------------------------------------------

    def rung_for(self, n: int) -> int:
        """Smallest ladder rung >= n (top rung when none fits; callers
        chunk at the top rung first). One rule for every ladder user:
        delegates to ``infer.rung_for`` (the batch tail and streaming
        batcher share it)."""
        return rung_for(self.ladder, n)

    def padded_size(self, n: int) -> int:
        """Total padded rows ``predict`` will dispatch for an n-window
        batch (top-rung chunks + one padded tail rung) — the batcher's
        batch-fill-ratio metric divides by this."""
        top = self.ladder[-1]
        full, rest = divmod(n, top)
        return full * top + (self.rung_for(rest) if rest else 0)

    def _dispatch(self, x: np.ndarray) -> np.ndarray:
        """One padded batch through the device, under the resilience
        watchdog (roko_tpu/resilience): a compile/predict call that
        outlives its deadline (``compile_deadline_s`` for a shape's
        first dispatch, ``predict_deadline_s`` after) dumps thread stacks
        and raises :class:`HangError` — the batcher's circuit breaker
        counts it as a device failure — or, with ``hang_fallback ==
        "cpu"``, the session permanently fails over to a host-CPU
        predict step and keeps serving (degraded)."""
        self.dispatched_shapes.add(x.shape[0])
        if self._cpu_predict is not None:
            return self._cpu_predict(x)
        step = self._aot.get(x.shape[0], self._step)

        def run() -> np.ndarray:
            fut = step(self.params, jax.device_put(x, self._sharding))
            return np.asarray(jax.device_get(fut))

        # first dispatch of a shape may include its compile (or AOT
        # executable validation): it gets the compile-grade budget, the
        # steady state keeps the tight predict one
        deadline_s, first = self._deadlines.deadline_for(x.shape[0])
        try:
            try:
                return call_with_deadline(
                    run,
                    deadline_s,
                    stage="serve-compile" if first else "serve-predict",
                )
            except BaseException:
                # a failed FIRST dispatch leaves no executable in the jit
                # cache: re-arm the compile budget so the retry's
                # recompile isn't judged by the tight predict deadline
                if first:
                    self._deadlines.forget(x.shape[0])
                raise
        except HangError:
            if self.resilience.hang_fallback != "cpu":
                raise
            obs_events.emit(
                "failover", "cpu_fallback",
                text="serve: device hang — session permanently "
                "failed over to host-CPU predict (degraded); healthz "
                "cpu_fallback=true, metrics roko_serve_cpu_fallback=1",
                stage="serve", shape=x.shape[0],
            )
            self._cpu_predict = make_cpu_predict(
                self.model, self._params_host
            )
            return self._cpu_predict(x)

    # -- ragged dispatch ----------------------------------------------------

    def ragged_slots(self, n: int) -> int:
        """Device slots an n-window ragged step actually spends compute
        on: the mask boundary rounds up to the dp shard granularity
        (each of the dp shards masks its own rows, so occupancy is
        denominated in dp-row units). This is the ragged analogue of
        ``padded_size`` and feeds the same padding-efficiency metric —
        real windows / ragged_slots -> 1.0 as packing densifies, vs the
        padded ladder's rung-quantised ~0.96 ceiling."""
        return -(-n // self.dp) * self.dp

    def _dispatch_ragged(self, x: np.ndarray, n: int) -> np.ndarray:
        """One top-rung slab + valid-row count through the ragged
        executable, under the same resilience watchdog as ``_dispatch``.
        After a CPU fail-over the mask applies host-side (zeros beyond
        ``n`` — exactly what the device mask computes), so the degraded
        path stays byte-identical too."""
        self.dispatched_shapes.add(x.shape[0])
        if self._cpu_predict is not None:
            return self._cpu_predict(self._mask_rows(x, n))
        if self._ragged_step is None:
            self._ragged_step = make_ragged_predict_step(
                self.model, self.mesh
            )
        step = self._ragged_step

        def run() -> np.ndarray:
            fut = step(
                self.params, jax.device_put(x, self._sharding), np.int32(n)
            )
            return np.asarray(jax.device_get(fut))

        key = ("ragged", x.shape[0])
        deadline_s, first = self._deadlines.deadline_for(key)
        try:
            try:
                return call_with_deadline(
                    run,
                    deadline_s,
                    stage="serve-compile" if first else "serve-predict",
                )
            except BaseException:
                if first:
                    self._deadlines.forget(key)
                raise
        except HangError:
            if self.resilience.hang_fallback != "cpu":
                raise
            obs_events.emit(
                "failover", "cpu_fallback",
                text="serve: device hang — session permanently "
                "failed over to host-CPU predict (degraded); healthz "
                "cpu_fallback=true, metrics roko_serve_cpu_fallback=1",
                stage="serve", shape=x.shape[0],
            )
            self._cpu_predict = make_cpu_predict(
                self.model, self._params_host
            )
            return self._cpu_predict(self._mask_rows(x, n))

    @staticmethod
    def _mask_rows(x: np.ndarray, n: int) -> np.ndarray:
        out = x.copy()
        out[n:] = 0
        return out

    def predict_ragged(self, x: np.ndarray, n: int) -> np.ndarray:
        """uint8[top, rows, cols] slab + valid-row count -> int32[n, cols]
        class ids. The slab must already be top-rung shaped (the ragged
        batcher owns slab packing); rows at or past ``n`` are masked on
        device, so stale slab contents never reach the model."""
        x = np.ascontiguousarray(x, dtype=np.uint8)
        top = self.ladder[-1]
        if x.ndim != 3 or x.shape != (top,) + self._window_shape:
            raise ValueError(
                f"ragged slab shaped {x.shape}, want "
                f"{(top,) + self._window_shape}"
            )
        if not 0 <= n <= top:
            raise ValueError(f"valid-row count {n} outside [0, {top}]")
        with self._lock:
            preds = self._dispatch_ragged(x, n)
        return preds[:n]

    @property
    def failed_over(self) -> bool:
        """True once a device hang has permanently switched this session
        onto the host-CPU predict path (``hang_fallback == "cpu"``) —
        surfaced in ``/healthz`` and the ``roko_serve_cpu_fallback``
        gauge so a degraded-but-serving process is visible to operators."""
        return self._cpu_predict is not None

    def predict(self, x: np.ndarray) -> np.ndarray:
        """uint8[n, rows, cols] -> int32[n, cols] class ids, padding to
        the ladder (chunked at the top rung) so no new shape ever
        reaches the compiler."""
        x = np.ascontiguousarray(x, dtype=np.uint8)
        if x.ndim != 3 or x.shape[1:] != self._window_shape:
            raise ValueError(
                f"windows shaped {x.shape}, want (n,) + {self._window_shape}"
            )
        n = x.shape[0]
        if n == 0:
            return np.zeros((0, self._window_shape[1]), np.int32)
        top = self.ladder[-1]
        outs = []
        with self._lock:
            for s in range(0, n, top):
                chunk = x[s : s + top]
                rung = self.rung_for(chunk.shape[0])
                preds = self._dispatch(pad_windows(chunk, rung))
                outs.append(preds[: chunk.shape[0]])
        return outs[0] if len(outs) == 1 else np.concatenate(outs)
