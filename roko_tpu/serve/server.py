"""Stdlib HTTP front end for the polishing service (no new deps).

``ThreadingHTTPServer`` + ``BaseHTTPRequestHandler`` only — the service
has to run inside the baked container image, so the transport is
deliberately boring; the interesting parts (warm session, micro-batch,
backpressure) live behind it.

Routes (payload schema: docs/SERVING.md):

- ``POST /polish`` — JSON body, two forms:

  1. **windows** (the wire format): ``contig``, ``draft``, ``n`` plus
     ``positions`` / ``examples`` as base64 raw little-endian arrays
     (``int64[n, cols, 2]`` / ``uint8[n, rows, cols]``) or small nested
     lists. Returns the stitched contig.
  2. **extractor convenience**: ``ref`` + ``bam`` (server-local paths)
     — runs the ``features.pipeline`` extractor on the BAM and polishes
     every contig. Returns ``{"contigs": {name: polished}}``.
  3. **work unit** (the distributed-polish tier): ``ref`` + ``bam`` +
     ``unit`` — extract and polish exactly one coordinator-named
     region slice (docs/PIPELINE.md "Distributed polish").

- ``GET /healthz`` — liveness + the compiled ladder. Goes **503** while
  the ladder is still warming (status ``"warming"`` — the socket binds
  before the compile so restarts are observable, docs/SERVING.md "Cold
  start & compile cache"), while the circuit breaker is open (device
  failing), or while the server is draining, so a load balancer stops
  routing here.
- ``GET /metrics`` — Prometheus text (``serve/metrics.py``).
- ``GET /tracez`` — request-trace ring (last N + slowest N replies with
  their span breakdowns) plus a live scheduler snapshot (backlog,
  in-flight segments, rung history) — docs/OBSERVABILITY.md.
- ``POST /profilez?seconds=N`` — wrap the next N seconds of device
  steps in a ``jax.profiler`` XPlane capture; returns the trace path
  (TensorBoard-loadable). One capture at a time.

Every ``POST /polish`` reply carries a ``request_id`` (minted here, or
honored from an ``X-Roko-Request-Id`` header — the fleet supervisor
assigns one per client request and re-sends it on failover re-dispatch)
and a ``timings`` span breakdown (queue-wait, pack, device steps,
scatter, stitch).

Backpressure — queue full, breaker open, or draining — surfaces as
**503** with a ``Retry-After`` header; malformed payloads as **400**;
anything unexpected as **500** with the exception type (message stays
server-side in the log).

Shutdown is graceful (docs/SERVING.md "Failure handling"): SIGTERM (or
:func:`drain`) stops admitting work, lets in-flight requests finish
under ``resilience.drain_deadline_s``, then exits — no mid-request
connection resets on a rolling restart.
"""

from __future__ import annotations

import base64
import contextlib
import json
import os
import re
import signal
import sys
import threading
import time
import traceback
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

import numpy as np

from roko_tpu import constants as C
from roko_tpu.config import ServeConfig
from roko_tpu.infer import VoteBoard
from roko_tpu.obs import events as obs_events
from roko_tpu.obs.trace import RequestTrace, TraceRing, new_request_id
from roko_tpu.resilience import CircuitBreaker
from roko_tpu.serve.batcher import Backpressure, MicroBatcher, QuotaExceeded
from roko_tpu.serve.metrics import ServeMetrics
from roko_tpu.serve.session import PolishSession

#: request bodies above this are refused before parsing (anti-OOM). One
#: window costs ~26 kB of base64 JSON (18 kB examples + 1.9 kB positions
#: before the 4/3 encoding overhead), so 256 MiB admits ~10k windows
#: (~300 kb of draft at stride 30) per request — whole-contig jobs past
#: that should use the ref+bam extractor form (server-side paths, no
#: window upload) or the batch CLI (docs/SERVING.md).
MAX_BODY_BYTES = 256 * 2**20

#: hard ceiling on one handler's wait for its predict result — a hung
#: or dead batcher worker must surface as an error response, not pin
#: handler threads (and their connections) forever
REQUEST_TIMEOUT_S = 600.0

#: Retry-After for the warming 503. The batcher's ``retry_after_s``
#: (default 1 s) names a queue-drain wait; warmup is a ladder compile
#: that can take minutes, and a 1 s promise would burn a client's whole
#: retry budget in seconds against a healthy warming server.
WARMING_RETRY_AFTER_S = 30.0


class _BadRequest(ValueError):
    pass


#: tenant / model-name grammar shared with the registry's _NAME_RE: a
#: malformed id is a client bug (400), never a new accounting bucket
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def request_tenant(headers, payload: Dict[str, Any]) -> Optional[str]:
    """The request's tenant id: ``X-Roko-Tenant`` header first (the
    fleet's canonical channel — the front end must not parse a 256 MiB
    body to route), then the payload's ``tenant`` field; None = the
    default tenant. Malformed ids refuse with 400 rather than opening
    an unbounded label namespace."""
    tenant = headers.get("X-Roko-Tenant") or payload.get("tenant")
    if tenant is None:
        return None
    if not isinstance(tenant, str) or not _NAME_RE.match(tenant):
        raise _BadRequest(
            "tenant id must match [A-Za-z0-9][A-Za-z0-9._-]{0,63}"
        )
    return tenant


def check_model_pin(headers, payload: Dict[str, Any], own: Optional[str]) -> None:
    """Worker-side model-lane guard: a request pinned to ``model=``
    must land on a worker RUNNING that version — anything else refuses
    loudly in the RegistryMismatch shape (docs/SERVING.md), never
    silently serves the incumbent."""
    model = headers.get("X-Roko-Model") or payload.get("model")
    if model is None:
        return
    if not isinstance(model, str) or not _NAME_RE.match(model):
        raise _BadRequest(
            "model name must match [A-Za-z0-9][A-Za-z0-9._-]{0,63}"
        )
    if own is None:
        raise _BadRequest(
            f"RegistryMismatch: request pinned model={model!r} but this "
            "worker has no registry version identity (started outside "
            "a versioned rollout)"
        )
    if model != own:
        raise _BadRequest(
            f"RegistryMismatch: request pinned model={model!r} but this "
            f"worker runs {own!r}"
        )


def _decode_array(
    payload: Dict[str, Any], key: str, dtype, shape: Tuple[int, ...]
) -> np.ndarray:
    """Base64 raw little-endian bytes, or nested lists for small
    hand-written payloads; always validated against ``shape``."""
    raw = payload.get(key)
    if raw is None:
        raise _BadRequest(f"missing field {key!r}")
    if isinstance(raw, str):
        try:
            buf = base64.b64decode(raw.encode("ascii"), validate=True)
        except Exception:
            raise _BadRequest(f"field {key!r} is not valid base64") from None
        try:
            arr = np.frombuffer(buf, dtype=np.dtype(dtype).newbyteorder("<"))
        except ValueError:  # byte count not a multiple of the item size
            raise _BadRequest(
                f"field {key!r} decodes to {len(buf)} bytes, not a "
                f"whole number of {np.dtype(dtype).name} elements"
            ) from None
        arr = arr.astype(dtype, copy=False)
    else:
        try:
            arr = np.asarray(raw, dtype=dtype)
        except (TypeError, ValueError):
            raise _BadRequest(
                f"field {key!r} is not a well-formed {np.dtype(dtype).name} "
                "array"
            ) from None
    try:
        arr = arr.reshape(shape)
    except ValueError:
        raise _BadRequest(
            f"field {key!r} has {arr.size} elements, want shape {shape}"
        ) from None
    return arr


def _cascade_override(payload: Dict[str, Any], router):
    """Per-request cascade control (docs/SERVING.md "Adaptive
    compute"): ``"cascade": false`` forces the plain path for this
    request; ``"cascade": {"threshold": t}`` re-routes through a
    same-identity router at a different threshold (identity rules mean
    a different threshold is a different cache keyspace — no
    cross-contamination). Absent field = server default."""
    raw = payload.get("cascade", None)
    if raw is None:
        return router
    if raw is False:
        return None
    if not isinstance(raw, dict):
        raise _BadRequest(
            "field 'cascade' must be false or an object like "
            '{"threshold": 0.02}'
        )
    if router is None:
        raise _BadRequest(
            "cascade override given but the server has no cascade "
            "configured (start with --cascade)"
        )
    try:
        threshold = float(raw["threshold"])
    except (KeyError, TypeError, ValueError):
        raise _BadRequest(
            "field 'cascade.threshold' must be a number in [0, 1]"
        ) from None
    if not 0.0 <= threshold <= 1.0:
        raise _BadRequest(
            "field 'cascade.threshold' must be a number in [0, 1]"
        )
    if threshold == router.threshold:
        return router
    return router.with_threshold(threshold)


def _batch_predict(
    batcher: MicroBatcher, x, trace=None, router=None, tenant=None,
):
    """One predict through the batching plane, cascaded when a router
    is attached — the single chokepoint all three /polish shapes use.
    ``tenant`` rides into ``submit`` for fair-share accounting; the
    router path closes over it because the router's submit_fn contract
    is ``(x, trace=)``."""
    if router is None:
        return batcher.submit(
            x, trace=trace, tenant=tenant
        ).result(REQUEST_TIMEOUT_S)
    submit = (
        batcher.submit
        if tenant is None
        else lambda xs, trace=None: batcher.submit(
            xs, trace=trace, tenant=tenant
        )
    )
    return router.predict(x, submit, timeout=REQUEST_TIMEOUT_S, trace=trace)


def _polish_windows(
    batcher: MicroBatcher, payload: Dict[str, Any],
    trace: Optional[RequestTrace] = None,
    router=None,
    tenant: Optional[str] = None,
) -> Dict[str, Any]:
    cfg = batcher.session.cfg.model
    draft = payload.get("draft")
    if not isinstance(draft, str) or not draft:
        raise _BadRequest("missing field 'draft' (contig sequence)")
    contig = payload.get("contig", "seq")
    try:
        n = int(payload["n"])
    except (KeyError, TypeError, ValueError):
        raise _BadRequest("missing/invalid field 'n' (window count)") from None
    if n < 0:
        raise _BadRequest("'n' must be >= 0")
    positions = _decode_array(
        payload, "positions", np.int64, (n, cfg.window_cols, 2)
    )
    examples = _decode_array(
        payload, "examples", np.uint8, (n, cfg.window_rows, cfg.window_cols)
    )
    if n:
        # value-validate client positions before they reach the vote
        # board: an out-of-range pos would crash the scatter (500) and a
        # negative one would WRAP via numpy indexing — votes landing on
        # the wrong draft bases and a silently corrupt 200 reply
        pos, ins = positions[:, :, 0], positions[:, :, 1]
        if (
            int(pos.min()) < 0 or int(pos.max()) >= len(draft)
            or int(ins.min()) < 0 or int(ins.max()) > C.MAX_INS
        ):
            raise _BadRequest(
                f"positions out of range: pos must lie in [0, {len(draft)})"
                f" (draft length) and ins in [0, {C.MAX_INS}]"
            )
    preds = _batch_predict(
        batcher, examples, trace=trace, router=router, tenant=tenant
    )
    t0 = time.perf_counter()
    board = VoteBoard({contig: draft})
    board.add([contig] * n, positions, preds)
    polished = board.stitch(contig)
    if trace is not None:
        trace.add("stitch", time.perf_counter() - t0)
    return {"contig": contig, "polished": polished, "windows": n}


def path_under_root(path: str, root: str) -> bool:
    """THE data-root containment rule (realpath-resolved): shared by
    the /polish path validation below and the supervisor's POST /job
    ``out`` check, so a hardening here covers every client-named
    server-side path."""
    import os

    real, rootr = os.path.realpath(path), os.path.realpath(root)
    return real == rootr or real.startswith(rootr + os.sep)


def _check_data_path(label: str, path: Any, data_root: Optional[str]) -> str:
    """Validate a client-named server-local path. ONE error message for
    every failure mode (bad type, outside the root, missing): the reply
    must not be a file-existence oracle for unauthenticated peers.

    A store-scheme URL (``gs://``/``s3://``/``http(s)://``) passes
    through UNLESS a data root is configured — remote inputs localize
    through the hardened store client (docs/STORAGE.md) and disclose no
    server-local file, but an operator who confined paths has also
    confined what this process may fetch."""
    import os

    err = _BadRequest(
        f"field {label!r} must name a readable data file"
        + (f" under the configured data root" if data_root else "")
    )
    if not isinstance(path, str) or not path:
        raise err
    from roko_tpu.datapipe.io import path_scheme
    from roko_tpu.datapipe.store import STORE_SCHEMES

    if path_scheme(path) in STORE_SCHEMES:
        if data_root is not None:
            raise err
        return path
    if data_root is not None and not path_under_root(path, data_root):
        raise err
    real = os.path.realpath(path)
    if not os.path.isfile(real):
        raise err
    return real


def _polish_bam(
    batcher: MicroBatcher, payload: Dict[str, Any],
    data_root: Optional[str] = None,
    trace: Optional[RequestTrace] = None,
    router=None,
    tenant: Optional[str] = None,
) -> Dict[str, Any]:
    """Extractor convenience path: feature-extract a server-local
    ref+BAM through ``features.pipeline`` and polish every contig
    through the same batcher as the wire path."""
    import os
    import tempfile

    from roko_tpu.data.hdf5 import iter_inference_windows, load_contigs
    from roko_tpu.features.pipeline import run_features

    ref = _check_data_path("ref", payload.get("ref"), data_root)
    bam = _check_data_path("bam", payload.get("bam"), data_root)
    try:
        workers = int(payload.get("workers", 1))
        seed = int(payload.get("seed", 0))
    except (TypeError, ValueError):
        raise _BadRequest(
            "fields 'workers'/'seed' must be integers"
        ) from None
    # a client names how much extraction parallelism it wants, the
    # server decides how much it grants: clamp to the host's cores so
    # one request can't command an arbitrary process fan-out
    workers = max(1, min(workers, os.cpu_count() or 1))
    session = batcher.session
    with tempfile.TemporaryDirectory() as td:
        h5 = os.path.join(td, "serve_features.hdf5")
        n = run_features(
            ref, bam, h5, workers=workers, seed=seed, config=session.cfg,
            log=lambda *_a, **_k: None,
        )
        board = VoteBoard(load_contigs(h5))
        # feed extractor batches at the top rung so the feature read and
        # device dispatch pipeline as in the batch path
        for names, positions, x in iter_inference_windows(
            h5, session.ladder[-1]
        ):
            board.add(
                names, positions,
                _batch_predict(
                    batcher, x, trace=trace, router=router, tenant=tenant
                ),
            )
        t0 = time.perf_counter()
        contigs = board.stitch_all()
        if trace is not None:
            trace.add("stitch", time.perf_counter() - t0)
    return {"contigs": contigs, "windows": n}


def _polish_unit(
    batcher: MicroBatcher, payload: Dict[str, Any],
    data_root: Optional[str] = None,
    trace: Optional[RequestTrace] = None,
    router=None,
    tenant: Optional[str] = None,
) -> Dict[str, Any]:
    """Worker-side execution of ONE distributed-polish work unit
    (roko_tpu/pipeline/distpolish.py, docs/PIPELINE.md "Distributed
    polish"): extract exactly the unit's region slice from server-local
    ``ref``+``bam``, predict over the warm session, and either stitch
    the contig (``emit: "contig"`` — whole-contig units) or return the
    raw per-window predictions (``emit: "preds"`` — span units of a
    giant contig, voted and stitched coordinator-side). The region
    table and seeds re-derive deterministically, so the windows are
    bit-identical to a single-process run's."""
    from roko_tpu.pipeline.distpolish import (
        b64_array,
        extract_unit_windows,
    )

    ref = _check_data_path("ref", payload.get("ref"), data_root)
    bam = _check_data_path("bam", payload.get("bam"), data_root)
    unit = payload.get("unit")
    if not isinstance(unit, dict):
        raise _BadRequest("field 'unit' must be an object")
    try:
        contig = unit["contig"]
        first = int(unit["first_region"])
        count = int(unit["n_regions"])
    except (KeyError, TypeError, ValueError):
        raise _BadRequest(
            "field 'unit' needs 'contig', 'first_region', 'n_regions'"
        ) from None
    if not isinstance(contig, str) or not contig:
        raise _BadRequest("'unit.contig' must be a contig name")
    emit = unit.get("emit", "contig")
    if emit not in ("contig", "preds"):
        raise _BadRequest("'unit.emit' must be 'contig' or 'preds'")
    try:
        seed = int(payload.get("seed", 0))
    except (TypeError, ValueError):
        raise _BadRequest("'seed' must be an integer") from None
    session = batcher.session
    t0 = time.perf_counter()
    try:
        draft, positions, x = extract_unit_windows(
            ref, bam, contig, first, count, seed, session.cfg
        )
    except ValueError as e:
        raise _BadRequest(str(e)) from None
    if trace is not None:
        trace.add("extract", time.perf_counter() - t0)
    n = int(len(positions))
    # chunk at the top ladder rung so one giant unit never exceeds the
    # batching plane's admission bounds (the _polish_bam rule)
    top = session.ladder[-1]
    chunks = [
        _batch_predict(
            batcher, x[i:i + top], trace=trace, router=router,
            tenant=tenant,
        )
        for i in range(0, n, top)
    ]
    preds = (
        np.concatenate(chunks)
        if chunks
        else np.empty((0, session.cfg.model.window_cols), np.int32)
    )
    if emit == "preds":
        return {
            "contig": contig,
            "windows": n,
            "positions": b64_array(positions, np.int64),
            "preds": b64_array(preds, np.int32),
        }
    t0 = time.perf_counter()
    board = VoteBoard({contig: draft})
    if n:
        board.add([contig] * n, positions, preds)
    polished = board.stitch(contig)
    if trace is not None:
        trace.add("stitch", time.perf_counter() - t0)
    return {"contig": contig, "polished": polished, "windows": n}


class JsonRequestHandler(BaseHTTPRequestHandler):
    """Shared handler base for the serve tier's HTTP surfaces — the
    single-process worker front end below and the fleet supervisor
    (``serve/supervisor.py``): JSON replies, bounded body reads, and
    drain-aware in-flight accounting over the lifecycle state
    ``init_lifecycle`` attaches to the server object."""

    protocol_version = "HTTP/1.1"
    #: socket timeout for reads on one request: a peer that promises
    #: Content-Length bytes and stalls mid-body must not pin a handler
    #: thread forever (slowloris); on timeout the connection closes
    timeout = 120.0

    def log_message(self, fmt: str, *args: Any) -> None:
        pass  # quiet by default; metrics carry the signal

    def _reply(
        self,
        code: int,
        body: bytes,
        content_type: str = "application/json",
        extra: Optional[Dict[str, str]] = None,
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, code: int, obj: Dict[str, Any], **kw: Any) -> None:
        self._reply(code, json.dumps(obj).encode(), **kw)

    def _read_body(self, max_bytes: int = MAX_BODY_BYTES) -> Optional[bytes]:
        """Validate ``Content-Length`` and read the request body; on a
        bad header (400) or oversized body (413) the error reply is
        sent here and ``None`` returned. A peer stalling mid-body still
        raises ``TimeoutError`` out of the read (socket timeout) for
        the caller to map."""
        try:
            length = int(self.headers.get("Content-Length", "0"))
        except ValueError:
            # body length unknown -> can't resync the keep-alive
            # stream; close after replying
            self.close_connection = True
            self._reply_json(400, {"error": "bad Content-Length header"})
            return None
        if length < 0:
            # rfile.read(-1) would block until the peer closes —
            # a handler thread pinned forever per such request
            self.close_connection = True
            self._reply_json(400, {"error": "bad Content-Length header"})
            return None
        if length > max_bytes:
            # body left unread: a keep-alive peer would otherwise
            # have its next request parsed out of these bytes
            self.close_connection = True
            self._reply_json(
                413, {"error": f"body exceeds {max_bytes} bytes"}
            )
            return None
        return self.rfile.read(length)

    @contextlib.contextmanager
    def _track_inflight(self):
        """Count this request in the server's in-flight set so a drain
        can wait for it (the counter, not thread bookkeeping, is what
        ``drain`` polls — handler threads are daemons)."""
        srv = self.server
        with srv._inflight_lock:
            srv._inflight += 1
        try:
            yield
        finally:
            with srv._inflight_lock:
                srv._inflight -= 1


def init_lifecycle(
    server: ThreadingHTTPServer,
    drain_deadline_s: float,
    warming: bool = False,
) -> None:
    """Attach the drain/warming lifecycle state ``drain`` and
    :class:`JsonRequestHandler` expect: the `_draining`/`_warming`
    events, the in-flight counter, and the drain deadline. One
    implementation for the worker server and the fleet supervisor."""
    server.daemon_threads = True
    server._draining = threading.Event()  # type: ignore[attr-defined]
    server._warming = threading.Event()  # type: ignore[attr-defined]
    if warming:
        server._warming.set()  # type: ignore[attr-defined]
    server._inflight = 0  # type: ignore[attr-defined]
    server._inflight_lock = threading.Lock()  # type: ignore[attr-defined]
    server.drain_deadline_s = drain_deadline_s  # type: ignore[attr-defined]


class _Handler(JsonRequestHandler):
    # set by make_server on the class copy
    batcher: MicroBatcher
    metrics: ServeMetrics
    ring: Optional[TraceRing] = None
    data_root: Optional[str] = None
    worker_id: Optional[int] = None
    #: CascadeRouter when the session serves with adaptive compute
    #: (None = plain single-tier path; docs/SERVING.md)
    router = None

    def do_GET(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] == "/tracez":
            self._handle_tracez()
            return
        if self.path == "/healthz":
            session = self.batcher.session
            breaker = getattr(self.server, "breaker", None)
            body: Dict[str, Any] = {
                "status": "ok",
                "ladder": list(session.ladder),
                # which batching policy is live (docs/SERVING.md
                # "Continuous batching") — derived from the batcher
                # actually serving, not the config, so an explicitly
                # passed batcher reports truthfully
                "batching": getattr(
                    self.batcher, "BATCHING_MODE", "deadline"
                ),
                "compiled": session.cache_size(),
                # mesh topology this ONE session drives — every ladder
                # rung shards rung/dp windows per device (getattr:
                # session stand-ins need not carry a mesh)
                "mesh_dp": getattr(session, "dp", 1),
                "devices": getattr(session, "n_devices", 1),
                # degraded-but-serving: a device hang permanently failed
                # this session over to host-CPU predict (getattr:
                # session stand-ins need not model the fail-over)
                "cpu_fallback": getattr(session, "failed_over", False),
            }
            if self.worker_id is not None:
                # fleet workers carry their id so the supervisor (and a
                # human curl) can confirm who answered after restarts
                body["worker_id"] = self.worker_id
            hint = getattr(self.batcher, "retry_after_s", None)
            if isinstance(hint, (int, float)):
                # the live Retry-After estimate (continuous mode:
                # backlog over observed windows/sec) rides in healthz so
                # the fleet supervisor's own 503s can promise a real
                # wait instead of the static config guess
                body["retry_after_s"] = round(float(hint), 3)
            backlog_fn = getattr(self.batcher, "backlog_windows", None)
            if callable(backlog_fn):
                # autoscaler inputs: queued-window backlog + occupancy
                # ride the same probe the supervisor already makes
                body["queue_windows"] = int(backlog_fn())
                occ = getattr(self.batcher, "occupancy", None)
                if callable(occ):
                    body["occupancy"] = round(float(occ()), 4)
            tb_fn = getattr(self.batcher, "tenant_backlogs", None)
            tr_fn = getattr(self.batcher, "tenant_retry_after_s", None)
            if callable(tb_fn) and callable(tr_fn):
                # per-tenant backlog + drain-rate Retry-After, cached
                # by the fleet's health checker so front-end 503/429s
                # quote the TENANT's wait, not the global queue's
                body["tenants"] = {
                    t: {
                        "backlog_windows": n,
                        "retry_after_s": round(float(tr_fn(t)), 3),
                    }
                    for t, n in sorted(tb_fn().items())
                }
            code = 200
            if breaker is not None:
                body["breaker"] = breaker.state
                body["breaker_trips"] = breaker.trip_count
                if breaker.state == "open":
                    # the device is failing: a load balancer must stop
                    # routing here until half-open probing recovers it
                    body["status"] = "unhealthy"
                    code = 503
            if self.server._warming.is_set():
                # bound but not yet compiled: alive (the process is
                # making progress) but not ready — don't route here yet
                body["status"] = "warming"
                code = 503
            if self.server._draining.is_set():
                body["status"] = "draining"
                code = 503
            self._reply_json(code, body)
        elif self.path == "/metrics":
            self._reply(
                200,
                self.metrics.render().encode(),
                content_type="text/plain; version=0.0.4",
            )
        else:
            self._reply_json(404, {"error": f"no route {self.path}"})

    def _handle_tracez(self) -> None:
        """Trace ring + live scheduler snapshot (docs/OBSERVABILITY.md):
        ``?last=N&slowest=M`` bound how many records return."""
        query = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query
        )

        def _qint(name: str) -> Optional[int]:
            try:
                return max(1, int(query[name][0]))
            except (KeyError, IndexError, ValueError):
                return None

        body: Dict[str, Any] = {
            "worker_id": self.worker_id,
            "batching": getattr(self.batcher, "BATCHING_MODE", "deadline"),
        }
        ring = self.ring
        if ring is not None:
            body.update(ring.snapshot(_qint("last"), _qint("slowest")))
        snap = getattr(self.batcher, "snapshot", None)
        if snap is not None:
            body["scheduler"] = snap()
        self._reply_json(200, body)

    def _handle_profilez(self) -> None:
        """On-demand XPlane capture: hold ``jax.profiler`` open over the
        next N seconds of device steps and return the trace directory.
        One capture at a time (409 while one runs); the capture runs on
        THIS handler thread — the reply lands when the trace is on disk
        and loadable."""
        import tempfile

        from roko_tpu.utils.profiling import capture_device_trace

        query = urllib.parse.parse_qs(
            urllib.parse.urlsplit(self.path).query
        )
        try:
            seconds = float(query.get("seconds", ["3"])[0])
        except ValueError:
            self._reply_json(400, {"error": "seconds must be a number"})
            return
        seconds = max(0.1, min(seconds, 120.0))
        lock: threading.Lock = self.server._profile_lock  # type: ignore[attr-defined]
        if not lock.acquire(blocking=False):
            self._reply_json(
                409, {"error": "a profile capture is already running"}
            )
            return
        try:
            trace_dir = tempfile.mkdtemp(prefix="roko-profilez-")
            obs_events.emit(
                "serve", "profile_start",
                trace_dir=trace_dir, seconds=seconds, quiet=True,
            )
            capture_device_trace(trace_dir, seconds)
        except Exception as e:
            self.metrics.inc("errors")
            traceback.print_exc(file=sys.stderr)
            self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
            return
        finally:
            lock.release()
        obs_events.emit(
            "serve", "profile_done",
            trace_dir=trace_dir, seconds=seconds, quiet=True,
        )
        self._reply_json(
            200,
            {"trace_dir": trace_dir, "seconds": seconds,
             "hint": "load in TensorBoard: tensorboard --logdir "
                     + trace_dir},
        )

    def do_POST(self) -> None:  # noqa: N802 - http.server API
        if self.path.split("?", 1)[0] == "/profilez":
            self._handle_profilez()
            return
        if self.path != "/polish":
            self._reply_json(404, {"error": f"no route {self.path}"})
            return
        with self._track_inflight():
            # checked AFTER the in-flight increment: drain() watches the
            # counter, so checking first would let it read 0 and shut
            # down while this request is between the check and the
            # increment
            if self.server._draining.is_set():
                # draining: in-flight work finishes, NEW work goes
                # elsewhere
                self.close_connection = True
                retry = self.batcher.retry_after_s
                self._reply_json(
                    503,
                    {"error": "server draining", "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            if self.server._warming.is_set():
                # the ladder is still compiling: shed the request now
                # instead of parking it behind a minutes-long compile
                # (the socket binds before warmup so restarts are
                # observable, but work waits for the flip to "ok")
                retry = max(self.batcher.retry_after_s, WARMING_RETRY_AFTER_S)
                self._reply_json(
                    503,
                    {"error": "server warming up (ladder compiling)",
                     "retry_after_s": retry},
                    extra={"Retry-After": f"{max(1, round(retry))}"},
                )
                return
            self._handle_polish()

    def _handle_polish(self) -> None:
        # request identity: honor the id a front end (or client)
        # assigned — across fleet failover the retried dispatch carries
        # the SAME id, so the event log and /tracez see one request —
        # else mint one here
        rid = self.headers.get("X-Roko-Request-Id") or new_request_id()
        trace = RequestTrace(rid, worker_id=self.worker_id)
        try:
            raw = self._read_body()
            if raw is None:
                return  # error reply already sent
            payload = json.loads(raw.decode())
            if not isinstance(payload, dict):
                raise _BadRequest("payload must be a JSON object")
            tenant = request_tenant(self.headers, payload)
            check_model_pin(
                self.headers, payload, self.metrics.model_version
            )
            trace.tenant = tenant
            trace.model = self.metrics.model_version
            router = _cascade_override(payload, self.router)
            if "unit" in payload:
                result = _polish_unit(
                    self.batcher, payload, self.data_root, trace=trace,
                    router=router, tenant=tenant,
                )
            elif "bam" in payload:
                result = _polish_bam(
                    self.batcher, payload, self.data_root, trace=trace,
                    router=router, tenant=tenant,
                )
            else:
                result = _polish_windows(
                    self.batcher, payload, trace=trace, router=router,
                    tenant=tenant,
                )
            trace.windows = int(result.get("windows", 0))
            result["request_id"] = rid
            result["timings"] = trace.timings()
            if self.ring is not None:
                self.ring.record(trace)
            self._reply_json(200, result)
        except QuotaExceeded as e:
            # the TENANT's quota, not global overload: 429 so clients
            # (and the fleet front end) can tell throttling from an
            # unhealthy service; Retry-After is the tenant's own drain
            # estimate
            self._reply_json(
                429,
                {"error": str(e), "tenant": e.tenant,
                 "retry_after_s": e.retry_after_s},
                extra={"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )
        except Backpressure as e:
            self._reply_json(
                503,
                {"error": str(e), "retry_after_s": e.retry_after_s},
                extra={"Retry-After": f"{max(1, round(e.retry_after_s))}"},
            )
        except TimeoutError:
            # either the batcher never answered within REQUEST_TIMEOUT_S
            # (service unhealthy) or — socket.timeout IS TimeoutError on
            # py>=3.10 — the peer stalled mid-body past the socket
            # timeout. Shed the request; close the connection in both
            # cases (a half-read body would desync the keep-alive
            # stream, and an unhealthy service shouldn't pool it)
            self.close_connection = True
            self.metrics.inc("errors")
            self._reply_json(
                503,
                {"error": "timed out reading the request or waiting for "
                          "the predict result"},
            )
        except (_BadRequest, json.JSONDecodeError, UnicodeDecodeError) as e:
            self._reply_json(400, {"error": str(e)})
        except Exception as e:  # pragma: no cover - defensive
            self.metrics.inc("errors")
            # the 500 body carries only the type; the message + traceback
            # stay server-side, but must actually be LOGGED or production
            # 500s are undiagnosable (log_message is silenced)
            traceback.print_exc(file=sys.stderr)
            self._reply_json(500, {"error": type(e).__name__})


def make_server(
    session: PolishSession,
    serve_cfg: Optional[ServeConfig] = None,
    *,
    batcher: Optional[MicroBatcher] = None,
    metrics: Optional[ServeMetrics] = None,
    breaker: Optional[CircuitBreaker] = None,
    host: Optional[str] = None,
    port: Optional[int] = None,
    warming: bool = False,
    worker_id: Optional[int] = None,
) -> ThreadingHTTPServer:
    """Bind (port 0 = ephemeral) and return the server; the caller runs
    ``serve_forever``. The batcher/metrics/breaker ride on the server
    object (``.batcher`` / ``.metrics`` / ``.breaker``) so tests and the
    CLI can reach them.

    ``warming=True`` starts the server in the not-ready state: healthz
    says ``"warming"`` (503) and ``/polish`` sheds with 503+Retry-After
    until the caller clears ``server._warming`` — the CLI binds the
    socket first, warms the ladder on a worker thread, then flips it."""
    serve_cfg = serve_cfg or session.cfg.serve
    rcfg = session.cfg.resilience
    metrics = metrics or ServeMetrics(latency_samples=serve_cfg.latency_samples)
    # per-size-class latency buckets follow the session's ladder rungs
    metrics.size_classes = tuple(session.ladder)
    # registry version identity (fleet spawns export it per launch
    # spec): labels the latency histogram per model and arms the
    # worker-side model-lane pin guard
    if metrics.model_version is None:
        metrics.model_version = os.environ.get("ROKO_MODEL_VERSION") or None
    if batcher is None:
        if breaker is None and rcfg.breaker_failures > 0:
            breaker = CircuitBreaker(
                failure_threshold=rcfg.breaker_failures,
                reset_s=rcfg.breaker_reset_s,
            )
        # batching policy is pluggable (ServeConfig.batching,
        # docs/SERVING.md "Continuous batching"): the continuous
        # scheduler packs windows from many requests densely into each
        # ladder-rung step; "deadline" restores the whole-request
        # coalescer. Knobs come from the EXPLICIT serve_cfg — the
        # batchers' own defaults read session.cfg.serve, which may be a
        # different config object than the one passed here.
        if serve_cfg.batching in ("continuous", "ragged"):
            from roko_tpu.serve.scheduler import (
                ContinuousBatcher,
                RaggedBatcher,
            )

            # "ragged" rides the same packing plane; its steps run the
            # session's one masked top-rung executable instead of the
            # padded ladder (docs/SERVING.md "Ragged dispatch")
            cls = (
                RaggedBatcher
                if serve_cfg.batching == "ragged"
                else ContinuousBatcher
            )
            batcher = cls(
                session,
                metrics=metrics,
                breaker=breaker,
                max_queue=serve_cfg.max_queue,
                max_queue_age_ms=serve_cfg.max_queue_age_ms,
                rung_upgrade_fill=serve_cfg.rung_upgrade_fill,
                retry_after_s=serve_cfg.retry_after_s,
            )
        else:
            batcher = MicroBatcher(
                session,
                metrics=metrics,
                breaker=breaker,
                max_queue=serve_cfg.max_queue,
                max_delay_ms=serve_cfg.max_delay_ms,
                retry_after_s=serve_cfg.retry_after_s,
            )
    else:
        breaker = breaker or batcher.breaker
    metrics.breaker = breaker
    metrics.cpu_fallback = lambda: getattr(session, "failed_over", False)
    # adaptive compute (roko_tpu/cascade): router built against the
    # session's post-quantize params — its cache keys/calibration
    # identity match exactly what the device predicts with
    router = None
    if session.cfg.cascade.enabled:
        from roko_tpu.cascade import build_router

        router = build_router(session.cfg, params=session.params,
                              metrics=metrics)
    ring = TraceRing(serve_cfg.trace_ring, serve_cfg.trace_slowest)
    handler = type("RokoServeHandler", (_Handler,), {
        "batcher": batcher, "metrics": metrics, "ring": ring,
        "data_root": serve_cfg.data_root,
        "worker_id": worker_id,
        "router": router,
    })
    server = ThreadingHTTPServer(
        (serve_cfg.host if host is None else host,
         serve_cfg.port if port is None else port),
        handler,
    )
    server.batcher = batcher  # type: ignore[attr-defined]
    server.metrics = metrics  # type: ignore[attr-defined]
    server.session = session  # type: ignore[attr-defined]
    server.breaker = breaker  # type: ignore[attr-defined]
    server.ring = ring  # type: ignore[attr-defined]
    server.router = router  # type: ignore[attr-defined]
    server._profile_lock = threading.Lock()  # type: ignore[attr-defined]
    init_lifecycle(server, rcfg.drain_deadline_s, warming=warming)
    return server


def drain(
    server: ThreadingHTTPServer,
    deadline_s: Optional[float] = None,
    log=print,
) -> bool:
    """Graceful shutdown: reject NEW ``/polish`` work with 503 +
    ``Retry-After`` immediately, wait up to ``deadline_s`` for in-flight
    requests to finish, then stop the accept loop. Returns True when
    every in-flight request completed inside the deadline. Idempotent —
    a second SIGTERM while draining is a no-op."""
    if server._draining.is_set():  # type: ignore[attr-defined]
        return True
    if deadline_s is None:
        deadline_s = getattr(server, "drain_deadline_s", 20.0)
    server._draining.set()  # type: ignore[attr-defined]
    log(
        f"roko serve: draining — rejecting new work, waiting up to "
        f"{deadline_s:.0f}s for in-flight requests"
    )
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        with server._inflight_lock:  # type: ignore[attr-defined]
            left = server._inflight  # type: ignore[attr-defined]
        if left == 0:
            break
        time.sleep(0.05)
    with server._inflight_lock:  # type: ignore[attr-defined]
        left = server._inflight  # type: ignore[attr-defined]
    if left:
        log(
            f"roko serve: drain deadline expired with {left} request(s) "
            "still in flight; shutting down anyway"
        )
    else:
        log("roko serve: drained clean")
    server.shutdown()
    return left == 0


def sigusr2_dump(server: ThreadingHTTPServer, log=None) -> None:
    """Operator-triggered post-mortem WITHOUT killing the service
    (docs/OBSERVABILITY.md): every thread's stack (the watchdog's dump
    machinery) plus the live scheduler snapshot to stderr —
    ``kill -USR2 <pid>`` answers "what is this process doing right
    now". Wired to SIGUSR2 by :func:`serve_forever` for both the
    worker server and the fleet supervisor front end."""
    from roko_tpu.resilience.watchdog import dump_thread_stacks

    snap = None
    batcher = getattr(server, "batcher", None)
    snap_fn = getattr(batcher, "snapshot", None)
    if snap_fn is not None:
        try:
            snap = snap_fn()
        except Exception:  # diagnostics never take the service down
            pass
    emit_log = log or (lambda m: print(m, file=sys.stderr, flush=True))
    obs_events.emit(
        "serve", "sigusr2_dump", log=emit_log,
        threads=threading.active_count(),
        scheduler=json.dumps(snap) if snap is not None else "n/a",
    )
    emit_log(dump_thread_stacks())


def serve_forever(server: ThreadingHTTPServer, log=print, drain_fn=None) -> None:
    """Blocking loop with clean shutdown on Ctrl-C and a graceful
    SIGTERM drain (finish in-flight, reject new, then exit).
    ``drain_fn`` overrides what SIGTERM runs — the fleet supervisor
    passes its rolling drain (front end first, then workers one at a
    time); the default is :func:`drain` on this server alone."""
    host, port = server.server_address[:2]
    log(f"roko serve: listening on http://{host}:{port} "
        f"(POST /polish, GET /healthz, GET /metrics)")
    if drain_fn is None:
        drain_fn = lambda: drain(server, log=log)  # noqa: E731

    def _on_sigterm(signum, frame):
        # drain blocks (and calls shutdown, which must not run on the
        # serve_forever thread) — hand it to a worker
        threading.Thread(
            target=drain_fn, name="roko-serve-drain", daemon=True
        ).start()

    def _on_sigusr2(signum, frame):
        sigusr2_dump(server)

    try:
        # only the main thread may set signal handlers; tests drive
        # serve_forever from worker threads and call drain() directly
        signal.signal(signal.SIGTERM, _on_sigterm)
        if hasattr(signal, "SIGUSR2"):  # not on Windows
            signal.signal(signal.SIGUSR2, _on_sigusr2)
    except ValueError:
        pass
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        log("roko serve: shutting down")
    finally:
        batcher = getattr(server, "batcher", None)
        if batcher is not None:  # the supervisor front end has none
            batcher.stop()
        server.server_close()
