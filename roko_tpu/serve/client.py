"""Stdlib client for the polishing service (tests + ``tools/``).

Encodes window arrays in the wire format ``server.py`` expects
(base64 raw little-endian), maps the server's backpressure reply to
:class:`ServerBusy` with the parsed ``retry_after_s``, and retries
through busy replies with the shared
:class:`roko_tpu.resilience.RetryPolicy` — exponential backoff +
jitter, FLOORED by the server's ``Retry-After`` (the server names the
minimum wait; the growing backoff and jitter keep a fleet of rejected
clients from returning in lockstep). An exhausted retry budget raises
the typed :class:`ServiceUnavailable` with the attempt count — shed
load is debuggable, not a bare HTTPError.
"""

from __future__ import annotations

import base64
import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

import numpy as np

from roko_tpu.resilience import RetryPolicy


class ServerBusy(RuntimeError):
    """503 from the service: queue full, retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"server busy; retry after {retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


class QuotaExceededBusy(ServerBusy):
    """429 from the service: THIS TENANT's quota (queue or in-flight
    cap) is exhausted, not the global queue — other tenants are still
    being served. Retryable like :class:`ServerBusy` (the Retry-After
    is sized from the tenant's own backlog and drain rate), typed so
    callers can distinguish their own quota from fleet-wide
    pressure."""

    def __init__(self, retry_after_s: float, tenant: Optional[str] = None):
        RuntimeError.__init__(
            self,
            f"tenant quota exceeded"
            + (f" ({tenant})" if tenant else "")
            + f"; retry after {retry_after_s:.1f}s",
        )
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class FleetDraining(ServerBusy):
    """503 with a DRAINING status: the fleet (or worker) is
    deliberately shedding all new work for a rollout/shutdown window —
    not transient queue pressure. Typed so callers PARK work and
    re-submit later instead of burning a retry budget against a wait
    that outlasts it (the distributed-polish coordinator parks its
    units on exactly this signal); :meth:`PolishClient.polish`
    propagates it immediately rather than retrying."""


class ServiceUnavailable(ServerBusy):
    """The retry budget was exhausted against 503s: every one of
    ``attempts`` tries was shed (queue full, breaker open, fleet
    degraded, or draining). Typed — not a bare HTTPError — so
    fleet-level load shedding is debuggable from the client side:
    ``attempts`` says how hard the client pushed and ``retry_after_s``
    what the server last asked for. When the client's total-deadline
    budget (not the attempt count) ended the retries, ``deadline_s``
    carries it and the message names it."""

    def __init__(
        self,
        retry_after_s: float,
        attempts: int,
        deadline_s: Optional[float] = None,
    ):
        if deadline_s is not None:
            msg = (
                f"service unavailable: {attempts} attempt(s) got 503 "
                f"and the next Retry-After wait would overshoot the "
                f"client deadline budget deadline_s={deadline_s:.1f}s; "
                f"last Retry-After {retry_after_s:.1f}s"
            )
        else:
            msg = (
                f"service unavailable: all {attempts} attempt(s) got "
                f"503; last Retry-After {retry_after_s:.1f}s"
            )
        RuntimeError.__init__(self, msg)
        self.retry_after_s = retry_after_s
        self.attempts = attempts
        self.deadline_s = deadline_s


class _DeadlineExceeded(Exception):
    """Internal: the deadline-aware sleep refused to start a wait that
    would overshoot ``deadline_s`` (converted to
    :class:`ServiceUnavailable` at the retry-loop boundary)."""


def parse_503_body(body) -> "tuple[str, float]":
    """``(error_detail, retry_after_s)`` from a 503 reply body,
    tolerant of ANY malformation — detail parses FIRST so a junk
    ``retry_after_s`` never costs the draining classification; the
    wait falls back to 1.0. Shared by this client and the
    distributed-polish coordinator's dispatch loop so the two 503
    classifiers cannot drift."""
    detail, retry = "", 1.0
    try:
        parsed = json.loads(body)
        detail = str(parsed.get("error", ""))
        retry = float(parsed.get("retry_after_s", 1.0))
    except (ValueError, AttributeError, TypeError, UnicodeDecodeError):
        pass
    return detail, retry


def _b64(arr: np.ndarray, dtype) -> str:
    return base64.b64encode(
        np.ascontiguousarray(arr, dtype=np.dtype(dtype).newbyteorder("<"))
        .tobytes()
    ).decode("ascii")


class PolishClient:
    #: backoff shape behind ``retries`` (attempt budget layers on top);
    #: swap the attribute for a custom policy or a no-sleep test double
    retry_policy = RetryPolicy(
        base_delay_s=0.5, max_delay_s=30.0, retryable=(ServerBusy,)
    )

    def __init__(
        self,
        base_url: str,
        timeout: float = 120.0,
        deadline_s: Optional[float] = None,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        #: client-side TOTAL wall-clock budget across a whole retry
        #: loop: a fleet shedding load with large Retry-After hints can
        #: otherwise stretch `retries` waits far past what the caller
        #: can afford. A retry wait that would overshoot the budget is
        #: refused up front with :class:`ServiceUnavailable` naming the
        #: budget. None = unbounded (the historical behavior).
        self.deadline_s = deadline_s
        self._sleep = time.sleep  # injection point for tests

    # -- transport ----------------------------------------------------------

    def _request(
        self, path: str, payload: Optional[Dict[str, Any]] = None,
        headers: Optional[Dict[str, str]] = None,
    ) -> Any:
        url = self.base_url + path
        data = None if payload is None else json.dumps(payload).encode()
        hdrs = {"Content-Type": "application/json"} if data else {}
        hdrs.update(headers or {})
        req = urllib.request.Request(
            url,
            data=data,
            headers=hdrs,
            method="POST" if data else "GET",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
            if e.code == 503:
                detail, retry = parse_503_body(body)
                if "draining" in detail:
                    # the server names a deliberate drain window
                    # (healthz=draining): typed, so callers can park
                    # instead of retrying into the drain
                    raise FleetDraining(retry) from None
                raise ServerBusy(retry) from None
            if e.code == 429:
                # per-tenant quota breach: the Retry-After header (or
                # body field) carries the tenant-sized wait
                try:
                    parsed = json.loads(body)
                    retry = float(parsed.get("retry_after_s", 1.0))
                    tenant = parsed.get("tenant")
                except (ValueError, TypeError, UnicodeDecodeError):
                    retry, tenant = 1.0, None
                try:
                    retry = max(
                        retry, float(e.headers.get("Retry-After", 0))
                    )
                except (TypeError, ValueError):
                    pass
                raise QuotaExceededBusy(retry, tenant) from None
            try:
                detail = json.loads(body).get("error", "")
            except ValueError:
                detail = body[:200].decode(errors="replace")
            raise RuntimeError(f"HTTP {e.code} from {path}: {detail}") from None
        return body

    # -- routes -------------------------------------------------------------

    def healthz(self) -> Dict[str, Any]:
        return json.loads(self._request("/healthz"))

    def metrics(self) -> str:
        return self._request("/metrics").decode()

    def tracez(self, last: Optional[int] = None) -> Dict[str, Any]:
        """The trace ring + scheduler snapshot (docs/OBSERVABILITY.md);
        against a fleet front end the body is keyed by worker id."""
        path = "/tracez" + (f"?last={int(last)}" if last else "")
        return json.loads(self._request(path))

    def _post_with_retries(
        self, payload: Dict[str, Any], retries: int,
        request_id: Optional[str] = None,
        extra_headers: Optional[Dict[str, str]] = None,
        deadline_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """POST /polish, sleeping through up to ``retries``
        :class:`ServerBusy` replies (503: queue full, breaker open, or
        draining; 429: tenant quota) with the policy's backoff floored
        by the server's ``Retry-After`` — never failing on the first
        backpressure response unless asked to (``retries=0``).
        Exhausting the budget raises the typed
        :class:`ServiceUnavailable` (a ServerBusy subclass) carrying
        the attempt count. ``deadline_s`` (or the constructor's)
        additionally bounds the TOTAL wall clock: a retry wait that
        would overshoot it raises ServiceUnavailable naming the budget
        instead of sleeping into it."""
        import dataclasses

        policy = dataclasses.replace(
            self.retry_policy, max_attempts=retries + 1
        )
        deadline_s = self.deadline_s if deadline_s is None else deadline_s
        t0 = time.monotonic()
        attempts = [0]
        last_hint = [1.0]

        def probe():
            attempts[0] += 1
            return (
                self._request("/polish", payload, headers)
                if headers
                else self._request("/polish", payload)
            )

        def hint(e):
            v = getattr(e, "retry_after_s", None)
            if v is not None:
                last_hint[0] = v
            return v

        def budget_sleep(delay: float) -> None:
            if (
                deadline_s is not None
                and time.monotonic() - t0 + delay > deadline_s
            ):
                raise _DeadlineExceeded()
            self._sleep(delay)

        # the 2-arg call stays the default so _request stand-ins (tests)
        # keep working; headers ride only when something is pinned
        headers = dict(extra_headers or {})
        if request_id:
            headers["X-Roko-Request-Id"] = request_id
        headers = headers or None
        try:
            return json.loads(
                policy.call(
                    probe,
                    retry_after=hint,
                    sleep=budget_sleep,
                    # a draining fleet asks callers to PARK, not retry:
                    # propagate the typed signal with the budget intact
                    giveup=lambda e: isinstance(e, FleetDraining),
                )
            )
        except _DeadlineExceeded:
            raise ServiceUnavailable(
                last_hint[0], attempts[0], deadline_s=deadline_s
            ) from None
        except (ServiceUnavailable, FleetDraining):
            raise
        except ServerBusy as e:
            raise ServiceUnavailable(e.retry_after_s, retries + 1) from e

    def polish(
        self,
        draft: str,
        positions: np.ndarray,
        examples: np.ndarray,
        contig: str = "seq",
        retries: int = 4,
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
        model: Optional[str] = None,
        deadline_s: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Polish one contig from pre-extracted windows. ``retries``
        bounds how many :class:`ServerBusy` replies are slept through
        (honouring the server's retry-after as a backoff floor) before
        giving up; 0 surfaces the first busy reply. ``request_id`` pins
        the trace identity (``X-Roko-Request-Id``) — by default the
        service mints one and returns it in the reply.

        ``tenant`` names the fair-share/quota bucket this request bills
        to (``X-Roko-Tenant``; the default tenant otherwise). ``model``
        PINS a registered model version (``X-Roko-Model``): the fleet
        verifies it against the registry and routes to workers running
        it, refusing loudly (RegistryMismatch, HTTP 400) rather than
        silently serving the incumbent.

        ``deadline_s`` caps the TOTAL wall clock the retry loop may
        spend (overrides the constructor's): large fleet Retry-After
        hints are honoured only while they fit the budget, past it
        :class:`ServiceUnavailable` names the budget."""
        examples = np.asarray(examples)
        payload = {
            "contig": contig,
            "draft": draft,
            "n": int(examples.shape[0]),
            "positions": _b64(positions, np.int64),
            "examples": _b64(examples, np.uint8),
        }
        headers: Dict[str, str] = {}
        if tenant is not None:
            payload["tenant"] = tenant
            headers["X-Roko-Tenant"] = tenant
        if model is not None:
            payload["model"] = model
            headers["X-Roko-Model"] = model
        return self._post_with_retries(
            payload, retries, request_id, headers or None,
            deadline_s=deadline_s,
        )

    def polish_bam(
        self, ref: str, bam: str, workers: int = 1, seed: int = 0,
        retries: int = 4,
    ) -> Dict[str, Any]:
        """Extractor convenience path: ``ref``/``bam`` are paths on the
        SERVER's filesystem; ``seed`` is the row-sampling seed (matches
        the ``features`` CLI's ``--seed``). Busy replies retry as in
        :meth:`polish`."""
        return self._post_with_retries(
            {"ref": ref, "bam": bam, "workers": workers, "seed": seed},
            retries,
        )
