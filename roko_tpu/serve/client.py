"""Stdlib client for the polishing service (tests + ``tools/``).

Encodes window arrays in the wire format ``server.py`` expects
(base64 raw little-endian), maps the server's backpressure reply to
:class:`ServerBusy` with the parsed ``retry_after_s``, and optionally
retries through it.
"""

from __future__ import annotations

import base64
import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, Optional

import numpy as np


class ServerBusy(RuntimeError):
    """503 from the service: queue full, retry after ``retry_after_s``."""

    def __init__(self, retry_after_s: float):
        super().__init__(f"server busy; retry after {retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


def _b64(arr: np.ndarray, dtype) -> str:
    return base64.b64encode(
        np.ascontiguousarray(arr, dtype=np.dtype(dtype).newbyteorder("<"))
        .tobytes()
    ).decode("ascii")


class PolishClient:
    def __init__(self, base_url: str, timeout: float = 120.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # -- transport ----------------------------------------------------------

    def _request(
        self, path: str, payload: Optional[Dict[str, Any]] = None
    ) -> Any:
        url = self.base_url + path
        data = None if payload is None else json.dumps(payload).encode()
        req = urllib.request.Request(
            url,
            data=data,
            headers={"Content-Type": "application/json"} if data else {},
            method="POST" if data else "GET",
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
            if e.code == 503:
                try:
                    retry = float(json.loads(body).get("retry_after_s", 1.0))
                except (ValueError, AttributeError):
                    retry = 1.0
                raise ServerBusy(retry) from None
            try:
                detail = json.loads(body).get("error", "")
            except ValueError:
                detail = body[:200].decode(errors="replace")
            raise RuntimeError(f"HTTP {e.code} from {path}: {detail}") from None
        return body

    # -- routes -------------------------------------------------------------

    def healthz(self) -> Dict[str, Any]:
        return json.loads(self._request("/healthz"))

    def metrics(self) -> str:
        return self._request("/metrics").decode()

    def polish(
        self,
        draft: str,
        positions: np.ndarray,
        examples: np.ndarray,
        contig: str = "seq",
        retries: int = 0,
    ) -> Dict[str, Any]:
        """Polish one contig from pre-extracted windows. ``retries`` > 0
        sleeps through :class:`ServerBusy` replies (honouring the
        server's retry-after) before giving up."""
        examples = np.asarray(examples)
        payload = {
            "contig": contig,
            "draft": draft,
            "n": int(examples.shape[0]),
            "positions": _b64(positions, np.int64),
            "examples": _b64(examples, np.uint8),
        }
        for attempt in range(retries + 1):
            try:
                return json.loads(self._request("/polish", payload))
            except ServerBusy as busy:
                if attempt == retries:
                    raise
                time.sleep(busy.retry_after_s)
        raise AssertionError("unreachable")

    def polish_bam(
        self, ref: str, bam: str, workers: int = 1, seed: int = 0
    ) -> Dict[str, Any]:
        """Extractor convenience path: ``ref``/``bam`` are paths on the
        SERVER's filesystem; ``seed`` is the row-sampling seed (matches
        the ``features`` CLI's ``--seed``)."""
        return json.loads(
            self._request(
                "/polish",
                {"ref": ref, "bam": bam, "workers": workers, "seed": seed},
            )
        )
