"""Zero-downtime model rollout with a canary health gate and automatic
rollback (docs/SERVING.md "Model lifecycle").

``roko-tpu rollout NAME`` (or ``POST /rollout`` on the supervisor)
drives the fleet onto a registered model version ONE worker at a time,
riding PR 6's rolling-drain machinery: worker *i* leaves rotation and
drains its in-flight requests, restarts from the new version's launch
spec, must flip its own ``/healthz`` to 200 (AOT re-warm), and must
then hold a contiguous ``bake_s`` healthy stretch before worker *i+1*
is touched — the fleet always has N-1 ready workers and clients never
see the swap (failover routing covers the one in motion).

The **canary gate** compares the new version's error rate and p99
against the incumbent's pre-rollout baseline (scraped from the same
per-worker ``/metrics`` the supervisor already aggregates). Regression
past ``rollback_error_pct`` / ``rollback_p99_x`` — or a restart storm
on the new bundle (the PR 6 breaker shape, applied to versions: the
per-worker storm counter resets on a version change so only NEW-bundle
deaths count) — halts the rollout and rolls every completed worker back
to the incumbent, loudly (``ROKO_ROLLOUT event=rollback ...``).

Every state transition is journaled FIRST to an atomic, fsync'd
``rollout.json`` in the fleet runtime dir (the PR 3 journal idiom), so
a supervisor SIGKILLed mid-rollout can never leave a silently mixed
fleet: on restart, :func:`recover_rollout` reads the journal and either
**finalizes** (every worker had already rolled — only the journal
delete was lost) or **reverts** to the incumbent recorded in the
journal, with a loud ``ROKO_ROLLOUT event=recovered`` line either way.
Since a restarted supervisor spawns ALL workers from one chosen spec,
recovery is mixed-fleet-proof by construction — the journal's job is to
pick WHICH version, and to make the interruption loud.
"""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from roko_tpu.obs import events as obs_events
from roko_tpu.resilience.journal import _fsync_write
from roko_tpu.serve.fleet import (
    BOOT_VERSION,
    FAILED,
    READY,
    Fleet,
    WorkerHandle,
)

Log = Callable[[str], None]

_FORMAT = 1

#: terminal + live states rendered by the ``roko_rollout_state`` gauge
ROLLOUT_STATE_CODES = {
    "idle": 0,
    "done": 0,
    "rolled_back": 0,
    "rolling": 1,
    "rolling_back": 2,
    "failed": 3,  # rollback itself failed: fleet needs an operator
}


def _now_unix() -> int:
    return int(time.time())


# -- journal ------------------------------------------------------------------


class _StateFile:
    """One atomic JSON state record (tmp + fsync + rename — the PR 3
    idiom): rewritten whole, read back tolerant of absence, loud on
    corruption. Shared by the rollout journal and the landed-version
    pointer so their crash-consistency discipline cannot drift."""

    #: ROKO_ROLLOUT event name emitted when the file is unreadable
    UNREADABLE_EVENT = "state_unreadable"
    #: what the caller will do about an unreadable file (log detail)
    UNREADABLE_ACTION = "ignore"

    def __init__(self, path: str):
        self.path = path

    def write(self, record: Dict[str, Any]) -> None:
        _fsync_write(
            self.path,
            json.dumps(dict(record, format=_FORMAT), sort_keys=True).encode(),
        )

    def load(self, log: Optional[Log] = None) -> Optional[Dict[str, Any]]:
        """The record, or None when there is none. An unreadable file
        is reported loudly and treated as absent — the caller's safe
        default (boot everything on its own incumbent spec) yields a
        uniform fleet either way."""
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError) as e:
            if log is not None:
                obs_events.emit(
                    "rollout", self.UNREADABLE_EVENT, log=log,
                    path=self.path, error=repr(e),
                    action=self.UNREADABLE_ACTION,
                )
            return None

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class RolloutJournal(_StateFile):
    """Atomic rollout state file (``<runtime_dir>/rollout.json``):
    rewritten BEFORE each state transition takes effect, deleted only
    once the fleet is uniformly on one version again. Presence = a
    rollout did not finish; contents = enough identity (model path,
    bundle dir, digest per side) to revert without the registry."""

    FILENAME = "rollout.json"
    UNREADABLE_EVENT = "journal_unreadable"
    UNREADABLE_ACTION = "revert_to_boot"


class CurrentVersionFile(_StateFile):
    """Durable pointer to the version a fleet LANDED on (atomic JSON in
    the runtime dir, same write discipline as the journal). Without it
    a plain supervisor restart — OOM kill, host reboot, systemd with
    the original argv — would silently re-boot the CLI-named incumbent
    after a completed rollout; with it the restart re-pins the landed
    version, loudly. Written by the controller on completion (and on a
    rollback to a previously landed version), removed when the fleet
    is back on the CLI incumbent."""

    FILENAME = "current-version.json"
    UNREADABLE_EVENT = "version_pin_unreadable"
    UNREADABLE_ACTION = "boot_incumbent"


def recover_rollout(
    journal: RolloutJournal, log: Log = print
) -> Optional[Dict[str, Any]]:
    """Startup half of crash consistency: decide what a restarted
    supervisor should do about a journaled, unfinished rollout.

    Returns ``None`` (no journal — boot normally) or
    ``{"action": "finalize"|"revert", "record": rec}``:

    - **finalize** — the interrupted rollout had already moved every
      worker (state ``rolling`` with all workers journaled done; only
      the completion mark was lost): boot the fleet on the TO version.
    - **revert** — anything else (mid-roll, mid-rollback, unknown):
      boot the fleet on the FROM version, restoring the incumbent
      digest on every worker.

    Either way the caller spawns ALL workers from the one chosen spec,
    so the fleet can never come back mixed; the loud ``ROKO_ROLLOUT``
    line is emitted here."""
    rec = journal.load(log)
    if rec is None:
        return None
    n = int(rec.get("workers", 0))
    done = sorted(set(rec.get("done", [])))
    if rec.get("state") == "rolling" and n and len(done) >= n:
        action = "finalize"
    else:
        action = "revert"
    frm = rec.get("from", {}) or {}
    to = rec.get("to", {}) or {}
    obs_events.emit(
        "rollout", "recovered", log=log,
        suffix="— an interrupted rollout was found; the fleet will boot "
        f"uniformly on {(to if action == 'finalize' else frm).get('version')!r}",
        state=rec.get("state"),
        **{"from": frm.get("version"), "to": to.get("version")},
        done=f"{done}/{n}", action=action,
    )
    return {"action": action, "record": rec}


# -- worker metrics scrape ----------------------------------------------------


@dataclass
class WorkerStats:
    """One worker's health numbers at a point in time, scraped from its
    own ``/metrics`` (lifetime-of-incarnation counters: a freshly
    rolled worker's numbers cover only the new version's traffic)."""

    requests: int
    errors: int
    p99_s: Optional[float]


def parse_worker_stats(text: str) -> WorkerStats:
    requests = errors = 0
    p99: Optional[float] = None
    for line in text.splitlines():
        if line.startswith("roko_serve_requests_total "):
            requests = int(float(line.split()[1]))
        elif line.startswith("roko_serve_errors_total "):
            errors = int(float(line.split()[1]))
        elif line.startswith('roko_serve_request_latency_seconds{quantile="0.99"} '):
            # the UNlabeled-by-size aggregate row only (size_class rows
            # carry a second label and don't match this prefix exactly)
            p99 = float(line.split()[1])
    return WorkerStats(requests=requests, errors=errors, p99_s=p99)


def scrape_worker(
    port: Optional[int], timeout_s: float
) -> Optional[WorkerStats]:
    if port is None:
        return None
    try:
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=timeout_s
        ) as r:
            return parse_worker_stats(r.read().decode())
    except OSError:
        return None


@dataclass
class Baseline:
    """The incumbent's pre-rollout health: aggregate error rate and the
    worst per-worker p99 across ready workers. ``error_pct`` is over
    lifetime counters (a regression gate, not a billing meter); a
    traffic-free fleet baselines at 0%/None and the gate then judges
    the canary on absolute thresholds alone."""

    error_pct: float
    p99_s: Optional[float]
    requests: int


def capture_baseline(fleet: Fleet, timeout_s: float) -> Baseline:
    req = err = 0
    p99s: List[float] = []
    for w in fleet.workers:
        if w.state != READY:
            continue
        stats = scrape_worker(w.port, timeout_s)
        if stats is None:
            continue
        req += stats.requests
        err += stats.errors
        if stats.p99_s is not None:
            p99s.append(stats.p99_s)
    return Baseline(
        error_pct=(100.0 * err / req) if req else 0.0,
        p99_s=max(p99s) if p99s else None,
        requests=req,
    )


# -- controller ---------------------------------------------------------------


class RolloutController:
    """One rollout (or its rollback), driven on its own thread.

    The controller owns the journal and the state machine; the fleet
    supplies the mechanics (``roll_worker``, supervision-maintained
    worker states, launch specs installed by the supervisor). Exactly
    one controller may be live per fleet (``fleet.rollout``)."""

    def __init__(
        self,
        fleet: Fleet,
        to_version: str,
        *,
        journal: RolloutJournal,
        bake_s: Optional[float] = None,
        rollback_error_pct: Optional[float] = None,
        rollback_p99_x: Optional[float] = None,
        ready_timeout_s: Optional[float] = None,
        log: Log = print,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        fc = fleet.fleet_cfg
        if not fleet.has_spec(to_version):
            raise ValueError(
                f"no launch spec installed for version {to_version!r}"
            )
        self.fleet = fleet
        self.journal = journal
        self.from_version = fleet.active_version
        self.to_version = to_version
        self.bake_s = fc.bake_s if bake_s is None else float(bake_s)
        self.rollback_error_pct = (
            fc.rollback_error_pct
            if rollback_error_pct is None
            else float(rollback_error_pct)
        )
        self.rollback_p99_x = (
            fc.rollback_p99_x
            if rollback_p99_x is None
            else float(rollback_p99_x)
        )
        self.ready_timeout_s = (
            fc.rollout_ready_timeout_s
            if ready_timeout_s is None
            else float(ready_timeout_s)
        )
        self._log = log
        self._clock = clock
        self._sleep = sleep
        self._poll_s = max(0.02, min(0.25, self.bake_s / 10 or 0.02))
        self.state = "idle"
        self.reason = ""
        self.done: List[int] = []
        self.started_unix: Optional[int] = None
        self.finished_unix: Optional[int] = None
        self.baseline: Optional[Baseline] = None
        #: durable pointer to the version the fleet LANDED on, kept in
        #: the same directory as the journal so a plain supervisor
        #: restart re-pins a completed rollout instead of silently
        #: re-booting the CLI incumbent
        self.current = CurrentVersionFile(
            os.path.join(
                os.path.dirname(journal.path), CurrentVersionFile.FILENAME
            )
        )
        self._thread: Optional[threading.Thread] = None

    # -- observation --------------------------------------------------------

    def state_code(self) -> int:
        return ROLLOUT_STATE_CODES.get(self.state, 3)

    def active(self) -> bool:
        return self.state in ("rolling", "rolling_back")

    def status(self) -> Dict[str, Any]:
        """The ``GET /rollout`` body."""
        return {
            "state": self.state,
            "from_version": self.from_version,
            "to_version": self.to_version,
            "workers": len(self.fleet.workers),
            "workers_done": sorted(self.done),
            "worker_versions": {
                str(w.id): w.version for w in self.fleet.workers
            },
            "reason": self.reason,
            "bake_s": self.bake_s,
            "rollback_error_pct": self.rollback_error_pct,
            "rollback_p99_x": self.rollback_p99_x,
            "baseline": (
                {
                    "error_pct": self.baseline.error_pct,
                    "p99_s": self.baseline.p99_s,
                    "requests": self.baseline.requests,
                }
                if self.baseline
                else None
            ),
            "started_unix": self.started_unix,
            "finished_unix": self.finished_unix,
        }

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        # state flips BEFORE the thread exists: the single-rollout 409
        # guard (and a fast-polling client) must never observe "idle"
        # on a controller that has been started
        self.state = "rolling"
        self._thread = threading.Thread(
            target=self.run, name="roko-rollout", daemon=True
        )
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _side(self, version: str) -> Dict[str, Any]:
        meta = dict(self.fleet.launch_spec(version).meta)
        meta["version"] = version
        return meta

    def _record(self, state: str) -> Dict[str, Any]:
        return {
            "state": state,
            "from": self._side(self.from_version),
            "to": self._side(self.to_version),
            "done": sorted(self.done),
            "workers": len(self.fleet.workers),
            "reason": self.reason,
            "started_unix": self.started_unix,
        }

    def run(self) -> None:
        """The whole rollout, journal-first at every transition."""
        self.started_unix = _now_unix()
        self.state = "rolling"
        hb = self.fleet.fleet_cfg.heartbeat_timeout_s
        self.baseline = capture_baseline(self.fleet, hb)
        obs_events.emit(
            "rollout", "start", log=self._log,
            **{"from": self.from_version, "to": self.to_version},
            workers=len(self.fleet.workers),
            bake_s=f"{self.bake_s:g}",
            baseline_error_pct=f"{self.baseline.error_pct:.3f}",
            # pre-stringified: str(float) keeps the historical full
            # repr; emit's %.6g compaction would alter the bytes
            baseline_p99_s=(
                str(self.baseline.p99_s)
                if self.baseline.p99_s is not None else "n/a"
            ),
        )
        self.journal.write(self._record("rolling"))
        try:
            for w in self.fleet.workers:
                why = self._roll_one(w, self.to_version, gate=True)
                if why is not None:
                    self._rollback(why)
                    return
                self.done.append(w.id)
                self.journal.write(self._record("rolling"))
                obs_events.emit(
                    "rollout", "worker_done", log=self._log,
                    worker=w.id, version=self.to_version,
                    done=f"{len(self.done)}/{len(self.fleet.workers)}",
                )
            with self.fleet._lock:
                self.fleet.active_version = self.to_version
            self.state = "done"
            self.finished_unix = _now_unix()
            # pointer BEFORE the journal delete: every moment after the
            # rollout finished, a restarted supervisor finds either the
            # all-done journal (finalize) or the pointer — never a
            # silent revert to the CLI incumbent
            self.current.write(self._side(self.to_version))
            self.journal.delete()
            obs_events.emit(
                "rollout", "done", log=self._log,
                version=self.to_version, workers=len(self.done),
            )
        except Exception as e:  # defensive: never leave state unjournaled
            self._rollback(f"internal rollout error: {e!r}")

    # -- one worker ---------------------------------------------------------

    def _storm_reason(self, w: WorkerHandle, version: str) -> Optional[str]:
        threshold = max(1, self.fleet.fleet_cfg.storm_threshold)
        if w.version == version and (
            w.state == FAILED or w.attempt >= threshold
        ):
            return (
                f"restart storm on version {version!r} (worker {w.id}: "
                f"{max(w.attempt, threshold)} death(s) without a stable "
                "stretch)"
            )
        return None

    def _roll_one(
        self, w: WorkerHandle, version: str, *, gate: bool
    ) -> Optional[str]:
        """Drain-restart one worker onto ``version`` and wait it back
        to READY; with ``gate`` also hold the bake window and judge the
        canary. Returns None on success, else the rollback reason."""
        obs_events.emit(
            "rollout", "roll", log=self._log,
            worker=w.id, **{"from": w.version, "to": version},
        )
        try:
            self.fleet.roll_worker(w, version)
        except (RuntimeError, ValueError, OSError) as e:
            # OSError: Popen itself failed (fork EAGAIN, bad argv) —
            # must surface as a rollback reason, never kill the
            # controller thread mid-rollback
            return f"could not restart worker {w.id}: {e}"
        deadline = self._clock() + self.ready_timeout_s
        while True:
            if self.fleet._draining:
                return "fleet draining"
            storm = self._storm_reason(w, version)
            if storm is not None:
                return storm
            if w.state == READY and w.version == version:
                break
            if self._clock() > deadline:
                return (
                    f"worker {w.id} not ready on {version!r} within "
                    f"{self.ready_timeout_s:.0f}s (state {w.state})"
                )
            self._sleep(self._poll_s)
        if not gate:
            return None
        return self._bake(w, version)

    def _bake(self, w: WorkerHandle, version: str) -> Optional[str]:
        """Hold worker ``w`` under observation until it has served a
        CONTIGUOUS ``bake_s`` healthy stretch on ``version``; judge the
        canary gate over that stretch. Leaving rotation resets the
        stretch (the storm breaker bounds how often that may happen)."""
        hb = self.fleet.fleet_cfg.heartbeat_timeout_s
        budget = self._clock() + self.ready_timeout_s + self.bake_s
        stretch_start: Optional[float] = self._clock()
        start = scrape_worker(w.port, hb)
        while True:
            if self.fleet._draining:
                return "fleet draining"
            storm = self._storm_reason(w, version)
            if storm is not None:
                return storm
            if self._clock() > budget:
                return (
                    f"worker {w.id} never held a {self.bake_s:g}s healthy "
                    f"stretch on {version!r}"
                )
            if w.state != READY:
                stretch_start = None
            elif stretch_start is None:
                stretch_start = self._clock()
                start = scrape_worker(w.port, hb)
            elif self._clock() - stretch_start >= self.bake_s:
                break
            self._sleep(self._poll_s)
        end = scrape_worker(w.port, hb)
        return self._gate_verdict(w, start, end)

    def _gate_verdict(
        self,
        w: WorkerHandle,
        start: Optional[WorkerStats],
        end: Optional[WorkerStats],
    ) -> Optional[str]:
        """Canary judgement over the bake window. No traffic during the
        bake (or unscrapeable metrics on a worker the health probe says
        is READY) passes on health alone — the gate detects regressions
        it can observe, it does not manufacture them."""
        base = self.baseline or Baseline(0.0, None, 0)
        if start is None or end is None:
            obs_events.emit(
                "rollout", "gate", log=self._log,
                suffix="(health gate only)",
                worker=w.id, verdict="pass", detail="metrics_unscrapeable",
            )
            return None
        d_req = max(0, end.requests - start.requests)
        d_err = max(0, end.errors - start.errors)
        if d_req > 0:
            err_pct = 100.0 * d_err / d_req
            if (
                err_pct > self.rollback_error_pct
                and err_pct > base.error_pct
            ):
                return (
                    f"canary error rate {err_pct:.2f}% over {d_req} "
                    f"request(s) exceeds rollback_error_pct="
                    f"{self.rollback_error_pct:g}% (baseline "
                    f"{base.error_pct:.2f}%)"
                )
        if (
            end.p99_s is not None
            and base.p99_s
            and end.p99_s > self.rollback_p99_x * base.p99_s
        ):
            return (
                f"canary p99 {end.p99_s * 1e3:.1f}ms exceeds "
                f"rollback_p99_x={self.rollback_p99_x:g} x baseline "
                f"{base.p99_s * 1e3:.1f}ms"
            )
        obs_events.emit(
            "rollout", "gate", log=self._log,
            worker=w.id, verdict="pass", requests=d_req, errors=d_err,
            p99_s=str(end.p99_s) if end.p99_s is not None else "n/a",
        )
        return None

    # -- rollback -----------------------------------------------------------

    def _rollback(self, reason: str) -> None:
        self.state = "rolling_back"
        self.reason = reason
        obs_events.emit(
            "rollout", "rollback", log=self._log,
            **{"from": self.to_version, "to": self.from_version},
            reason=repr(reason),
        )
        self.journal.write(self._record("rolling_back"))
        for w in self.fleet.workers:
            if w.version != self.to_version and (
                w.target_version != self.to_version
            ):
                continue
            if self.fleet._draining:
                # the fleet is going down anyway; the journal survives
                # and the next start reverts the rest
                self.state = "failed"
                obs_events.emit(
                    "rollout", "rollback_interrupted", log=self._log,
                    suffix="(journal kept)", reason="fleet_draining",
                )
                return
            why = self._roll_one(w, self.from_version, gate=False)
            if why is not None:
                # the INCUMBENT will not come back either: degraded
                # fleet, operator problem — keep the journal as the
                # record of the mixed state and scream
                self.state = "failed"
                self.finished_unix = _now_unix()
                self.journal.write(self._record("rolling_back"))
                obs_events.emit(
                    "rollout", "rollback_failed", log=self._log,
                    suffix="— fleet left degraded, journal kept at "
                    f"{self.journal.path}",
                    worker=w.id, reason=repr(why),
                )
                return
        self.state = "rolled_back"
        self.finished_unix = _now_unix()
        # the fleet is back on from_version: re-pin it (or drop the
        # pointer when that IS the CLI incumbent)
        if self.from_version == BOOT_VERSION:
            self.current.delete()
        else:
            self.current.write(self._side(self.from_version))
        self.journal.delete()
        obs_events.emit(
            "rollout", "rolled_back", log=self._log,
            suffix="— incumbent restored on every worker",
            version=self.from_version,
        )
