"""Worker fleet: process supervision + failover routing for the
multi-worker serving tier (docs/SERVING.md "Multi-worker topology &
failure handling").

One :class:`Fleet` owns N worker processes, each a full single-process
``roko-tpu serve`` stack (warm PolishSession + MicroBatcher + HTTP)
pinned to a device slice (``parallel.mesh.fleet_worker_env``) and
sharing one AOT bundle. The fleet's job is to make worker failure a
latency event, never a correctness or availability event:

- **liveness** — every worker is heartbeat-probed on ``/healthz``
  (``FleetConfig.heartbeat_interval_s``); any answer — 200 ready, 503
  warming/draining/breaker-open — proves the process alive, but only a
  200 keeps it in rotation. ``heartbeat_misses`` consecutive
  *unanswered* probes declare it hung.
- **supervision** — a crashed worker (``waitpid`` via ``Popen.poll``)
  or a hung one (SIGTERM, then SIGKILL after ``term_grace_s``) is
  restarted under the shared :class:`~roko_tpu.resilience.RetryPolicy`
  exponential-backoff shape, guarded by a per-worker restart-storm
  :class:`~roko_tpu.resilience.CircuitBreaker`: ``storm_threshold``
  restarts without a ``stable_after_s`` healthy stretch mark the worker
  FAILED and the fleet degrades (serves on the survivors) instead of
  flapping; after ``storm_reset_s`` one half-open probe restart is
  admitted.
- **failover** — :meth:`Fleet.post_polish` routes a request to a ready
  worker; a connection-level failure (the worker died or was killed
  mid-request) transparently re-dispatches to another ready worker —
  polish is deterministic and idempotent, so clients observe latency,
  not errors. Worker 503s (busy/warming) try the next worker once each
  before surfacing as a fleet 503 with the largest ``Retry-After``
  seen.
- **restart re-warm** — a restarted worker re-enters rotation only
  after its own warmup flips ``/healthz`` to 200 (AOT bundle
  deserialization when one is configured; binds-first/warming-503
  semantics from ``serve/server.py``).

The supervisor front end (``serve/supervisor.py``) puts the HTTP
surface over this class; tests drive it directly with stub worker
processes, so the supervision machinery is covered by real
kill/restart/waitpid paths without paying a jax import per worker.
"""

from __future__ import annotations

import http.client
import json
import os
import shutil
import subprocess
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from roko_tpu.config import FleetConfig, RokoConfig
from roko_tpu.obs import events as obs_events
from roko_tpu.obs.hist import merge_histogram_rows, parse_histogram_rows, render_histogram_rows
from roko_tpu.resilience import CircuitBreaker, RetryPolicy
from roko_tpu.serve.metrics import (
    HISTOGRAM_SERIES,
    LABELED_SERIES,
    parse_labeled_rows,
    parse_metric_values,
)

# worker lifecycle states (rendered in /healthz and the
# roko_fleet_worker_state gauge)
STARTING = "starting"    # spawned, port not yet announced
WARMING = "warming"      # bound, ladder still compiling (healthz 503)
READY = "ready"          # in rotation
UNHEALTHY = "unhealthy"  # alive but out of rotation (breaker tripped)
DRAINING = "draining"    # worker reports draining (rolling restart)
DEAD = "dead"            # process gone; restart scheduled
FAILED = "failed"        # restart-storm breaker open; not restarting
STOPPED = "stopped"      # deliberately terminated (fleet drain)

#: gauge encoding for roko_fleet_worker_state
STATE_CODES = {
    READY: 0, WARMING: 1, STARTING: 1, UNHEALTHY: 2, DRAINING: 3,
    DEAD: 4, FAILED: 5, STOPPED: 6,
}

#: worker series re-exported at the front end labeled by worker id
#: (breaker + compile-cache gauges, plus the continuous scheduler's
#: occupancy/padding series so fleet dashboards see per-worker packing
#: density — docs/SERVING.md "Continuous batching")
PASSTHROUGH_SERIES = (
    ("roko_serve_breaker_state", "gauge"),
    ("roko_serve_breaker_trips_total", "counter"),
    ("roko_serve_padding_efficiency", "gauge"),
    ("roko_serve_fill_windows_total", "counter"),
    ("roko_serve_fill_padded_total", "counter"),
    ("roko_serve_queue_windows", "gauge"),
    ("roko_serve_scheduler_occupancy", "gauge"),
    ("roko_compile_cache_hits", "counter"),
    ("roko_compile_cache_misses", "counter"),
    ("roko_serve_cascade_windows_total", "counter"),
    ("roko_serve_cascade_escalated_total", "counter"),
    ("roko_serve_cascade_cache_hits_total", "counter"),
)

#: connection-level failures that mean "this worker did not answer" —
#: the failover trigger (a dead/killed worker mid-request lands here)
_CONN_ERRORS = (OSError, http.client.HTTPException)

#: version label of the launch spec a fleet boots with, before any
#: rollout has installed a registry-named one (docs/SERVING.md
#: "Model lifecycle")
BOOT_VERSION = "boot"


class WorkerLaunchSpec:
    """Everything that determines WHICH program a spawned worker runs:
    the argv builder (model path + per-version worker config, so the
    AOT bundle rides inside), the env overlay (device pinning), and the
    version label it serves under. Initial spawn, crash restart, and
    rollout all resolve a worker's launch through ONE spec
    (``Fleet._spawn``), so the three paths cannot drift on which
    bundle/params a worker gets.

    ``meta`` carries operator-facing identity (bundle digest, model
    path) for the rollout journal and logs — the spec itself is the
    source of truth for what actually launches."""

    def __init__(
        self,
        command: Callable[[int, str], List[str]],
        *,
        env: Optional[Callable[[int], Dict[str, str]]] = None,
        version: str = BOOT_VERSION,
        meta: Optional[Dict[str, object]] = None,
    ):
        self.command = command
        self.env = env or (lambda wid: {})
        self.version = version
        self.meta: Dict[str, object] = dict(meta or {})


def write_announce(path: str, port: int) -> None:
    """Atomically publish a bound address as ``{"pid", "port"}`` — the
    contract between a port-0 bind and whoever needs the port (the
    supervisor's ``_read_announce``, test automation). One writer for
    the worker CLI and the supervisor front end."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"pid": os.getpid(), "port": int(port)}, f)
    os.replace(tmp, path)


def _tail(path: str, n: int = 2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - n))
            return f.read().decode(errors="replace")
    except OSError:
        return "(no worker log)"


class WorkerHandle:
    """One supervised worker process: its Popen, announced port,
    lifecycle state, heartbeat bookkeeping, and restart-storm breaker."""

    def __init__(self, wid: int, runtime_dir: str, cfg: FleetConfig):
        self.id = wid
        self._cfg = cfg
        self.announce_path = os.path.join(
            runtime_dir, f"worker-{wid}.announce.json"
        )
        self.log_path = os.path.join(runtime_dir, f"worker-{wid}.log")
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.state = DEAD
        self.spawned_at = 0.0
        self.restart_at = 0.0   # monotonic time the next restart is due
        self.restarts = 0       # lifetime respawn count (metrics)
        self.attempt = 0        # consecutive deaths w/o a stable stretch
        self.misses = 0         # consecutive unanswered heartbeats
        self.ready_since = 0.0
        self.stable = False     # this incarnation survived stable_after_s
        #: model version this incarnation runs / the next spawn targets
        #: (rollout moves target_version, _spawn follows it)
        self.version = BOOT_VERSION
        self.target_version = BOOT_VERSION
        #: True while a rollout is deliberately restarting this worker —
        #: the supervision loop leaves held workers alone so the planned
        #: restart is not double-handled as a crash
        self.hold = False
        #: last Retry-After hint this worker reported in /healthz (the
        #: PR 10 live backlog/throughput estimate); None until it
        #: answers a probe
        self.retry_hint: Optional[float] = None
        #: live queue depth (windows) from the last answered /healthz —
        #: the autoscaler's backlog signal
        self.queue_windows: Optional[int] = None
        #: per-tenant {"backlog_windows", "retry_after_s"} hints from
        #: the last answered /healthz (multi-tenant 429/503 sizing)
        self.tenant_hints: Dict[str, Dict[str, float]] = {}
        #: restart-storm breaker: record_failure per death, record_success
        #: once stable; OPEN = stop restarting (fleet degrades), half-open
        #: after storm_reset_s admits exactly one probe restart
        self.storm = CircuitBreaker(
            failure_threshold=max(1, cfg.storm_threshold),
            reset_s=cfg.storm_reset_s,
        )

    def reset_regime(self) -> None:
        """Fresh restart-storm history: a version change is a new
        regime — deaths under the old bundle must not pre-charge the
        new bundle's breaker (nor vice versa: the rollback trigger
        counts NEW-bundle deaths only)."""
        self.attempt = 0
        self.stable = False
        self.storm = CircuitBreaker(
            failure_threshold=max(1, self._cfg.storm_threshold),
            reset_s=self._cfg.storm_reset_s,
        )

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Fleet:
    """Spawn, supervise, and route across N worker processes.

    ``worker_command(worker_id, announce_path) -> argv`` builds each
    worker's command line (the supervisor builds a ``roko-tpu serve``
    invocation; tests substitute a stdlib stub worker), and
    ``worker_env(worker_id) -> dict`` the per-worker environment overlay
    (device-slice pinning by default)."""

    def __init__(
        self,
        cfg: RokoConfig,
        worker_command: Callable[[int, str], List[str]],
        *,
        worker_env: Optional[Callable[[int], Dict[str, str]]] = None,
        runtime_dir: Optional[str] = None,
        log: Callable[[str], None] = print,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        fc = cfg.fleet
        if fc.workers < 1:
            raise ValueError("FleetConfig.workers must be >= 1 for a fleet")
        self.fleet_cfg = fc
        #: launch specs by version label; every spawn resolves through
        #: one of these (docs/SERVING.md "Model lifecycle"). The
        #: constructor's command/env pair becomes the BOOT spec;
        #: rollouts install more via add_launch_spec.
        self._specs: Dict[str, WorkerLaunchSpec] = {
            BOOT_VERSION: WorkerLaunchSpec(
                worker_command, env=worker_env, version=BOOT_VERSION
            )
        }
        self.active_version = BOOT_VERSION
        #: the live RolloutController when a rollout is running or has
        #: run (supervisor wires it; metrics render its state)
        self.rollout = None
        #: the live (or last) DistPolishJob when a distributed polish
        #: runs over this fleet (pipeline/distpolish.py; GET /jobz
        #: renders its snapshot)
        self.job = None
        self._log = log
        self._clock = clock
        self.runtime_dir = (
            runtime_dir
            or fc.runtime_dir
            or os.path.join(
                tempfile.gettempdir(), f"roko-fleet-{os.getpid()}"
            )
        )
        #: removed on a CLEAN stop only — a wedged run leaves the worker
        #: logs behind for the CI failure dump to collect
        self._own_runtime_dir = runtime_dir is None and fc.runtime_dir is None
        self.workers = [
            WorkerHandle(i, self.runtime_dir, fc) for i in range(fc.workers)
        ]
        self.restart_policy = RetryPolicy(
            base_delay_s=fc.restart_base_delay_s,
            max_delay_s=fc.restart_max_delay_s,
            jitter=0.1,
        )
        self.max_inflight = fc.max_inflight or (
            fc.workers * cfg.serve.max_queue
        )
        self._lock = threading.RLock()
        self._rr = 0
        #: ids of workers still drain-terminating off the routing path —
        #: scale-up must not re-mint such an id while its announce file
        #: and device slice may still be live
        self._retiring: set = set()
        #: autoscaler parks background distpolish jobs while interactive
        #: backlog spikes; DistPolishJob reads it via _inflight_limit
        #: (journal checkpoints make park/resume ≤ 1 contig re-run)
        self.jobs_parked = False
        self._counters = {"restarts": 0, "failovers": 0,
                          "requests": 0, "rejected": 0,
                          "scale_ups": 0, "scale_downs": 0}
        self._stop = threading.Event()
        self._draining = False
        self._drain_done = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- launch specs -------------------------------------------------------

    def _spec_live(self, version: str) -> bool:
        # caller holds self._lock
        return version in self._specs and any(
            w.version == version or w.target_version == version
            for w in self.workers
        )

    def spec_installable(self, version: str) -> bool:
        """True when :meth:`add_launch_spec` would accept ``version`` —
        the rollout starter checks this BEFORE building a spec, because
        building one writes the per-version worker config to disk and a
        refused swap must not have already changed what a live worker's
        next crash-restart would run."""
        with self._lock:
            return not self._spec_live(version)

    def add_launch_spec(self, spec: WorkerLaunchSpec) -> None:
        """Register a version's launch spec so rollout / restart can
        spawn workers onto it. Replacing the spec of a version workers
        currently run is refused — that is exactly the silent-drift this
        indirection exists to prevent (register a new version instead)."""
        with self._lock:
            if self._spec_live(spec.version):
                raise ValueError(
                    f"launch spec {spec.version!r} is live on the fleet; "
                    "refusing to swap it underneath running workers"
                )
            self._specs[spec.version] = spec

    def install_boot_spec(self, spec: WorkerLaunchSpec) -> None:
        """Replace the constructor's placeholder boot spec BEFORE
        ``start()`` — the supervisor can only build the real one (whose
        per-version worker config lives in the runtime dir) after the
        runtime dir exists, and rollout recovery may boot a different
        version than the CLI named."""
        with self._lock:
            if any(w.alive() for w in self.workers):
                raise RuntimeError(
                    "install_boot_spec must run before the fleet starts"
                )
            self._specs = {spec.version: spec}
            self.active_version = spec.version
            for w in self.workers:
                w.version = w.target_version = spec.version

    def launch_spec(self, version: Optional[str] = None) -> WorkerLaunchSpec:
        with self._lock:
            return self._specs[version or self.active_version]

    def has_spec(self, version: str) -> bool:
        with self._lock:
            return version in self._specs

    # -- counters -----------------------------------------------------------

    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name] += by

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters[name]

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        """Spawn every worker and start the supervision thread."""
        os.makedirs(self.runtime_dir, exist_ok=True)
        now = self._clock()
        for w in self.workers:
            self._spawn(w, now)
        self._thread = threading.Thread(
            target=self._supervise, name="roko-fleet-supervise", daemon=True
        )
        self._thread.start()

    def _spawn(self, w: WorkerHandle, now: float) -> None:
        try:
            os.unlink(w.announce_path)
        except OSError:
            pass
        # THE one resolution point for what a worker runs: initial
        # spawn, crash restart, and rollout all land here, and all read
        # the worker's target version's launch spec — argv (model path +
        # per-version config carrying the bundle) and env overlay both
        spec = self.launch_spec(w.target_version)
        if spec.version != w.version:
            w.reset_regime()  # storm history belongs to the old bundle
        w.version = spec.version
        env = dict(os.environ)
        env.update(spec.env(w.id))
        env["ROKO_WORKER_ID"] = str(w.id)
        # the worker's model-lane identity: labels its latency
        # histograms and arms the X-Roko-Model pin guard server-side
        env["ROKO_MODEL_VERSION"] = spec.version
        # append: across restarts one log per worker slot keeps the
        # whole crash history in a single CI-dumpable file
        logf = open(w.log_path, "ab", buffering=0)
        try:
            logf.write(
                f"\n--- spawn worker {w.id} (restart {w.restarts}, "
                f"version {spec.version}) ---\n".encode()
            )
            w.proc = subprocess.Popen(
                spec.command(w.id, w.announce_path),
                stdout=logf,
                stderr=subprocess.STDOUT,
                env=env,
            )
        finally:
            logf.close()  # the child keeps its own copy of the fd
        w.state = STARTING
        w.spawned_at = now
        w.port = None
        w.misses = 0
        w.stable = False
        # a dead incarnation's backlog estimate must not inflate
        # front-end 503s (live_retry_after_s takes the fleet MAX)
        w.retry_hint = None
        w.queue_windows = None
        w.tenant_hints = {}

    def roll_worker(self, w: WorkerHandle, version: str) -> None:
        """Deliberate restart of ONE worker onto ``version`` (the
        rollout path, docs/SERVING.md "Model lifecycle"): the worker
        leaves rotation (DRAINING), gets SIGTERM — it finishes its own
        in-flight requests under the drain deadline — then respawns
        immediately from the new version's launch spec. ``hold`` keeps
        the supervision loop from double-handling the planned death as
        a crash; it resumes tracking the fresh incarnation (warming →
        ready) the moment the spawn lands."""
        if not self.has_spec(version):
            raise ValueError(f"no launch spec for version {version!r}")
        with self._lock:
            if self._draining:
                # a stopping fleet must not grow fresh workers that
                # would outlive the drain as orphans
                raise RuntimeError("fleet is draining; not rolling workers")
            w.hold = True
            if w.state == READY:
                w.state = DRAINING  # routing excludes it from here on
        try:
            grace = (
                self.cfg.resilience.drain_deadline_s
                + self.fleet_cfg.term_grace_s
            )
            self._terminate(w, grace)
            with self._lock:
                w.target_version = version
            w.restarts += 1
            self.inc("restarts")
            self._spawn(w, self._clock())
        finally:
            with self._lock:
                w.hold = False

    # -- elastic sizing -----------------------------------------------------

    def scale_to(self, n: int, *, reason: str = "") -> int:
        """Resize the fleet to ``n`` workers (the autoscaler's actuator;
        docs/SERVING.md "Multi-tenant & elastic fleet").

        Scale-UP appends fresh :class:`WorkerHandle`\\ s on the LOWEST
        free ids (ids double as device-slice indices, so they stay
        dense; a retiring worker's id is not free until its drain
        completes) targeting the active version, spawned through the
        same launch-spec resolution as boot/restart/rollout. Scale-DOWN
        retires the highest-id non-held workers: each leaves
        ``self.workers`` immediately (routing and supervision stop
        seeing it) and drains in a background thread — SIGTERM lets it
        finish in-flight requests under the drain deadline, so clients
        never observe the shrink. Refused (no-op) while the fleet is
        draining. Returns the new worker count."""
        added: List[WorkerHandle] = []
        victims: List[WorkerHandle] = []
        with self._lock:
            if self._draining:
                return len(self.workers)
            n = max(1, int(n))
            cur = len(self.workers)
            if n == cur:
                return cur
            if n > cur:
                # lowest free id: ids double as device-slice indices
                # (fleet_worker_env), so they must stay dense within
                # [0, max_workers) — a retiring worker's id is NOT free
                # until its drain completes (announce file + slice)
                used = {w.id for w in self.workers} | self._retiring
                for _ in range(n - cur):
                    wid = 0
                    while wid in used:
                        wid += 1
                    used.add(wid)
                    w = WorkerHandle(wid, self.runtime_dir, self.fleet_cfg)
                    w.version = w.target_version = self.active_version
                    self.workers.append(w)
                    added.append(w)
                self.inc("scale_ups")
            else:
                pool = sorted(
                    (w for w in self.workers if not w.hold),
                    key=lambda w: w.id,
                )
                while len(self.workers) - len(victims) > n and pool:
                    v = pool.pop()  # highest id first: LIFO shrink
                    victims.append(v)
                for v in victims:
                    self.workers.remove(v)
                    self._retiring.add(v.id)
                    if v.state == READY:
                        v.state = DRAINING
                self.inc("scale_downs")
            if self.fleet_cfg.max_inflight == 0:
                # derived admission cap tracks the live worker count
                self.max_inflight = (
                    len(self.workers) * self.cfg.serve.max_queue
                )
        now = self._clock()
        for w in added:
            try:
                self._spawn(w, now)
            except OSError as e:
                self._note_death(w, now, f"spawn failed: {e}")
        for v in victims:
            threading.Thread(
                target=self._retire, args=(v,),
                name=f"roko-fleet-retire-{v.id}", daemon=True,
            ).start()
        self._log(
            f"roko fleet: scaled {cur} -> {len(self.workers)} workers"
            + (f" ({reason})" if reason else "")
        )
        return len(self.workers)

    def _retire(self, w: WorkerHandle) -> None:
        """Drain-terminate one retired worker off the routing path."""
        grace = (
            self.cfg.resilience.drain_deadline_s
            + self.fleet_cfg.term_grace_s
        )
        self._terminate(w, grace)
        w.state = STOPPED
        w.port = None
        try:
            os.unlink(w.announce_path)
        except OSError:
            pass
        with self._lock:
            self._retiring.discard(w.id)

    def stop(
        self, *, rolling: bool = True, cleanup: bool = True
    ) -> None:
        """Stop supervision, then terminate workers — sequentially
        (rolling: each worker gets its own SIGTERM drain + exit before
        the next is touched) or in one sweep (``rolling=False``, the
        Ctrl-C path). Idempotent; a second caller BLOCKS until the
        first stop finishes (the supervisor's exit path must not
        return while the SIGTERM drain thread is still terminating
        workers — orphans would outlive the supervisor)."""
        with self._lock:
            first = not self._draining
            self._draining = True
        grace = (
            self.cfg.resilience.drain_deadline_s
            + self.fleet_cfg.term_grace_s
        )
        if not first:
            self._drain_done.wait((grace + 5.0) * (len(self.workers) + 1))
            return
        try:
            self._stop.set()
            if self._thread is not None:
                self._thread.join(
                    self.fleet_cfg.heartbeat_timeout_s
                    + self.fleet_cfg.heartbeat_interval_s + 5.0
                )
            if not rolling:
                for w in self.workers:
                    if w.alive():
                        w.proc.terminate()
            for w in self.workers:
                self._terminate(w, grace)
                w.state = STOPPED
            if cleanup and self._own_runtime_dir:
                shutil.rmtree(self.runtime_dir, ignore_errors=True)
        finally:
            self._drain_done.set()

    def _terminate(self, w: WorkerHandle, grace_s: float) -> None:
        """SIGTERM (the worker drains its in-flight requests), escalate
        to SIGKILL after ``grace_s``."""
        if not w.alive():
            return
        w.proc.terminate()
        try:
            w.proc.wait(grace_s)
        except subprocess.TimeoutExpired:
            self._log(
                f"roko fleet: worker {w.id} ignored SIGTERM for "
                f"{grace_s:.0f}s; escalating to SIGKILL"
            )
            w.proc.kill()
            try:
                w.proc.wait(self.fleet_cfg.term_grace_s)
            except subprocess.TimeoutExpired:  # pragma: no cover
                pass

    # -- supervision --------------------------------------------------------

    def _supervise(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception as e:  # pragma: no cover - defensive
                self._log(f"roko fleet: supervision tick failed: {e!r}")
            self._stop.wait(self.fleet_cfg.heartbeat_interval_s)

    def tick(self) -> None:
        """One supervision pass over every worker (public so tests can
        drive supervision synchronously with a fake clock)."""
        # snapshot: scale_to() mutates self.workers concurrently
        for w in list(self.workers):
            if self._draining:
                return
            self._check(w, self._clock())

    def _check(self, w: WorkerHandle, now: float) -> None:
        cfg = self.fleet_cfg
        if w.hold:
            # a rollout is deliberately restarting this worker; its
            # death is planned, not a crash to supervise
            return
        if w.state in (FAILED, DEAD):
            if w.state == DEAD and now < w.restart_at:
                return
            # storm breaker gates the respawn: CLOSED passes, OPEN
            # refuses (FAILED = degraded fleet), half-open admits one
            # probe restart after storm_reset_s
            if w.storm.allow():
                if w.state == FAILED:
                    self._log(
                        f"roko fleet: worker {w.id} storm breaker half-open;"
                        " admitting one probe restart"
                    )
                self._restart(w, now)
            elif w.state != FAILED:
                w.state = FAILED
                self._log(
                    f"roko fleet: worker {w.id} restart storm "
                    f"({cfg.storm_threshold} restarts without a stable "
                    f"stretch) — marking FAILED, fleet degraded; next "
                    f"probe in {cfg.storm_reset_s:.0f}s"
                )
            return
        rc = w.proc.poll() if w.proc is not None else None
        if rc is not None:
            self._note_death(w, now, f"exited rc={rc}")
            return
        if w.state == STARTING:
            port = self._read_announce(w)
            if port is not None:
                w.port = port
                w.state = WARMING
                self._log(
                    f"roko fleet: worker {w.id} bound 127.0.0.1:{port} "
                    "(warming)"
                )
            elif now - w.spawned_at > cfg.spawn_deadline_s:
                self._kill_hung(
                    w, now,
                    f"never announced within {cfg.spawn_deadline_s:.0f}s",
                )
            return
        # bound: heartbeat via /healthz
        try:
            code, body = self._probe(w, "/healthz")
        except _CONN_ERRORS:
            w.misses += 1
            if w.misses >= cfg.heartbeat_misses:
                self._kill_hung(
                    w, now, f"{w.misses} consecutive missed heartbeats"
                )
            return
        w.misses = 0
        hint = body.get("retry_after_s")
        if isinstance(hint, (int, float)) and hint > 0:
            # the worker's live backlog/throughput Retry-After estimate
            # (PR 10) rides in healthz; cache it so front-end 503s can
            # promise a real wait instead of the static config guess
            w.retry_hint = float(hint)
        qw = body.get("queue_windows")
        if isinstance(qw, (int, float)):
            w.queue_windows = int(qw)
        th = body.get("tenants")
        if isinstance(th, dict):
            # per-tenant backlog/Retry-After hints for 429/503 sizing
            w.tenant_hints = {
                str(t): h for t, h in th.items() if isinstance(h, dict)
            }
        status = body.get("status", "")
        if code == 200:
            if w.state != READY:
                self._log(f"roko fleet: worker {w.id} in rotation")
                w.state = READY
                w.ready_since = now
            elif not w.stable and now - w.ready_since >= cfg.stable_after_s:
                # survived the probation window: the storm breaker
                # records recovery and the backoff schedule resets
                w.stable = True
                w.attempt = 0
                w.storm.record_success()
        elif status == "warming":
            w.state = WARMING
        elif status == "draining":
            w.state = DRAINING
        else:
            # breaker-tripped (or otherwise unhealthy) but answering:
            # out of rotation, left alive — the worker's own half-open
            # probing may recover it without losing the warm session
            if w.state != UNHEALTHY:
                self._log(
                    f"roko fleet: worker {w.id} out of rotation "
                    f"(healthz {code} status={status or '?'})"
                )
            w.state = UNHEALTHY

    def _read_announce(self, w: WorkerHandle) -> Optional[int]:
        try:
            with open(w.announce_path) as f:
                return int(json.load(f)["port"])
        except (OSError, ValueError, KeyError):
            return None

    def _note_death(self, w: WorkerHandle, now: float, why: str) -> None:
        w.storm.record_failure()
        delay = self.restart_policy.delay_for(w.attempt + 1)
        w.attempt += 1
        w.restart_at = now + delay
        w.state = DEAD
        w.port = None
        self._log(
            f"roko fleet: worker {w.id} {why}; restart "
            f"{w.attempt} in {delay:.1f}s; log tail:\n{_tail(w.log_path)}"
        )

    def _kill_hung(self, w: WorkerHandle, now: float, why: str) -> None:
        self._log(
            f"roko fleet: worker {w.id} presumed hung ({why}); "
            "SIGTERM -> SIGKILL"
        )
        self._terminate(w, self.fleet_cfg.term_grace_s)
        self._note_death(w, now, f"killed ({why})")

    def _restart(self, w: WorkerHandle, now: float) -> None:
        w.restarts += 1
        self.inc("restarts")
        try:
            self._spawn(w, now)
        except OSError as e:  # spawn itself failed: another death
            self._note_death(w, now, f"respawn failed: {e}")

    # -- routing ------------------------------------------------------------

    def ready_count(self) -> int:
        return sum(1 for w in list(self.workers) if w.state == READY)

    def live_retry_after_s(self, tenant: Optional[str] = None) -> float:
        """Retry-After for front-end 503/429s (draining, at capacity, no
        worker available): the LARGEST hint any live worker reported in
        its last answered /healthz — each worker computes its own from
        live backlog over observed throughput (docs/SERVING.md
        "Continuous batching") — falling back to the static
        ``serve.retry_after_s`` only when no worker has answered (none
        bound yet, or all dead).

        With ``tenant`` given, the hint is sized from THAT tenant's
        backlog and observed drain rate (the workers' per-tenant
        healthz hints), not the global queue — a quota-limited bulk
        tenant must not inflate the wait promised to an interactive
        one."""
        with self._lock:
            workers = list(self.workers)
        if tenant is not None:
            t_hints = []
            for w in workers:
                if not w.alive():
                    continue
                h = w.tenant_hints.get(tenant)
                ra = h.get("retry_after_s") if h else None
                if isinstance(ra, (int, float)) and ra > 0:
                    t_hints.append(float(ra))
            if t_hints:
                return max(t_hints)
        hints = [
            w.retry_hint
            for w in workers
            if w.retry_hint is not None and w.alive()
        ]
        return max(hints) if hints else self.cfg.serve.retry_after_s

    def backlog_windows(self) -> int:
        """Total queued windows across live workers (last answered
        /healthz) — the autoscaler's raw backlog signal."""
        with self._lock:
            workers = list(self.workers)
        return sum(
            w.queue_windows
            for w in workers
            if w.queue_windows is not None and w.alive()
        )

    def suspect(self, w: WorkerHandle) -> None:
        """A worker that dropped a connection leaves rotation NOW; the
        supervision loop confirms via waitpid/heartbeat and either
        restarts it or probes it straight back to READY. Shared by the
        front end's failover path and the distributed-polish
        coordinator."""
        with self._lock:
            if w.state == READY:
                w.state = UNHEALTHY

    def pick(
        self, exclude: Sequence[int] = (), version: Optional[str] = None
    ) -> Optional[Tuple[WorkerHandle, int]]:
        """Round-robin over in-rotation workers, skipping ``exclude``
        (ids already tried for this request); ``version`` restricts to
        workers running that model version (per-request model lanes).
        Returns the handle AND a port snapshot taken under the lock:
        the supervision thread nulls ``w.port`` when a worker dies, and
        reading it later would race — ``HTTPConnection(host, None)``
        silently falls back to port 80."""
        with self._lock:
            ready = [
                w for w in self.workers
                if w.state == READY and w.id not in exclude
                and w.port is not None
                and (version is None or w.version == version)
            ]
            if not ready:
                return None
            self._rr += 1
            w = ready[self._rr % len(ready)]
            return w, w.port

    def post_polish(
        self,
        body: bytes,
        timeout: Optional[float] = None,
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
        model_version: Optional[str] = None,
        pinned: bool = False,
    ) -> Tuple[int, bytes, Dict[str, str]]:
        """Route one ``POST /polish`` body to a ready worker with
        transparent failover: a connection-level failure (worker died
        mid-request) retries on another ready worker — polish is
        idempotent, so the client sees added latency, never the crash.
        Worker 503s try the next worker, then surface as a fleet 503
        with the largest ``Retry-After`` observed. Returns
        ``(status, reply_body, extra_headers)``.

        ``tenant`` rides every dispatch as ``X-Roko-Tenant`` so worker
        fair-share/quota accounting sees the tenant without the front
        end re-serializing the body. ``model_version`` restricts
        routing to workers running that version: ``pinned=True`` (the
        client named it) also forwards ``X-Roko-Model`` for the
        worker-side identity guard and surfaces a loud 503 when no
        ready worker runs it; ``pinned=False`` (A/B lane assignment)
        falls back softly to any ready worker when the candidate lane
        is empty — lane routing is an optimization, never an outage.

        ``request_id`` (assigned by the front end) rides every dispatch
        as ``X-Roko-Request-Id`` — including the failover RE-dispatch,
        so the worker trace and event log see ONE request however many
        workers it visited; each dispatch appends a quiet ``fleet
        dispatch`` event to the configured event log."""
        cfg = self.fleet_cfg
        tried: List[int] = []
        # resolved lazily: the live hint sweeps every worker's waitpid
        # under the lock, which the hot 200 path must never pay
        retry_after: Optional[float] = None
        for _ in range(max(1, cfg.failover_attempts)):
            picked = self.pick(exclude=tried, version=model_version)
            if picked is None and model_version is not None and not pinned:
                # lane soft-fallback: candidate lane empty or busy —
                # the incumbent serves the request
                picked = self.pick(exclude=tried)
            if picked is None:
                break
            w, port = picked
            tried.append(w.id)
            if request_id is not None:
                # sink-only (quiet): one record per dispatch attempt —
                # after a mid-request SIGKILL the log shows the SAME
                # request_id with two dispatch spans on two workers
                obs_events.emit(
                    "fleet", "dispatch", quiet=True,
                    request_id=request_id, worker=w.id,
                    attempt=len(tried),
                )
            try:
                code, reply, hdrs = self._forward(
                    port, body, timeout, request_id=request_id,
                    tenant=tenant,
                    model_version=model_version if pinned else None,
                )
            except _CONN_ERRORS as e:
                # the worker vanished mid-request: suspect it (the
                # supervision loop confirms via waitpid/heartbeat and
                # restarts it) and fail over
                self.inc("failovers")
                self._log(
                    f"roko fleet: worker {w.id} dropped a request "
                    f"({type(e).__name__}); failing over"
                )
                if request_id is not None:
                    obs_events.emit(
                        "fleet", "failover", quiet=True,
                        request_id=request_id, worker=w.id,
                        error=type(e).__name__,
                    )
                self.suspect(w)
                continue
            if code == 503:
                if retry_after is None:
                    retry_after = self.live_retry_after_s(tenant)
                try:
                    retry_after = max(
                        retry_after, float(hdrs.get("Retry-After", 0))
                    )
                except ValueError:
                    pass
                continue
            if code == 429:
                # tenant quota breach: the worker's Retry-After promise
                # must reach the client intact
                keep = {
                    k: v for k, v in hdrs.items()
                    if k.lower() == "retry-after"
                }
                return code, reply, keep
            return code, reply, {}
        if retry_after is None:
            retry_after = self.live_retry_after_s(tenant)
        if pinned and model_version is not None and not tried:
            msg = (
                f"no ready worker runs model {model_version!r} "
                "(fleet busy, rolling, or lane not deployed)"
            )
        else:
            msg = "no worker available (fleet busy or degraded)"
        body_out = json.dumps({
            "error": msg,
            "retry_after_s": retry_after,
        }).encode()
        return 503, body_out, {"Retry-After": f"{max(1, round(retry_after))}"}

    def _forward(
        self,
        port: int,
        body: bytes,
        timeout: Optional[float] = None,
        request_id: Optional[str] = None,
        tenant: Optional[str] = None,
        model_version: Optional[str] = None,
    ) -> Tuple[int, bytes, Dict[str, str]]:
        """One POST /polish to one worker's snapshotted port, no
        retries here. The default read timeout is generous (a polish
        can legitimately take minutes); a worker that HANGS mid-request
        is killed by the supervision loop, which closes this socket and
        converts the hang into a connection error -> failover."""
        from roko_tpu.serve.server import REQUEST_TIMEOUT_S

        conn = http.client.HTTPConnection(
            "127.0.0.1", port,
            timeout=REQUEST_TIMEOUT_S if timeout is None else timeout,
        )
        headers = {"Content-Type": "application/json"}
        if request_id is not None:
            headers["X-Roko-Request-Id"] = request_id
        if tenant is not None:
            headers["X-Roko-Tenant"] = tenant
        if model_version is not None:
            headers["X-Roko-Model"] = model_version
        try:
            conn.request("POST", "/polish", body=body, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, data, dict(resp.getheaders())
        finally:
            conn.close()

    def _probe(
        self, w: WorkerHandle, path: str
    ) -> Tuple[int, Dict[str, object]]:
        """GET a worker's JSON endpoint with the heartbeat timeout;
        HTTP error codes (503 warming/unhealthy) parse as answers, only
        transport failures raise."""
        url = f"http://127.0.0.1:{w.port}{path}"
        try:
            with urllib.request.urlopen(
                url, timeout=self.fleet_cfg.heartbeat_timeout_s
            ) as r:
                return r.status, json.loads(r.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                return e.code, json.loads(e.read() or b"{}")
            except ValueError:
                return e.code, {}

    # -- observation --------------------------------------------------------

    def summary(self) -> Dict[str, object]:
        """The supervisor ``/healthz`` body: aggregate status + the
        per-worker state map."""
        workers = list(self.workers)
        up = self.ready_count()
        states = {
            str(w.id): {
                "state": w.state,
                "port": w.port,
                "restarts": w.restarts,
                "version": w.version,
            }
            for w in workers
        }
        if self._draining:
            status, code = "draining", 503
        elif up == len(workers):
            status, code = "ok", 200
        elif up >= 1:
            # serving on the survivors: a load balancer may still route
            # here, but the degradation is visible
            status, code = "degraded", 200
        elif any(w.state in (WARMING, STARTING) for w in workers):
            status, code = "warming", 503
        else:
            status, code = "unhealthy", 503
        return {
            "status": status,
            "code": code,
            "workers": states,
            "workers_up": up,
            "version": self.active_version,
            # host-level load signal: the federation front's
            # HostAutoscaler sizes each host from this
            # (serve/federation.py)
            "backlog_windows": self.backlog_windows(),
        }

    def render_metrics(self) -> str:
        """The supervisor ``/metrics`` body: fleet gauges/counters plus
        selected per-worker series re-labeled by worker id (scraped
        live from each bound worker with the heartbeat timeout;
        unanswering workers are simply absent from the passthrough)."""
        workers = list(self.workers)
        p = "roko_fleet_"
        lines = [
            f"# TYPE {p}workers gauge",
            f"{p}workers {len(workers)}",
            f"# TYPE {p}workers_up gauge",
            f"{p}workers_up {self.ready_count()}",
        ]
        for name in ("restarts", "failovers", "requests", "rejected",
                     "scale_ups", "scale_downs"):
            lines.append(f"# TYPE {p}{name}_total counter")
            lines.append(f"{p}{name}_total {self.counter(name)}")
        lines.append(f"# TYPE {p}jobs_parked gauge")
        lines.append(f"{p}jobs_parked {1 if self.jobs_parked else 0}")
        lines.append(f"# TYPE {p}worker_state gauge")
        for w in workers:
            lines.append(
                f'{p}worker_state{{worker="{w.id}"}} '
                f"{STATE_CODES.get(w.state, 9)}"
            )
        lines.append(f"# TYPE {p}worker_restarts_total counter")
        for w in workers:
            lines.append(
                f'{p}worker_restarts_total{{worker="{w.id}"}} {w.restarts}'
            )
        # info-style: which model version each worker runs (the mixed-
        # fleet window during a rollout or an A/B lane is visible from
        # one scrape)
        lines.append(f"# TYPE {p}model_version gauge")
        for w in workers:
            lines.append(
                f'{p}model_version{{worker="{w.id}",'
                f'version="{w.version}"}} 1'
            )
        lines.append("# TYPE roko_rollout_state gauge")
        lines.append(
            "roko_rollout_state "
            f"{getattr(self.rollout, 'state_code', lambda: 0)() if self.rollout is not None else 0}"
        )
        names = tuple(n for n, _ in PASSTHROUGH_SERIES)
        scraped: Dict[int, Dict[str, str]] = {}
        bodies: Dict[int, str] = {}
        for w in workers:
            if w.port is None or not w.alive():
                continue
            try:
                url = f"http://127.0.0.1:{w.port}/metrics"
                with urllib.request.urlopen(
                    url, timeout=self.fleet_cfg.heartbeat_timeout_s
                ) as r:
                    bodies[w.id] = body = r.read().decode()
                    scraped[w.id] = parse_metric_values(body, names)
            except _CONN_ERRORS:  # URLError subclasses OSError
                continue
        for name, kind in PASSTHROUGH_SERIES:
            rows = [
                (wid, vals[name])
                for wid, vals in sorted(scraped.items())
                if name in vals
            ]
            if not rows:
                continue
            lines.append(f"# TYPE {name} {kind}")
            for wid, val in rows:
                lines.append(f'{name}{{worker="{wid}"}} {val}')
        # tenant-/model-labeled worker series, re-exported with the
        # worker id appended inside the braces — fleet dashboards see
        # per-tenant admission and per-model traffic per worker
        labeled = {
            wid: parse_labeled_rows(body, LABELED_SERIES)
            for wid, body in sorted(bodies.items())
        }
        for name in LABELED_SERIES:
            rows2 = [
                (wid, lbody, val)
                for wid, per in labeled.items()
                for lbody, val in per.get(name, [])
            ]
            if not rows2:
                continue
            kind = "counter" if name.endswith("_total") else "gauge"
            lines.append(f"# TYPE {name} {kind}")
            for wid, lbody, val in rows2:
                lines.append(f'{name}{{{lbody},worker="{wid}"}} {val}')
        # MERGEABLE histograms (docs/OBSERVABILITY.md): fleet-level rows
        # are the bucket-wise SUM of the worker rows — sound because
        # every process shares DEFAULT_LATENCY_BUCKETS — so a fleet p99
        # derives from the summed CDF instead of a percentile
        # passthrough that cannot aggregate; the per-worker rows stay
        # beside them labeled worker="i"
        for name in HISTOGRAM_SERIES:
            per_worker = {
                wid: parse_histogram_rows(body, name)
                for wid, body in sorted(bodies.items())
            }
            merged = merge_histogram_rows(per_worker.values())
            if not merged:
                continue
            lines.append(f"# TYPE {name} histogram")
            lines.extend(render_histogram_rows(name, merged))
            for wid, rows in per_worker.items():
                lines.extend(
                    render_histogram_rows(name, rows, extra=f'worker="{wid}"')
                )
        return "\n".join(lines) + "\n"

    def tracez(self, query: str = "") -> Dict[str, object]:
        """The supervisor ``GET /tracez`` body: every live worker's
        trace ring + scheduler snapshot, keyed by worker id (a worker
        not answering is simply absent)."""
        out: Dict[str, object] = {}
        path = "/tracez" + (f"?{query}" if query else "")
        for w in list(self.workers):
            if w.port is None or not w.alive():
                continue
            try:
                _, body = self._probe(w, path)
                out[str(w.id)] = body
            except _CONN_ERRORS:
                continue
        return {"workers": out}
