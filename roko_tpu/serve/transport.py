"""Fault-injectable TCP transport for the federated serving plane.

A *transport* is the one seam every federation byte crosses: the front
end's relays to host agents, the agents' lease heartbeats back to the
front end, and the metrics scrapes in between all go through a callable
with the signature

    transport(method, host, port, path, headers=None, body=None,
              timeout=..., peer="") -> (status, headers_dict, body_bytes)

``HttpTransport`` is the real thing (http.client over TCP).
``FaultyTransport`` wraps any transport and injects scripted network
faults — the network-plane twin of ``datapipe.store.FaultyStore``: it
lets tier-1 tests drive real multi-process fleets through drops,
delays, duplicated requests, and named partitions on loopback, without
ever touching a real flaky network.

Fault spec (env ``ROKO_FED_FAULTS``), comma-separated:

    drop:0.05,delay:0.1,duplicate:0.02,partition:front-h1

- ``drop:RATE``       raise ConnectionError before any byte is sent
- ``delay:RATE``      sleep ``ROKO_FED_DELAY_S`` (default 0.05 s) first
- ``duplicate:RATE``  send the request twice; the *second* reply is
                      returned (exercises idempotency + epoch fencing)
- ``partition:A-B``   total blackhole between endpoints named A and B
                      (unordered pair; repeatable)

Rates are in [0,1]. ``rate 0`` is the identity transport; ``drop:1``
is a total partition — both endpoints are pinned by tests. Unknown
kinds and out-of-range rates are refused loudly: a chaos test that
silently injects nothing is worse than no chaos test.
"""

from __future__ import annotations

import http.client
import os
import random
import threading
import time
from typing import Dict, FrozenSet, Iterable, Optional, Set, Tuple

__all__ = [
    "FED_FAULT_KINDS",
    "FaultyTransport",
    "HttpTransport",
    "parse_fed_faults",
    "transport_from_env",
]

FED_FAULT_KINDS = ("drop", "delay", "duplicate", "partition")

TransportReply = Tuple[int, Dict[str, str], bytes]


class HttpTransport:
    """Plain HTTP/TCP transport. One connection per call — federation
    control traffic is low-rate and the simplicity buys clean failure
    semantics (every fault is a fresh ConnectionError, never a
    half-poisoned keep-alive socket)."""

    def __call__(
        self,
        method: str,
        host: str,
        port: int,
        path: str,
        headers: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        timeout: float = 10.0,
        peer: str = "",
    ) -> TransportReply:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request(method, path, body=body, headers=headers or {})
            resp = conn.getresponse()
            data = resp.read()
            return resp.status, dict(resp.getheaders()), data
        finally:
            conn.close()


def parse_fed_faults(
    spec: str,
) -> Tuple[Dict[str, float], Set[FrozenSet[str]]]:
    """Parse a ``ROKO_FED_FAULTS`` spec into (rates, partition pairs).

    Refuses unknown kinds and out-of-range rates with a loud
    ValueError naming the valid kinds — mirrors the FaultyStore spec
    parser so a typo'd chaos config can never silently become a
    no-fault run.
    """
    rates: Dict[str, float] = {}
    partitions: Set[FrozenSet[str]] = set()
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        kind, _, val = part.partition(":")
        kind = kind.strip()
        if kind not in FED_FAULT_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r} in ROKO_FED_FAULTS "
                f"(valid: {', '.join(FED_FAULT_KINDS)})"
            )
        if kind == "partition":
            a, sep, b = val.partition("-")
            a, b = a.strip(), b.strip()
            if not sep or not a or not b or a == b:
                raise ValueError(
                    f"partition spec {part!r} must name two distinct "
                    "endpoints as partition:a-b"
                )
            partitions.add(frozenset((a, b)))
            continue
        try:
            rate = float(val)
        except ValueError:
            raise ValueError(
                f"fault rate {val!r} for {kind!r} is not a number"
            ) from None
        if not 0.0 <= rate <= 1.0:
            raise ValueError(
                f"fault rate {rate} for {kind!r} out of range [0, 1]"
            )
        rates[kind] = rate
    return rates, partitions


class FaultyTransport:
    """Wrap a transport and inject scripted network faults.

    ``name`` is this endpoint's identity for partition matching: a
    partition pair {A, B} blackholes any call where {self.name, peer}
    equals the pair. ``partition()`` / ``heal()`` script partitions
    mid-test; ``injected`` counts every fault actually fired so tests
    can assert the chaos really happened.
    """

    def __init__(
        self,
        inner,
        rates: Optional[Dict[str, float]] = None,
        partitions: Iterable[FrozenSet[str]] = (),
        seed: int = 0,
        name: str = "",
        delay_s: float = 0.05,
    ) -> None:
        rates = dict(rates or {})
        for kind, rate in rates.items():
            if kind not in FED_FAULT_KINDS or kind == "partition":
                raise ValueError(f"unknown fault kind {kind!r}")
            if not 0.0 <= rate <= 1.0:
                raise ValueError(
                    f"fault rate {rate} for {kind!r} out of range [0, 1]"
                )
        self.inner = inner
        self.rates = rates
        self.name = name
        self.delay_s = delay_s
        self._partitions: Set[FrozenSet[str]] = set(partitions)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected: Dict[str, int] = {k: 0 for k in FED_FAULT_KINDS}

    def partition(self, a: str, b: str) -> None:
        with self._lock:
            self._partitions.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        with self._lock:
            self._partitions.discard(frozenset((a, b)))

    def _roll(self, kind: str) -> bool:
        rate = self.rates.get(kind, 0.0)
        if rate <= 0.0:
            return False
        with self._lock:
            hit = self._rng.random() < rate
            if hit:
                self.injected[kind] += 1
        return hit

    def _partitioned(self, peer: str) -> bool:
        if not peer or not self.name:
            return False
        pair = frozenset((self.name, peer))
        with self._lock:
            if pair in self._partitions:
                self.injected["partition"] += 1
                return True
        return False

    def __call__(
        self,
        method: str,
        host: str,
        port: int,
        path: str,
        headers: Optional[Dict[str, str]] = None,
        body: Optional[bytes] = None,
        timeout: float = 10.0,
        peer: str = "",
    ) -> TransportReply:
        if self._partitioned(peer):
            raise ConnectionError(
                f"injected partition between {self.name!r} and {peer!r}"
            )
        if self._roll("drop"):
            raise ConnectionError(
                f"injected drop from {self.name!r} to {peer!r} ({path})"
            )
        if self._roll("delay"):
            time.sleep(self.delay_s)
        send = lambda: self.inner(  # noqa: E731
            method, host, port, path, headers=headers, body=body,
            timeout=timeout, peer=peer,
        )
        if self._roll("duplicate"):
            first = send()
            try:
                return send()
            except (OSError, http.client.HTTPException):
                return first
        return send()


def transport_from_env(
    name: str,
    inner=None,
    env: Optional[Dict[str, str]] = None,
):
    """Build this endpoint's transport, honoring ``ROKO_FED_FAULTS``.

    Returns a bare ``HttpTransport`` when no faults are configured, so
    the common path pays nothing for the chaos machinery.
    """
    env = os.environ if env is None else env
    inner = inner or HttpTransport()
    spec = env.get("ROKO_FED_FAULTS", "").strip()
    if not spec:
        return inner
    rates, partitions = parse_fed_faults(spec)
    if not rates and not partitions:
        return inner
    try:
        delay_s = float(env.get("ROKO_FED_DELAY_S", "0.05"))
    except ValueError:
        delay_s = 0.05
    try:
        seed = int(env.get("ROKO_FED_FAULTS_SEED", "0"))
    except ValueError:
        seed = 0
    return FaultyTransport(
        inner,
        rates,
        partitions=partitions,
        seed=seed,
        name=name,
        delay_s=delay_s,
    )
