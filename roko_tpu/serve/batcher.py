"""Dynamic micro-batching over a warm :class:`PolishSession`.

Requests (each a batch of ``uint8[n, rows, cols]`` windows) land in a
bounded queue; one worker thread coalesces them into device batches
under two limits:

- **fill**: stop gathering once the coalesced batch reaches the
  session's top ladder rung (no point padding past it);
- **deadline**: a partially filled batch dispatches at most
  ``max_delay_ms`` after its FIRST request arrived, so a lone request's
  latency is bounded by the deadline, not by traffic.

Backpressure is explicit: when the queue is full, ``submit`` raises
:class:`Backpressure` (the HTTP layer maps it to 503 + ``Retry-After``)
instead of queueing unboundedly — throughput degrades gracefully under
overload rather than OOMing the host (ISSUE tentpole; the same shape
LLM serving uses for admission control).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import List, Optional

import numpy as np

from roko_tpu.resilience import CircuitBreaker
from roko_tpu.serve.metrics import ServeMetrics
from roko_tpu.serve.session import PolishSession

#: exception classes that indict the REQUEST, not the device: they never
#: move the circuit breaker (a client's bad window geometry says nothing
#: about chip health)
_REQUEST_ERRORS = (ValueError, TypeError)


class Backpressure(Exception):
    """Request rejected without touching the device — queue full or
    circuit breaker open; retry after ``retry_after_s`` seconds."""

    def __init__(self, retry_after_s: float, reason: str = "request queue full"):
        super().__init__(f"{reason}; retry after {retry_after_s:.1f}s")
        self.retry_after_s = retry_after_s


class QuotaExceeded(Backpressure):
    """A TENANT'S quota rejected the request, not global overload: the
    HTTP layer maps this to 429 + ``Retry-After`` (retrying helps once
    this tenant's own backlog drains; other tenants are unaffected)."""

    def __init__(self, retry_after_s: float, tenant: str, reason: str):
        super().__init__(retry_after_s, reason=f"tenant {tenant!r} {reason}")
        self.tenant = tenant


class _Request:
    __slots__ = ("x", "done", "preds", "error", "t_submit", "trace", "tenant")

    def __init__(self, x: np.ndarray, trace=None, tenant: Optional[str] = None):
        self.x = x
        self.done = threading.Event()
        self.preds: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        self.t_submit = time.perf_counter()
        #: optional per-request obs.trace.RequestTrace riding the
        #: request through the batching plane (docs/OBSERVABILITY.md)
        self.trace = trace
        #: tenant id for fair-share accounting + latency labels
        self.tenant = tenant


class PredictFuture:
    """Handle for one submitted request."""

    def __init__(self, req: _Request, metrics: Optional[ServeMetrics]):
        self._req = req
        self._metrics = metrics

    def done(self) -> bool:
        """True once the result (or its error) is ready — a non-blocking
        probe for callers draining many futures opportunistically (the
        streaming polish pipeline rides the batcher this way)."""
        return self._req.done.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        if not self._req.done.wait(timeout):
            raise TimeoutError("predict result not ready")
        if self._req.error is not None:
            raise self._req.error
        if self._metrics is not None:
            # records the aggregate "request" span AND the request's
            # size-class span (ladder-rung buckets) so /metrics can show
            # small-request p99 beside large-request p99 — the
            # head-of-line-blocking signal continuous batching exists
            # to fix (docs/SERVING.md "Continuous batching")
            self._metrics.observe_request(
                len(self._req.x),
                time.perf_counter() - self._req.t_submit,
                tenant=getattr(self._req, "tenant", None),
            )
        return self._req.preds


class MicroBatcher:
    #: policy name reported in /healthz (``ServeConfig.batching`` value
    #: that selects this class in ``make_server``)
    BATCHING_MODE = "deadline"

    def __init__(
        self,
        session: PolishSession,
        *,
        max_queue: Optional[int] = None,
        max_delay_ms: Optional[float] = None,
        retry_after_s: Optional[float] = None,
        metrics: Optional[ServeMetrics] = None,
        breaker: Optional[CircuitBreaker] = None,
        start: bool = True,
    ):
        serve_cfg = session.cfg.serve
        self.session = session
        #: circuit breaker over DEVICE failures (None = disabled): trips
        #: after N consecutive failed dispatches; while open, submit()
        #: sheds load instantly with Backpressure instead of feeding a
        #: sick device whole request timeouts (docs/SERVING.md)
        self.breaker = breaker
        self.max_delay_s = (
            serve_cfg.max_delay_ms if max_delay_ms is None else max_delay_ms
        ) / 1e3
        self.retry_after_s = (
            serve_cfg.retry_after_s if retry_after_s is None else retry_after_s
        )
        self.metrics = metrics
        self._q: "queue.Queue[Optional[_Request]]" = queue.Queue(
            maxsize=serve_cfg.max_queue if max_queue is None else max_queue
        )
        self._running = False
        self._stopped = False  # set once by stop(); submissions then fail fast
        self._thread: Optional[threading.Thread] = None
        self._steps = 0  # device dispatches so far (trace step ids)
        if metrics is not None:
            metrics.queue_depth = self._q.qsize
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(
            target=self._loop, name="roko-microbatcher", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 5.0) -> None:
        self._stopped = True
        if self._running:
            self._running = False
            self._repost_sentinel()  # wake the worker (best-effort)
            if self._thread is not None:
                self._thread.join(timeout)
                self._thread = None
        # second drain AFTER the worker is gone: a submit() racing
        # stop() can land a request behind the worker's own final
        # drain, and nothing would ever complete it
        self._fail_queued()

    def _fail_queued(self) -> None:
        while True:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                return
            if req is not None and not req.done.is_set():
                req.error = RuntimeError("batcher stopped")
                req.done.set()

    # -- client side --------------------------------------------------------

    def submit(
        self, x: np.ndarray, trace=None, tenant: Optional[str] = None
    ) -> PredictFuture:
        """Enqueue one window batch; raises :class:`Backpressure` when
        the queue is full and ``RuntimeError`` once the batcher has been
        stopped (a dead worker must fail requests fast, not strand
        their futures). ``trace`` (a
        :class:`roko_tpu.obs.trace.RequestTrace`) collects the
        queue-wait / device span breakdown for the reply's ``timings``
        field. ``tenant`` labels the request's latency row; the
        deadline coalescer has no tenant fair-share (whole requests
        dispatch FIFO — use continuous/ragged mode for DRR admission),
        so here it is accounting only."""
        if self._stopped:
            raise RuntimeError("batcher stopped")
        if self.breaker is not None and not self.breaker.allow():
            # open (or half-open with the probe slot taken): shed load
            # without touching the queue; tell the client when the
            # breaker could next admit it
            if self.metrics is not None:
                self.metrics.inc("rejected")
            raise Backpressure(
                max(self.breaker.retry_after_s(), self.retry_after_s),
                reason="circuit breaker open (device failing)",
            )
        req = _Request(np.ascontiguousarray(x, dtype=np.uint8), trace, tenant)
        try:
            self._q.put_nowait(req)
        except queue.Full:
            if self.breaker is not None:
                # a half-open allow() claimed the probe slot for a
                # request that never made it in — release it
                self.breaker.cancel_probe()
            if self.metrics is not None:
                self.metrics.inc("rejected")
            raise Backpressure(self.retry_after_s) from None
        if self._stopped:
            # raced stop(): the worker may already be gone, so nothing
            # would drain this request — fail it here (done.set is
            # idempotent; if the worker did take it, its result stands)
            self._fail_queued()
        if self.metrics is not None:
            self.metrics.inc("requests")
            self.metrics.inc("windows", len(req.x))
        return PredictFuture(req, self.metrics)

    def predict(
        self, x: np.ndarray, timeout: Optional[float] = None, trace=None
    ) -> np.ndarray:
        """submit + result in one call (the HTTP handler's path)."""
        return self.submit(x, trace=trace).result(timeout)

    def snapshot(self) -> dict:
        """The ``GET /tracez`` scheduler block, deadline-mode edition:
        whole requests queue here, so the view is just depth + steps
        (the continuous scheduler reports the richer slot-pool state)."""
        return {
            "mode": self.BATCHING_MODE,
            "queue_depth": self._q.qsize(),
            "steps": self._steps,
            "ladder": list(self.session.ladder),
        }

    # -- worker side --------------------------------------------------------

    def _gather(self, first: _Request) -> List[_Request]:
        """Coalesce queued requests behind ``first`` until the top rung
        fills or the deadline expires. Factored from the loop so tests
        can drive it synchronously.

        Two phases, so batching survives backlog: already-queued
        requests coalesce unconditionally (their age is irrelevant —
        under load, when the previous dispatch outlived the deadline,
        the backlog must still form full batches or device throughput
        collapses to one padded request per dispatch); the deadline
        only bounds how long a PARTIAL batch waits for NEW arrivals,
        measured from ``first``'s submit so a lone request's latency
        stays <= max_delay_ms."""
        batch = [first]
        total = len(first.x)
        top = self.session.ladder[-1]
        deadline = first.t_submit + self.max_delay_s
        while total < top:
            try:  # phase 1: drain the existing backlog, no waiting
                req = self._q.get_nowait()
            except queue.Empty:
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                try:  # phase 2: wait out the deadline for new arrivals
                    req = self._q.get(timeout=remaining)
                except queue.Empty:
                    break
            if req is None:  # shutdown sentinel
                self._repost_sentinel()  # for the outer loop
                break
            batch.append(req)
            total += len(req.x)
        return batch

    def _repost_sentinel(self) -> None:
        # never a blocking put: on a full queue it would deadlock the
        # only consumer; the outer loop's _running check (0.1 s poll)
        # ends the worker even when the sentinel is lost
        try:
            self._q.put_nowait(None)
        except queue.Full:
            pass

    def _dispatch(self, batch: List[_Request]) -> None:
        """Predict one coalesced batch and scatter results back."""
        sizes = [len(r.x) for r in batch]
        total = sum(sizes)
        now = time.perf_counter()
        for r in batch:
            # queue-wait: submit until this dispatch formed (the
            # deadline coalescer packs a whole request at once)
            wait = now - r.t_submit
            if r.trace is not None:
                r.trace.add("queue_wait", wait)
            if self.metrics is not None:
                self.metrics.hist_queue_wait.observe(wait)
        try:
            t_pack = time.perf_counter()
            x = (
                batch[0].x
                if len(batch) == 1
                else np.concatenate([r.x for r in batch])
            )
            t_dev = time.perf_counter()
            preds = self.session.predict(x)
        except BaseException as e:  # propagate to every waiter
            if self.breaker is not None:
                if isinstance(e, _REQUEST_ERRORS):
                    # request-shaped failure proves nothing about the
                    # device; a half-open probe it rode must be released
                    self.breaker.cancel_probe()
                else:
                    # device-shaped failure (HangError, XLA runtime
                    # error, ...): one step toward tripping the breaker
                    self.breaker.record_failure()
            for r in batch:
                r.error = e
                r.done.set()
            # errors_total is counted per failed REQUEST where the
            # exception resurfaces (PredictFuture.result -> the HTTP
            # 500 handler) — counting the shared batch failure here too
            # would inflate the series by 1 per coalesced batch
            return
        if self.breaker is not None:
            self.breaker.record_success()
        dt_dev = time.perf_counter() - t_dev
        self._steps += 1
        if self.metrics is not None:
            self.metrics.hist_device.observe(dt_dev)
        padded = max(1, self.session.padded_size(total))
        dp = getattr(self.session, "dp", 1)
        off = 0
        for r, n in zip(batch, sizes):
            if r.trace is not None:
                r.trace.add("pack", t_dev - t_pack)
                r.trace.add_step(
                    dt_dev, rung=padded, step=self._steps,
                    occupancy=total / padded, dp=dp, windows=n,
                )
            r.preds = preds[off : off + n]
            off += n
            r.done.set()
        if self.metrics is not None:
            self.metrics.inc("batches")
            self.metrics.observe_fill(total, padded)

    def _loop(self) -> None:
        while self._running:
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            self._dispatch(self._gather(first))
        # drain: fail any stragglers loudly rather than hanging clients
        self._fail_queued()
