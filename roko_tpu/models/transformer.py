"""Transformer-encoder drop-in for the GRU recurrence.

The consensus network's sequence axis is the 90 pileup columns
(SURVEY.md §3.5); this variant replaces the 3-layer bidirectional GRU
with a pre-LN transformer encoder over that axis (BASELINE.md
"Transformer variant" row). Same contract as `RokoGRU.apply`:
``[B, T, gru_in_size] -> [B, T, 2*hidden_size]`` so the classification
head is shared between the two families.

TPU mapping: attention and MLP are batched matmuls on the MXU; the head
dim stays a multiple of 128. Tensor parallelism shards the head/MLP
hidden axes (see `roko_tpu/parallel/tp.py` sharding rules); sequence
parallelism for long-context variants runs this same attention body
under `shard_map` with ring K/V rotation (`roko_tpu/parallel/ring.py`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from roko_tpu.config import ModelConfig
from roko_tpu.models.layers import (
    dense as _dense,
    dense_params as _dense_init,
    dropout as _dropout,
    layernorm as _layernorm,
    layernorm_params as _ln_init,
)

Params = Dict[str, Any]


def attention(q, k, v, num_heads: int):
    """Dense bidirectional multi-head attention.

    q,k,v: [B, T, D]. Exposed standalone so the ring-attention path can
    reuse the identical per-block math (`roko_tpu/parallel/ring.py`).
    """
    B, T, D = q.shape
    H = num_heads
    hd = D // H
    q = q.reshape(B, T, H, hd).transpose(0, 2, 1, 3)  # [B,H,T,hd]
    k = k.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(hd)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return out.transpose(0, 2, 1, 3).reshape(B, T, D)


def _layer_init(rng, d_model: int, mlp_dim: int, dtype=jnp.float32) -> Params:
    keys = jax.random.split(rng, 6)
    return {
        "ln1": _ln_init(d_model, dtype),
        # q/k/v kept as separate projections so tensor-parallel sharding
        # of each output axis is head-aligned (a fused [d,3d] kernel would
        # put tp shard boundaries inside k and force activation reshards)
        "q": _dense_init(keys[0], d_model, d_model, dtype),
        "k": _dense_init(keys[1], d_model, d_model, dtype),
        "v": _dense_init(keys[2], d_model, d_model, dtype),
        "proj": _dense_init(keys[3], d_model, d_model, dtype),
        "ln2": _ln_init(d_model, dtype),
        "mlp_in": _dense_init(keys[4], d_model, mlp_dim, dtype),
        "mlp_out": _dense_init(keys[5], mlp_dim, d_model, dtype),
    }


def encoder_layer(
    p: Params,
    x: jax.Array,
    num_heads: int,
    *,
    dropout: float = 0.0,
    deterministic: bool = True,
    rng: Optional[jax.Array] = None,
    attn_fn=attention,
) -> jax.Array:
    """Pre-LN encoder block: x + MHA(LN(x)); x + MLP(LN(x))."""
    h = _layernorm(p["ln1"], x)
    q = _dense(p["q"], h)
    k = _dense(p["k"], h)
    v = _dense(p["v"], h)
    a = attn_fn(q, k, v, num_heads)
    a = _dense(p["proj"], a)
    if not deterministic:
        rng, sub = jax.random.split(rng)
        a = _dropout(sub, a, dropout)
    x = x + a

    h = _layernorm(p["ln2"], x)
    h = _dense(p["mlp_out"], jax.nn.gelu(_dense(p["mlp_in"], h)))
    if not deterministic:
        rng, sub = jax.random.split(rng)
        h = _dropout(sub, h, dropout)
    return x + h


def transformer_init(rng: jax.Array, cfg: ModelConfig) -> Params:
    d = cfg.d_model
    if d != 2 * cfg.hidden_size:
        raise ValueError(
            f"d_model ({d}) must equal 2*hidden_size ({2 * cfg.hidden_size}) "
            "so the classification head is shared with the GRU family"
        )
    if d % cfg.num_heads:
        raise ValueError(f"d_model {d} not divisible by {cfg.num_heads} heads")
    keys = jax.random.split(rng, cfg.num_layers + 3)

    return {
        "in_proj": _dense_init(keys[0], cfg.gru_in_size, d),
        # learned positional embedding over the pileup-column axis
        "pos_embed": 0.02
        * jax.random.normal(keys[1], (cfg.window_cols, d), jnp.float32),
        "layers": tuple(
            _layer_init(keys[2 + i], d, cfg.mlp_ratio * d)
            for i in range(cfg.num_layers)
        ),
        "ln_out": _ln_init(d),
    }


def transformer_apply(
    params: Params,
    cfg: ModelConfig,
    x: jax.Array,  # [B, T, gru_in_size]
    *,
    deterministic: bool = True,
    rng: Optional[jax.Array] = None,
    attn_fn=attention,
) -> jax.Array:
    h = _dense(params["in_proj"], x)
    T = h.shape[1]
    h = h + params["pos_embed"][:T].astype(h.dtype)
    for i, layer in enumerate(params["layers"]):
        sub = None
        if not deterministic:
            rng, sub = jax.random.split(rng)
        h = encoder_layer(
            layer,
            h,
            cfg.num_heads,
            dropout=cfg.dropout,
            deterministic=deterministic,
            rng=sub,
            attn_fn=attn_fn,
        )
    return _layernorm(params["ln_out"], h)  # [B, T, d_model]
