"""Weight-only int8 quantization for the consensus models.

The serving model is a stack of large batched matmuls that are
memory-bound at serving batch sizes: every predict step streams each
weight matrix from HBM once per dispatch, while the MXU/ALUs wait.
Weight-only quantization attacks exactly those bytes — each matmul
kernel ``w: f32[in, out]`` is stored as

    q:     int8[in, out]          round(w / scale), clipped to ±127
    scale: f32[out]               max(|w|, axis=0) / 127  (per OUTPUT channel)

and dequantized *inside* the compiled predict program right where it
feeds its matmul (``dequant_weight`` — the ``weight()`` helper in
models/layers.py is the one use-site idiom), so the bytes that move are
int8, not f32. Everything else stays float: activations, biases, the
(12-row) embedding, recurrence state, and the final logits — this is
the standard weight-only recipe, which keeps the numerics close enough
that the held-out-Q gate (polished Q within 0.5 of the f32 reference,
tests/test_precision.py slow lane) holds.

Quantization is CONVERSION-TIME only: training always runs full
precision (training/loop.py refuses a quantized config), and the f32
checkpoint is quantized when loaded for inference/serve
(``maybe_quantize``) or when ``roko-tpu compile --quantize int8``
builds an AOT bundle. The bundle identity digest covers
``ModelConfig.quantize``, so a quantized bundle refuses to load into a
plain session (and vice versa) with the usual field-naming
:class:`~roko_tpu.compile.BundleMismatch` diff.

Targeted kernels (per-output-channel on the LAST axis, which is the
output-channel axis for every one of them):

- front end + head: ``fc1.kernel``, ``fc2.kernel``, ``head.kernel``
- ``kind="gru"``:    per layer/direction ``w_ih`` [in,3H], ``w_hh`` [H,3H]
- ``kind="lingru"``: per layer/direction ``w_zx`` [in,H], ``w_cx`` [in,H]

The transformer variant has no int8 path (ModelConfig refuses the
combination at construction).
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

from roko_tpu.config import ModelConfig
from roko_tpu.models.layers import dequant_weight, is_quantized_weight

Params = Dict[str, Any]

#: symmetric int8 range; ±127 (not -128) keeps the scale symmetric so
#: dequantization is a single multiply
QMAX = 127.0

#: kernel key names quantized per model sub-tree (biases and scales in
#: the same dicts stay f32)
_DENSE_KERNELS = ("fc1", "fc2", "head")
_GRU_KERNELS = ("w_ih", "w_hh")
_LINGRU_KERNELS = ("w_zx", "w_cx")


def quantize_weight(w: jax.Array) -> Dict[str, jax.Array]:
    """One f32 kernel -> ``{"q": int8, "scale": f32[out]}`` with
    per-output-channel (last axis) absmax scales. Traceable — runs
    under ``jax.eval_shape`` so AOT bundle export needs no real
    checkpoint."""
    absmax = jnp.max(jnp.abs(w), axis=tuple(range(w.ndim - 1)))
    # an all-zero channel would divide 0/0; its q rows are 0 either way
    scale = jnp.where(absmax > 0, absmax, 1.0).astype(jnp.float32) / QMAX
    q = jnp.clip(jnp.round(w / scale), -QMAX, QMAX).astype(jnp.int8)
    return {"q": q, "scale": scale}


def _quantize_recurrence(layers, kernel_names) -> Any:
    out = []
    for layer in layers:
        new_layer = {}
        for direction, p in layer.items():
            new_layer[direction] = {
                k: quantize_weight(v) if k in kernel_names else v
                for k, v in p.items()
            }
        out.append(new_layer)
    return tuple(out)


def quantize_params(params: Params, cfg: ModelConfig) -> Params:
    """f32 param pytree -> the quantized serving tree for ``cfg``
    (``cfg.quantize`` must be "int8"). Embedding, biases, and anything
    not a targeted matmul kernel pass through untouched."""
    if cfg.quantize != "int8":
        raise ValueError(f"unsupported quantize mode {cfg.quantize!r}")
    out = dict(params)
    for name in _DENSE_KERNELS:
        if name in out:
            out[name] = dict(
                out[name], kernel=quantize_weight(out[name]["kernel"])
            )
    if "gru" in out:
        out["gru"] = _quantize_recurrence(out["gru"], _GRU_KERNELS)
    if "lingru" in out:
        out["lingru"] = _quantize_recurrence(out["lingru"], _LINGRU_KERNELS)
    return out


def is_quantized(params: Params) -> bool:
    """True when ``params`` already carries int8 weight dicts (any
    targeted kernel suffices — quantization is all-or-nothing per
    tree)."""
    for name in _DENSE_KERNELS:
        if name in params and is_quantized_weight(params[name].get("kernel")):
            return True
    return False


def maybe_quantize(params: Params, cfg: ModelConfig) -> Params:
    """The one conversion gate every inference/serve path loads params
    through: quantizes when ``cfg.quantize`` asks for it, is a no-op
    when quantization is off or the tree is already quantized (so a
    session handed pre-converted params never double-quantizes)."""
    if cfg.quantize is None or is_quantized(params):
        return params
    return quantize_params(params, cfg)


def dequantize_params(params: Params, dtype=jnp.float32) -> Params:
    """Back to a dense float tree (every int8 weight dict replaced by
    its dequantized kernel in ``dtype``). Used by apply paths that need
    plain arrays — e.g. the fused Pallas GRU kernels — and by tests
    bounding the quantization error."""
    return jax.tree.map(
        lambda leaf: dequant_weight(leaf, dtype)
        if is_quantized_weight(leaf)
        else leaf,
        params,
        is_leaf=is_quantized_weight,
    )
