"""Log-depth linear recurrence: the ``kind="lingru"`` model variant.

The torch-exact GRU (``roko_tpu/models/gru.py``) is the accuracy/byte-
identity reference, but its recurrence is *nonlinear in h* — every
timestep needs the previous hidden state before its gates can be
computed, so inference serialises T=90 tiny [B,H]x[H,3H] matmuls while
the MXU idles (ROADMAP item 1: 31.7 s of the 32.9 s end-to-end polish).

This module implements the GILR-style gated *linear* recurrence from
"Parallelizing Linear Recurrent Neural Nets Over Sequence Length"
(PAPERS.md): gates depend on the input only,

    z_t = sigmoid(x_t @ W_z + b_z)
    c_t = tanh   (x_t @ W_c + b_c)
    h_t = (1 - z_t) * h_{t-1} + z_t * c_t

so the recurrence is a first-order affine map ``h_t = a_t*h_{t-1} + b_t``
with ``a_t = 1 - z_t`` and ``b_t = z_t * c_t``. Affine maps compose
associatively — ``(a, b) o (a', b') = (a*a', a*b' + b)`` — which lets
``jax.lax.associative_scan`` evaluate all T steps in O(log T) depth
instead of T sequential steps. Everything with real arithmetic density
(the gate projections) hoists out of the scan into one [B*T, in]x[in, 4H]
MXU matmul per bidirectional layer; the scan itself is purely
elementwise.

Structure mirrors the GRU container exactly (bidirectional, multi-layer,
fwd ++ bwd on the feature axis, inter-layer dropout), so
``models/model.py`` swaps it in behind ``ModelConfig.kind`` with the
same embed -> read-MLP front end and fc head. The associative-scan path
is pinned against a naive per-step evaluation of the same recurrence
(forward AND gradients) in ``tests/test_lingru.py``.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from roko_tpu.models.layers import dropout as _dropout, weight as _weight


def lingru_layer_params(
    rng: jax.Array, in_size: int, hidden: int, dtype=jnp.float32
) -> Dict[str, jax.Array]:
    """One direction of one layer. Orthogonal init for the gate
    projections, standard normal for biases — the same scheme the GRU
    layers use (``gru.gru_layer_params``) so the existing training
    recipe transfers unchanged."""
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    ortho = jax.nn.initializers.orthogonal()
    return {
        "w_zx": ortho(k1, (in_size, hidden), dtype),
        "w_cx": ortho(k2, (in_size, hidden), dtype),
        "b_z": jax.random.normal(k3, (hidden,), dtype),
        "b_c": jax.random.normal(k4, (hidden,), dtype),
    }


def lingru_gates(
    params: Dict[str, jax.Array], x: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """``x`` [..., in] -> the affine-recurrence coefficients
    ``(a, b)`` with ``h_t = a_t * h_{t-1} + b_t``. One fused [in, 2H]
    matmul for both gates."""
    w_zx = _weight(params["w_zx"], x.dtype)
    hidden = w_zx.shape[1]
    w = jnp.concatenate([w_zx, _weight(params["w_cx"], x.dtype)], axis=1)
    bias = jnp.concatenate([params["b_z"], params["b_c"]])
    proj = x @ w + bias
    z = jax.nn.sigmoid(proj[..., :hidden])
    c = jnp.tanh(proj[..., hidden:])
    return 1.0 - z, z * c


def linear_scan(a: jax.Array, b: jax.Array, axis: int = 1) -> jax.Array:
    """All-timestep solution of ``h_t = a_t * h_{t-1} + b_t`` (h_0 = 0)
    via ``lax.associative_scan`` over the affine composition — log-depth
    in the scan axis instead of one step per element."""

    def combine(left, right):
        # left covers earlier timesteps; composed map = right AFTER left
        a_l, b_l = left
        a_r, b_r = right
        return a_l * a_r, b_l * a_r + b_r

    return lax.associative_scan(combine, (a, b), axis=axis)[1]


def linear_scan_ref(a: jax.Array, b: jax.Array) -> jax.Array:
    """Naive per-step evaluation of the same recurrence over axis 1 —
    the numerical oracle the associative-scan path is tested against
    (tests/test_lingru.py), differentiable so gradient parity is checked
    too. Never used on a hot path."""

    def cell(h, ab):
        a_t, b_t = ab
        h = a_t * h + b_t
        return h, h

    h0 = jnp.zeros(a.shape[:1] + a.shape[2:], a.dtype)
    _, hs = lax.scan(cell, h0, (a.swapaxes(0, 1), b.swapaxes(0, 1)))
    return hs.swapaxes(0, 1)


def lingru_direction(
    params: Dict[str, jax.Array],
    x: jax.Array,
    reverse: bool = False,
    *,
    naive: bool = False,
) -> jax.Array:
    """Run one direction over ``x`` [B,T,in] -> [B,T,H]. ``naive=True``
    substitutes the per-step reference scan (test oracle)."""
    a, b = lingru_gates(params, x)
    if reverse:
        a, b = jnp.flip(a, axis=1), jnp.flip(b, axis=1)
    h = linear_scan_ref(a, b) if naive else linear_scan(a, b, axis=1)
    return jnp.flip(h, axis=1) if reverse else h


def bidir_lingru_layer(layer: Dict[str, Any], x: jax.Array) -> jax.Array:
    """Both directions of one layer, [B,T,in] -> [B,T,2H] (fwd ++ bwd on
    the feature axis, as ``gru.bidir_layer``).

    One [B*T, in] x [in, 4H] matmul projects all four gates of both
    directions; the backward direction's coefficients are time-reversed
    so a SINGLE associative scan (directions stacked as a leading
    batch dim) solves both recurrences at once."""
    # weight() dequantizes int8 weight-only kernels in place
    # (models/quant.py); plain f32/bf16 kernels pass through untouched
    w_zx_f = _weight(layer["fwd"]["w_zx"], x.dtype)
    hidden = w_zx_f.shape[1]
    w4 = jnp.concatenate(
        [
            w_zx_f, _weight(layer["fwd"]["w_cx"], x.dtype),
            _weight(layer["bwd"]["w_zx"], x.dtype),
            _weight(layer["bwd"]["w_cx"], x.dtype),
        ],
        axis=1,
    )
    b4 = jnp.concatenate(
        [
            layer["fwd"]["b_z"], layer["fwd"]["b_c"],
            layer["bwd"]["b_z"], layer["bwd"]["b_c"],
        ]
    )
    proj = x @ w4 + b4  # [B,T,4H]
    z_f = jax.nn.sigmoid(proj[..., :hidden])
    c_f = jnp.tanh(proj[..., hidden : 2 * hidden])
    z_b = jax.nn.sigmoid(proj[..., 2 * hidden : 3 * hidden])
    c_b = jnp.tanh(proj[..., 3 * hidden :])
    a = jnp.stack([1.0 - z_f, jnp.flip(1.0 - z_b, axis=1)])  # [2,B,T,H]
    b = jnp.stack([z_f * c_f, jnp.flip(z_b * c_b, axis=1)])
    h = linear_scan(a, b, axis=2)
    return jnp.concatenate(
        [h[0], jnp.flip(h[1], axis=1)], axis=-1
    )  # [B,T,2H]


def bidir_lingru_stack(
    params: Tuple[Dict[str, Any], ...],
    x: jax.Array,
    *,
    dropout: float = 0.0,
    deterministic: bool = True,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Stacked bidirectional linear GRU, [B,T,in] -> [B,T,2H]. Dropout
    between layers only, matching the GRU stack's (and torch's)
    placement."""
    num_layers = len(params)
    for i, layer in enumerate(params):
        x = bidir_lingru_layer(layer, x)
        if dropout > 0.0 and not deterministic and i < num_layers - 1:
            assert rng is not None
            rng, sub = jax.random.split(rng)
            x = _dropout(sub, x, dropout)
    return x


class RokoLinGRU:
    """Functional container mirroring :class:`~roko_tpu.models.gru.RokoGRU`:
    builds/holds no state, just init + apply."""

    def __init__(
        self,
        in_size: int,
        hidden: int,
        num_layers: int,
        dropout: float,
        use_pallas: bool = False,
    ):
        self.in_size = in_size
        self.hidden = hidden
        self.num_layers = num_layers
        self.dropout = dropout
        self.use_pallas = use_pallas

    def init(self, rng: jax.Array, dtype=jnp.float32) -> Tuple[Dict[str, Any], ...]:
        layers = []
        for i in range(self.num_layers):
            in_size = self.in_size if i == 0 else 2 * self.hidden
            rng, kf, kb = jax.random.split(rng, 3)
            layers.append(
                {
                    "fwd": lingru_layer_params(kf, in_size, self.hidden, dtype),
                    "bwd": lingru_layer_params(kb, in_size, self.hidden, dtype),
                }
            )
        return tuple(layers)

    def apply(self, params, x, *, deterministic=True, rng=None):
        # Fused Pallas scan kernel (models/pallas_lingru.py): covers
        # inference AND training (custom VJP recomputes the gates in
        # the backward; dropout lives between layers, outside the
        # kernel). Off-TPU the flag falls back to the associative-scan
        # path — interpret-mode Pallas is orders of magnitude slower —
        # unless ROKO_PALLAS_INTERPRET=1 forces the interpret kernels
        # (the tier-1 CI story: full-model pallas parity without a
        # TPU). Callers that need the fallback to be loud observe it
        # at the dispatch site (benchmark emits a structured event).
        if self.use_pallas:
            import os

            from roko_tpu.models.gru import _pallas_backend

            interpret = os.environ.get("ROKO_PALLAS_INTERPRET") == "1"
            if _pallas_backend() or interpret:
                from roko_tpu.models.pallas_lingru import (
                    bidir_lingru_stack_pallas,
                )

                # int8 weight-only params dequantize inside the layer's
                # projection via layers.weight(), same as the scan path
                return bidir_lingru_stack_pallas(
                    params,
                    x,
                    dropout=self.dropout,
                    deterministic=deterministic,
                    rng=rng,
                    interpret=interpret and jax.default_backend() != "tpu",
                )
        return bidir_lingru_stack(
            params,
            x,
            dropout=self.dropout,
            deterministic=deterministic,
            rng=rng,
        )
