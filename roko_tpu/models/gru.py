"""Bidirectional multi-layer GRU with torch-exact gate semantics.

The recurrence matches ``torch.nn.GRU`` bit-for-bit in float32 (ref:
roko/rnn_model.py:40-41 uses a 3-layer bidirectional GRU): gate order is
(r, z, n) along the stacked weight axis and the new-gate uses the two-bias
form ``n = tanh(W_in x + b_in + r * (W_hn h + b_hn))`` — the hidden-side
bias stays *inside* the reset-gate product, which matters for checkpoint
parity with the published ``r10_2.3.8.pth``.

TPU-first structure: the input projection ``x @ W_ih + b_ih`` for all T
timesteps is one large MXU-friendly matmul *outside* the ``lax.scan``; the
scan body only does the [B,H]x[H,3H] hidden matmul plus pointwise gates.
With T=90 and H=128 the recurrence is latency-bound — hoisting the input
projection removes two thirds of the per-step FLOPs from the serial chain.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from roko_tpu.models.layers import dropout as _dropout, weight as _weight


def _pallas_backend() -> bool:
    """True when the fused Pallas kernels can lower: the live backend is
    TPU, or ``ROKO_FORCE_PALLAS=1`` (used by the deviceless AOT-compile
    tests, where the default backend is CPU but compilation targets a
    TPU topology)."""
    import os

    if os.environ.get("ROKO_FORCE_PALLAS") == "1":
        return True
    return jax.default_backend() == "tpu"


def gru_layer_params(
    rng: jax.Array, in_size: int, hidden: int, dtype=jnp.float32
) -> Dict[str, jax.Array]:
    """One direction of one layer. Orthogonal init for matrices, standard
    normal for biases (ref: roko/rnn_model.py:15-21 ``gru_init``)."""
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    ortho = jax.nn.initializers.orthogonal()
    return {
        # stored transposed relative to torch: [in, 3H] so x @ w_ih works
        "w_ih": ortho(k1, (in_size, 3 * hidden), dtype),
        "w_hh": ortho(k2, (hidden, 3 * hidden), dtype),
        "b_ih": jax.random.normal(k3, (3 * hidden,), dtype),
        "b_hh": jax.random.normal(k4, (3 * hidden,), dtype),
    }


def _gru_scan(
    x_proj: jax.Array,  # [B,T,3H] = x @ w_ih + b_ih, precomputed
    h0: jax.Array,  # [B,H]
    w_hh: jax.Array,  # [H,3H]
    b_hh: jax.Array,  # [3H]
    reverse: bool,
) -> jax.Array:
    hidden = h0.shape[-1]

    def cell(h, xp):
        hp = h @ w_hh + b_hh
        r = jax.nn.sigmoid(xp[..., :hidden] + hp[..., :hidden])
        z = jax.nn.sigmoid(xp[..., hidden : 2 * hidden] + hp[..., hidden : 2 * hidden])
        n = jnp.tanh(xp[..., 2 * hidden :] + r * hp[..., 2 * hidden :])
        h_new = (1.0 - z) * n + z * h
        return h_new, h_new

    # scan over the time axis; [T,B,3H]
    _, ys = lax.scan(cell, h0, x_proj.swapaxes(0, 1), reverse=reverse)
    return ys.swapaxes(0, 1)  # [B,T,H]


def gru_direction(
    params: Dict[str, jax.Array], x: jax.Array, reverse: bool
) -> jax.Array:
    """Run one direction over ``x`` [B,T,in] -> [B,T,H]."""
    w_hh = _weight(params["w_hh"], x.dtype)
    hidden = w_hh.shape[0]
    x_proj = x @ _weight(params["w_ih"], x.dtype) + params["b_ih"]
    h0 = jnp.zeros((x.shape[0], hidden), x_proj.dtype)
    return _gru_scan(x_proj, h0, w_hh, params["b_hh"], reverse)


def bidir_layer(
    layer: Dict[str, Any], x: jax.Array, *, remat_cell: bool = False
) -> jax.Array:
    """Both directions of one layer in a SINGLE ``lax.scan``,
    [B,T,in] -> [B,T,2H] (fwd ++ bwd on the feature axis).

    The backward direction's hoisted input projection is time-reversed
    so both recurrences run forward in scan time; the per-step hidden
    matmul becomes one batched ``[2,B,H] x [2,H,3H]`` einsum. Halves the
    scan count per forward (3 instead of 6) and doubles the per-step
    MXU work — the serial chain is latency-bound, so fewer/fatter steps
    win. Numerically identical to two ``gru_direction`` calls."""
    # weight() dequantizes int8 weight-only kernels in place
    # (models/quant.py); plain f32/bf16 kernels pass through untouched
    w_hh_f = _weight(layer["fwd"]["w_hh"], x.dtype)
    hidden = w_hh_f.shape[0]
    B = x.shape[0]
    # one [B*T, in] x [in, 6H] MXU matmul projects both directions
    w_ih2 = jnp.concatenate(
        [
            _weight(layer["fwd"]["w_ih"], x.dtype),
            _weight(layer["bwd"]["w_ih"], x.dtype),
        ],
        axis=1,
    )
    b_ih2 = jnp.concatenate([layer["fwd"]["b_ih"], layer["bwd"]["b_ih"]])
    xp = x @ w_ih2 + b_ih2  # [B,T,6H]
    xp_f = xp[..., : 3 * hidden]
    xp_b = jnp.flip(xp[..., 3 * hidden :], axis=1)
    # [T, 2, B, 3H]: scan axis leads, direction is a batched-matmul dim
    xs = jnp.stack([xp_f, xp_b], axis=0).transpose(2, 0, 1, 3)
    w_hh2 = jnp.stack(
        [w_hh_f, _weight(layer["bwd"]["w_hh"], x.dtype)]
    )  # [2,H,3H]
    b_hh2 = jnp.stack([layer["fwd"]["b_hh"], layer["bwd"]["b_hh"]])[:, None]

    def cell(h, xp_t):  # h [2,B,H], xp_t [2,B,3H]
        hp = jnp.einsum("dbh,dhn->dbn", h, w_hh2) + b_hh2
        r = jax.nn.sigmoid(xp_t[..., :hidden] + hp[..., :hidden])
        z = jax.nn.sigmoid(
            xp_t[..., hidden : 2 * hidden] + hp[..., hidden : 2 * hidden]
        )
        n = jnp.tanh(xp_t[..., 2 * hidden :] + r * hp[..., 2 * hidden :])
        h_new = (1.0 - z) * n + z * h
        return h_new, h_new

    h0 = jnp.zeros((2, B, hidden), xp.dtype)
    # remat_cell: recompute the gates from (h, xp_t) in the backward
    # instead of storing r/z/n/hp per step — the stored residual stream
    # shrinks to the carries the scan keeps anyway (ys) at the cost of
    # one extra per-step matmul in the backward
    # (ModelConfig.remat_scan)
    cell_fn = jax.checkpoint(cell) if remat_cell else cell
    _, ys = lax.scan(cell_fn, h0, xs)  # [T,2,B,H]
    fwd = ys[:, 0].swapaxes(0, 1)
    bwd = jnp.flip(ys[:, 1].swapaxes(0, 1), axis=1)
    return jnp.concatenate([fwd, bwd], axis=-1)


def bidir_gru_stack(
    params: Tuple[Dict[str, Any], ...],
    x: jax.Array,
    *,
    dropout: float = 0.0,
    deterministic: bool = True,
    rng: jax.Array | None = None,
    remat_cell: bool = False,
) -> jax.Array:
    """Stacked bidirectional GRU, [B,T,in] -> [B,T,2H].

    ``params`` is a tuple of ``{"fwd": layer_params, "bwd": layer_params}``.
    Dropout is applied to each layer's output except the last, matching
    ``torch.nn.GRU(dropout=p)`` placement (between layers only).
    """
    num_layers = len(params)
    for i, layer in enumerate(params):
        x = bidir_layer(layer, x, remat_cell=remat_cell)
        if dropout > 0.0 and not deterministic and i < num_layers - 1:
            assert rng is not None
            rng, sub = jax.random.split(rng)
            x = _dropout(sub, x, dropout)
    return x


class RokoGRU:
    """Functional container: builds/holds no state, just init + apply."""

    def __init__(
        self,
        in_size: int,
        hidden: int,
        num_layers: int,
        dropout: float,
        use_pallas: bool = False,
        remat_scan: bool = False,
    ):
        self.in_size = in_size
        self.hidden = hidden
        self.num_layers = num_layers
        self.dropout = dropout
        self.use_pallas = use_pallas
        self.remat_scan = remat_scan

    def init(self, rng: jax.Array, dtype=jnp.float32) -> Tuple[Dict[str, Any], ...]:
        layers = []
        for i in range(self.num_layers):
            in_size = self.in_size if i == 0 else 2 * self.hidden
            rng, kf, kb = jax.random.split(rng, 3)
            layers.append(
                {
                    "fwd": gru_layer_params(kf, in_size, self.hidden, dtype),
                    "bwd": gru_layer_params(kb, in_size, self.hidden, dtype),
                }
            )
        return tuple(layers)

    def apply(self, params, x, *, deterministic=True, rng=None):
        # The fused Pallas kernels cover both inference and training
        # (custom VJP recomputes the gates backward; dropout lives
        # between layers, outside the kernels). Off-TPU the flag is
        # ignored — interpret-mode Pallas is orders of magnitude slower
        # than the numerically-identical scan, and use_pallas can ride
        # along in checkpointed configs.
        if self.use_pallas and _pallas_backend():
            from roko_tpu.models.pallas_gru import bidir_gru_stack_pallas

            from roko_tpu.models.quant import dequantize_params

            # the fused kernels take dense weights; int8 weight-only
            # params dequantize here (still inside the jitted program)
            params = dequantize_params(params, x.dtype)
            return bidir_gru_stack_pallas(
                params,
                x,
                dropout=self.dropout,
                deterministic=deterministic,
                rng=rng,
                compute_dtype=x.dtype,
            )
        return bidir_gru_stack(
            params,
            x,
            dropout=self.dropout,
            deterministic=deterministic,
            rng=rng,
            remat_cell=self.remat_scan,
        )
