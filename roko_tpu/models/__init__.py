from roko_tpu.models.gru import RokoGRU, bidir_gru_stack
from roko_tpu.models.lingru import RokoLinGRU, bidir_lingru_stack
from roko_tpu.models.model import RokoModel, build_model, init_params

__all__ = [
    "RokoGRU",
    "RokoLinGRU",
    "RokoModel",
    "bidir_gru_stack",
    "bidir_lingru_stack",
    "build_model",
    "init_params",
]
