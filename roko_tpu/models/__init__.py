from roko_tpu.models.gru import RokoGRU, bidir_gru_stack
from roko_tpu.models.model import RokoModel, build_model, init_params

__all__ = [
    "RokoGRU",
    "RokoModel",
    "bidir_gru_stack",
    "build_model",
    "init_params",
]
